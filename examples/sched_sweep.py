"""The paper grid via the elastic sweep scheduler: ``run_experiments.sh``
as a fleet, not a loop.

The reference walks its (multiplier × instances) grid serially in bash
and recovers crashes by hand; here the same grid is a sweep-spec JSON
scheduled across N worker processes, with dead workers' cells revoked
and re-leased until the registry shows every cell completed exactly
once (docs/SCHEDULER.md). Idempotent like the serial grid: re-running
pre-completes whatever the registry already recorded.

    python examples/sched_sweep.py [dataset.csv] [workers]
"""

import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))  # repo checkout

from distributed_drift_detection_tpu.harness.grid import sweep_spec


def main():
    dataset = sys.argv[1] if len(sys.argv) > 1 else "synth:rialto,seed=0"
    workers = int(sys.argv[2]) if len(sys.argv) > 2 else 3
    # The paper's grid shape (scaled down for a laptop when synthetic;
    # pass outdoorStream.csv and widen mults to 64..512 for the real one).
    spec = sweep_spec(
        dataset,
        mults=[1.0, 2.0, 4.0],
        partitions=[1, 2],
        trials=2,
        per_batch=50,
        results_csv="sched_sweep_runs.csv",
        spec="off",
    )
    with open("sweep.json", "w") as fh:
        json.dump(spec, fh, indent=2, sort_keys=True)
    proc = subprocess.run(
        [
            sys.executable, "-m", "distributed_drift_detection_tpu",
            "sched", "sweep.json",
            "--telemetry-dir", "sched_runs",
            "--workers", str(workers),
            "--compile-cache-dir", ".jax_cache",
            "--timeout", "900",
            "--json",
        ],
        # Propagate this process's environment (the test harness pins a
        # hermetic CPU backend through it) + the repo checkout on
        # PYTHONPATH so the scheduler/worker subprocesses resolve the
        # package from any cwd, exactly like this script's sys.path line.
        env={
            **os.environ,
            "PYTHONPATH": os.pathsep.join(filter(None, [
                os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."),
                os.environ.get("PYTHONPATH", ""),
            ])),
        },
        capture_output=True,
        text=True,
    )
    sys.stderr.write(proc.stderr[-2000:])
    print(proc.stdout, end="")
    if proc.returncode != 0:
        raise SystemExit(f"scheduler exited rc={proc.returncode}")
    summary = json.loads(proc.stdout.splitlines()[-1])
    assert summary["whole"] and summary["audit"]["ok"], summary
    print(
        f"sweep whole: {summary['completed']}/{summary['total']} cells "
        f"completed exactly once by {workers} workers "
        f"({summary['evictions']} evictions) -> sched_sweep_runs.csv"
    )


if __name__ == "__main__":
    main()
