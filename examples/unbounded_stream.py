"""Unbounded-stream detection with checkpoint/resume.

Feeds an endless synthetic stream through the chunked engine (speculative
window execution across chunk boundaries), checkpoints mid-stream, and
resumes from the checkpoint — the carry is a few KB per partition.

    python examples/unbounded_stream.py [total_rows]

Set ``DDD_TELEMETRY_DIR=<dir>`` to persist a JSONL run log with one
``chunk_completed`` progress event per chunk plus the feeder's ingest /
prefetch metric exports (``python -m distributed_drift_detection_tpu
report <run.jsonl>`` summarizes the log).
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))  # repo checkout

import tempfile
import time

import numpy as np

from distributed_drift_detection_tpu.engine import ChunkedDetector
from distributed_drift_detection_tpu.io import generator_chunks, prefetch_chunks
from distributed_drift_detection_tpu.io.synth import sea_chunk
from distributed_drift_detection_tpu.models import ModelSpec, build_model


def main():
    total = int(float(sys.argv[1])) if len(sys.argv) > 1 else 2_000_000
    p, b, cb = 8, 1000, 50

    log = reg = None
    if os.environ.get("DDD_TELEMETRY_DIR"):
        from distributed_drift_detection_tpu.telemetry.events import EventLog
        from distributed_drift_detection_tpu.telemetry.metrics import (
            MetricsRegistry,
        )

        log = EventLog.open_run(
            os.environ["DDD_TELEMETRY_DIR"], name="unbounded_stream"
        )
        log.emit(
            "run_started",
            run_id=log.run_id,
            config={
                "dataset": "synth:sea,drift_every=100000",
                "model": "centroid",
                "detector": "ddm",
                "partitions": p,
                "per_batch": b,
                "chunk_batches": cb,
                "total_rows": total,
            },
        )
        reg = MetricsRegistry()
        print(f"telemetry -> {log.path}")

    det = ChunkedDetector(
        build_model("centroid", ModelSpec(3, 2)),
        partitions=p,
        window=16,
    )
    chunks = prefetch_chunks(  # background-thread host assembly (depth 2)
        generator_chunks(
            lambda s, e: sea_chunk(seed=0, start=s, stop=e, drift_every=100_000),
            total_rows=total, partitions=p, per_batch=b, chunk_batches=cb,
            metrics=reg,
        ),
        metrics=reg,
    )

    half = total // (p * b * cb) // 2
    fed = detections = 0
    t0 = time.perf_counter()
    for i, chunk in enumerate(chunks):
        flags = det.feed(chunk)
        if log is not None:
            _, found = det.emit_chunk_event(log, i, flags)
            detections += found
        fed += 1
        if i + 1 == half:
            with tempfile.NamedTemporaryFile(suffix=".npz", delete=False) as f:
                path = f.name
            det.save(path)
            print(f"checkpointed after {det.batches_done} batches -> {path}")
            det = ChunkedDetector(
                build_model("centroid", ModelSpec(3, 2)), partitions=p, window=16
            )
            det.restore(path, example_chunk=chunk)
            print("resumed from checkpoint")
    print(f"fed {fed} chunks ({det.batches_done} batches/partition)")
    if log is not None:
        from distributed_drift_detection_tpu.telemetry.metrics import (
            write_exports,
        )

        log.emit(
            "run_completed",
            rows=total,
            seconds=time.perf_counter() - t0,
            detections=detections,
        )
        log.close()
        write_exports(reg, os.path.splitext(log.path)[0])


if __name__ == "__main__":
    main()
