"""Unbounded-stream detection with checkpoint/resume.

Feeds an endless synthetic stream through the chunked engine (speculative
window execution across chunk boundaries), checkpoints mid-stream, and
resumes from the checkpoint — the carry is a few KB per partition.

    python examples/unbounded_stream.py [total_rows]
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))  # repo checkout

import tempfile

import numpy as np

from distributed_drift_detection_tpu.engine import ChunkedDetector
from distributed_drift_detection_tpu.io import generator_chunks, prefetch_chunks
from distributed_drift_detection_tpu.io.synth import sea_chunk
from distributed_drift_detection_tpu.models import ModelSpec, build_model


def main():
    total = int(float(sys.argv[1])) if len(sys.argv) > 1 else 2_000_000
    p, b, cb = 8, 1000, 50

    det = ChunkedDetector(
        build_model("centroid", ModelSpec(3, 2)),
        partitions=p,
        window=16,
    )
    chunks = prefetch_chunks(  # background-thread host assembly (depth 2)
        generator_chunks(
            lambda s, e: sea_chunk(seed=0, start=s, stop=e, drift_every=100_000),
            total_rows=total, partitions=p, per_batch=b, chunk_batches=cb,
        )
    )

    half = total // (p * b * cb) // 2
    fed = 0
    for i, chunk in enumerate(chunks):
        det.feed(chunk)
        fed += 1
        if i + 1 == half:
            with tempfile.NamedTemporaryFile(suffix=".npz", delete=False) as f:
                path = f.name
            det.save(path)
            print(f"checkpointed after {det.batches_done} batches -> {path}")
            det = ChunkedDetector(
                build_model("centroid", ModelSpec(3, 2)), partitions=p, window=16
            )
            det.restore(path, example_chunk=chunk)
            print("resumed from checkpoint")
    print(f"fed {fed} chunks ({det.batches_done} batches/partition)")


if __name__ == "__main__":
    main()
