"""Minimal end-to-end run: load a drift benchmark CSV, detect, report.

Equivalent of executing the reference's ``DDM_Process.py`` once
(SURVEY.md §3.1), on whatever accelerator JAX finds (TPU, or CPU with
``JAX_PLATFORMS=cpu``).

    python examples/quickstart.py [dataset.csv] [mult] [partitions]

Set ``DDD_TELEMETRY_DIR=<dir>`` to persist the structured JSONL run log +
metric exports (telemetry subsystem; the CI smoke gate drives exactly
this), then summarize it offline with
``python -m distributed_drift_detection_tpu report <run.jsonl>``.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))  # repo checkout

from distributed_drift_detection_tpu import RunConfig, run


def main():
    # Geometry note: per_batch must stay below the per-partition concept
    # length (mult·100/partitions for outdoorStream) or DDM hits its
    # structural blindspot (SURVEY §7) — the defaults here keep 2 batches
    # per concept per partition.
    cfg = RunConfig(
        # Default: self-contained synthetic stand-in for the paper's rialto
        # benchmark (no CSV needed); pass a CSV path to use real data.
        dataset=sys.argv[1] if len(sys.argv) > 1 else "synth:rialto,seed=0",
        mult_data=float(sys.argv[2]) if len(sys.argv) > 2 else 2,
        partitions=int(sys.argv[3]) if len(sys.argv) > 3 else 8,
        per_batch=50,
        model="centroid",
        results_csv="ddm_cluster_runs.csv",  # C11 schema, appended per run
        validate=True,  # host-side flag-table audit after the run
        telemetry_dir=os.environ.get("DDD_TELEMETRY_DIR") or None,
    )
    res = run(cfg)
    m = res.metrics
    print(f"rows            {res.stream.num_rows:,}")
    print(f"detections      {m.num_detections}")
    print(f"mean delay      {m.mean_delay_rows:.1f} rows "
          f"({m.mean_delay_batches:.2f} batches)")
    print(f"Final Time      {res.total_time:.3f} s  "
          f"({res.stream.num_rows / res.total_time:,.0f} rows/s)")
    print(f"phase breakdown {res.timings}")
    if res.telemetry_path:
        print(f"telemetry       {res.telemetry_path}")


if __name__ == "__main__":
    main()
