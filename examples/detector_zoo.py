"""Compare the seven drift detectors on one planted-drift stream.

The reference ships a single statistic (skmultiflow's DDM,
``DDM_Process.py:133``); this framework adds Page–Hinkley, EDDM, HDDM-A,
HDDM-W, ADWIN and KSWIN — the full skmultiflow ``drift_detection`` zoo —
behind the same engine seam (``ops/detectors.py`` + ``ops/adwin.py``).
This example runs all seven on the same stream/model/seed and reports
boundary-attributed quality side by side — detections decomposed into
first hits vs spurious extra fires, with recall and hit-based delay
(``metrics.attribution_metrics``) — the quickest way to see how their
sensitivity profiles differ.

    python examples/detector_zoo.py [dataset.csv] [mult] [partitions]
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))  # repo checkout

from distributed_drift_detection_tpu import RunConfig, run
from distributed_drift_detection_tpu.config import replace


def main():
    base = RunConfig(
        dataset=sys.argv[1] if len(sys.argv) > 1 else "synth:rialto,seed=0",
        mult_data=float(sys.argv[2]) if len(sys.argv) > 2 else 2,
        partitions=int(sys.argv[3]) if len(sys.argv) > 3 else 8,
        per_batch=50,
        model="centroid",
        results_csv="",
        # PH's λ (a cumulative excess-error budget) auto-tunes from the
        # stream's planted-drift geometry by default — PHParams.threshold = 0
        # → config.auto_ph_threshold; pass PHParams(threshold=...) to pin it.
    )
    from distributed_drift_detection_tpu.metrics import attribution_metrics

    print(f"{'detector':<10} {'detections':>10} {'hits':>6} {'spurious':>9} "
          f"{'recall':>7} {'first-hit delay':>16} {'Final Time (s)':>15}")
    for name in ("ddm", "ph", "eddm", "hddm", "hddm_w", "adwin", "kswin"):
        res = run(replace(base, detector=name))
        m = res.metrics
        a = attribution_metrics(
            res.flags.change_global,
            res.stream.dist_between_changes,
            res.stream.num_rows,
        )
        fh = f"{a.mean_first_hit_delay_rows:.1f}" if a.hits else "-"
        print(f"{name:<10} {m.num_detections:>10} {a.hits:>6} "
              f"{a.spurious:>9} {a.recall:>7.3f} {fh:>16} "
              f"{res.total_time:>15.3f}")


if __name__ == "__main__":
    main()
