"""Compare the eight drift detectors on one planted-drift stream.

The reference ships a single statistic (skmultiflow's DDM,
``DDM_Process.py:133``); this framework adds Page–Hinkley, EDDM, HDDM-A,
HDDM-W, ADWIN, KSWIN — the full skmultiflow ``drift_detection`` zoo —
plus STEPD,
behind the same engine seam (``ops/detectors.py`` + ``ops/adwin.py``).
This example runs all eight on the same stream/model/seed and reports
boundary-attributed quality side by side — detections decomposed into
first hits vs spurious extra fires, with recall and hit-based delay
(``metrics.attribution_metrics``) — the quickest way to see how their
sensitivity profiles differ.

    python examples/detector_zoo.py [dataset.csv] [mult] [partitions]
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))  # repo checkout

from _zoo_report import zoo_report

from distributed_drift_detection_tpu import RunConfig


def main():
    base = RunConfig(
        dataset=sys.argv[1] if len(sys.argv) > 1 else "synth:rialto,seed=0",
        mult_data=float(sys.argv[2]) if len(sys.argv) > 2 else 2,
        partitions=int(sys.argv[3]) if len(sys.argv) > 3 else 8,
        per_batch=50,
        model="centroid",
        results_csv="",
        # PH's λ (a cumulative excess-error budget) auto-tunes from the
        # stream's planted-drift geometry by default — PHParams.threshold = 0
        # → config.auto_ph_threshold; pass PHParams(threshold=...) to pin it.
    )
    zoo_report(
        base,
        "detector",
        ("ddm", "ph", "eddm", "hddm", "hddm_w", "adwin", "kswin", "stepd"),
    )


if __name__ == "__main__":
    main()
