"""Compare the model families on one planted-drift stream.

The reference fits one model — sklearn's RandomForest on every microbatch
(``DDM_Process.py:96-105``); this framework ships six on-device pure-pytree
families (majority / centroid / gnb / linear / mlp / forest —
``models/classifiers.py``) plus the host-callback ``rf`` parity path. This
example runs each on-device family on the same stream/detector/seed and
reports boundary-attributed quality side by side — detections decomposed
into first hits vs spurious extra fires, with recall and hit-based delay
(``metrics.attribution_metrics``). The full acceptance methodology (the
"≤ 1-batch change vs rf" criterion, both benchmark geometries) lives in
``harness/parity.py``; this is its one-screen interactive cousin.

    python examples/model_zoo.py [dataset.csv] [mult] [partitions]
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))  # repo checkout

from _zoo_report import zoo_report

from distributed_drift_detection_tpu import RunConfig


def main():
    base = RunConfig(
        dataset=sys.argv[1] if len(sys.argv) > 1 else "synth:rialto,seed=0",
        mult_data=float(sys.argv[2]) if len(sys.argv) > 2 else 2,
        partitions=int(sys.argv[3]) if len(sys.argv) > 3 else 8,
        per_batch=50,
        results_csv="",
    )
    zoo_report(
        base,
        "model",
        # linear appears twice: raw reference sensitivity (documented
        # over-firing on rialto-like regimes) and the shipped gated form
        # with the DDM_ROBUST excursion floor (config.DDM_ROBUST).
        ("majority", "centroid", "gnb", "linear", "linear@robust", "mlp",
         "forest"),
    )


if __name__ == "__main__":
    main()
