"""Shared one-screen report for the zoo examples (detector_zoo / model_zoo).

Runs the same base config once per variant and prints boundary-attributed
quality side by side — detections decomposed into first hits vs spurious
extra fires, with recall and hit-based delay (``metrics.attribution_metrics``).
"""

from distributed_drift_detection_tpu import run
from distributed_drift_detection_tpu.config import replace
from distributed_drift_detection_tpu.metrics import attribution_metrics


def zoo_report(base, field: str, names) -> None:
    """Print one attribution row per variant: ``replace(base, field=name)``.

    Model names go through the shared ``family[@variant]`` grammar
    (``config.parse_model_spec``) — e.g. ``linear@robust``, the gated form
    of linear with the shipped ``DDM_ROBUST`` detector preset.
    """
    from distributed_drift_detection_tpu.config import parse_model_spec

    print(f"{field:<14} {'detections':>10} {'hits':>6} {'spurious':>9} "
          f"{'recall':>7} {'first-hit delay':>16} {'Final Time (s)':>15}")
    for name in names:
        if field == "model":
            family, extra = parse_model_spec(name)
            kw = {"model": family, **extra}
        else:
            kw = {field: name}
        res = run(replace(base, **kw))
        m = res.metrics
        a = attribution_metrics(
            res.flags.change_global,
            res.stream.dist_between_changes,
            res.stream.num_rows,
        )
        fh = f"{a.mean_first_hit_delay_rows:.1f}" if a.hits else "-"
        print(f"{name:<14} {m.num_detections:>10} {a.hits:>6} "
              f"{a.spurious:>9} {a.recall:>7.3f} {fh:>16} "
              f"{res.total_time:>15.3f}")
