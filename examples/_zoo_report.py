"""Shared one-screen report for the zoo examples (detector_zoo / model_zoo).

Runs the same base config once per variant and prints boundary-attributed
quality side by side — detections decomposed into first hits vs spurious
extra fires, with recall and hit-based delay (``metrics.attribution_metrics``).
"""

from distributed_drift_detection_tpu import run
from distributed_drift_detection_tpu.config import replace
from distributed_drift_detection_tpu.metrics import attribution_metrics


def zoo_report(base, field: str, names) -> None:
    """Print one attribution row per variant: ``replace(base, field=name)``."""
    print(f"{field:<10} {'detections':>10} {'hits':>6} {'spurious':>9} "
          f"{'recall':>7} {'first-hit delay':>16} {'Final Time (s)':>15}")
    for name in names:
        res = run(replace(base, **{field: name}))
        m = res.metrics
        a = attribution_metrics(
            res.flags.change_global,
            res.stream.dist_between_changes,
            res.stream.num_rows,
        )
        fh = f"{a.mean_first_hit_delay_rows:.1f}" if a.hits else "-"
        print(f"{name:<10} {m.num_detections:>10} {a.hits:>6} "
              f"{a.spurious:>9} {a.recall:>7.3f} {fh:>16} "
              f"{res.total_time:>15.3f}")
