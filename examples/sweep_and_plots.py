"""The full experiment methodology: grid sweep → aggregate → paper figures.

Reference C12–C15 (``run_experiments.sh`` + ``Plot Results.ipynb``) as one
script. Idempotent: re-running resumes any missing trials (the built-in
crash-recovery of ``harness.grid``).

    python examples/sweep_and_plots.py [dataset.csv]
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))  # repo checkout

from distributed_drift_detection_tpu.config import RunConfig
from distributed_drift_detection_tpu.harness.grid import run_grid
from distributed_drift_detection_tpu.harness.plots import render_all


def main():
    dataset = sys.argv[1] if len(sys.argv) > 1 else "synth:rialto,seed=0"
    base = RunConfig(dataset=dataset, results_csv="sweep_runs.csv")
    run_grid(base, mults=[8, 16, 32], partitions=[1, 2, 4, 8], trials=3)
    outputs = render_all(base.results_csv, "figures")
    for name, path in outputs.items():
        print(f"{name} -> {path}")


if __name__ == "__main__":
    main()
