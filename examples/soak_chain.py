"""Billion-row soak, chained past the int32 ceiling, with crash recovery.

Runs a device-generated drift stream (zero host feeding) through the
chained soak: the stream splits into device-program legs whose full
detection state — model params, detector statistics, carried batch *a*,
loop PRNG keys — flows across leg boundaries, so the chain is semantically
ONE stream and bit-identical to an unchained run. A checkpoint is written
after every leg; interrupt the process (Ctrl-C) and re-run the same command
to watch it resume at the first unfinished leg.

    python examples/soak_chain.py [total_rows]      # default 3e8 (CPU-friendly)

On a TPU chip, `python bench.py --soak 3e9` runs the measured benchmark
configuration of the same path (55 M rows/s, every planted boundary found).

Set ``DDD_TELEMETRY_DIR=<dir>`` to persist a JSONL run log with one
``leg_completed`` event per chained leg — mid-flight progress for
multi-minute soaks, readable while the chain is still running
(``python -m distributed_drift_detection_tpu report <run.jsonl>``).
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))  # repo checkout

import numpy as np

from distributed_drift_detection_tpu.engine import run_soak_chained
from distributed_drift_detection_tpu.models import ModelSpec, build_model


def main():
    total = int(float(sys.argv[1])) if len(sys.argv) > 1 else 300_000_000
    p, b = 64, 1000
    # ~10 concepts per partition at any requested size (the benchmark pins
    # drift_every=100_000; an example should plant visible boundaries even
    # on a small CPU-friendly run), kept a multiple of the batch size so
    # legs can align.
    drift_every = max(b, total // p // 10 // b * b)
    ckpt = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), ".soak_chain.npz"
    )
    if os.path.exists(ckpt):
        print(f"resuming from {ckpt}")

    legs_this_run = []
    found_this_run = []

    def report(leg, flags):
        legs_this_run.append(leg)
        found = int((np.asarray(flags.change_global) >= 0).sum())
        found_this_run.append(found)
        print(f"  leg {leg}: {found} detections")

    log = None
    if os.environ.get("DDD_TELEMETRY_DIR"):
        from distributed_drift_detection_tpu.telemetry.events import EventLog

        log = EventLog.open_run(os.environ["DDD_TELEMETRY_DIR"], name="soak_chain")
        log.emit(
            "run_started",
            run_id=log.run_id,
            config={
                "dataset": f"soak:drift_every={drift_every}",
                "model": "centroid",
                "detector": "ddm",
                "partitions": p,
                "per_batch": b,
                "total_rows": total,
            },
        )
        print(f"telemetry -> {log.path}")

    s = run_soak_chained(
        build_model("centroid", ModelSpec(8, 8)),
        partitions=p,
        per_batch=b,
        total_rows=total,
        drift_every=drift_every,
        max_leg_rows=2**27,  # small legs so interruptions are visible
        checkpoint_path=ckpt,
        on_leg=report,
        telemetry=log,
    )
    # Throughput over the rows THIS process executed: after a resume,
    # exec_time_s covers only the resumed legs, not the checkpointed ones.
    rows_this_run = s.rows_processed // s.legs * len(legs_this_run)
    if log is not None:
        # This-run totals only: exec_time_s covers the resumed legs, so
        # rows/detections must too, or the report's throughput inflates
        # after a resume — and they match the log's leg_completed sums.
        log.emit(
            "run_completed",
            rows=rows_this_run,
            seconds=s.exec_time_s,
            detections=sum(found_this_run),
        )
        log.close()
    rate = (
        f"≈ {rows_this_run / s.exec_time_s / 1e6:.1f}M rows/s"
        if rows_this_run
        else "(nothing left to run — resumed a finished chain)"
    )
    print(
        f"{s.rows_processed:,} rows in {s.legs} legs "
        f"({len(legs_this_run)} run now, {s.exec_time_s:.1f}s exec {rate})\n"
        f"detections {s.detections} / {s.planted_boundaries} planted, "
        f"median delay {np.median(s.delays) if s.detections else float('nan'):.0f} rows"
    )


if __name__ == "__main__":
    main()
