// Native data-plane for distributed_drift_detection_tpu.
//
// The reference's data plane is Spark's JVM + Arrow serialization
// (DDM_Process.py:222, pandas_udf boundary); its CSV ingest is pandas. Here
// the host-side ingest path is a small C++ library exposed over a C ABI and
// bound with ctypes (io/native.py): a multithreaded CSV -> float32 parser
// used to feed streams to the device at memory speed instead of Python
// parsing speed. Compute stays in XLA; this is host runtime only.
//
// Handle-based API so the file is read and line-indexed exactly once:
//   h = ddd_csv_open(path); ddd_csv_rows(h); ddd_csv_cols(h);
//   ddd_csv_read(h, out);   ddd_csv_close(h);
//
// Parsing is strict: any field std::from_chars cannot fully consume (after
// an optional leading '+') fails the row, ddd_csv_read returns the count of
// bad rows as a negative number, and the Python binding falls back to the
// NumPy path (which raises) — malformed data never silently becomes zeros.
//
// Build: make -C native   (g++ -O3 -shared -fPIC -pthread)

#include <algorithm>
#include <atomic>
#include <charconv>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Csv {
  std::string data;
  std::vector<std::pair<const char*, const char*>> lines;  // body lines
  int64_t rows = 0;
  int64_t cols = 0;
};

// Floating-point std::from_chars landed in libstdc++ 11 (the library
// feature-test macro is only defined once the FP overloads exist —
// gcc 10 ships the integer ones only). On older toolchains fall back to
// strtod on a bounded NUL-terminated copy: glibc's strtod is correctly
// rounded like from_chars, so parsed values are bit-identical; a field
// longer than the copy buffer mis-consumes and fails the row (falls to
// the NumPy path) rather than ever parsing wrong. Keeps TPU-host and
// dev-container builds on one source.
struct FpResult {
  const char* ptr;
  std::errc ec;
};

#if defined(__cpp_lib_to_chars) && __cpp_lib_to_chars >= 201611L
inline FpResult parse_fp(const char* p, const char* end, double& v) {
  auto [next, ec] = std::from_chars(p, end, v);
  return {next, ec};
}
#else
inline FpResult parse_fp(const char* p, const char* end, double& v) {
  // strtod accepts a wider grammar than from_chars (leading whitespace,
  // hex floats) and honors LC_NUMERIC. Reject those up front so both
  // builds parse exactly the same language — cross-toolchain determinism
  // of which rows are "malformed" matters as much as the values. (Python
  // processes leave LC_NUMERIC in the C locale; nothing here calls
  // setlocale.)
  if (p < end && (*p == ' ' || *p == '\t' || *p == '\v' || *p == '\f' ||
                  *p == '\r' || *p == '\n'))
    return {p, std::errc::invalid_argument};
  {
    const char* q = p;
    if (q < end && *q == '-') ++q;
    if (q + 1 < end && q[0] == '0' && (q[1] == 'x' || q[1] == 'X'))
      return {p, std::errc::invalid_argument};
  }
  char tmp[128];
  size_t n = std::min<size_t>(static_cast<size_t>(end - p), sizeof(tmp) - 1);
  std::memcpy(tmp, p, n);
  tmp[n] = '\0';
  char* endp = nullptr;
  double parsed = std::strtod(tmp, &endp);
  if (endp == tmp) return {p, std::errc::invalid_argument};
  v = parsed;
  return {p + (endp - tmp), std::errc()};
}
#endif

// Parse one CSV line of `cols` floats into out[0..cols). Strict: returns
// false on any malformed/missing/extra field.
bool parse_line(const char* p, const char* end, float* out, int64_t cols) {
  int64_t c = 0;
  while (c < cols) {
    while (p < end && (*p == ' ' || *p == '\t')) ++p;
    if (p < end && *p == '+') {  // from_chars rejects leading '+'
      ++p;
      if (p < end && (*p == '+' || *p == '-')) return false;  // "+-3.5"
    }
    double v = 0.0;
    auto [next, ec] = parse_fp(p, end, v);
    if (ec != std::errc() || next == p) return false;
    out[c++] = static_cast<float>(v);
    p = next;
    while (p < end && (*p == ' ' || *p == '\t')) ++p;
    if (c < cols) {
      if (p >= end || *p != ',') return false;
      ++p;
    }
  }
  return p == end;  // trailing garbage fails the row
}

unsigned num_threads() {
  unsigned t = std::thread::hardware_concurrency();
  return t ? t : 4;
}

using Line = std::pair<const char*, const char*>;

// Split [buf, end) into non-empty lines, trimming a trailing '\r' per line.
std::vector<Line> split_lines(const char* buf, const char* end) {
  std::vector<Line> lines;
  for (const char* q = buf; q < end;) {
    const char* e = static_cast<const char*>(memchr(q, '\n', end - q));
    const char* line_end = e ? e : end;
    if (line_end > q && *(line_end - 1) == '\r') --line_end;
    if (line_end > q) lines.emplace_back(q, line_end);
    if (!e) break;
    q = e + 1;
  }
  return lines;
}

// Parse every line into out[i*cols ..); returns the number of malformed rows.
int64_t parse_rows(const std::vector<Line>& lines, int64_t cols, float* out) {
  const int64_t n = static_cast<int64_t>(lines.size());
  unsigned T = num_threads();
  std::atomic<int64_t> bad{0};
  std::vector<std::thread> threads;
  int64_t per = (n + T - 1) / T;
  for (unsigned t = 0; t < T; ++t) {
    int64_t lo = t * per, hi = std::min<int64_t>(n, lo + per);
    if (lo >= hi) break;
    threads.emplace_back([&, lo, hi] {
      for (int64_t i = lo; i < hi; ++i) {
        const auto& ln = lines[static_cast<size_t>(i)];
        if (!parse_line(ln.first, ln.second, out + i * cols, cols))
          bad.fetch_add(1);
      }
    });
  }
  for (auto& th : threads) th.join();
  return bad.load();
}

}  // namespace

extern "C" {

// Returns an opaque handle, or null on IO/format error.
void* ddd_csv_open(const char* path) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return nullptr;
  auto* csv = new Csv();
  std::fseek(f, 0, SEEK_END);
  long n = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  csv->data.resize(static_cast<size_t>(n));
  bool ok = std::fread(csv->data.data(), 1, csv->data.size(), f) ==
            csv->data.size();
  std::fclose(f);
  const char* base = csv->data.data();
  const char* end = base + csv->data.size();
  const char* nl =
      ok && n > 0
          ? static_cast<const char*>(memchr(base, '\n', csv->data.size()))
          : nullptr;
  if (!nl) {
    delete csv;
    return nullptr;
  }
  csv->cols = 1 + std::count(base, nl, ',');
  csv->lines = split_lines(nl + 1, end);
  csv->rows = static_cast<int64_t>(csv->lines.size());
  return csv;
}

int64_t ddd_csv_rows(void* handle) { return static_cast<Csv*>(handle)->rows; }
int64_t ddd_csv_cols(void* handle) { return static_cast<Csv*>(handle)->cols; }

// Parse all rows into out[rows*cols] (row-major f32). Returns 0 on success,
// or -(number of malformed rows).
int64_t ddd_csv_read(void* handle, float* out) {
  Csv* csv = static_cast<Csv*>(handle);
  return -parse_rows(csv->lines, csv->cols, out);
}

void ddd_csv_close(void* handle) { delete static_cast<Csv*>(handle); }

// Parse a block of complete newline-separated data rows (no header) into
// out[max_rows*cols]. The block need not end with '\n'. Returns the number
// of rows parsed (>= 0), or -1 on any malformed row, or -2 if the block
// holds more than max_rows rows. Multithreaded like ddd_csv_read; used by
// the streaming ingest path (io.feeder.csv_chunks), which reads a large
// file in bounded blocks instead of materialising it.
int64_t ddd_parse_block(const char* buf, int64_t len, int64_t cols,
                        float* out, int64_t max_rows) {
  auto lines = split_lines(buf, buf + len);
  const int64_t n = static_cast<int64_t>(lines.size());
  if (n > max_rows) return -2;
  return parse_rows(lines, cols, out) ? -1 : n;
}

}  // extern "C"
