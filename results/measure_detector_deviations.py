"""Measure the zoo's two owed deviation quantifications (VERDICT r4 #2/#4).

Runs host-side only (the chunked/kernel side is represented by the
test-pinned mirror oracles — ``tests/test_golden.py`` proves the JAX
kernels bit-match them, so measuring the oracles measures the kernels).
Writes ``results/detector_deviations.json``; the numbers are quoted in
PARITY.md "Detector exactness".

1. **ADWIN clock-split** (``ops/adwin.py`` "TPU restructuring"): the kernel
   fuses bucket granularity and check cadence into one ``clock``. Compared
   per stream seed against the *classic* form (element-granularity buckets,
   ``tests/classic.py``) at the same check cadence (32, the classic
   implementations' default) and at cadence 1 (the textbook maximum):
   detection rate, first-detection delay after the planted jump, false
   alarms before it.

2. **KSWIN** (``config.KSWINParams`` deviations): the kernel form
   (full-older-window sample + asymptotic critical value) vs the published
   form (``stat_size`` subsample with replacement + scipy's exact
   two-sample KS p-value + retain-recent-on-change), which is stochastic —
   classic numbers are over subsample draws. The third deviation
   (empty-on-reset re-arm) is deterministic: re-arm spans are measured
   directly with a drift/recover/drift stream.

Usage: python results/measure_detector_deviations.py  (from the repo root)
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(os.path.dirname(HERE), "tests"))
sys.path.insert(0, os.path.dirname(HERE))

sys.path.insert(0, os.path.join(os.path.dirname(HERE), "tests", "golden"))

from classic import ClassicADWIN, ClassicKSWIN  # noqa: E402
from generate import make_stream  # noqa: E402  (canonical stream builder)
from test_detectors import OracleADWIN, OracleKSWIN  # noqa: E402

from distributed_drift_detection_tpu.config import (  # noqa: E402
    ADWINParams,
    KSWINParams,
)


def stream(seed, n, flip_at, p0, p1):
    return make_stream(dict(seed=seed, n=n, flip_at=flip_at, p0=p0, p1=p1))


def first_change_stats(det_factory, errs, flip_at, reset_on_change=True):
    """Caller-reset protocol (the engines'): feed elements, reset the
    detector after each change. Returns (false_alarms_before_flip,
    first_detection_delay_after_flip_or_None)."""
    det = det_factory()
    false_alarms, delay = 0, None
    for i, e in enumerate(errs):
        det.add_element(float(e))
        if det.in_change:
            if i < flip_at:
                false_alarms += 1
            elif delay is None:
                delay = i - flip_at
                break
            if reset_on_change:
                det = det_factory()
    return false_alarms, delay


def adwin_block():
    p = ADWINParams()  # delta=0.002, clock=32
    seeds, n, flip_at = range(10), 30_000, 15_000
    variants = {
        "chunked_clock32(kernel)": lambda: OracleADWIN(p),
        "classic_check32": lambda: ClassicADWIN(
            delta=p.delta, check_every=32, max_buckets=p.max_buckets,
            max_levels=p.max_levels, min_window=p.min_window,
            min_side=p.min_side,
        ),
        "classic_check1(textbook)": lambda: ClassicADWIN(
            delta=p.delta, check_every=1, max_buckets=p.max_buckets,
            max_levels=p.max_levels, min_window=p.min_window,
            min_side=p.min_side,
        ),
    }
    out = {}
    for name, factory in variants.items():
        fas, delays, misses = [], [], 0
        for s in seeds:
            errs = stream(s, n, flip_at, 0.05, 0.3)
            fa, d = first_change_stats(factory, errs, flip_at)
            fas.append(fa)
            if d is None:
                misses += 1
            else:
                delays.append(d)
        out[name] = {
            "streams": len(list(seeds)),
            "missed": misses,
            "false_alarms_total": int(np.sum(fas)),
            "delay_mean_elements": round(float(np.mean(delays)), 1),
            "delay_std_elements": round(float(np.std(delays)), 1),
        }
    return out


def kswin_block():
    p = KSWINParams()  # alpha=0.005, window 100, stat 30
    seeds, n, flip_at = range(8), 6_000, 3_000
    out = {}

    fas, delays, misses = [], [], 0
    for s in seeds:
        errs = stream(s, n, flip_at, 0.05, 0.6)
        fa, d = first_change_stats(lambda: OracleKSWIN(p), errs, flip_at)
        fas.append(fa)
        if d is None:
            misses += 1
        else:
            delays.append(d)
    out["kernel_form(full_older+asymptotic)"] = {
        "streams": len(list(seeds)),
        "missed": misses,
        "false_alarms_total": int(np.sum(fas)),
        "delay_mean_elements": round(float(np.mean(delays)), 1),
        "delay_std_elements": round(float(np.std(delays)), 1),
    }

    # Classic form is stochastic (subsample draw) — 3 draws per stream.
    fas, delays, misses, runs = [], [], 0, 0
    for s in seeds:
        errs = stream(s, n, flip_at, 0.05, 0.6)
        for sub in range(3):
            runs += 1
            rng = np.random.default_rng(1000 * s + sub)
            fa, d = first_change_stats(
                lambda: ClassicKSWIN(
                    alpha=p.alpha, window_size=p.window_size,
                    stat_size=p.stat_size, rng=rng,
                ),
                errs,
                flip_at,
                reset_on_change=False,  # classic self-manages its window
            )
            fas.append(fa)
            if d is None:
                misses += 1
            else:
                delays.append(d)
    out["classic_form(subsample+exact_p+retain)"] = {
        "runs": runs,
        "missed": misses,
        "false_alarms_total": int(np.sum(fas)),
        "delay_mean_elements": round(float(np.mean(delays)), 1),
        "delay_std_elements": round(float(np.std(delays)), 1),
    }

    # Re-arm after a detection (deviation 3, deterministic): drift at t1;
    # after the detection the stream returns in-control; a second drift at
    # t1+gap — the smallest gap each variant re-detects measures its
    # re-arm span.
    def rearm(variant):
        for gap in range(10, 301, 10):
            t1, t2 = 500, 500 + gap
            n2 = t2 + 400
            rng = np.random.default_rng(99)
            probs = np.full(n2, 0.02)
            probs[t1 : t1 + 40] = 0.95  # first drift burst
            probs[t2:] = 0.95  # second drift
            errs = (rng.random(n2) < probs).astype(np.float32)
            if variant == "kernel":
                det = OracleKSWIN(p)
                seen_first = False
                det_t = None
                i = 0
                while i < n2:
                    det.add_element(float(errs[i]))
                    if det.in_change:
                        if not seen_first:
                            seen_first = True
                            det = OracleKSWIN(p)  # engine empty-reset
                        elif i >= t2:
                            det_t = i
                            break
                    i += 1
            else:
                det = ClassicKSWIN(
                    alpha=p.alpha, window_size=p.window_size,
                    stat_size=p.stat_size,
                    rng=np.random.default_rng(7),
                )
                seen_first = False
                det_t = None
                for i in range(n2):
                    det.add_element(float(errs[i]))
                    if det.in_change:
                        if not seen_first:
                            seen_first = True  # classic retains stat_size
                        elif i >= t2:
                            det_t = i
                            break
            if det_t is not None:
                return gap
        return None

    out["rearm_min_gap_elements"] = {
        "kernel_empty_reset": rearm("kernel"),
        "classic_retain_stat_size": rearm("classic"),
        "note": (
            "kernel re-arms after window_size fresh elements, classic after "
            "window_size - stat_size; at the benchmark geometries "
            "(>=512-element per-partition concepts) the extra stat_size "
            "elements of blindness cost 0 missed boundaries (grid artifact: "
            "kswin recall 1.000 on outdoorStream x64)"
        ),
    }
    return out


def main():
    out = {"adwin": adwin_block(), "kswin": kswin_block()}
    path = os.path.join(HERE, "detector_deviations.json")
    with open(path, "w") as fh:
        json.dump(out, fh, indent=1, sort_keys=True)
        fh.write("\n")
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
