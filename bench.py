"""Benchmark: sustained drift-detection throughput on one TPU chip.

Reproduces the reference's headline methodology (BASELINE.md): the
outdoorStream benchmark at mult_data=512 (2.048 M rows), 16 stream
partitions, per_batch=100 — the configuration where the reference's Spark
cluster peaks at ≈25.7 k rows/s cluster-wide (16 instances × 4 cores,
2.048 M rows / 79.62 s). Timed span matches the reference's "Final Time"
(``DDM_Process.py:224→:260``): device upload + detection loop + flag
collection + delay metric.

Prints ONE JSON line:
  {"metric": "rows_per_sec_chip", "value": ..., "unit": "rows/s",
   "vs_baseline": ...}  (+ diagnostic extras, including the 1e9-row
   sustained-soak stats as soak_*-prefixed keys)
vs_baseline is against the 25.7 k rows/s cluster-wide best — the
BASELINE.json north star asks for ≥20×.

``--soak N`` runs only the soak at N rows (chained beyond 2^31 — exact
state-carrying legs, ``engine.soak.run_soak_chained``).

The first device interaction of a fresh process over the remote-TPU tunnel
can absorb tens of seconds of one-time setup (device init, remote compile
service) that a single warm-up does not always amortise, and individual
repetitions occasionally catch multi-second stalls of the shared tunnel
itself. The benchmark therefore runs two warm-ups and reports the **median
of nine timed repetitions** (each well under a second warm, so the extra
repetitions are cheap insurance against stall-polluted medians) — the
closest robust analog of the reference's trial-mean methodology (means of
≥4 trials on a warm, dedicated cluster, BASELINE.md) under noisy
measurement infrastructure.
"""

import json
import sys
import time

import numpy as np

# Best cluster-wide throughput of the reference: 2.048 M rows / 79.62 s at
# 16 instances × 4 cores (BASELINE.md); both benchmark modes compare to it.
BASELINE_ROWS_PER_SEC = 25_700.0


def _enable_compile_cache(jax) -> None:
    # The remote TPU compile service can be slow; cache executables across
    # bench invocations (shapes are stable).
    jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)


def _soak_stats(total_rows: int) -> dict:
    """The BASELINE.json 1e9-row sustained-throughput config (engine.soak:
    the synthetic stream is generated in-jit, zero host feeding). Returns
    the stats dict for one soak of ``total_rows`` rows on the chip.

    ≤ 2^31 rows runs as ONE device program (median of 3 warm repetitions);
    beyond the int32 position ceiling it switches to the state-carrying
    chained soak (``engine.soak.run_soak_chained``: exact single-stream
    semantics across legs, leg executables AOT-compiled outside its
    ``exec_time_s`` measurement span)."""
    import jax

    from distributed_drift_detection_tpu.engine.soak import (
        make_soak_runner,
        planted_interior_boundaries,
        run_soak_chained,
    )
    from distributed_drift_detection_tpu.models import ModelSpec, build_model

    p, b, drift_every = 64, 1000, 100_000
    model = build_model("centroid", ModelSpec(8, 8))
    key = jax.random.key(0)
    chained = total_rows > 2**31 - 1

    if chained:
        s = run_soak_chained(
            model,
            partitions=p,
            per_batch=b,
            drift_every=drift_every,
            key=key,
            total_rows=total_rows,
        )
        elapsed = s.exec_time_s
        rows, detections = s.rows_processed, s.detections
        boundaries, delays, legs = s.planted_boundaries, s.delays, s.legs
    else:
        nb = max(total_rows // (p * b), 2)
        run = jax.jit(
            make_soak_runner(
                model,
                partitions=p,
                per_batch=b,
                num_batches=nb,
                drift_every=drift_every,
            )
        )
        np.asarray(run(key).flags.change_global)  # compile + warm
        times, cg = [], None
        for _ in range(3):
            start = time.perf_counter()
            out = run(key)
            cg = np.asarray(out.flags.change_global)
            times.append(time.perf_counter() - start)
        rows = int(out.rows_processed)
        elapsed = float(np.median(times))
        detections = int((cg >= 0).sum())
        boundaries = planted_interior_boundaries(p, nb * b, drift_every)
        delays = cg[cg >= 0] % drift_every
        legs = 1
    return {
        "value": round(rows / elapsed, 1),
        "vs_baseline": round(rows / elapsed / BASELINE_ROWS_PER_SEC, 2),
        "time_s": round(elapsed, 4),
        "rows": rows,
        "partitions": p,
        "legs": legs,
        "detections": detections,
        "planted_boundaries": boundaries,
        "median_delay_rows": float(np.median(delays)) if detections else None,
    }


def soak(total_rows: int) -> None:
    """--soak mode: print the soak stats as the one JSON line."""
    import jax

    _enable_compile_cache(jax)
    stats = _soak_stats(total_rows)
    print(
        json.dumps(
            {
                "metric": "soak_rows_per_sec_chip",
                "unit": "rows/s",
                **stats,
                "device": str(jax.devices()[0].platform),
            }
        )
    )


def main() -> None:
    import jax

    _enable_compile_cache(jax)

    from distributed_drift_detection_tpu.api import prepare
    from distributed_drift_detection_tpu.config import RunConfig
    from distributed_drift_detection_tpu.metrics import delay_metrics
    from distributed_drift_detection_tpu.parallel import shard_batches
    from distributed_drift_detection_tpu.parallel.mesh import unpack_flags

    mult = int(sys.argv[1]) if len(sys.argv) > 1 else 512
    partitions = int(sys.argv[2]) if len(sys.argv) > 2 else 16
    cfg = RunConfig(
        dataset="/root/reference/outdoorStream.csv",
        mult_data=mult,
        partitions=partitions,
        per_batch=100,
        model="centroid",  # closed-form fit; the RF-equivalent flagship
        # Wider speculation than the default 16: at the headline geometry
        # (concept spacing 32 batches/partition) the sequential while-loop
        # iteration count, not per-step FLOPs, bounds the detect phase, and
        # measured medians improve monotonically up to the clamp (W=64
        # ≈ 0.50 s vs W=16 ≈ 0.62 s end-to-end at mult=512).
        window=64,
        results_csv="",
    )
    prep = prepare(cfg)
    stream, batches, runner, keys, mesh = (
        prep.stream, prep.batches, prep.runner, prep.keys, prep.mesh
    )

    # Warm-ups: compile once on the real shapes, then once more to flush any
    # remaining one-time device/tunnel setup out of the timed region.
    for _ in range(2):
        db, dk = shard_batches(batches, keys, mesh)
        jax.block_until_ready(runner(db, dk))

    # Timed runs — each spans the reference's Final Time
    # (upload + detect + collect + delay metric); report the median of 9
    # (see module docstring).
    times = []
    for _ in range(9):
        start = time.perf_counter()
        db, dk = shard_batches(batches, keys, mesh)
        out = runner(db, dk)
        change_global = unpack_flags(np.asarray(out.packed)).change_global
        m = delay_metrics(
            change_global, stream.dist_between_changes, cfg.per_batch
        )
        times.append(time.perf_counter() - start)
    elapsed = float(np.median(times))

    rows_per_sec = stream.num_rows / elapsed
    delay_batches = m.mean_delay_batches

    # The 1e9-row sustained soak rides along in the same JSON line (as
    # soak_*-prefixed keys, keeping the one-line contract) so the soak claim
    # is driver-captured every round, not README-only. TPU only: on XLA CPU
    # the same scan is ~500× the headline workload and would stall the bench
    # for hours (the CPU fallback path in the verify recipe hits this).
    if jax.devices()[0].platform == "tpu":
        try:
            soak_stats = {
                f"soak_{k}": v for k, v in _soak_stats(1_000_000_000).items()
            }
        except Exception as e:  # headline result still reported on soak failure
            import traceback

            traceback.print_exc(file=sys.stderr)
            soak_stats = {"soak_error": f"{type(e).__name__}: {e}"[:300]}
    else:
        soak_stats = {"soak_skipped": "non-TPU device; use --soak explicitly"}

    print(
        json.dumps(
            {
                "metric": "rows_per_sec_chip",
                "value": round(rows_per_sec, 1),
                "unit": "rows/s",
                "vs_baseline": round(rows_per_sec / BASELINE_ROWS_PER_SEC, 2),
                "final_time_s": round(elapsed, 4),
                "rows": stream.num_rows,
                "partitions": cfg.partitions,
                "mean_delay_batches": (
                    round(delay_batches, 3) if np.isfinite(delay_batches) else None
                ),
                "detections": m.num_detections,
                **soak_stats,
                "device": str(jax.devices()[0].platform),
            }
        )
    )


if __name__ == "__main__":
    is_soak = len(sys.argv) > 1 and sys.argv[1] == "--soak"
    try:
        if is_soak:
            soak(int(float(sys.argv[2])) if len(sys.argv) > 2 else 1_000_000_000)
        else:
            main()
    except Exception as e:  # still emit ONE parseable JSON line on failure
        import traceback

        traceback.print_exc(file=sys.stderr)  # full diagnostic to stderr
        print(
            json.dumps(
                {
                    "metric": (
                        "soak_rows_per_sec_chip" if is_soak else "rows_per_sec_chip"
                    ),
                    "value": None,
                    "unit": "rows/s",
                    "vs_baseline": None,
                    "error": f"{type(e).__name__}: {e}"[:300],
                }
            )
        )
        raise SystemExit(1)
