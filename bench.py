"""Benchmark: sustained drift-detection throughput on one TPU chip.

Reproduces the reference's headline methodology (BASELINE.md): the
outdoorStream benchmark at mult_data=512 (2.048 M rows), 16 stream
partitions, per_batch=100 — the configuration where the reference's Spark
cluster peaks at ≈25.7 k rows/s cluster-wide (16 instances × 4 cores,
2.048 M rows / 79.62 s). Timed span matches the reference's "Final Time"
(``DDM_Process.py:224→:260``): device upload + detection loop + flag
collection + delay metric.

Prints ONE JSON line:
  {"metric": "rows_per_sec_chip", "value": ..., "unit": "rows/s",
   "vs_baseline": ...}  (+ diagnostic extras, including the 1e9-row
   sustained-soak stats as soak_*-prefixed keys)
vs_baseline is against the 25.7 k rows/s cluster-wide best — the
BASELINE.json north star asks for ≥20×.

``--soak N`` runs only the soak at N rows (chained beyond 2^31 — exact
state-carrying legs, ``engine.soak.run_soak_chained``). The default line
additionally rides a ``soak_xl_*`` block: the same chained-only branch at a
3e9-row request (>2^31 rows, ≥3 legs on hardware every round).

The first device interaction of a fresh process over the remote-TPU tunnel
can absorb tens of seconds of one-time setup (device init, remote compile
service) that a single warm-up does not always amortise, and individual
repetitions occasionally catch multi-second stalls of the shared tunnel
itself. The benchmark therefore runs two warm-ups and reports the **median
of nine timed repetitions** — the closest robust analog of the reference's
trial-mean methodology (means of ≥4 trials on a warm, dedicated cluster,
BASELINE.md) under noisy measurement infrastructure. Because a stalled
median is indistinguishable from a real regression after the fact, the
JSON line also carries the full per-repetition record: ``rep_times_s``
(all nine spans), ``final_time_min_s`` (the min — the cleanest view of
what the code can do), and ``phase_s`` (per-repetition
upload/detect/collect breakdown via ``utils.timing.PhaseTimer``; ``detect``
is the pure device-execution span, measured to ``block_until_ready``) — so
a tunnel stall is visible *in the artifact*: it shows up as outlier
repetitions whose excess lives in ``upload``/``collect`` (host↔device
link) rather than ``detect`` (device compute).
"""

import json
import sys
import time

import numpy as np

# Best cluster-wide throughput of the reference: 2.048 M rows / 79.62 s at
# 16 instances × 4 cores (BASELINE.md); both benchmark modes compare to it.
BASELINE_ROWS_PER_SEC = 25_700.0


def _enable_compile_cache(jax) -> None:
    # The remote TPU compile service can be slow; cache executables across
    # bench invocations (shapes are stable).
    jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)


def _chained_stats(s, partitions: int) -> dict:
    """Stats dict for a ChainedSoakSummary — the one soak-JSON shape shared
    by the >2^31 chained-only branch and the leg-rounding-overflow fallback
    (one source of truth for the --soak contract)."""
    return {
        "value": round(s.rows_processed / s.exec_time_s, 1),
        "vs_baseline": round(
            s.rows_processed / s.exec_time_s / BASELINE_ROWS_PER_SEC, 2
        ),
        "time_s": round(s.exec_time_s, 4),
        "rows": s.rows_processed,
        "requested_rows": s.requested_rows,
        "reps": 1,  # single measurement (chain state is carried, not replayed)
        "partitions": partitions,
        "legs": s.legs,
        "detections": s.detections,
        "planted_boundaries": s.planted_boundaries,
        "median_delay_rows": (
            float(np.median(s.delays)) if s.detections else None
        ),
    }


def _soak_stats(total_rows: int, chained_proof: bool = True) -> dict:
    """The BASELINE.json 1e9-row sustained-throughput config (engine.soak:
    the synthetic stream is generated in-jit, zero host feeding). Returns
    the stats dict for one soak of ``total_rows`` rows on the chip.

    ≤ 2^31 rows runs as ONE device program (median of 3 warm repetitions,
    ``reps: 3``) — and, with ``chained_proof``, additionally runs the SAME
    stream as a 2-leg state-carrying chain (``engine.soak.run_soak_chained``,
    legs forced via ``max_leg_rows``) and asserts its per-partition
    detection positions equal the one-shot run's exactly, recording the proof as
    ``chained_legs``/``chained_time_s``/``chained_matches`` (the >2³¹
    mechanism, exercised and verified on TPU every round). The chain is run
    first and the one-shot geometry is taken from its leg-aligned row count,
    so both process identical streams (leg boundaries must align to
    ``drift_every``; delays and generator concept ids are then
    leg-invariant — ``engine.soak.make_soak_chain``'s exactness contract).

    Beyond the int32 position ceiling only the chain can run; it executes
    once (``reps: 1`` — single-measurement provenance, ADVICE r2) with leg
    executables AOT-compiled outside its ``exec_time_s`` span.
    """
    import jax

    from distributed_drift_detection_tpu.engine.soak import (
        make_soak_runner,
        planted_interior_boundaries,
        run_soak_chained,
    )
    from distributed_drift_detection_tpu.models import ModelSpec, build_model

    # Geometry from the r04 on-hardware (p × b) sweep: the soak scan is
    # iteration-latency-bound, and 128 × 2000 (≈256 k rows/step) measured
    # 105 M rows/s vs 58 M at the former 64 × 1000 — wider or deeper steps
    # (512 k rows/step at any split) regress to ~60 M (transient generator
    # buffers outgrow what the compiler keeps resident), so this is the
    # measured sweet spot, not the scaling limit.
    p, b, drift_every = 128, 2000, 100_000
    model = build_model("centroid", ModelSpec(8, 8))
    key = jax.random.key(0)
    chained_only = total_rows > 2**31 - 1

    if chained_only:
        s = run_soak_chained(
            model,
            partitions=p,
            per_batch=b,
            drift_every=drift_every,
            key=key,
            total_rows=total_rows,
        )
        return _chained_stats(s, p)

    extras = {}
    if chained_proof:
        # 2-leg chain first: its leg-aligned geometry defines the stream
        # both paths run (1e9 requested → 2 × ~2050 batches/partition at
        # the 128 × 2000 geometry).
        # The proof below compares *per-partition detection positions*, so
        # collect them leg by leg (the summary folds flags into global delay
        # stats; a compensating mismatch — same delays attributed to
        # different partitions — must not pass, ADVICE r3).
        chain_pos = [[] for _ in range(p)]

        def _collect_positions(leg_idx, flags):
            leg_cg = np.asarray(flags.change_global)
            for q in range(p):
                hit = leg_cg[q][leg_cg[q] >= 0]
                if hit.size:
                    chain_pos[q].append(hit.astype(np.int64))

        s = run_soak_chained(
            model,
            partitions=p,
            per_batch=b,
            drift_every=drift_every,
            key=key,
            total_rows=total_rows,
            max_leg_rows=2**29,
            on_leg=_collect_positions,
        )
        nb = s.rows_processed // (p * b)
        if p * nb * b > 2**31 - 1:
            # Leg rounding pushed the aligned total past the one-shot
            # runner's int32 ceiling (requests in (~2.125e9, 2^31−1]):
            # report the chained run itself — same stats shape as the
            # chained-only branch above, no one-shot comparison possible.
            return _chained_stats(s, p)
        extras = {
            "requested_rows": int(total_rows),
            "chained_legs": s.legs,
            "chained_time_s": round(s.exec_time_s, 4),
            "chained_reps": 1,
        }
    else:
        nb = max(total_rows // (p * b), 2)

    run = jax.jit(
        make_soak_runner(
            model,
            partitions=p,
            per_batch=b,
            num_batches=nb,
            drift_every=drift_every,
        )
    )
    np.asarray(run(key).flags.change_global)  # compile + warm
    times, cg = [], None
    for _ in range(3):
        start = time.perf_counter()
        out = run(key)
        cg = np.asarray(out.flags.change_global)
        times.append(time.perf_counter() - start)
    rows = int(out.rows_processed)
    elapsed = float(np.median(times))
    detections = int((cg >= 0).sum())
    delays = cg[cg >= 0] % drift_every

    if chained_proof:
        # The exactness contract, proven on this hardware: the 2-leg chain
        # found the same changes at the same stream positions, PER PARTITION
        # (chain rows are partition-local; one-shot rows carry the q·nb·b
        # partition offset, a multiple of drift_every by leg alignment).
        # Strictly stronger than the old global delay-multiset check: equal
        # per-partition position multisets imply equal delay multisets, and
        # a compensating cross-partition attribution mismatch cannot pass.
        # A mismatch raises — in --soak mode that is the error JSON +
        # exit 1; in the default bench the rider converts it to a
        # soak_error key, so the artifact can never carry a normal-looking
        # soak block over a broken >2^31 mechanism.
        matches = s.detections == detections
        for q in range(p):
            one = np.sort(
                cg[q][cg[q] >= 0].astype(np.int64) - q * nb * b
            )
            ch = (
                np.sort(np.concatenate(chain_pos[q]))
                if chain_pos[q]
                else np.empty(0, np.int64)
            )
            matches = matches and np.array_equal(one, ch)
        if not matches:
            raise RuntimeError(
                "chained-soak proof FAILED: 2-leg chain found "
                f"{int(s.detections)} detections vs one-shot {detections} "
                "(or per-partition position multisets differ) on identical "
                "streams"
            )
        extras["chained_matches"] = True

    return {
        "value": round(rows / elapsed, 1),
        "vs_baseline": round(rows / elapsed / BASELINE_ROWS_PER_SEC, 2),
        "time_s": round(elapsed, 4),
        "rep_times_s": [round(t, 4) for t in times],
        "reps": 3,
        "rows": rows,
        "partitions": p,
        "legs": 1,
        "detections": detections,
        "planted_boundaries": planted_interior_boundaries(
            p, nb * b, drift_every
        ),
        "median_delay_rows": float(np.median(delays)) if detections else None,
        **extras,
    }


def soak(total_rows: int) -> None:
    """--soak mode: print the soak stats as the one JSON line."""
    import jax

    _enable_compile_cache(jax)
    stats = _soak_stats(total_rows)
    print(
        json.dumps(
            {
                "metric": "soak_rows_per_sec_chip",
                "unit": "rows/s",
                **stats,
                "device": str(jax.devices()[0].platform),
            }
        )
    )


def main() -> None:
    import jax

    _enable_compile_cache(jax)

    from distributed_drift_detection_tpu.api import prepare
    from distributed_drift_detection_tpu.config import RunConfig
    from distributed_drift_detection_tpu.metrics import delay_metrics
    from distributed_drift_detection_tpu.parallel import shard_batches
    from distributed_drift_detection_tpu.parallel.mesh import unpack_flags
    from distributed_drift_detection_tpu.utils.timing import PhaseTimer

    # argv: [mult] [partitions] [window] [rotations] — the last two expose
    # the speculative engine's knobs for on-hardware sweeps via this CLI.
    mult = int(sys.argv[1]) if len(sys.argv) > 1 else 512
    partitions = int(sys.argv[2]) if len(sys.argv) > 2 else 16
    # Default 0/0 = auto: the bench measures the *shipped* execution policy
    # (config.auto_window / auto_rotations co-resolve W×R from stream
    # geometry; at this headline geometry that is 128×4 — the measured
    # optimum of the r03 W×R sweep on one TPU chip, detect-phase medians of
    # 7, uncontended conditions, flags bit-identical across all configs):
    #
    #   W=64  R=1: 0.165 s   (round-2 default)
    #   W=64  R=4: 0.161 s   W=64  R=8: 0.199 s
    #   W=128 R=1: 0.218 s   (wide window without rotations: replay waste)
    #   W=128 R=2: 0.176 s   W=128 R=3: 0.161 s
    #   W=128 R=4: 0.156 s   ← best    W=128 R=5: 0.159 s
    #   W=192 R=4: 0.191 s   W=256 R=5: 0.212 s (per-iteration slice cost)
    #
    # Depth 4 commits a whole 128-batch window (4 planted boundaries at the
    # headline geometry) per sequential step: iterations ≈ NB/W + drifts/R
    # ≈ 10 + 10 vs the round-2 default's ≈ 20 + 39. Under the shared
    # tunnel's contended conditions (per-iteration cost 3-5× higher) the
    # iteration-count reduction is worth proportionally more.
    window = int(sys.argv[3]) if len(sys.argv) > 3 else 0
    rotations = int(sys.argv[4]) if len(sys.argv) > 4 else 0
    cfg = RunConfig(
        dataset="/root/reference/outdoorStream.csv",
        mult_data=mult,
        partitions=partitions,
        per_batch=100,
        model="centroid",  # closed-form fit; the RF-equivalent flagship
        window=window,
        window_rotations=rotations,
        results_csv="",
    )
    prep = prepare(cfg)
    stream, batches, runner, keys, mesh = (
        prep.stream, prep.batches, prep.runner, prep.keys, prep.mesh
    )

    # Warm-ups: compile once on the real shapes, then once more to flush any
    # remaining one-time device/tunnel setup out of the timed region — the
    # flag fetch included: the first device→host transfer of the packed
    # table pays multi-second one-time setup over the remote-TPU link, and
    # without fetching here it lands in timed repetition 1's collect phase
    # (both r03 captures recorded a 3.5–6.4 s first-rep collect outlier).
    for _ in range(2):
        db, dk = shard_batches(batches, keys, mesh)
        np.asarray(runner(db, dk).packed)

    # Timed runs — each spans the reference's Final Time
    # (upload + detect + collect + delay metric); report the median of 9
    # plus the full per-repetition and per-phase record (module docstring:
    # the artifact itself must distinguish a tunnel stall from a real
    # regression).
    times = []
    phases = {"upload": [], "detect": [], "collect": []}
    for _ in range(9):
        timer = PhaseTimer()
        start = time.perf_counter()
        with timer.phase("upload"):
            db, dk = shard_batches(batches, keys, mesh)
        with timer.phase("detect"):
            out = runner(db, dk)
            jax.block_until_ready(out)  # pure device-execution span
        with timer.phase("collect"):
            change_global = unpack_flags(np.asarray(out.packed)).change_global
            m = delay_metrics(
                change_global, stream.dist_between_changes, cfg.per_batch
            )
        times.append(time.perf_counter() - start)
        for k, v in timer.as_dict().items():
            phases[k].append(round(v, 4))
    elapsed = float(np.median(times))

    rows_per_sec = stream.num_rows / elapsed
    delay_batches = m.mean_delay_batches

    # The 1e9-row sustained soak rides along in the same JSON line (as
    # soak_*-prefixed keys, keeping the one-line contract) so the soak claim
    # is driver-captured every round, not README-only — including the 2-leg
    # state-carrying chained proof (soak_chained_*). TPU only: on XLA CPU
    # the same scan is ~500× the headline workload and would stall the bench
    # for hours (the CPU fallback path in the verify recipe hits this).
    if jax.devices()[0].platform == "tpu":
        try:
            soak_stats = {
                f"soak_{k}": v for k, v in _soak_stats(1_000_000_000).items()
            }
        except Exception as e:  # headline result still reported on soak failure
            import traceback

            traceback.print_exc(file=sys.stderr)
            soak_stats = {"soak_error": f"{type(e).__name__}: {e}"[:300]}
        # The int32-ceiling branch (total_rows > 2^31−1) — the one only the
        # state-carrying chain can serve — captured at true >2^31 scale on
        # hardware every round (VERDICT r3 #5: rows > 2^31, legs ≥ 3; leg
        # sizing rounds the 3e9 request up to 3 × ~1.07e9-row legs). Its own
        # try: an xl failure must not take down the soak block above. Budget
        # guard: a 1e9 soak rep beyond 30 s signals heavy shared-tunnel
        # contention (uncontended ≈ 18 s) under which the xl chain would
        # run for several minutes — skip with provenance instead of risking
        # the whole bench invocation's budget (the standalone capture lives
        # in results/soak_xl_r04.json; `python bench.py --soak 3e9` reruns it).
        soak_t = soak_stats.get("soak_time_s")
        if soak_t is None:
            # The 1e9 soak itself failed — that, not contention, is why
            # there's no xl capture this invocation.
            soak_stats["soak_xl_skipped"] = (
                "1e9 soak failed (see soak_error); xl not attempted"
            )
        elif soak_t <= 30.0:
            try:
                soak_stats.update(
                    {
                        f"soak_xl_{k}": v
                        for k, v in _soak_stats(3_000_000_000).items()
                    }
                )
            except Exception as e:
                import traceback

                traceback.print_exc(file=sys.stderr)
                soak_stats["soak_xl_error"] = f"{type(e).__name__}: {e}"[:300]
        else:
            soak_stats["soak_xl_skipped"] = (
                f"contended tunnel (soak_time_s={soak_t}); see "
                "results/soak_xl_r04.json or run bench.py --soak 3e9"
            )
    else:
        soak_stats = {"soak_skipped": "non-TPU device; use --soak explicitly"}

    print(
        json.dumps(
            {
                "metric": "rows_per_sec_chip",
                "value": round(rows_per_sec, 1),
                "unit": "rows/s",
                "vs_baseline": round(rows_per_sec / BASELINE_ROWS_PER_SEC, 2),
                "final_time_s": round(elapsed, 4),
                "final_time_min_s": round(min(times), 4),
                "rep_times_s": [round(t, 4) for t in times],
                "phase_s": phases,
                "rows": stream.num_rows,
                "partitions": cfg.partitions,
                # From the resolved config: window=0 (auto) is resolved to a
                # concrete width inside prepare() — report that, not argv.
                "window": prep.config.window,
                "window_rotations": prep.config.window_rotations,
                "mean_delay_batches": (
                    round(delay_batches, 3) if np.isfinite(delay_batches) else None
                ),
                "detections": m.num_detections,
                **soak_stats,
                "device": str(jax.devices()[0].platform),
            }
        )
    )


if __name__ == "__main__":
    is_soak = len(sys.argv) > 1 and sys.argv[1] == "--soak"
    try:
        if is_soak:
            soak(int(float(sys.argv[2])) if len(sys.argv) > 2 else 1_000_000_000)
        else:
            main()
    except Exception as e:  # still emit ONE parseable JSON line on failure
        import traceback

        traceback.print_exc(file=sys.stderr)  # full diagnostic to stderr
        print(
            json.dumps(
                {
                    "metric": (
                        "soak_rows_per_sec_chip" if is_soak else "rows_per_sec_chip"
                    ),
                    "value": None,
                    "unit": "rows/s",
                    "vs_baseline": None,
                    "error": f"{type(e).__name__}: {e}"[:300],
                }
            )
        )
        raise SystemExit(1)
