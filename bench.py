"""Benchmark: sustained drift-detection throughput on one TPU chip.

Reproduces the reference's headline methodology (BASELINE.md): the
outdoorStream benchmark at mult_data=512 (2.048 M rows), 16 stream
partitions, per_batch=100 — the configuration where the reference's Spark
cluster peaks at ≈25.7 k rows/s cluster-wide (16 instances × 4 cores,
2.048 M rows / 79.62 s). Timed span matches the reference's "Final Time"
(``DDM_Process.py:224→:260``): device upload + detection loop + flag
collection + delay metric.

Prints ONE JSON line:
  {"metric": "rows_per_sec_chip", "value": ..., "unit": "rows/s",
   "vs_baseline": ...}  (+ diagnostic extras, including the 1e9-row
   sustained-soak stats as soak_*-prefixed keys)
vs_baseline is against the 25.7 k rows/s cluster-wide best — the
BASELINE.json north star asks for ≥20×.

``--soak N`` runs only the soak at N rows (chained beyond 2^31 — exact
state-carrying legs, ``engine.soak.run_soak_chained``). The default line
additionally rides a ``soak_xl_*`` block: the same chained-only branch at a
3e9-row request (>2^31 rows, ≥3 legs on hardware every round).

The first device interaction of a fresh process over the remote-TPU tunnel
can absorb tens of seconds of one-time setup (device init, remote compile
service) that a single warm-up does not always amortise, and individual
repetitions catch multi-second stalls of the shared tunnel itself — r01-r04
recorded headline swings of 2× with bit-identical flags from exactly this.
The benchmark therefore runs two warm-ups and **15 timed repetitions with
stall-aware selection** (VERDICT r4 #3): any repetition slower than 1.5×
the invocation's fastest is classified a stall (the fastest repetition is
stall-free by construction, and a real regression moves the fastest too,
so regressions cannot be filtered away), and the headline is the median of
the non-stalled repetitions — the closest robust analog of the reference's
trial-mean methodology (means of ≥4 trials on a warm, dedicated cluster,
BASELINE.md) under noisy measurement infrastructure. The JSON line carries
the evidence: ``stalled_reps`` (the excluded indices), ``contended``
(≥half the reps stalled — treat the headline with suspicion),
``rep_times_s`` (all 15 spans), ``final_time_min_s``, ``detect_time_s``
(median non-stalled device-execution span — the detect phase is closed by
a 1-element d2h fetch because ``block_until_ready`` alone is unreliable
over this tunnel), and ``phase_s`` (per-repetition upload/detect/collect
breakdown) — so a tunnel stall is visible *in the artifact*: excess in
``upload``/``collect`` (host↔device link) rather than ``detect`` (device
compute). ``compile_s`` records the compile split explicitly (first-call
warm-up span vs the steady-state median) and ``phase_hist`` the per-phase
histograms (telemetry metrics registry, Prometheus bucket semantics), so
BENCH_*.json trajectories separate recompilation from kernel regressions.
``xla`` carries the compiler's own cost/memory model of the headline
runner (flops, bytes accessed, argument/output/temp/generated-code bytes —
telemetry.profile, extracted outside the timed repetitions), separating
"the kernel got more expensive" from "the host got slower". ``--smoke``
emits the same artifact shape from a CI-scale synthetic run (3 reps, no
riders) so the schema and the ``perf`` diff CLI (``python -m
distributed_drift_detection_tpu perf BENCH_r*.json``) are exercisable
without hardware.

Round-6 additions: the collect phase ships the device-compacted detection
table by default (``collect``/``collect_events``/``collect_overflow``
provenance fields; ``--collect full`` pins the round-5 full-plane path),
``collect_share`` records collect's share of the span (gated by the perf
CLI), and ``cold_vs_warm_compile_s`` records the AOT warm-start split —
``cold_s`` is prepare's ``lower().compile()`` span (near-zero against a
populated persistent cache), ``warm_s`` the same-process re-lower floor.
``--compile-cache-dir DIR`` redirects the persistent compilation cache
(default: ``.jax_cache`` next to this script); the CI warm-start gate runs
``--smoke`` twice against a shared directory and asserts the second run's
``cold_s`` collapses.

Round-7 addition: ``--serve [ROWS [RATE]]`` runs the online-serving SLO
bench — an in-process ``serve`` daemon on a loopback socket, warmed
(AOT prepare + one warm-up replay), then driven by the loadgen at RATE
rows/s — and emits ``serve_rows_per_sec`` with ``serve_p50_ms`` /
``serve_p99_ms`` row→verdict latency (tracked informationally by the
``perf`` CLI). Round-12 rider: the same mode measures the adaptation
plane — a second in-process daemon with ``on_drift=retrain`` consumes a
planted recurring-drift stream and emits ``serve_adapt_recovery_rows``
(rows from drift verdict until post-drift error returns within ε of the
pre-drift level; informational).
"""

import json
import os
import sys
import time

import numpy as np

# Best cluster-wide throughput of the reference: 2.048 M rows / 79.62 s at
# 16 instances × 4 cores (BASELINE.md); both benchmark modes compare to it.
BASELINE_ROWS_PER_SEC = 25_700.0

# Cache artifacts live next to this script, wherever the checkout lands
# (advisor round-5: no hardcoded absolute repo paths).
_BENCH_DIR = os.path.dirname(os.path.abspath(__file__))

# CLI-flag overrides shared by every mode (parsed in __main__ before the
# positional argv): --compile-cache-dir redirects the persistent compile
# cache (the warm-start CI runs two --smoke invocations against a shared
# directory and asserts the second's compile split ≈ 0); --collect
# pins the collect transport (compact|full) for A/B runs.
_CLI = {"compile_cache_dir": "", "collect": "", "ingest_workers": 0}


# One argv-mutating flag parser for the whole project (the package CLI owns
# it; importing pulls in no jax).
from distributed_drift_detection_tpu.__main__ import _pop_flag  # noqa: E402


def _emit(artifact: dict) -> None:
    """Print one bench artifact under the summary-line contract
    (``telemetry.perf.summary_lines``): the FINAL stdout line always
    parses and always carries every gated cell. When the full artifact
    outgrows the round driver's ~2 KB tail window (BENCH_r05.json
    recorded ``parsed: null`` from exactly that), the full line prints
    first and a trimmed, budget-fitting gate line prints last — the perf
    CLI re-merges the pair."""
    from distributed_drift_detection_tpu.telemetry.perf import summary_lines

    for line in summary_lines(artifact):
        print(line)


def _enable_compile_cache(jax) -> None:
    # The remote TPU compile service can be slow; cache executables across
    # bench invocations (shapes are stable). utils.compile_cache is the
    # shared switch (min compile time 0: sweep-scale programs must cache
    # too — the warm-start contract the CI gate asserts).
    from distributed_drift_detection_tpu.utils.compile_cache import (
        enable_persistent_cache,
    )

    enable_persistent_cache(
        _CLI["compile_cache_dir"] or os.path.join(_BENCH_DIR, ".jax_cache")
    )


def _xla_fields(runner, *args) -> dict:
    """Compiler-reported cost/memory of the headline runner (one flat dict
    for the artifact's ``xla`` key: flops, bytes_accessed, argument/output/
    temp/generated-code bytes — telemetry.profile). Extracted OUTSIDE the
    timed repetitions; empty where the backend reports nothing, so the
    artifact never fabricates a cost model it didn't get."""
    from distributed_drift_detection_tpu.telemetry.profile import (
        compiled_stats,
    )

    stats = compiled_stats(runner, *args)
    out = {}
    cost = stats.get("cost") or {}
    for k in ("flops", "bytes_accessed", "transcendentals"):
        if cost.get(k) is not None:
            out[k] = cost[k]
    out.update(stats.get("memory") or {})
    return out


def _chained_stats(s, partitions: int) -> dict:
    """Stats dict for a ChainedSoakSummary — the one soak-JSON shape shared
    by the >2^31 chained-only branch and the leg-rounding-overflow fallback
    (one source of truth for the --soak contract)."""
    return {
        "value": round(s.rows_processed / s.exec_time_s, 1),
        "vs_baseline": round(
            s.rows_processed / s.exec_time_s / BASELINE_ROWS_PER_SEC, 2
        ),
        "time_s": round(s.exec_time_s, 4),
        "rows": s.rows_processed,
        "requested_rows": s.requested_rows,
        "reps": 1,  # single measurement (chain state is carried, not replayed)
        "partitions": partitions,
        "legs": s.legs,
        "detections": s.detections,
        "planted_boundaries": s.planted_boundaries,
        "median_delay_rows": (
            float(np.median(s.delays)) if s.detections else None
        ),
    }


def _soak_stats(total_rows: int, chained_proof: bool = True) -> dict:
    """The BASELINE.json 1e9-row sustained-throughput config (engine.soak:
    the synthetic stream is generated in-jit, zero host feeding). Returns
    the stats dict for one soak of ``total_rows`` rows on the chip.

    ≤ 2^31 rows runs as ONE device program (median of 3 warm repetitions,
    ``reps: 3``) — and, with ``chained_proof``, additionally runs the SAME
    stream as a 2-leg state-carrying chain (``engine.soak.run_soak_chained``,
    legs forced via ``max_leg_rows``) and asserts its per-partition
    detection positions equal the one-shot run's exactly, recording the proof as
    ``chained_legs``/``chained_time_s``/``chained_matches`` (the >2³¹
    mechanism, exercised and verified on TPU every round). The chain is run
    first and the one-shot geometry is taken from its leg-aligned row count,
    so both process identical streams (leg boundaries must align to
    ``drift_every``; delays and generator concept ids are then
    leg-invariant — ``engine.soak.make_soak_chain``'s exactness contract).

    Beyond the int32 position ceiling only the chain can run; it executes
    once (``reps: 1`` — single-measurement provenance, ADVICE r2) with leg
    executables AOT-compiled outside its ``exec_time_s`` span.
    """
    import jax

    from distributed_drift_detection_tpu.engine.soak import (
        make_soak_runner,
        planted_interior_boundaries,
        run_soak_chained,
    )
    from distributed_drift_detection_tpu.models import ModelSpec, build_model

    # Geometry from the r04 on-hardware (p × b) sweep: the soak scan is
    # iteration-latency-bound, and 128 × 2000 (≈256 k rows/step) measured
    # 105 M rows/s vs 58 M at the former 64 × 1000 — wider or deeper steps
    # (512 k rows/step at any split) regress to ~60 M (transient generator
    # buffers outgrow what the compiler keeps resident), so this is the
    # measured sweet spot, not the scaling limit.
    p, b, drift_every = 128, 2000, 100_000
    model = build_model("centroid", ModelSpec(8, 8))
    key = jax.random.key(0)
    chained_only = total_rows > 2**31 - 1

    if chained_only:
        s = run_soak_chained(
            model,
            partitions=p,
            per_batch=b,
            drift_every=drift_every,
            key=key,
            total_rows=total_rows,
        )
        return _chained_stats(s, p)

    extras = {}
    if chained_proof:
        # 2-leg chain first: its leg-aligned geometry defines the stream
        # both paths run (1e9 requested → 2 × ~2050 batches/partition at
        # the 128 × 2000 geometry).
        # The proof below compares *per-partition detection positions*, so
        # collect them leg by leg (the summary folds flags into global delay
        # stats; a compensating mismatch — same delays attributed to
        # different partitions — must not pass, ADVICE r3).
        chain_pos = [[] for _ in range(p)]

        def _collect_positions(leg_idx, flags):
            leg_cg = np.asarray(flags.change_global)
            for q in range(p):
                hit = leg_cg[q][leg_cg[q] >= 0]
                if hit.size:
                    chain_pos[q].append(hit.astype(np.int64))

        s = run_soak_chained(
            model,
            partitions=p,
            per_batch=b,
            drift_every=drift_every,
            key=key,
            total_rows=total_rows,
            max_leg_rows=2**29,
            on_leg=_collect_positions,
        )
        nb = s.rows_processed // (p * b)
        if p * nb * b > 2**31 - 1:
            # Leg rounding pushed the aligned total past the one-shot
            # runner's int32 ceiling (requests in (~2.125e9, 2^31−1]):
            # report the chained run itself — same stats shape as the
            # chained-only branch above, no one-shot comparison possible.
            return _chained_stats(s, p)
        extras = {
            "requested_rows": int(total_rows),
            "chained_legs": s.legs,
            "chained_time_s": round(s.exec_time_s, 4),
            "chained_reps": 1,
        }
    else:
        nb = max(total_rows // (p * b), 2)

    run = jax.jit(
        make_soak_runner(
            model,
            partitions=p,
            per_batch=b,
            num_batches=nb,
            drift_every=drift_every,
        )
    )
    np.asarray(run(key).flags.change_global)  # compile + warm
    times, cg = [], None
    for _ in range(3):
        start = time.perf_counter()
        out = run(key)
        cg = np.asarray(out.flags.change_global)
        times.append(time.perf_counter() - start)
    rows = int(out.rows_processed)
    elapsed = float(np.median(times))
    detections = int((cg >= 0).sum())
    delays = cg[cg >= 0] % drift_every

    if chained_proof:
        # The exactness contract, proven on this hardware: the 2-leg chain
        # found the same changes at the same stream positions, PER PARTITION
        # (chain rows are partition-local; one-shot rows carry the q·nb·b
        # partition offset, a multiple of drift_every by leg alignment).
        # Strictly stronger than the old global delay-multiset check: equal
        # per-partition position multisets imply equal delay multisets, and
        # a compensating cross-partition attribution mismatch cannot pass.
        # A mismatch raises — in --soak mode that is the error JSON +
        # exit 1; in the default bench the rider converts it to a
        # soak_error key, so the artifact can never carry a normal-looking
        # soak block over a broken >2^31 mechanism.
        matches = s.detections == detections
        for q in range(p):
            one = np.sort(
                cg[q][cg[q] >= 0].astype(np.int64) - q * nb * b
            )
            ch = (
                np.sort(np.concatenate(chain_pos[q]))
                if chain_pos[q]
                else np.empty(0, np.int64)
            )
            matches = matches and np.array_equal(one, ch)
        if not matches:
            raise RuntimeError(
                "chained-soak proof FAILED: 2-leg chain found "
                f"{int(s.detections)} detections vs one-shot {detections} "
                "(or per-partition position multisets differ) on identical "
                "streams"
            )
        extras["chained_matches"] = True

    return {
        "value": round(rows / elapsed, 1),
        "vs_baseline": round(rows / elapsed / BASELINE_ROWS_PER_SEC, 2),
        "time_s": round(elapsed, 4),
        "rep_times_s": [round(t, 4) for t in times],
        "reps": 3,
        "rows": rows,
        "partitions": p,
        "legs": 1,
        "detections": detections,
        "planted_boundaries": planted_interior_boundaries(
            p, nb * b, drift_every
        ),
        "median_delay_rows": float(np.median(delays)) if detections else None,
        **extras,
    }


# --------------------------------------------------------------------------
# Multi-tenant aggregate throughput (ISSUE 9 tentpole): T independent
# streams stacked into ONE compiled kernel vs T sequential solo runs.
# --------------------------------------------------------------------------


def _tenant_stats(
    tenant_counts=(8, 64), rows_per_class: int = 200, reps: int = 3
) -> dict:
    """The tenant-plane headline: for each T, run T independent streams
    (per-tenant seeds, same kernel geometry) BOTH ways — stacked through
    one ``[T·P, NB, B]`` kernel (``api.prepare_multi``) and as T
    sequential solo spans — and record aggregate rows/s for each, the
    speedup, and the bit-parity verdict (every tenant's stacked flags
    must equal its solo run's; a mismatch raises — the artifact can never
    carry a tenant headline over broken tenancy). Both paths are warmed
    before timing (compile excluded from every span); the win being
    measured is dispatch + collect amortization across the tenant axis,
    which is exactly what a per-user/per-sensor serving fleet pays T
    times over without the stacked plane."""
    import jax

    from distributed_drift_detection_tpu.api import prepare, prepare_multi
    from distributed_drift_detection_tpu.config import RunConfig
    from distributed_drift_detection_tpu.parallel import shard_batches
    from distributed_drift_detection_tpu.parallel.mesh import (
        host_flags,
        split_tenant_flags,
    )

    out = {}
    for tcount in tenant_counts:
        base = RunConfig(
            dataset=(
                "synth:rialto,seed={tenant},rows_per_class=%d" % rows_per_class
            ),
            partitions=8,
            per_batch=100,
            model="centroid",
            window=1,
            results_csv="",
            tenants=int(tcount),
        )
        prep = prepare_multi(base)
        if min(prep.nb_list) < 2:
            # NB=1 leaves no flag rows (batch 0 only seeds batch_a): the
            # parity assertion would compare zero-width tables and the
            # window engine cannot even run the geometry — refuse loudly
            # instead of emitting a vacuous headline.
            raise ValueError(
                f"rows_per_class={rows_per_class} gives only "
                f"{min(prep.nb_list)} microbatch(es) per partition at the "
                "bench geometry (8 partitions x 100 per_batch); use >= "
                "100 so every tenant has at least 2"
            )
        rows_total = sum(s.num_rows for s in prep.streams)

        def span_multi():
            db, dk = shard_batches(prep.batches, prep.keys, prep.mesh)
            o = (prep.exec_fn or prep.runner)(db, dk)
            jax.block_until_ready(o)
            return host_flags(o)[0]

        flags = span_multi()  # warm (compile + one-time device setup)
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            flags = span_multi()
            times.append(time.perf_counter() - t0)
        # min-of-reps on BOTH sides: interference (a noisy CI neighbor, a
        # scheduler stall) can only inflate a span, never deflate it, so
        # the fastest rep is the robust estimator for the amortization
        # claim — a stall would have to hit every rep of one side to skew
        # the agg-vs-seq comparison.
        multi_s = float(min(times))

        # Solo baselines from the RESOLVED per-tenant configs (the plane
        # pins auto knobs against tenant 0's geometry): the parity claim
        # is solo-run-of-the-resolved-config, same as the CI smoke —
        # unresolved configs would re-resolve auto knobs per stream and
        # compare different programs on ragged tenants.
        preps = [
            prepare(c, stream=s)
            for c, s in zip(prep.configs, prep.streams)
        ]

        def span_solo(pr):
            db, dk = shard_batches(pr.batches, pr.keys, pr.mesh)
            o = (pr.exec_fn or pr.runner)(db, dk)
            jax.block_until_ready(o)
            return host_flags(o)[0]

        solo_flags = [span_solo(pr) for pr in preps]  # warm + parity ref
        seq_times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            for pr in preps:
                span_solo(pr)
            seq_times.append(time.perf_counter() - t0)
        seq_s = float(min(seq_times))

        per = split_tenant_flags(
            flags, tcount, flag_cols=[nb - 1 for nb in prep.nb_list]
        )
        for t in range(tcount):
            for name in per[t]._fields:
                if not np.array_equal(
                    np.asarray(getattr(per[t], name)),
                    np.asarray(getattr(solo_flags[t], name)),
                ):
                    raise RuntimeError(
                        f"tenant-plane parity FAILED: tenant {t} leaf "
                        f"{name} differs between the stacked kernel and "
                        "the solo run at identical streams"
                    )
        detections = int(
            sum((np.asarray(f.change_global) >= 0).sum() for f in per)
        )
        sfx = f"_t{tcount}"
        out.update(
            {
                f"tenant_agg_rows_per_sec{sfx}": round(
                    rows_total / multi_s, 1
                ),
                f"tenant_seq_rows_per_sec{sfx}": round(
                    rows_total / seq_s, 1
                ),
                f"tenant_speedup{sfx}": round(seq_s / multi_s, 3),
                f"tenant_rows{sfx}": rows_total,
                f"tenant_multi_time_s{sfx}": round(multi_s, 4),
                f"tenant_seq_time_s{sfx}": round(seq_s, 4),
                f"tenant_detections{sfx}": detections,
                f"tenant_flags_match{sfx}": True,  # a mismatch raised above
            }
        )
    return out


def tenants_bench(counts, rows_per_class: int) -> None:
    """--tenants mode: print the tenant-plane stats as the one JSON line."""
    import jax

    _enable_compile_cache(jax)
    stats = _tenant_stats(tuple(counts), rows_per_class)
    _emit(
        {
            "metric": "tenant_agg_rows_per_sec",
            "unit": "rows/s",
            "tenant_counts": list(counts),
            **stats,
            "device": str(jax.devices()[0].platform),
        }
    )


# --------------------------------------------------------------------------
# Host-fed sustained benchmark (VERDICT r4 #6: the SURVEY §7 "host-feed
# bandwidth" hard part, measured on hardware instead of argued).
# --------------------------------------------------------------------------

# ~2.1 GB on-disk stream: 10 class-concepts × 1.15 M rows of 27 features —
# the rialto shape at ~25× its volume, in the sorted-by-target layout the
# benchmark pipeline uses (each class is one concept; boundary = drift).
CHUNKED_CLASSES = 10
CHUNKED_ROWS_PER_CLASS = 1_150_000
CHUNKED_DISTINCT = 10_000  # distinct rows per class, tiled to volume
CHUNKED_PATH = os.path.join(_BENCH_DIR, ".bench_data", "chunked_stream.csv")


def _ensure_chunked_file(path: str = CHUNKED_PATH) -> int:
    """Create (once, ~2.1 GB, seeded) the on-disk stream; returns its rows.

    Rows within a class tile a 10k-row distinct sample — byte-level block
    tiling writes multi-GB in seconds, and duplicated in-concept rows are
    exactly what the benchmark's ``mult_data`` duplication produces anyway.
    The file is a cache artifact (gitignored), deterministic in content.
    """
    total = CHUNKED_CLASSES * CHUNKED_ROWS_PER_CLASS
    if os.path.exists(path):
        return total
    os.makedirs(os.path.dirname(path), exist_ok=True)
    rng = np.random.default_rng(42)
    protos = rng.normal(size=(CHUNKED_CLASSES, 27)).astype(np.float32) * 1.6
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        fh.write(",".join(f"f{i}" for i in range(27)) + ",target\n")
        reps = CHUNKED_ROWS_PER_CLASS // CHUNKED_DISTINCT
        for c in range(CHUNKED_CLASSES):
            X = protos[c] + 0.4 * rng.normal(
                size=(CHUNKED_DISTINCT, 27)
            ).astype(np.float32)
            lines = [
                ",".join(f"{v:.4f}" for v in row) + f",{c}\n" for row in X
            ]
            block = "".join(lines)
            for _ in range(reps):
                fh.write(block)
    os.replace(tmp, path)
    return total


def _chunked_stats(workers: "int | None" = None) -> dict:
    """Drive the on-disk stream through the staged ingest pipeline →
    ChunkedDetector.

    Two measured passes over the same file, BOTH through the parallel
    pipeline (``--ingest-workers``; 0 = auto):
      * ``parse`` — drain ``io.feeder.csv_chunks`` alone (mmap'd
        line-aligned blocks → parse worker pool → ordered sanitize →
        pooled striper), no device: the host-feed bandwidth ceiling at
        this worker count.
      * ``overlapped`` — the shipped pipeline: the same feeder behind a
        ``prefetch_chunks`` producer + ``ChunkedDetector.feed`` with JAX
        async dispatch, so chunks parse/stripe while the device computes.
    ``overlap_efficiency = parse_time / overlapped_time`` → 1.0 means the
    device compute is fully hidden behind the feed (the SURVEY §7
    double-buffering claim, measured against the *pipeline's own* ceiling
    — the per-stage breakdown below shows where that ceiling comes from).

    ``pipeline_s`` is the per-stage busy breakdown of the overlapped pass
    from the ingest pipeline gauges: read/parse (worker pool — sums
    across workers, so it can exceed wall-clock), sanitize/stripe (the
    ordered consumer), upload (feed/place dispatch), and ``feed_wait``
    (consumer time blocked waiting on the host pipeline — the starvation
    signal: ~0 means the device, not ingest, bounds the path).

    Regime note (r05 captures, serial parser): over the shared remote-TPU
    *tunnel* the per-chunk h2d transfer (~22 MB) was the bottleneck —
    efficiency ~0.27, transport-bound; on a local device the path was
    parse-bound at 0.374 overlap efficiency, which is what the r10
    parallel pipeline attacks.
    """
    from distributed_drift_detection_tpu.engine.chunked import ChunkedDetector
    from distributed_drift_detection_tpu.io.feeder import (
        csv_chunks,
        prefetch_chunks,
        resolve_ingest_workers,
        stage_breakdown,
    )
    from distributed_drift_detection_tpu.models import ModelSpec, build_model
    from distributed_drift_detection_tpu.telemetry.metrics import (
        MetricsRegistry,
    )

    workers = resolve_ingest_workers(
        workers if workers is not None else _CLI["ingest_workers"]
    )
    total_rows = _ensure_chunked_file()
    p, b, cb, window = 16, 100, 128, 128  # 204.8k-row chunks, W=128 spans

    def feeder(metrics=None):
        return csv_chunks(CHUNKED_PATH, p, b, cb, workers=workers,
                          metrics=metrics)

    # Warm the page cache first so BOTH passes read the file warm — a
    # freshly written file would otherwise give pass 1 a cold-cache read
    # and bias overlap_efficiency upward.
    with open(CHUNKED_PATH, "rb") as fh:
        while fh.read(64 << 20):
            pass

    # Pass 1: host-feed ceiling (no device work at all).
    start = time.perf_counter()
    parsed_rows = 0
    for chunk in feeder():
        parsed_rows += int(chunk.valid.sum())
    parse_s = time.perf_counter() - start

    # Pass 2: the shipped overlapped pipeline. Compile warm-up happens on
    # SYNTHETIC chunks (both shape paths: the carry-seeding first feed
    # loses a batch, steady chunks are full), after which the detector
    # state is reset — so the timed span covers the *entire* real pipeline
    # from cold (including the prefetch producer's spin-up: starting the
    # timer mid-stream would let up to `depth` pre-parsed chunks ride in
    # free, biasing the rate up) with zero compile cost inside it.
    parse_rate = parsed_rows / parse_s
    model = build_model("centroid", ModelSpec(27, CHUNKED_CLASSES))
    det = ChunkedDetector(
        model, partitions=p, seed=0, window=window, rotations=1
    )
    from distributed_drift_detection_tpu.io.stream import stripe_chunk

    rows_chunk = p * b * cb
    for i in range(2):
        warm = stripe_chunk(
            np.zeros((rows_chunk, 27), np.float32),
            np.zeros(rows_chunk, np.int32),
            i * rows_chunk, p, b, cb,
        )
        np.asarray(det.feed(warm).change_global)
    det.carry = None  # discard warm-up state; executables stay cached
    det.batches_done = 0

    reg = MetricsRegistry()
    flags_async = []
    rows_done = 0
    wait_s = feed_s = 0.0
    it = iter(prefetch_chunks(feeder(metrics=reg), depth=2, metrics=reg))
    start = time.perf_counter()
    while True:
        t0 = time.perf_counter()
        chunk = next(it, None)
        wait_s += time.perf_counter() - t0  # host pipeline starving the feed
        if chunk is None:
            break
        t0 = time.perf_counter()
        flags_async.append(det.feed(chunk))
        feed_s += time.perf_counter() - t0
        rows_done += int(chunk.valid.sum())  # numpy, no device sync
    np.asarray(flags_async[-1].change_global)  # final device sync
    overlapped_s = time.perf_counter() - start
    overlapped_rate = rows_done / overlapped_s
    detections = sum(
        int((np.asarray(f.change_global) >= 0).sum()) for f in flags_async
    )
    pipeline_s = stage_breakdown(reg)
    pipeline_s["upload"] = round(feed_s, 4)
    pipeline_s["feed_wait"] = round(wait_s, 4)

    return {
        "value": round(overlapped_rate, 1),
        "vs_baseline": round(overlapped_rate / BASELINE_ROWS_PER_SEC, 2),
        "rows": total_rows,
        "measured_rows": rows_done,
        "parsed_rows": parsed_rows,
        "file_bytes": os.path.getsize(CHUNKED_PATH),
        "time_s": round(overlapped_s, 4),
        "parse_only_s": round(parse_s, 4),
        "parse_rows_per_sec": round(parse_rate, 1),
        # Fraction of the parse-only feed rate sustained with device
        # compute attached: → 1.0 means compute fully hidden behind the
        # feed (the SURVEY §7 double-buffering claim, measured).
        "overlap_efficiency": round(overlapped_rate / parse_rate, 3),
        "ingest_workers": workers,
        "pipeline_s": pipeline_s,
        "partitions": p,
        "chunk_batches": cb,
        "window": window,
        "detections": detections,
        "planted_boundaries": CHUNKED_CLASSES - 1,
    }


def chunked() -> None:
    """--chunked mode: print the host-fed sustained stats as the JSON line."""
    import jax

    _enable_compile_cache(jax)
    stats = _chunked_stats()
    _emit(
        {
            "metric": "chunked_rows_per_sec_chip",
            "unit": "rows/s",
            **stats,
            "device": str(jax.devices()[0].platform),
        }
    )


def soak(total_rows: int) -> None:
    """--soak mode: print the soak stats as the one JSON line."""
    import jax

    _enable_compile_cache(jax)
    stats = _soak_stats(total_rows)
    _emit(
        {
            "metric": "soak_rows_per_sec_chip",
            "unit": "rows/s",
            **stats,
            "device": str(jax.devices()[0].platform),
        }
    )


def _headline_core(prep, reps: int = 15, stall_factor: float = 1.5) -> dict:
    """Warm-ups + stall-aware timed repetitions of one prepared run: every
    headline artifact field except the mode envelope (metric/unit/device)
    and the soak/chunked riders — shared by :func:`main` (15 reps, the TPU
    headline) and :func:`smoke` (3 reps, the CI artifact-contract check).
    See the module docstring for the measurement methodology the fields
    encode (warm-up split, stall classification, phase histograms, XLA
    cost/memory)."""
    import jax

    from distributed_drift_detection_tpu.metrics import delay_metrics
    from distributed_drift_detection_tpu.parallel import shard_batches
    from distributed_drift_detection_tpu.parallel.mesh import host_flags
    from distributed_drift_detection_tpu.telemetry.metrics import (
        MetricsRegistry,
    )
    from distributed_drift_detection_tpu.utils.timing import PhaseTimer

    stream, batches, runner, keys, mesh = (
        prep.stream, prep.batches, prep.runner, prep.keys, prep.mesh
    )
    # The detect phase executes what api.run executes: the AOT-compiled
    # executable when prepare's warm-start succeeded (compile paid there,
    # outside every timed region below), else the jitted runner.
    exec_fn = prep.exec_fn or runner
    cfg = prep.config

    # Warm-ups: compile once on the real shapes, then once more to flush any
    # remaining one-time device/tunnel setup out of the timed region — the
    # flag fetch included: the first device→host transfer of the packed
    # table pays multi-second one-time setup over the remote-TPU link, and
    # without fetching here it lands in timed repetition 1's collect phase
    # (both r03 captures recorded a 3.5–6.4 s first-rep collect outlier).
    # Each warm-up is timed individually: warm-up 1 is the first-call span
    # (jit trace + XLA compile — or persistent-cache load — + one-time
    # device setup), warm-up 2 the first compile-free call, and together
    # with the steady-state median below they make the compile split an
    # explicit artifact field (compile_s) instead of a vanished cost —
    # BENCH_*.json trajectories can then separate recompilation regressions
    # from kernel regressions.
    warmup_times = []
    for _ in range(2):
        t0 = time.perf_counter()
        db, dk = shard_batches(batches, keys, mesh)
        np.asarray(exec_fn(db, dk).packed)
        warmup_times.append(time.perf_counter() - t0)

    # Timed runs — each spans the reference's Final Time
    # (upload + detect + collect + delay metric). Contention-robust headline
    # (VERDICT r4 #3 — the shared tunnel's stalls moved recorded headlines
    # 2× across rounds): a repetition whose span exceeds 1.5× the
    # invocation's fastest is classified a *stall* (the fastest rep is by
    # construction stall-free; real regressions move the fastest rep too,
    # so they cannot be misclassified away), and the headline is the median
    # of the non-stalled repetitions. The full per-repetition and per-phase
    # record still rides in the JSON — including ``detect_time_s`` (the
    # device-execution span, closed by a 1-element d2h fetch because
    # ``block_until_ready`` alone is unreliable over this tunnel) so stalls
    # are separable from compute in the artifact itself.
    times = []
    phases = {"upload": [], "detect": [], "collect": []}
    collect_info = {"mode": "full"}
    for _ in range(reps):
        timer = PhaseTimer()
        start = time.perf_counter()
        with timer.phase("upload"):
            db, dk = shard_batches(batches, keys, mesh)
        with timer.phase("detect"):
            out = exec_fn(db, dk)
            jax.block_until_ready(out)
            np.asarray(out.packed[:1, :1])  # force a real device sync
        with timer.phase("collect"):
            # The shipped collect transport: the device-compacted detection
            # table (O(detections) bytes, one transfer) under the default
            # RunConfig.collect='compact', the packed plane under 'full' —
            # exactly what api.run's collect phase does (parallel.mesh.
            # host_flags, loud full-plane fallback on table overflow).
            flags, collect_info = host_flags(out)
            m = delay_metrics(
                flags.change_global, stream.dist_between_changes, cfg.per_batch
            )
        times.append(time.perf_counter() - start)
        for k, v in timer.as_dict().items():
            phases[k].append(round(v, 4))
    floor_t = min(times)
    stalled = [i for i, t in enumerate(times) if t > stall_factor * floor_t]
    clean = [t for i, t in enumerate(times) if i not in stalled]
    elapsed = float(np.median(clean))
    if stalled:
        # Top-level warning (satellite, ISSUE 9): r05 recorded 11/15 reps
        # stalled — a headline whose provenance deserves a loud line on
        # stderr, not just a buried stalled_reps field. The headline
        # median (and every derived cell: value, detect_time_s,
        # collect_share) already EXCLUDES the stalled repetitions; the
        # raw per-rep lists keep them for the artifact's evidence trail.
        print(
            f"bench: WARNING: {len(stalled)}/{reps} timed repetitions "
            f"stalled (>{stall_factor}x the fastest); headline is the "
            f"median of the {len(clean)} clean reps"
            + (" — CONTENDED, treat with suspicion"
               if len(stalled) >= (reps + 1) // 2 else ""),
            file=sys.stderr,
        )
    detect_clean = [
        t for i, t in enumerate(phases["detect"]) if i not in stalled
    ]
    # Collect's share of each repetition's Final Time span (non-stalled
    # median): the tentpole's first win made visible — and gateable
    # (telemetry.perf) — as one number per artifact.
    collect_share = float(
        np.median(
            [
                c / t
                for i, (c, t) in enumerate(zip(phases["collect"], times))
                if i not in stalled and t > 0
            ]
        )
    )

    # Warm-start evidence pair: cold_s is prepare's AOT lower().compile()
    # span (the only place XLA compilation happens now — against a
    # populated persistent cache it collapses to trace + deserialize, the
    # CI-asserted "compile_s ≈ 0" contract); warm_s re-lowers the same
    # program here, after the cache is guaranteed hot, as the same-process
    # floor to compare cold_s against.
    info = prep.compile_info or {}
    t0 = time.perf_counter()
    try:
        runner.lower(db, dk).compile()
        warm_s = time.perf_counter() - t0
    except Exception:
        warm_s = None

    rows_per_sec = stream.num_rows / elapsed
    delay_batches = m.mean_delay_batches

    # Per-phase histograms over the timed repetitions (telemetry metrics
    # registry, Prometheus bucket semantics): the artifact carries the
    # distribution shape, not just the per-rep lists — a bimodal upload
    # histogram is a stalling tunnel even when the median looks clean.
    reg = MetricsRegistry()
    phase_h = reg.histogram(
        "phase_seconds", help="Wall-clock seconds by phase over timed reps"
    )
    for name, vs in phases.items():
        for v in vs:
            phase_h.observe(v, phase=name)

    # Compiler cost/memory of the headline runner (outside the timed reps;
    # the compile is cache-served — the runner just executed): BENCH_*.json
    # trajectories can then separate "the kernel got more expensive"
    # (flops/temp bytes moved) from "the host/tunnel got slower"
    # (unchanged cost model, slower phases).
    xla = _xla_fields(runner, db, dk)

    return {
        "value": round(rows_per_sec, 1),
        "unit": "rows/s",
        "vs_baseline": round(rows_per_sec / BASELINE_ROWS_PER_SEC, 2),
        "final_time_s": round(elapsed, 4),
        "final_time_min_s": round(floor_t, 4),
        # Device-execution time (true-synced detect phase) of the
        # non-stalled reps: the compute-only view the wall-clock headline
        # is judged against.
        "detect_time_s": round(float(np.median(detect_clean)), 4),
        "reps": reps,
        "stalled_reps": stalled,  # indices excluded from the median
        "contended": len(stalled) >= (reps + 1) // 2,
        "rep_times_s": [round(t, 4) for t in times],
        # Compile split (first-rep vs steady-state): warm-up 1 is the only
        # span containing jit trace + XLA compile; steady_median_s repeats
        # final_time_s for side-by-side reading. compile_overhead_s ≈ the
        # compile + one-time-setup cost a cold process pays once.
        "compile_s": {
            "first_call_s": round(warmup_times[0], 4),
            "second_call_s": round(warmup_times[1], 4),
            "steady_median_s": round(elapsed, 4),
            "compile_overhead_s": round(warmup_times[0] - elapsed, 4),
        },
        # The warm-start pair (see above): cold_s is prepare's whole AOT
        # span, cold_xla_s the backend-compile half inside it — the half
        # the persistent cache serves, which collapses to ~0 on a re-run
        # against a populated cache (the CI gate's evidence that restarted
        # processes skip compilation; trace+lower is paid regardless).
        "cold_vs_warm_compile_s": {
            "cold_s": round(float(info.get("aot_seconds", 0.0)), 4),
            "cold_xla_s": round(float(info.get("aot_compile_seconds", 0.0)), 4),
            "aot_cached": bool(info.get("aot_cached", False)),
            "warm_s": None if warm_s is None else round(warm_s, 4),
        },
        # Collect transport provenance: the mode the reps actually ran
        # (compact table vs full plane), the flagged-slot count, and the
        # share of the span collect consumed (gated by the perf CLI).
        "collect": collect_info.get("mode"),
        "collect_events": collect_info.get("events"),
        "collect_overflow": bool(collect_info.get("overflow", False)),
        "collect_share": round(collect_share, 4),
        "phase_s": phases,
        # Stall-filtered per-phase medians (satellite, ISSUE 9): phase_s
        # keeps every repetition for the evidence trail, but a median over
        # a contended invocation (r05: 11/15 stalled) describes the
        # tunnel, not the code — these cells are what the perf CLI reads.
        "phase_median_s": {
            name: round(
                float(
                    np.median(
                        [v for i, v in enumerate(vs) if i not in stalled]
                        or vs
                    )
                ),
                4,
            )
            for name, vs in phases.items()
        },
        "phase_hist": reg.to_json(),
        "xla": xla,
        "rows": stream.num_rows,
        "partitions": cfg.partitions,
        # From the resolved config: window=0 (auto) is resolved to a
        # concrete width inside prepare() — report that, not argv.
        "window": cfg.window,
        "window_rotations": cfg.window_rotations,
        "mean_delay_batches": (
            round(delay_batches, 3) if np.isfinite(delay_batches) else None
        ),
        "detections": m.num_detections,
    }


def _serve_stats(
    rows: int = 20_000, rate: float = 0.0, tenants: int = 1
) -> dict:
    """``--serve``: the online-serving SLO bench — an in-process daemon on
    a loopback socket, driven by the loadgen at ``rate`` rows/s (0 = as
    fast as the socket takes them).

    The daemon is **warm** before the measured replay: AOT prepare paid at
    start (persistent compile cache shared with the other bench modes),
    plus one warm-up replay through the full ingress→admission→detect→
    verdict path — so the reported p50/p99 row→verdict latency and
    sustained rows/s describe steady-state serving, not cold-start. The
    measured replay ends with a drain (STOP), and the daemon's registry
    record must read ``completed`` for the numbers to be trusted.
    """
    import threading

    from distributed_drift_detection_tpu.config import RunConfig, ServeParams
    from distributed_drift_detection_tpu.io.synth import rialto_like_xy
    from distributed_drift_detection_tpu.serve import ServeRunner
    from distributed_drift_detection_tpu.serve.loadgen import (
        format_lines,
        run_loadgen,
    )

    cfg = RunConfig(
        partitions=8,
        per_batch=100,
        model="centroid",
        window=1,
        data_policy="quarantine",
        results_csv="",
        # tenants > 1 exercises the multi-tenant admission path end to
        # end: stacked [T·P, CB, B] chunk program, TENANT wire routing,
        # per-tenant verdict attribution (loadgen deals round-robin).
        tenants=max(int(tenants), 1),
        compile_cache_dir=_CLI["compile_cache_dir"]
        or os.path.join(_BENCH_DIR, ".jax_cache"),
    )
    X, y = rialto_like_xy(seed=0, rows_per_class=max(rows // 10, 100))
    params = ServeParams(
        num_features=int(X.shape[1]),
        num_classes=10,
        port=0,
        chunk_batches=4,
        linger_s=0.1,
        # No SLO evaluator for a bench probe: nothing to alert, and its
        # reader thread must not race the histogram reset below.
        slo=("none",),
    )
    runner = ServeRunner(cfg, params)
    banner = runner.start()
    thread = threading.Thread(target=runner.serve_forever, daemon=True)
    thread.start()
    from distributed_drift_detection_tpu.telemetry.trace import (
        hist_quantile,
        latency_histogram,
    )

    lines = format_lines(X[:rows], y[:rows])
    # Warm-up replay: one full pipeline's worth of chunks through the wire
    # path, so the measured replay sees a steady-state daemon.
    warm_n = min(len(lines) // 2, 2 * params.chunk_batches * cfg.partitions * cfg.per_batch)
    run_loadgen(
        banner["host"],
        banner["port"],
        lines[:warm_n],
        verdicts=banner["verdicts"],
        timeout=300,
        tenants=cfg.tenants,
    )
    # Reset the row-latency histogram between warm-up and measurement:
    # the warm-up runs unpaced with backpressure, and its congested
    # samples would otherwise ride the lifetime percentiles while the
    # sidecar pair below covers only the measured replay. The pipeline
    # is idle here (warm-up verdicts fully covered), no ops server is
    # attached, and slo=("none",) above means no evaluator thread reads
    # the histogram — the clear races nothing.
    hist = latency_histogram(runner.metrics)
    hist.values.clear()
    rep = run_loadgen(
        banner["host"],
        banner["port"],
        lines,
        rate=rate,
        verdicts=banner["verdicts"],
        timeout=600,
        stop=True,
        tenants=cfg.tenants,
    )
    thread.join(timeout=120)
    # Live-registry percentiles (telemetry.trace): the daemon's own
    # serve_row_latency_seconds{stage="total"} histogram over the
    # measured replay only (cleared post-warm-up above) — the same
    # numbers the /metrics scrape and /statusz expose, recorded next to
    # the loadgen's sidecar-derived pair so the artifact pins their
    # agreement round over round.
    reg_p50 = hist_quantile(hist, 0.5, stage="total")
    reg_p99 = hist_quantile(hist, 0.99, stage="total")
    # Serve-pipeline observatory rider (telemetry.pipeline): the drained
    # daemon's per-stage busy split — the chunked rider's `pipeline_s`
    # twin — plus busy/wall coverage, the perf CLI's gated honesty cell
    # (instrumentation losing track of where the loop's time goes reads
    # as a regression, exactly like a throughput drop would).
    pipe = runner.pipeline_snapshot() or {}
    return {
        "serve_pipeline_s": pipe.get("busy_s") or None,
        "serve_busy_utilization": pipe.get("coverage"),
        "serve_dominant_stage": pipe.get("dominant_stage"),
        "serve_rows": rep["rows_sent"],
        "serve_tenants": cfg.tenants,
        "serve_rows_per_sec": rep["achieved_rows_per_sec"],
        "serve_target_rows_per_sec": rate or None,
        "serve_p50_ms": rep["p50_ms"],
        "serve_p99_ms": rep["p99_ms"],
        "serve_mean_ms": rep["mean_ms"],
        "serve_registry_p50_ms": (
            None if reg_p50 is None else round(reg_p50 * 1000.0, 2)
        ),
        "serve_registry_p99_ms": (
            None if reg_p99 is None else round(reg_p99 * 1000.0, 2)
        ),
        "serve_detections": rep["detections"],
        "serve_verdicts": rep["verdicts"],
        "serve_timeout": rep["timeout"],
        "serve_drained": not thread.is_alive(),
    }


def _adapt_stats(rows: int = 4800) -> dict:
    """``--serve`` rider: the adaptation-recovery bench. An in-process
    daemon with ``on_drift=retrain`` consumes a planted recurring-drift
    stream (``io.synth.recurring_drift_xy`` — per-concept class
    prototypes, so the stale model measurably fails on each boundary)
    and the adapt plane's own recovery watch measures
    ``serve_adapt_recovery_rows``: rows from the drift verdict until
    post-drift chunk error returns within the policy's epsilon of the
    pre-drift running level. Informational in the perf CLI — recovery
    spans move with the stream geometry; correctness is owned by
    tests/test_adapt.py and the adapt-smoke CI job."""
    from distributed_drift_detection_tpu.config import RunConfig, ServeParams
    from distributed_drift_detection_tpu.io.synth import recurring_drift_xy
    from distributed_drift_detection_tpu.serve import ServeRunner
    from distributed_drift_detection_tpu.serve.loadgen import format_lines

    concepts = max(rows // 1200, 2)
    X, y = recurring_drift_xy(
        seed=1, concepts=concepts, rows_per_concept=rows // concepts
    )
    cfg = RunConfig(
        partitions=4,
        per_batch=50,
        model="centroid",
        window=1,
        data_policy="quarantine",
        results_csv="",
        compile_cache_dir=_CLI["compile_cache_dir"]
        or os.path.join(_BENCH_DIR, ".jax_cache"),
    )
    params = ServeParams(
        num_features=int(X.shape[1]),
        num_classes=int(y.max()) + 1,
        port=None,  # in-process embedding: admission driven directly
        chunk_batches=2,
        linger_s=0.05,
        slo=("none",),
        on_drift=("retrain",),
    )
    runner = ServeRunner(cfg, params)
    runner.start()
    lines = format_lines(X, y)
    for i in range(0, len(lines), 200):
        runner.admission.admit_lines(lines[i : i + 200])
    runner.batcher.flush()
    runner.request_stop()
    drained = runner.serve_forever() == 0
    adapt = runner._adapt
    snap = adapt.snapshot() if adapt is not None else {}
    return {
        "serve_adapt_rows": len(lines),
        "serve_adaptations": snap.get("adaptations", 0),
        "serve_adapt_recovery_rows": (
            adapt.recovery_rows() if adapt is not None else None
        ),
        "serve_adapt_drained": drained,
    }


def _ingest_stats(
    rows: int = 4_000_000, features: int = 27, frame_rows: int = 16384
) -> dict:
    """Warmed admission-only ingest bench: one small replay first (numpy
    dispatch, thread/socket setup, allocator state all go hot), then the
    measured replay — the reported cell describes steady-state ingress,
    not process cold-start. See :func:`_ingest_once`."""
    _ingest_once(rows=max(rows // 16, frame_rows * 8), features=features,
                 frame_rows=frame_rows)
    return _ingest_once(rows=rows, features=features, frame_rows=frame_rows)


def _ingest_once(
    rows: int = 4_000_000, features: int = 27, frame_rows: int = 16384
) -> dict:
    """``--serve`` rider: the **admission-only** ingest bench (ISSUE 13
    acceptance: ≥10M rows/s on loopback). v2 binary frames stream over a
    real loopback socket through the event-loop ingress, the vectorized
    frame admission and the pooled-striper microbatch seals — everything
    the serve path does to a row *except* the device feed — and the cell
    is rows admitted-and-sealed per wall-clock second. jax-free by
    construction (the admission plane is numpy + stdlib), so the cell
    isolates the host ingress from kernel/tunnel noise.

    The payload is one clean pre-encoded frame replayed N times (the
    admission fast path cannot tell — every frame is decoded, bounds-
    checked, finiteness/domain-scanned and striped individually), so the
    client side is a pure ``sendall`` loop and the measured ceiling is
    the daemon's, not the generator's.
    """
    import socket
    import threading

    from distributed_drift_detection_tpu.serve import wire
    from distributed_drift_detection_tpu.serve.admission import (
        AdmissionController,
        MicroBatcher,
    )
    from distributed_drift_detection_tpu.serve.ingress import IngressServer

    frames = max(rows // frame_rows, 1)
    rows = frames * frame_rows
    # Grid span == frame_rows: every frame seals exactly one chunk, the
    # steady-state shape of a saturated v2 ingress.
    partitions, per_batch = 8, 128
    chunk_batches = max(frame_rows // (partitions * per_batch), 1)
    batcher = MicroBatcher(
        partitions, per_batch, chunk_batches,
        shuffle_seed=None, linger_s=60.0, max_queue=64,
    )
    adm = AdmissionController(
        batcher, features, 10, policy="quarantine"
    )
    srv = IngressServer("127.0.0.1", 0, [adm], batcher, on_stop=lambda: None)
    srv.start()
    rng = np.random.default_rng(0)
    X = rng.standard_normal((frame_rows, features), dtype=np.float32)
    y = (rng.integers(0, 10, frame_rows)).astype(np.int32)
    frame = wire.encode_frame(X, y)
    slab = frame * max(1, min(frames, (1 << 22) // len(frame)))

    drained = {"rows": 0}

    def _consume() -> None:
        while drained["rows"] < rows:
            item = batcher.get(5.0)
            if item is None:
                return  # stalled producer — the timeout marker will show
            drained["rows"] += item.meta["rows"]

    consumer = threading.Thread(target=_consume, daemon=True)
    consumer.start()
    # srv.stop() must run even when the replay dies mid-stream (poisoned
    # batcher, connection reset): serve_bench deliberately survives an
    # ingest failure and goes on to measure the SLO cells in THIS
    # process — leaked ingress/admitter threads would pollute them.
    try:
        sock = socket.create_connection(("127.0.0.1", srv.port), timeout=10)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        t0 = time.perf_counter()
        sent = 0
        try:
            while sent + len(slab) <= frames * len(frame):
                sock.sendall(slab)
                sent += len(slab)
            remainder = frames * len(frame) - sent
            if remainder:
                sock.sendall(frame * (remainder // len(frame)))
        finally:
            sock.close()
        consumer.join(timeout=300)
        span = time.perf_counter() - t0
    finally:
        srv.stop()
    complete = drained["rows"] >= rows
    payload_mb = frames * len(frame) / 1e6
    return {
        "serve_ingest_rows": rows,
        "serve_ingest_frames": frames,
        "serve_ingest_frame_rows": frame_rows,
        "serve_ingest_features": features,
        "serve_ingest_rows_per_sec": (
            round(rows / span, 1) if complete and span > 0 else None
        ),
        "serve_ingest_mb_per_sec": (
            round(payload_mb / span, 1) if complete and span > 0 else None
        ),
        "serve_ingest_seconds": round(span, 4),
        "serve_ingest_complete": complete,
    }


def serve_bench(rows: int, rate: float, tenants: int = 1) -> None:
    import jax

    _enable_compile_cache(jax)
    # The admission-only rider must not take down the SLO bench (or vice
    # versa): each failure is recorded in its own field.
    try:
        ingest = _ingest_stats()
    except Exception as e:
        import traceback

        traceback.print_exc(file=sys.stderr)
        ingest = {"serve_ingest_error": f"{type(e).__name__}: {e}"[:300]}
    _emit(
        {
            "metric": "serve_row_to_verdict",
            "unit": "ms",
            **_serve_stats(rows, rate, tenants),
            **_adapt_stats(),
            **ingest,
            "device": str(jax.devices()[0].platform),
        }
    )


def _fleet_stats(
    tenants: int = 8, daemons: int = 2, rows: int = 400_000
) -> dict:
    """``--fleet``: the fleet-scale serving bench (ISSUE 14) — N REAL
    serve daemons (subprocesses, own GIL + compiled plane each) behind
    an in-process :class:`~serve.router.TenantRouter`, driven through
    the router endpoint with v2 frames dealt over ``tenants`` global
    tenants.

    Measures the same replay at 1 daemon and at ``daemons`` daemons:
    ``fleet_agg_rows_per_sec`` is the aggregate serving rate (replay
    start → full fleet verdict coverage) of the ``daemons``-sized fleet,
    with the 1-daemon baseline and the scaling ratio alongside — the
    acceptance claim is aggregate rows/s scaling with daemon count, not
    plateauing at one process. Placement is :func:`serve.plan_fleet`'s
    consistent-hash deal (one vacant spare per daemon, the live-migration
    posture), so the bench exercises the real fleet topology end to end:
    router header rewrites, per-backend wire, fleet verdict tailing.
    """
    import shutil
    import subprocess
    import tempfile
    import time as _time

    from distributed_drift_detection_tpu.io.synth import rialto_like_xy
    from distributed_drift_detection_tpu.serve import (
        BackendSpec,
        TenantRouter,
    )
    from distributed_drift_detection_tpu.serve.loadgen import run_loadgen

    X, y = rialto_like_xy(seed=0, rows_per_class=-(-rows // 10))
    X = np.ascontiguousarray(X[:rows], np.float32)
    y = np.ascontiguousarray(y[:rows], np.int32)
    features = int(X.shape[1])
    cache = _CLI["compile_cache_dir"] or os.path.join(
        _BENCH_DIR, ".jax_cache"
    )

    def run_fleet(d: int) -> dict:
        names = [f"b{i}" for i in range(d)]
        # Balanced round-robin deal (not plan_fleet's consistent hash):
        # the bench's claim is aggregate capacity scaling with daemon
        # count, and a hash-skewed split (5/3 at T=8, D=2) caps the
        # measurable speedup at T/max_share regardless of capacity —
        # placement skew is the rebalancer's job, measured elsewhere.
        # One vacant spare per daemon keeps the fleet posture real.
        placement = {
            n: [g for g in range(tenants) if g % d == i] + [-1]
            for i, n in enumerate(names)
        }
        workdir = tempfile.mkdtemp(prefix="fleet_bench_")
        procs: list = []
        dirs: list[str] = []
        router = None
        try:
            for name in names:
                ids = placement[name]
                tele = os.path.join(workdir, f"tele_{name}")
                cmd = [
                    sys.executable, "-m", "distributed_drift_detection_tpu",
                    "serve",
                    "--features", str(features), "--classes", "10",
                    "--partitions", "4", "--per-batch", "100",
                    "--chunk-batches", "4", "--port", "0", "--ops-port",
                    "0", "--seed", "0", "--linger-s", "0.05",
                    "--tenants", str(len(ids)),
                    "--tenant-ids", ",".join(map(str, ids)),
                    "--name", name,
                    "--telemetry-dir", tele,
                    "--compile-cache-dir", cache,
                ]
                fh = open(os.path.join(workdir, f"{name}.banner"), "w+")
                procs.append(
                    (
                        subprocess.Popen(
                            cmd, stdout=fh, stderr=subprocess.DEVNULL
                        ),
                        fh,
                    )
                )
                dirs.append(tele)
            specs = []
            for proc, fh in procs:
                deadline = _time.monotonic() + 300
                banner = None
                while _time.monotonic() < deadline:
                    if proc.poll() is not None:
                        raise RuntimeError(
                            f"fleet daemon exited rc={proc.returncode} "
                            "before its banner"
                        )
                    fh.seek(0)
                    line = fh.readline().strip()
                    if line:
                        banner = json.loads(line)
                        break
                    _time.sleep(0.2)
                if banner is None:
                    raise RuntimeError("fleet daemon banner timed out")
                specs.append(
                    BackendSpec(
                        f"127.0.0.1:{banner['port']}:{banner['ops_port']}"
                    )
                )
            router = TenantRouter(specs, telemetry_dir=workdir)
            b = router.start()
            warm = min(len(y) // 4, 40_000)
            run_loadgen(
                b["host"], b["port"], None, rate=0.0, timeout=600,
                tenants=tenants, wire_version="v2",
                arrays=(X[:warm], y[:warm]), frame_rows=1024,
                fleet_dirs=dirs,
            )
            # per-daemon counters are cumulative since router start —
            # snapshot after the warm-up so the breakdown covers exactly
            # the timed span (else warm rows inflate it ~rows/warm)
            warm_fwd = {
                be["name"]: be["rows_forwarded"]
                for be in router.status()["backends"]
            }
            t0 = _time.monotonic()
            rep = run_loadgen(
                b["host"], b["port"], None, rate=0.0, timeout=600,
                stop=True, tenants=tenants, wire_version="v2",
                arrays=(X, y), frame_rows=1024, fleet_dirs=dirs,
            )
            span = _time.monotonic() - t0
            status = router.status()
            drained = True
            for proc, fh in procs:
                try:
                    drained = (proc.wait(timeout=120) == 0) and drained
                except subprocess.TimeoutExpired:
                    proc.kill()
                    drained = False
            return {
                "agg_rows_per_sec": (
                    round(len(y) / span, 1) if span > 0 else None
                ),
                "per_daemon_rows_per_sec": {
                    be["name"]: round(
                        (be["rows_forwarded"] - warm_fwd.get(be["name"], 0))
                        / span,
                        1,
                    )
                    for be in status["backends"]
                },
                "rows_lost": status["rows_lost"],
                "timeout": bool(rep["timeout"]),
                "covered": rep["rows_covered"],
                "drained": drained,
            }
        finally:
            for proc, fh in procs:
                if proc.poll() is None:
                    proc.kill()
                fh.close()
            if router is not None:
                router.stop()
            shutil.rmtree(workdir, ignore_errors=True)

    solo = run_fleet(1)
    fleet = run_fleet(daemons)
    agg1 = solo["agg_rows_per_sec"]
    aggd = fleet["agg_rows_per_sec"]
    return {
        "fleet_tenants": tenants,
        "fleet_daemons": daemons,
        "fleet_rows": len(y),
        "fleet_agg_rows_per_sec": aggd,
        "fleet_agg_rows_per_sec_d1": agg1,
        "fleet_speedup": (
            round(aggd / agg1, 2) if agg1 and aggd else None
        ),
        "fleet_per_daemon_rows_per_sec": fleet["per_daemon_rows_per_sec"],
        "fleet_rows_lost": fleet["rows_lost"] + solo["rows_lost"],
        "fleet_timeout": fleet["timeout"] or solo["timeout"],
        "fleet_drained": fleet["drained"] and solo["drained"],
    }


def fleet_bench(tenants: int, daemons: int, rows: int) -> None:
    """--fleet mode: print the fleet-scaling stats as the one JSON line
    (jax-free in THIS process — the daemons are subprocesses)."""
    _emit(
        {
            "metric": "fleet_agg_rows_per_sec",
            "unit": "rows/s",
            **_fleet_stats(tenants, daemons, rows),
        }
    )


def _sched_stats(workers: int = 3, trials: int = 2) -> dict:
    """``--sched``: the elastic-sweep-scheduler bench (ISSUE 15) — the
    SAME grid swept twice: serially through the ``harness.grid`` CLI
    (the paper's ``run_experiments.sh`` shape) and through the
    ``sched/`` scheduler driving ``workers`` REAL worker subprocesses
    (own GIL + jax runtime each), clean fleet (no injected faults — the
    sched-smoke CI job owns the kill-a-worker proof; this bench refuses
    to report a run whose registry audit is not exactly-once).

    ``sched_cells_per_sec`` is the gated cell: cells completed per
    wall-clock second of the scheduled sweep, subprocess launch to exit
    — the fleet controller's whole claim is finishing a grid faster
    than walking it. The serial rate and the speedup ratio print
    informationally (both move with host load). Each mode gets its own
    cold compile cache (no warm-start bias either way)."""
    import shutil
    import subprocess
    import tempfile
    import time as _time

    mults, parts, per_batch = [1.0, 2.0, 4.0], [1, 2], 50
    cells = len(mults) * len(parts) * trials
    workdir = tempfile.mkdtemp(prefix="sched_bench_")
    try:
        spec_path = os.path.join(workdir, "spec.json")
        sched_csv = os.path.join(workdir, "sched.csv")
        with open(spec_path, "w") as fh:
            json.dump(
                {
                    "dataset": "synth:rialto,seed=0",
                    "mults": mults,
                    "partitions": parts,
                    "trials": trials,
                    "per_batch": per_batch,
                    "results_csv": sched_csv,
                    "spec": "off",
                },
                fh,
            )

        def timed(cmd) -> "tuple[float, subprocess.CompletedProcess]":
            t0 = _time.monotonic()
            proc = subprocess.run(
                cmd, cwd=_BENCH_DIR, capture_output=True, text=True,
                timeout=1800,
            )
            span = _time.monotonic() - t0
            if proc.returncode != 0:
                raise RuntimeError(
                    f"sched bench command failed rc={proc.returncode}: "
                    f"{proc.stderr[-1000:]}"
                )
            return span, proc

        serial_span, _ = timed(
            [
                sys.executable, "-m",
                "distributed_drift_detection_tpu.harness.grid",
                "--dataset", "synth:rialto,seed=0",
                "--mults", ",".join(str(m) for m in mults),
                "--partitions", ",".join(str(p) for p in parts),
                "--trials", str(trials), "--per-batch", str(per_batch),
                "--spec", "off",
                "--results-csv", os.path.join(workdir, "serial.csv"),
                "--compile-cache-dir", os.path.join(workdir, "cache_serial"),
            ]
        )
        sched_span, proc = timed(
            [
                sys.executable, "-m", "distributed_drift_detection_tpu",
                "sched", spec_path,
                "--telemetry-dir", os.path.join(workdir, "tele"),
                "--workers", str(workers),
                "--compile-cache-dir", os.path.join(workdir, "cache_sched"),
                "--json", "--timeout", "1500",
            ]
        )
        summary = json.loads(proc.stdout.splitlines()[-1])
        if not (summary.get("whole") and summary["audit"]["ok"]):
            raise RuntimeError(
                f"scheduled sweep did not converge exactly-once: {summary}"
            )
        return {
            "sched_cells": cells,
            "sched_workers": workers,
            "sched_cells_per_sec": round(cells / sched_span, 4),
            "sched_serial_cells_per_sec": round(cells / serial_span, 4),
            "sched_speedup": round(serial_span / sched_span, 2),
            "sched_serial_span_s": round(serial_span, 2),
            "sched_span_s": round(sched_span, 2),
            "sched_evictions": summary["evictions"],
            "sched_leases_granted": summary["leases_granted"],
        }
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


def sched_bench(workers: int, trials: int) -> None:
    """--sched mode: print the scheduler-scaling stats as the one JSON
    line (jax-free in THIS process — grid and fleet are subprocesses)."""
    _emit(
        {
            "metric": "sched_cells_per_sec",
            "unit": "cells/s",
            **_sched_stats(workers, trials),
        }
    )


def _history_stats(batches: int = 2000, series: int = 32) -> dict:
    """``--history``: micro-bench of the telemetry history store (ISSUE
    17) — append throughput (``batches`` scrape-shaped batches of
    ``series`` labeled samples each, flushed + segment-rotated like the
    collector's writes) and the median latency of a ``rate()`` query over
    the resulting store. Both cells print informationally in the perf CLI
    (filesystem-bound); the history-smoke CI job and tests/test_history.py
    own correctness. jax-free."""
    import shutil
    import statistics
    import tempfile
    import time as _time

    from distributed_drift_detection_tpu.telemetry import history

    root = tempfile.mkdtemp(prefix="history_bench_")
    try:
        t0 = _time.monotonic()
        with history.HistoryStore(root) as store:
            for b in range(batches):
                ts = 1_000_000.0 + b
                store.append_samples(
                    [
                        (
                            "bench_counter_total",
                            {"instance": f"i{s}"},
                            float(b * series + s),
                        )
                        for s in range(series)
                    ],
                    ts=ts,
                    mono=float(b),
                )
        append_span = _time.monotonic() - t0
        q_times = []
        for _ in range(20):
            q0 = _time.monotonic()
            history.rate(
                root,
                "bench_counter_total",
                labels={"instance": "i0"},
                window_s=float(batches),
                at=1_000_000.0 + batches,
            )
            q_times.append(_time.monotonic() - q0)
        segs = len(history.list_segments(root))
        return {
            "history_batches": batches,
            "history_series": series,
            "history_segments": segs,
            "history_append_samples_per_sec": round(
                batches * series / append_span, 1
            ),
            "history_rate_query_ms": round(
                statistics.median(q_times) * 1000.0, 3
            ),
        }
    finally:
        shutil.rmtree(root, ignore_errors=True)


def history_bench(batches: int, series: int) -> None:
    """--history mode: print the history-store micro-bench as the one
    JSON line (jax-free)."""
    _emit(
        {
            "metric": "history_append_samples_per_sec",
            "unit": "samples/s",
            **_history_stats(batches, series),
        }
    )


def _incident_capture_stats(reps: int = 5) -> dict:
    """--smoke rider: the incident-autopsy capture span (telemetry.incident,
    jax-free). One :class:`IncidentRecorder` with realistic evidence
    sources — a full 512-event flight ring, statusz/pipeline snapshots,
    a 256-record verdict sidecar to tail — captures ``reps`` bundles and
    the cell is the median wall-clock per capture. Informational in the
    perf CLI: the capture runs on the SLO evaluator thread, off the serve
    hot loop (the sidecar bit-parity test owns that claim), so this cell
    is about keeping the off-loop cost visible round over round, not
    about gating throughput."""
    import shutil
    import statistics
    import tempfile

    from distributed_drift_detection_tpu.telemetry.incident import (
        IncidentRecorder,
    )
    from distributed_drift_detection_tpu.telemetry.ops import FlightRecorder

    root = tempfile.mkdtemp(prefix="incident_bench_")
    try:
        stem = os.path.join(root, "bench-run")
        with open(stem + ".verdicts.jsonl", "w") as fh:
            for i in range(256):
                fh.write(
                    json.dumps({"kind": "verdict", "chunk": i, "rows": 6400})
                    + "\n"
                )
        flight = FlightRecorder(capacity=512)
        for i in range(512):
            flight.record(
                {"type": "heartbeat", "i": i, "rows_per_sec": 1e5}
            )
        rec = IncidentRecorder(
            stem,
            flight=flight,
            statusz_fn=lambda: {
                "rows": {"ingress_seen": 10_000, "quarantined": 3},
                "alerts": [],
            },
            pipeline_fn=lambda: {
                "busy_s": {"device": 3.0, "publish": 0.4},
                "wall_s": 4.0,
                "shares": {"device": 0.75, "publish": 0.1},
                "dominant_stage": "device",
                "current_stage": {"stage": "device", "for_s": 0.1},
            },
            verdicts_path=stem + ".verdicts.jsonl",
            max_bundles=reps + 1,
        )
        reason = {"rule": "stall_s", "state": "firing", "value": 1.0,
                  "threshold": 0.4}
        rec.capture(reason)  # warm (dir creation, allocator, page cache)
        spans = []
        for _ in range(reps):
            t0 = time.perf_counter()
            rec.capture(reason)
            spans.append(time.perf_counter() - t0)
        return {
            "serve_incident_capture_ms": round(
                statistics.median(spans) * 1000.0, 3
            ),
            "serve_incident_capture_reps": reps,
        }
    finally:
        shutil.rmtree(root, ignore_errors=True)


def smoke() -> None:
    """--smoke mode: the CI-scale artifact-contract check — the headline
    measurement pipeline on the self-contained synthetic rialto stand-in
    (no reference CSV, no TPU), 3 timed repetitions, emitting the SAME
    field shape as the real headline (value/final_time_s/rep_times_s/
    compile_s/phase_s/phase_hist/xla/...), so the perf CLI and the CI
    schema gate can exercise every field in seconds. The soak/chunked
    riders are skipped (hardware-scale by construction) and the line
    carries ``"smoke": true`` — the numbers are about the *contract*, not
    the hardware."""
    import jax

    _enable_compile_cache(jax)
    from distributed_drift_detection_tpu.api import prepare
    from distributed_drift_detection_tpu.config import RunConfig

    cfg = RunConfig(
        dataset="synth:rialto,seed=0",
        mult_data=2,
        partitions=4,
        per_batch=50,
        model="centroid",
        results_csv="",
        **({"collect": _CLI["collect"]} if _CLI["collect"] else {}),
    )
    # Incident-autopsy rider (jax-free; must not take down the contract
    # check — recorded in its own error field on failure, like the serve
    # riders).
    try:
        inc = _incident_capture_stats()
    except Exception as e:
        import traceback

        traceback.print_exc(file=sys.stderr)
        inc = {"serve_incident_error": f"{type(e).__name__}: {e}"[:300]}
    _emit(
        {
            "metric": "rows_per_sec_chip",
            "smoke": True,
            **_headline_core(prepare(cfg), reps=3),
            **inc,
            "device": str(jax.devices()[0].platform),
        }
    )


def main() -> None:
    import jax

    _enable_compile_cache(jax)

    from distributed_drift_detection_tpu.api import prepare
    from distributed_drift_detection_tpu.config import RunConfig

    # argv: [mult] [partitions] [window] [rotations] — the last two expose
    # the speculative engine's knobs for on-hardware sweeps via this CLI.
    mult = int(sys.argv[1]) if len(sys.argv) > 1 else 512
    partitions = int(sys.argv[2]) if len(sys.argv) > 2 else 16
    # Default 0/0 = auto: the bench measures the *shipped* execution policy
    # (config.auto_window / auto_rotations co-resolve W×R from stream
    # geometry; at this headline geometry that is 128×4 — the measured
    # optimum of the r03 W×R sweep on one TPU chip, detect-phase medians of
    # 7, uncontended conditions, flags bit-identical across all configs):
    #
    #   W=64  R=1: 0.165 s   (round-2 default)
    #   W=64  R=4: 0.161 s   W=64  R=8: 0.199 s
    #   W=128 R=1: 0.218 s   (wide window without rotations: replay waste)
    #   W=128 R=2: 0.176 s   W=128 R=3: 0.161 s
    #   W=128 R=4: 0.156 s   ← best    W=128 R=5: 0.159 s
    #   W=192 R=4: 0.191 s   W=256 R=5: 0.212 s (per-iteration slice cost)
    #
    # Depth 4 commits a whole 128-batch window (4 planted boundaries at the
    # headline geometry) per sequential step: iterations ≈ NB/W + drifts/R
    # ≈ 10 + 10 vs the round-2 default's ≈ 20 + 39. Under the shared
    # tunnel's contended conditions (per-iteration cost 3-5× higher) the
    # iteration-count reduction is worth proportionally more.
    window = int(sys.argv[3]) if len(sys.argv) > 3 else 0
    rotations = int(sys.argv[4]) if len(sys.argv) > 4 else 0
    cfg = RunConfig(
        dataset="/root/reference/outdoorStream.csv",
        mult_data=mult,
        partitions=partitions,
        per_batch=100,
        model="centroid",  # closed-form fit; the RF-equivalent flagship
        window=window,
        window_rotations=rotations,
        results_csv="",
        **({"collect": _CLI["collect"]} if _CLI["collect"] else {}),
    )
    prep = prepare(cfg)
    # The full measurement methodology (warm-up/compile split, 15 timed
    # repetitions with stall-aware selection, phase histograms, XLA
    # cost/memory) lives in _headline_core — shared with --smoke.
    core = _headline_core(prep, reps=15)

    # The 1e9-row sustained soak rides along in the same JSON line (as
    # soak_*-prefixed keys, keeping the one-line contract) so the soak claim
    # is driver-captured every round, not README-only — including the 2-leg
    # state-carrying chained proof (soak_chained_*). TPU only: on XLA CPU
    # the same scan is ~500× the headline workload and would stall the bench
    # for hours (the CPU fallback path in the verify recipe hits this).
    if jax.devices()[0].platform == "tpu":
        try:
            soak_stats = {
                f"soak_{k}": v for k, v in _soak_stats(1_000_000_000).items()
            }
        except Exception as e:  # headline result still reported on soak failure
            import traceback

            traceback.print_exc(file=sys.stderr)
            soak_stats = {"soak_error": f"{type(e).__name__}: {e}"[:300]}
        # The int32-ceiling branch (total_rows > 2^31−1) — the one only the
        # state-carrying chain can serve — captured at true >2^31 scale on
        # hardware every round (VERDICT r3 #5: rows > 2^31, legs ≥ 3; leg
        # sizing rounds the 3e9 request up to 3 × ~1.07e9-row legs). Its own
        # try: an xl failure must not take down the soak block above. Budget
        # guard: a 1e9 soak rep beyond 30 s signals heavy shared-tunnel
        # contention (uncontended ≈ 18 s) under which the xl chain would
        # run for several minutes — skip with provenance instead of risking
        # the whole bench invocation's budget (the standalone capture lives
        # in results/soak_xl_r04.json; `python bench.py --soak 3e9` reruns it).
        soak_t = soak_stats.get("soak_time_s")
        if soak_t is None:
            # The 1e9 soak itself failed — that, not contention, is why
            # there's no xl capture this invocation.
            soak_stats["soak_xl_skipped"] = (
                "1e9 soak failed (see soak_error); xl not attempted"
            )
        elif soak_t <= 30.0:
            try:
                soak_stats.update(
                    {
                        f"soak_xl_{k}": v
                        for k, v in _soak_stats(3_000_000_000).items()
                    }
                )
            except Exception as e:
                import traceback

                traceback.print_exc(file=sys.stderr)
                soak_stats["soak_xl_error"] = f"{type(e).__name__}: {e}"[:300]
        else:
            soak_stats["soak_xl_skipped"] = (
                f"contended tunnel (soak_time_s={soak_t}); see "
                "results/soak_xl_r04.json or run bench.py --soak 3e9"
            )
        # Host-fed sustained rider (VERDICT r4 #6): the on-disk ~2.1 GB
        # stream through native ingest + ChunkedDetector. Same contention
        # guard as the xl soak — parse-bound, so a contended host makes it
        # meaningless rather than merely slow.
        if soak_t is not None and soak_t <= 30.0:
            try:
                soak_stats.update(
                    {f"chunked_{k}": v for k, v in _chunked_stats().items()}
                )
            except Exception as e:
                import traceback

                traceback.print_exc(file=sys.stderr)
                soak_stats["chunked_error"] = f"{type(e).__name__}: {e}"[:300]
        else:
            soak_stats["chunked_skipped"] = (
                "contended tunnel or failed soak; run bench.py --chunked"
            )
    else:
        soak_stats = {"soak_skipped": "non-TPU device; use --soak explicitly"}

    _emit(
        {
            "metric": "rows_per_sec_chip",
            **core,
            **soak_stats,
            "device": str(jax.devices()[0].platform),
        }
    )


if __name__ == "__main__":
    _argv = sys.argv[1:]
    _cache = _pop_flag(_argv, "--compile-cache-dir")
    if _cache is not None:
        _CLI["compile_cache_dir"] = _cache
    _collect = _pop_flag(_argv, "--collect")
    if _collect is not None:
        from distributed_drift_detection_tpu.config import COLLECT_MODES

        if _collect not in COLLECT_MODES:
            raise SystemExit(
                f"bench.py: --collect must be one of {'|'.join(COLLECT_MODES)},"
                f" got {_collect!r}"
            )
        _CLI["collect"] = _collect
    _workers = _pop_flag(_argv, "--ingest-workers")
    if _workers is not None:
        try:
            _CLI["ingest_workers"] = int(_workers)
        except ValueError:
            raise SystemExit(
                f"bench.py: --ingest-workers must be an int, got {_workers!r}"
            ) from None
    sys.argv = [sys.argv[0]] + _argv  # modes below read positionals from argv
    is_soak = len(sys.argv) > 1 and sys.argv[1] == "--soak"
    is_chunked = len(sys.argv) > 1 and sys.argv[1] == "--chunked"
    is_smoke = len(sys.argv) > 1 and sys.argv[1] == "--smoke"
    is_serve = len(sys.argv) > 1 and sys.argv[1] == "--serve"
    is_tenants = len(sys.argv) > 1 and sys.argv[1] == "--tenants"
    is_fleet = len(sys.argv) > 1 and sys.argv[1] == "--fleet"
    is_sched = len(sys.argv) > 1 and sys.argv[1] == "--sched"
    is_history = len(sys.argv) > 1 and sys.argv[1] == "--history"
    try:
        if is_soak:
            soak(int(float(sys.argv[2])) if len(sys.argv) > 2 else 1_000_000_000)
        elif is_chunked:
            chunked()
        elif is_smoke:
            smoke()
        elif is_serve:
            serve_bench(
                int(float(sys.argv[2])) if len(sys.argv) > 2 else 20_000,
                float(sys.argv[3]) if len(sys.argv) > 3 else 0.0,
                int(sys.argv[4]) if len(sys.argv) > 4 else 1,
            )
        elif is_tenants:
            # --tenants [T1,T2,... [ROWS_PER_CLASS]] — default the ISSUE-9
            # acceptance pair T∈{8,64}.
            tenants_bench(
                [
                    int(x)
                    for x in (
                        sys.argv[2].split(",")
                        if len(sys.argv) > 2
                        else ("8", "64")
                    )
                ],
                int(sys.argv[3]) if len(sys.argv) > 3 else 200,
            )
        elif is_fleet:
            # --fleet [TENANTS [DAEMONS [ROWS]]] — aggregate rows/s of a
            # router-fronted multi-process serve fleet vs one daemon.
            fleet_bench(
                int(sys.argv[2]) if len(sys.argv) > 2 else 8,
                int(sys.argv[3]) if len(sys.argv) > 3 else 2,
                int(float(sys.argv[4])) if len(sys.argv) > 4 else 400_000,
            )
        elif is_sched:
            # --sched [WORKERS [TRIALS]] — cells/s of a scheduler-run
            # grid (WORKERS worker subprocesses) vs the serial grid CLI.
            sched_bench(
                int(sys.argv[2]) if len(sys.argv) > 2 else 3,
                int(sys.argv[3]) if len(sys.argv) > 3 else 2,
            )
        elif is_history:
            # --history [BATCHES [SERIES]] — history-store append
            # throughput + rate()-query latency (jax-free).
            history_bench(
                int(sys.argv[2]) if len(sys.argv) > 2 else 2000,
                int(sys.argv[3]) if len(sys.argv) > 3 else 32,
            )
        else:
            main()
    except Exception as e:  # still emit ONE parseable JSON line on failure
        import traceback

        traceback.print_exc(file=sys.stderr)  # full diagnostic to stderr
        metric = "rows_per_sec_chip"
        if is_soak:
            metric = "soak_rows_per_sec_chip"
        elif is_chunked:
            metric = "chunked_rows_per_sec_chip"
        elif is_serve:
            metric = "serve_row_to_verdict"
        elif is_tenants:
            metric = "tenant_agg_rows_per_sec"
        elif is_fleet:
            metric = "fleet_agg_rows_per_sec"
        elif is_sched:
            metric = "sched_cells_per_sec"
        elif is_history:
            metric = "history_append_samples_per_sec"
        _emit(
            {
                "metric": metric,
                "value": None,
                "unit": "rows/s",
                "vs_baseline": None,
                "error": f"{type(e).__name__}: {e}"[:300],
            }
        )
        raise SystemExit(1)
