"""Drift-triggered live refit: the reaction arm of the serving plane.

On a published drift verdict for tenant *t* (policy ``retrain`` or
``shadow``), the :class:`AdaptationController`:

1. **accumulates** a post-drift window of that tenant's admitted rows
   (host-side, from the sealed chunks' numpy copies — rows *after* the
   firing position, so the window samples the new concept only);
2. **refits** the classifier on the full window with one jitted fit
   (static window shape — compiled once per daemon) and scores champion
   (the tenant's current per-partition params) against the challenger on
   the same window in one compiled pair plane (:mod:`.shadow`);
3. **applies** the winner at a chunk boundary by *data surgery* on the
   detector carry: the tenant's param leaves are overwritten with the
   window fit, its detector state is re-initialised, and ``batch_a``
   becomes the window's tail microbatch — the paper-exact post-drift
   reset (*a ← b*, reset, retrain; ``DDM_Process.py:75-92`` steps 2-3)
   at window granularity. ``retrain = False`` so the fresh window fit
   actually serves (the kernel would otherwise refit on ``batch_a`` at
   the next step and discard it).

Nothing recompiles: the serving chunk program is untouched (the carry
update is pure data, shapes static — the PR-6 AOT executables keep
serving every feed, pinned by test), and every adaptation-plane program
(fit, swap, pair scorer, chunk scorer) has static shapes fixed at
construction, so each compiles exactly once.

The controller is engine-level, not serve-level: ``ServeRunner`` routes
published verdicts through it, and ``ChunkedDetector.run(on_drift=...)``
gives the offline chunked loop the same hook — one adaptation code path
for the paper's batch loop and the live daemon.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

from .policy import (
    AdaptPolicy,
    resolve_cooldown_rows,
    resolve_window_rows,
)
from .shadow import (
    make_pair_scorer,
    pair_errors,
    should_demote,
    should_promote,
)

ADAPT_METRIC = "adaptations_total"
ADAPT_HELP = "Applied/held/demoted drift adaptations by tenant and policy"
ACTIVE_METRIC = "adaptation_active"
ACTIVE_HELP = "Tenants currently accumulating or probing an adaptation"
RECOVERY_METRIC = "adaptation_recovery_rows"
RECOVERY_HELP = (
    "Rows from drift verdict to post-drift error back within epsilon of "
    "the pre-drift level"
)

ADAPT_STATE_SUFFIX = ".adapt"

#: EWMA weight of the newest chunk error in the pre-drift baseline.
_EWMA_ALPHA = 0.2


def extract_tenant_rows(chunk, lo: int, hi: int, min_pos: int = -1):
    """One tenant's real rows from a sealed chunk's host copy, in stream
    order: ``(X [N, F], y [N])`` for valid rows with stream position
    strictly greater than ``min_pos`` (the post-drift filter on the
    trigger chunk; ``-1`` takes everything). Padding and quarantined
    rows are excluded — the refit window holds admitted data only."""
    rows = np.asarray(chunk.rows[lo:hi]).ravel()
    X = np.asarray(chunk.X[lo:hi]).reshape(rows.size, -1)
    y = np.asarray(chunk.y[lo:hi]).ravel()
    valid = np.asarray(chunk.valid[lo:hi]).ravel()
    keep = valid & (rows > min_pos)
    if not keep.any():
        return X[:0], y[:0]
    order = np.argsort(rows[keep], kind="stable")
    return (
        X[keep][order].astype(np.float32),
        y[keep][order].astype(np.int32),
    )


class WindowBuffer:
    """Fixed-capacity post-drift row accumulator (one per adapting
    tenant). Static capacity = static fit shapes = one compile."""

    def __init__(self, window_rows: int, num_features: int):
        self.capacity = int(window_rows)
        self.X = np.zeros((self.capacity, int(num_features)), np.float32)
        self.y = np.zeros(self.capacity, np.int32)
        self.n = 0

    @property
    def full(self) -> bool:
        return self.n >= self.capacity

    def add(self, X: np.ndarray, y: np.ndarray) -> None:
        take = min(len(X), self.capacity - self.n)
        if take > 0:
            self.X[self.n : self.n + take] = X[:take]
            self.y[self.n : self.n + take] = y[:take]
            self.n += take

    def arrays(self):
        """``(X, y, w)`` at full capacity shape; ``w`` masks the unfilled
        tail (the fit and the scorers are weight-masked throughout)."""
        w = np.zeros(self.capacity, np.float32)
        w[: self.n] = 1.0
        return self.X, self.y, w

    def reset(self) -> None:
        self.n = 0


class _TenantState:
    """One tenant's adaptation state machine (host-side bookkeeping)."""

    __slots__ = (
        "policy", "window_rows", "cooldown_rows", "phase", "buffer",
        "trigger_chunk", "trigger_rows", "trigger_wall", "cooldown_until",
        "pre_err", "champion", "watch_recovery", "recovered_rows",
        "recoveries", "applied_rows", "adaptations",
    )

    def __init__(self, policy: AdaptPolicy, rows_per_chunk: int,
                 num_features: int):
        self.policy = policy
        self.window_rows = resolve_window_rows(policy, rows_per_chunk)
        self.cooldown_rows = resolve_cooldown_rows(policy, self.window_rows)
        self.phase = "idle"  # idle | accum | probation
        self.buffer = (
            WindowBuffer(self.window_rows, num_features)
            if policy.active
            else None
        )
        self.trigger_chunk = -1
        self.trigger_rows = 0
        self.trigger_wall = 0.0
        self.cooldown_until = 0
        self.pre_err: "float | None" = None
        self.champion = None  # host param pytree during probation
        self.watch_recovery = False
        self.recovered_rows: "int | None" = None  # latest completed watch
        self.recoveries: "list[int]" = []  # every completed watch
        self.applied_rows = 0
        self.adaptations = 0


class AdaptationController:
    """Consumes published drift verdicts and mutates the serving plane
    (see module docstring). One per daemon / chunked drain.

    ``det`` is the live :class:`~..engine.chunked.ChunkedDetector`;
    ``policies`` one :class:`~.policy.AdaptPolicy` per tenant;
    ``rows_per_chunk`` the per-tenant grid span (window auto-resolution
    unit); ``log`` an optional :class:`~..telemetry.events.EventLog`
    (``adaptation`` events + ``adaptation`` trace spans); ``metrics`` an
    optional registry (counters/gauges above).
    """

    def __init__(
        self,
        det,
        policies,
        *,
        per_batch: int,
        num_features: int,
        rows_per_chunk: int,
        log=None,
        metrics=None,
        seed: int = 0,
    ):
        import jax

        if len(policies) != det.tenants:
            raise ValueError(
                f"{len(policies)} policies for {det.tenants} tenant(s)"
            )
        self.det = det
        self.per_batch = int(per_batch)
        self.num_features = int(num_features)
        self.log = log
        self._seed = int(seed)
        self.states = [
            _TenantState(p, rows_per_chunk, num_features) for p in policies
        ]
        self._c_adapt = self._g_active = self._g_recovery = None
        if metrics is not None:
            self._c_adapt = metrics.counter(ADAPT_METRIC, help=ADAPT_HELP)
            self._g_active = metrics.gauge(ACTIVE_METRIC, help=ACTIVE_HELP)
            self._g_active.set(0)
            self._g_recovery = metrics.gauge(
                RECOVERY_METRIC, help=RECOVERY_HELP
            )
        self._base_key = jax.random.key(self._seed + 0xADA27)
        self._build_programs()

    @property
    def active(self) -> bool:
        """Whether any tenant's policy reacts (the runner skips the whole
        plane — construction included — when False)."""
        return any(s.policy.active for s in self.states)

    # -- compiled programs (static shapes; each compiles exactly once) ------

    def _build_programs(self) -> None:
        import jax
        import jax.numpy as jnp
        from jax import lax

        model = self.det.model
        kernel = self.det._detector
        p_per = self.det.tenant_partitions

        def fit_window(key, X, y, w):
            # One fit on the whole window, tiled to the tenant's P
            # partitions — every partition serves the same fresh concept
            # model (the window pools all partitions' post-drift rows).
            params = model.fit(key, X, y, w)
            return jax.tree.map(
                lambda l: jnp.broadcast_to(l[None], (p_per,) + l.shape),
                params,
            )

        self._fit_window = jax.jit(fit_window)

        def upd(leaf, sub, lo):
            return lax.dynamic_update_slice_in_dim(
                leaf, sub.astype(leaf.dtype), lo, axis=0
            )

        def swap_full(carry, params_t, aX, ay, aw, lo):
            # The paper-exact post-drift reset at window granularity:
            # fresh params, re-initialised detector, batch_a <- the
            # window's tail microbatch, retrain off (the window fit must
            # serve, not be overwritten by a batch_a refit next step).
            ddm_init = jax.vmap(lambda _: kernel.init())(jnp.arange(p_per))
            tile = lambda a: jnp.broadcast_to(a[None], (p_per,) + a.shape)
            return carry._replace(
                params=jax.tree.map(
                    lambda l, s: upd(l, s, lo), carry.params, params_t
                ),
                ddm=jax.tree.map(
                    lambda l, s: upd(l, s, lo), carry.ddm, ddm_init
                ),
                a_X=upd(carry.a_X, tile(aX), lo),
                a_y=upd(carry.a_y, tile(ay), lo),
                a_w=upd(carry.a_w, tile(aw), lo),
                retrain=upd(carry.retrain, jnp.zeros(p_per, bool), lo),
            )

        self._swap_full = jax.jit(swap_full)

        def swap_params(carry, params_t, lo):
            # Demotion restores the champion's params ONLY — the
            # detector has been watching the live stream throughout and
            # its state stays.
            return carry._replace(
                params=jax.tree.map(
                    lambda l, s: upd(l, s, lo), carry.params, params_t
                )
            )

        self._swap_params = jax.jit(swap_params)
        self._score_pair = make_pair_scorer(model)

        def chunk_err(params, Xs, ys, valids, lo):
            # Post-publish chunk error of one tenant's slice with its
            # current params — the pre-drift baseline / recovery probe.
            params_t = jax.tree.map(
                lambda l: lax.dynamic_slice_in_dim(l, lo, p_per, axis=0),
                params,
            )
            X2 = Xs.reshape(p_per, -1, Xs.shape[-1])
            y2 = ys.reshape(p_per, -1)
            v2 = valids.reshape(p_per, -1).astype(jnp.float32)
            preds = jax.vmap(model.predict)(params_t, X2)
            errs = (preds != y2).astype(jnp.float32) * v2
            n = jnp.sum(v2)
            return jnp.sum(errs) / jnp.maximum(n, 1.0), n

        self._chunk_err = jax.jit(chunk_err)

    def prepare(self, chunk_batches: "int | None" = None) -> None:
        """Warm the adaptation programs before traffic (the serving
        plane's AOT posture): each jitted program runs once on zeros so
        no XLA compile lands inside the serve loop. The swap programs
        are warmed only when a carry exists (a resumed daemon); on a
        fresh one their single compile rides the first adaptation —
        still outside the chunk program, which never recompiles."""
        from .shadow import stack_sides

        # numpy zeros, NOT jnp: the hot path hands the jitted programs
        # host arrays (window buffers, chunk host copies), and a jnp-warm
        # would leave a second trace-cache entry to pay at first use
        p_per = self.det.tenant_partitions
        f = self.num_features
        for w_rows in sorted({s.window_rows for s in self.states
                              if s.policy.active}):
            X = np.zeros((w_rows, f), np.float32)
            y = np.zeros(w_rows, np.int32)
            w = np.zeros(w_rows, np.float32)
            params_t = self._fit_window(self._base_key, X, y, w)
            self._score_pair(stack_sides(params_t, params_t), X, y, w)
            if self.det.carry is not None:
                aX = np.zeros((self.per_batch, f), np.float32)
                ay = np.zeros(self.per_batch, np.int32)
                aw = np.zeros(self.per_batch, np.float32)
                self._swap_full(self.det.carry, params_t, aX, ay, aw, 0)
                self._swap_params(self.det.carry, params_t, 0)
        if chunk_batches and self.det.carry is not None:
            shape = (p_per, int(chunk_batches), self.per_batch)
            self._chunk_err(
                self.det.carry.params,
                np.zeros(shape + (f,), np.float32),
                np.zeros(shape, np.int32),
                np.zeros(shape, bool),
                0,
            )

    # -- the hook ------------------------------------------------------------

    def on_chunk(self, meta: dict, flags, chunk) -> None:
        """Route one published chunk through every adapting tenant's
        policy. ``meta`` is the sealed chunk's accounting dict (the
        batch path synthesizes ``{"chunk", "rows_through"}``), ``flags``
        the chunk's HOST flag table, ``chunk`` its host copy."""
        cg = np.asarray(flags.change_global)
        p_per = self.det.tenant_partitions
        t_through = meta.get("t_rows_through")
        for t, st in enumerate(self.states):
            if not st.policy.active:
                continue
            lo, hi = t * p_per, (t + 1) * p_per
            rows_through = int(
                t_through[t] if t_through is not None
                else meta["rows_through"]
            )
            err_chunk = self._tenant_chunk_err(chunk, lo)
            if st.watch_recovery and err_chunk is not None:
                if err_chunk <= (st.pre_err or 0.0) + st.policy.epsilon:
                    st.watch_recovery = False
                    st.recovered_rows = rows_through - st.trigger_rows
                    st.recoveries.append(st.recovered_rows)
                    if self._g_recovery is not None:
                        self._g_recovery.set(
                            st.recovered_rows, tenant=str(t)
                        )
            elif st.phase == "idle" and err_chunk is not None:
                st.pre_err = (
                    err_chunk
                    if st.pre_err is None
                    else (1 - _EWMA_ALPHA) * st.pre_err
                    + _EWMA_ALPHA * err_chunk
                )
            if st.phase in ("accum", "probation"):
                X, y = extract_tenant_rows(chunk, lo, hi)
                st.buffer.add(X, y)
                if st.buffer.full:
                    if st.phase == "accum":
                        self._refit(t, st, meta, rows_through)
                    else:
                        self._probe(t, st, meta, rows_through)
            elif st.phase == "idle":
                fired = cg[lo:hi]
                if (fired >= 0).any() and rows_through >= st.cooldown_until:
                    st.phase = "accum"
                    st.trigger_chunk = int(meta["chunk"])
                    st.trigger_rows = rows_through
                    st.trigger_wall = time.time()
                    st.buffer.reset()
                    st.watch_recovery = False
                    # the trigger chunk's own post-drift tail seeds the
                    # window
                    X, y = extract_tenant_rows(
                        chunk, lo, hi, int(fired[fired >= 0].max())
                    )
                    st.buffer.add(X, y)
                    if st.buffer.full:
                        self._refit(t, st, meta, rows_through)
        self._set_active_gauge()

    # -- internals -----------------------------------------------------------

    def _tenant_chunk_err(self, chunk, lo: int) -> "float | None":
        if chunk is None:
            return None
        import jax

        err, n = self._chunk_err(
            self.det.carry.params,
            np.asarray(chunk.X[lo : lo + self.det.tenant_partitions]),
            np.asarray(chunk.y[lo : lo + self.det.tenant_partitions]),
            np.asarray(chunk.valid[lo : lo + self.det.tenant_partitions]),
            lo,
        )
        if float(jax.device_get(n)) <= 0.0:
            return None
        return float(jax.device_get(err))

    def _tenant_params(self, lo: int):
        import jax

        hi = lo + self.det.tenant_partitions
        return jax.tree.map(lambda l: l[lo:hi], self.det.carry.params)

    def _next_key(self, t: int, st: _TenantState):
        import jax

        st.adaptations += 1
        # tenant-salted: two tenants at the same adaptation ordinal must
        # not share a refit key (key-consuming fits — mlp/forest — would
        # otherwise correlate across the plane)
        return jax.random.fold_in(
            jax.random.fold_in(self._base_key, t), st.adaptations
        )

    def _window_tail(self, st: _TenantState):
        """The window's last ``per_batch`` rows as the new ``batch_a``
        (*a ← b* at window granularity); short windows pad with zero
        weight."""
        B = self.per_batch
        n = st.buffer.n
        take = min(n, B)
        aX = np.zeros((B, self.num_features), np.float32)
        ay = np.zeros(B, np.int32)
        aw = np.zeros(B, np.float32)
        aX[:take] = st.buffer.X[n - take : n]
        ay[:take] = st.buffer.y[n - take : n]
        aw[:take] = 1.0
        return aX, ay, aw

    def _refit(self, t, st: _TenantState, meta, rows_through: int) -> None:
        lo = t * self.det.tenant_partitions
        n_window = st.buffer.n
        X, y, w = st.buffer.arrays()
        challenger = self._fit_window(self._next_key(t, st), X, y, w)
        champion = self._tenant_params(lo)
        err_before, err_after = pair_errors(
            self._score_pair, champion, challenger, X, y, w
        )
        promote = st.policy.on_drift == "retrain" or should_promote(
            err_before, err_after, st.policy.margin
        )
        if promote:
            aX, ay, aw = self._window_tail(st)
            self.det.carry = self._swap_full(
                self.det.carry, challenger, aX, ay, aw, lo
            )
            st.applied_rows = rows_through
            st.watch_recovery = st.pre_err is not None
            if st.policy.on_drift == "shadow":
                import jax

                # retain the deposed champion host-side for the
                # probation window's demotion gate
                st.champion = jax.device_get(champion)
                st.phase = "probation"
                st.buffer.reset()
            else:
                st.phase = "idle"
                st.cooldown_until = rows_through + st.cooldown_rows
        else:
            st.phase = "idle"
            st.cooldown_until = rows_through + st.cooldown_rows
        self._emit(
            t, st, meta,
            rows_refit=n_window,
            err_before=err_before, err_after=err_after,
            promoted=bool(promote), rows_through=rows_through,
        )
        self._count(t, st, "promoted" if promote else "held")
        # the consumed window must not linger: /statusz would read a
        # full idle buffer as a stuck accumulation and every .adapt
        # checkpoint would persist the dead rows (no-op for the
        # probation path, which reset above)
        st.buffer.reset()

    def _probe(self, t, st: _TenantState, meta, rows_through: int) -> None:
        """Probation: the deposed champion scores the next window in
        shadow against the live challenger; a measured regression
        demotes the challenger (params-only restore)."""
        lo = t * self.det.tenant_partitions
        X, y, w = st.buffer.arrays()
        challenger = self._tenant_params(lo)
        err_champ, err_chall = pair_errors(
            self._score_pair, st.champion, challenger, X, y, w
        )
        demote = should_demote(err_champ, err_chall, st.policy.margin)
        if demote:
            import jax
            import jax.numpy as jnp

            champ = jax.tree.map(jnp.asarray, st.champion)
            self.det.carry = self._swap_params(self.det.carry, champ, lo)
            st.adaptations += 1  # snapshot/statusz must match the events
            self._emit(
                t, st, meta,
                rows_refit=st.buffer.n,
                err_before=err_champ, err_after=err_chall,
                promoted=False, rows_through=rows_through, demoted=True,
            )
            self._count(t, st, "demoted")
        st.champion = None
        st.phase = "idle"
        st.cooldown_until = rows_through + st.cooldown_rows
        st.buffer.reset()

    def _emit(self, t, st: _TenantState, meta, *, rows_refit, err_before,
              err_after, promoted, rows_through, **extra) -> None:
        if self.log is None:
            return
        self.log.emit(
            "adaptation",
            tenant=t,
            trigger_chunk=st.trigger_chunk,
            policy=st.policy.on_drift,
            rows_refit=int(rows_refit),
            err_before=err_before,
            err_after=err_after,
            promoted=bool(promoted),
            applied_chunk=int(meta["chunk"]),
            rows_to_apply=int(rows_through - st.trigger_rows),
            pre_drift_err=st.pre_err,
            window_rows=st.window_rows,
            **extra,
        )
        from ..telemetry.tracing import emit_span, new_trace_id

        now = time.time()
        emit_span(
            self.log,
            name="adaptation",
            trace_id=new_trace_id(),
            parent_id=None,
            start_ts=st.trigger_wall or now,
            dur_s=max(now - (st.trigger_wall or now), 0.0),
            tenant=t,
            policy=st.policy.on_drift,
            promoted=bool(promoted),
        )

    def _count(self, t, st: _TenantState, outcome: str) -> None:
        if self._c_adapt is not None:
            self._c_adapt.inc(
                1, tenant=str(t), policy=st.policy.on_drift, outcome=outcome
            )

    def _set_active_gauge(self) -> None:
        if self._g_active is not None:
            self._g_active.set(
                sum(1 for s in self.states if s.phase != "idle")
            )

    # -- observability surface ----------------------------------------------

    def snapshot(self) -> dict:
        """The ``/statusz`` adaptation section."""
        return {
            "policies": [s.policy.on_drift for s in self.states],
            "active": sum(1 for s in self.states if s.phase != "idle"),
            "adaptations": sum(s.adaptations for s in self.states),
            "tenants": [
                {
                    "tenant": t,
                    "phase": s.phase,
                    "window_rows": s.window_rows,
                    "buffered": s.buffer.n if s.buffer is not None else 0,
                    "pre_drift_err": s.pre_err,
                    "recovered_rows": s.recovered_rows,
                }
                for t, s in enumerate(self.states)
                if s.policy.active
            ],
        }

    def recovery_rows(self) -> "int | None":
        """Smallest measured drift→recovered span across tenants (the
        ``serve_adapt_recovery_rows`` bench cell); None until a
        recovery was observed."""
        spans = [r for s in self.states for r in s.recoveries]
        return min(spans) if spans else None

    # -- drain / resume ------------------------------------------------------

    def save(self, path: str) -> None:
        """Atomically persist the adaptation state (buffers, phases,
        retained champions) next to the detector checkpoint — the
        drain→resume contract for mid-adaptation state."""
        import jax

        arrays: dict = {}
        states_meta = []
        for t, st in enumerate(self.states):
            m = {
                "phase": st.phase,
                "trigger_chunk": st.trigger_chunk,
                "trigger_rows": st.trigger_rows,
                "trigger_wall": st.trigger_wall,
                "cooldown_until": st.cooldown_until,
                "pre_err": st.pre_err,
                "watch_recovery": st.watch_recovery,
                "recovered_rows": st.recovered_rows,
                "recoveries": st.recoveries,
                "applied_rows": st.applied_rows,
                "adaptations": st.adaptations,
                "buffered": st.buffer.n if st.buffer is not None else 0,
                "champion": st.champion is not None,
            }
            states_meta.append(m)
            if st.buffer is not None and st.buffer.n:
                arrays[f"t{t}_bufX"] = st.buffer.X[: st.buffer.n]
                arrays[f"t{t}_bufy"] = st.buffer.y[: st.buffer.n]
            if st.champion is not None:
                for i, leaf in enumerate(jax.tree.leaves(st.champion)):
                    arrays[f"t{t}_champ_{i}"] = np.asarray(leaf)
        arrays["__meta__"] = np.frombuffer(
            json.dumps({"v": 1, "states": states_meta}).encode(),
            dtype=np.uint8,
        )
        tmp = path + ".tmp"
        with open(tmp, "wb") as fh:
            np.savez(fh, **arrays)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)

    def restore(self, path: str) -> bool:
        """Restore a :meth:`save`d state; returns False when ``path``
        does not exist (a fresh daemon). The detector carry must already
        be restored (champion templates come from it)."""
        import jax

        if not os.path.exists(path):
            return False
        with np.load(path) as data:
            meta = json.loads(bytes(data["__meta__"]).decode())
            states_meta = meta["states"]
            if len(states_meta) != len(self.states):
                raise ValueError(
                    f"adapt state {path!r} holds {len(states_meta)} "
                    f"tenant(s); this plane has {len(self.states)}"
                )
            for t, (st, m) in enumerate(zip(self.states, states_meta)):
                st.phase = m["phase"]
                st.trigger_chunk = int(m["trigger_chunk"])
                st.trigger_rows = int(m["trigger_rows"])
                st.trigger_wall = float(m["trigger_wall"])
                st.cooldown_until = int(m["cooldown_until"])
                st.pre_err = m["pre_err"]
                st.watch_recovery = bool(m["watch_recovery"])
                st.recovered_rows = m["recovered_rows"]
                st.recoveries = [int(r) for r in m.get("recoveries", [])]
                st.applied_rows = int(m["applied_rows"])
                st.adaptations = int(m["adaptations"])
                if st.buffer is not None:
                    st.buffer.reset()
                    if m["buffered"]:
                        st.buffer.add(
                            data[f"t{t}_bufX"], data[f"t{t}_bufy"]
                        )
                if m["champion"]:
                    assert self.det.carry is not None, (
                        "adapt restore with a retained champion needs the "
                        "detector carry restored first"
                    )
                    template = self._tenant_params(
                        t * self.det.tenant_partitions
                    )
                    leaves, treedef = jax.tree.flatten(template)
                    loaded = [
                        data[f"t{t}_champ_{i}"] for i in range(len(leaves))
                    ]
                    for got, want in zip(loaded, leaves):
                        if got.shape != np.asarray(want).shape:
                            raise ValueError(
                                f"adapt champion leaf shape {got.shape} != "
                                f"template {np.asarray(want).shape}"
                            )
                    st.champion = jax.tree.unflatten(treedef, loaded)
        self._set_active_gauge()
        return True
