"""Adaptation plane: drift-triggered live retraining with
champion/challenger serving (ROADMAP item 3 — the reaction arm).

The serving daemon publishes drift verdicts; this package *consumes*
them. Per-tenant policy (:mod:`.policy`, jax-free), host-side post-drift
window refit with paper-exact detector reset (:mod:`.refit`), and
champion/challenger shadow scoring with measured promotion/demotion
(:mod:`.shadow`). The serving chunk program never recompiles — every
adaptation is a data update on the detector carry at a chunk boundary.

Lazy exports (PEP 562), like :mod:`..serve`: importing the package pulls
no jax — the ``serve`` CLI validates ``--on-drift`` specs backend-free.
"""

from __future__ import annotations

_EXPORTS = {
    "AdaptPolicy": ".policy",
    "POLICY_KINDS": ".policy",
    "parse_policy": ".policy",
    "resolve_policies": ".policy",
    "AdaptationController": ".refit",
    "WindowBuffer": ".refit",
    "extract_tenant_rows": ".refit",
    "ADAPT_STATE_SUFFIX": ".refit",
    "make_pair_scorer": ".shadow",
    "stack_sides": ".shadow",
    "should_promote": ".shadow",
    "should_demote": ".shadow",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    if name in _EXPORTS:
        import importlib

        mod = importlib.import_module(_EXPORTS[name], __name__)
        return getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
