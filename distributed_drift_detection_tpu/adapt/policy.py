"""Per-tenant adaptation policy: what a drift verdict *does*.

The reference's algorithmic contract is react-to-drift — train on batch
*a*, predict *b*, and on a DDM signal set *a ← b*, reset the detector
and retrain (``DDM_Process.py:75-92``, steps 2-3). The compiled kernel
already performs that reaction *per microbatch* inside the scan
(``engine.loop.make_partition_step``: rotate + reset + refit-on-*b*).
This module is the **policy layer above it**: what the serving plane (or
the offline chunked loop) does with a published drift *verdict* —
nothing (``alert_only``, today's behaviour, bit-exact), a host-side
refit of that tenant's classifier on a post-drift window of real rows
(``retrain``), or a champion/challenger shadow evaluation gating the
swap on measured error (``shadow``).

jax-free by design, like the rest of the config layer: the ``serve``
CLI validates ``--on-drift`` specs without a backend, and the policy
grammar is shared with :class:`~.refit.AdaptationController`.

Spec grammar (one string per ``--on-drift`` flag, repeatable)::

    retrain                          # every tenant
    shadow,window_rows=800           # every tenant, explicit window
    2=retrain,cooldown_rows=1600     # tenant 2 only (overrides a default)

Later specs override earlier ones; a bare policy name applies
plane-wide, a ``T=`` prefix targets one tenant. Knobs:

``window_rows``
    post-drift rows to accumulate before the refit (0 = auto: one chunk
    span — the smallest window that is already striped and scored).
``cooldown_rows``
    rows after an applied adaptation during which new verdicts for that
    tenant only alert (0 = auto: 2 × window_rows). Without it a noisy
    detector would thrash refits back to back.
``margin``
    shadow promotion/demotion gate: the challenger must beat the
    champion's shadow-slice error by more than this to be promoted, and
    the champion must beat the challenger by more than this to demote
    it back.
``epsilon``
    recovery band: post-drift chunk error is "recovered" once it drops
    back within ``epsilon`` of the pre-drift running error (feeds the
    ``serve_adapt_recovery_rows`` bench cell; never gates a swap).
"""

from __future__ import annotations

from typing import NamedTuple

POLICY_KINDS = ("alert_only", "retrain", "shadow")


class AdaptPolicy(NamedTuple):
    """One tenant's resolved drift-reaction policy (see module docstring)."""

    on_drift: str = "alert_only"
    window_rows: int = 0  # 0 = auto: one chunk span
    cooldown_rows: int = 0  # 0 = auto: 2 x window_rows
    margin: float = 0.02
    epsilon: float = 0.1

    @property
    def active(self) -> bool:
        """Whether this policy ever touches the serving plane —
        ``alert_only`` tenants pay zero adaptation work (the bit-parity
        contract with a policy-free daemon)."""
        return self.on_drift != "alert_only"


def parse_policy(spec: str) -> "tuple[int | None, AdaptPolicy]":
    """Parse one ``--on-drift`` spec → ``(tenant | None, policy)``.

    ``None`` means plane-wide. Unknown kinds/knobs fail loudly here, at
    argv time, never downstream in the serve loop.
    """
    spec = spec.strip()
    if not spec:
        raise ValueError("empty on_drift policy spec")
    head, _, rest = spec.partition(",")
    tenant: "int | None" = None
    if "=" in head:
        t_str, _, kind = head.partition("=")
        try:
            tenant = int(t_str)
        except ValueError:
            raise ValueError(
                f"bad on_drift tenant prefix {t_str!r} in {spec!r}; "
                "expected T=POLICY"
            ) from None
        if tenant < 0:
            raise ValueError(f"on_drift tenant must be >= 0, got {tenant}")
    else:
        kind = head
    kind = kind.strip()
    if kind not in POLICY_KINDS:
        raise ValueError(
            f"unknown on_drift policy {kind!r}; expected one of "
            f"{POLICY_KINDS}"
        )
    kw: dict = {}
    if rest:
        for item in rest.split(","):
            if not item.strip():
                continue
            k, sep, v = item.partition("=")
            k = k.strip()
            if not sep or k not in AdaptPolicy._fields or k == "on_drift":
                knobs = [f for f in AdaptPolicy._fields if f != "on_drift"]
                raise ValueError(
                    f"bad on_drift knob {item!r} in {spec!r}; expected "
                    f"key=value with key in {knobs}"
                )
            try:
                kw[k] = (
                    float(v) if k in ("margin", "epsilon") else int(v)
                )
            except ValueError:
                raise ValueError(
                    f"bad on_drift value {item!r}; must be numeric"
                ) from None
    policy = AdaptPolicy(on_drift=kind, **kw)
    if policy.window_rows < 0 or policy.cooldown_rows < 0:
        raise ValueError(
            f"on_drift window_rows/cooldown_rows must be >= 0 in {spec!r}"
        )
    return tenant, policy


def resolve_policies(
    specs, tenants: int
) -> "list[AdaptPolicy]":
    """Expand ``--on-drift`` specs into one policy per tenant.

    Plane-wide specs set every tenant; ``T=`` specs override one slot
    (later specs win either way — CLI order is precedence). No specs at
    all means ``alert_only`` everywhere: the policy-free daemon,
    byte-identical to one that never imported this module.
    """
    out = [AdaptPolicy() for _ in range(tenants)]
    for spec in specs or ():
        tenant, policy = parse_policy(spec)
        if tenant is None:
            out = [policy for _ in range(tenants)]
        else:
            if tenant >= tenants:
                raise ValueError(
                    f"on_drift spec {spec!r} targets tenant {tenant}; the "
                    f"plane serves {tenants} tenant(s)"
                )
            out[tenant] = policy
    return out


def resolve_window_rows(policy: AdaptPolicy, rows_per_chunk: int) -> int:
    """The concrete post-drift window for a tenant (0 = auto: one chunk
    span — the per-tenant grid span of the serving plane)."""
    return int(policy.window_rows) or int(rows_per_chunk)


def resolve_cooldown_rows(policy: AdaptPolicy, window_rows: int) -> int:
    """The concrete post-apply cooldown (0 = auto: 2 × the window)."""
    return int(policy.cooldown_rows) or 2 * int(window_rows)
