"""Champion/challenger shadow scoring for the adaptation plane.

Under ``on_drift=shadow`` the stale model (the **champion**) is not
swapped out on a drift verdict: a **challenger** is refitted on the
post-drift window and both are scored **in one compiled plane** — the
pair of per-partition parameter pytrees is stacked on a leading ``side``
axis and the predict runs ``vmap(side) ∘ vmap(partition)`` in a single
jitted program, so champion and challenger see exactly the same rows at
exactly the same cost as two independent evaluations would dispatch.
Promotion is gated on the measured shadow-slice error (the challenger
must beat the champion by more than ``AdaptPolicy.margin``); after a
promotion the deposed champion is retained host-side for one probation
window, and if the challenger *regresses* against it there the swap is
reverted (demotion).

All programs here have static shapes fixed at construction (window
length, partition count, feature width), so the whole shadow plane
compiles exactly once per daemon — the serving kernel is untouched and
the PR-6 AOT/compile-cache counters stay flat (pinned by test).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def stack_sides(champion, challenger):
    """Stack two per-partition param pytrees on a leading ``side`` axis
    (side 0 = champion, side 1 = challenger) — the pair scorer's input."""
    return jax.tree.map(
        lambda a, b: jnp.stack([jnp.asarray(a), jnp.asarray(b)]),
        champion,
        challenger,
    )


def make_pair_scorer(model):
    """Build the jitted shadow scorer:
    ``(stacked_params [S, P, ...], X [W, F], y [W], w [W]) -> err [S]``.

    Every side's every partition scores the same window; a side's error
    is the validity-weighted mean mis-prediction rate pooled over its
    partitions (each partition of a tenant carries its own evolved
    params, so the pool is the honest per-tenant number). ``w`` masks
    window padding. An all-masked window returns 0-weight errors of 0 —
    callers treat ``n == 0`` as "no evidence" (:func:`pair_errors`).
    """

    def _side(params_p, X, y, w):
        # vmap over partitions: each partition's params predict the rows
        preds = jax.vmap(model.predict, in_axes=(0, None))(params_p, X)
        errs = (preds != y[None, :]).astype(jnp.float32) * w[None, :]
        return jnp.sum(errs), jnp.float32(preds.shape[0]) * jnp.sum(w)

    def score(stacked, X, y, w):
        err_sum, n = jax.vmap(_side, in_axes=(0, None, None, None))(
            stacked, X, y, w
        )
        return err_sum / jnp.maximum(n, 1.0), n

    return jax.jit(score)


def pair_errors(scorer, champion, challenger, X, y, w):
    """Score a champion/challenger pair on one window; returns
    ``(err_champion, err_challenger)`` as floats, or ``(None, None)``
    when the window carries no valid rows."""
    err, n = scorer(stack_sides(champion, challenger), X, y, w)
    err = jax.device_get(err)
    n = jax.device_get(n)
    if float(n[0]) <= 0.0:
        return None, None
    return float(err[0]), float(err[1])


def should_promote(
    err_champion: "float | None",
    err_challenger: "float | None",
    margin: float,
) -> bool:
    """The promotion gate: the challenger must *measurably* beat the
    champion on the shadow slice. No evidence (empty window) keeps the
    champion — a swap must never ride on zero rows."""
    if err_champion is None or err_challenger is None:
        return False
    return err_challenger < err_champion - margin


def should_demote(
    err_champion: "float | None",
    err_challenger: "float | None",
    margin: float,
) -> bool:
    """The probation gate after a promotion: demote (restore the old
    champion) only when it *measurably* beats the challenger on the
    probation window — ties and missing evidence keep the challenger
    (the promotion already carried its own measured justification)."""
    if err_champion is None or err_challenger is None:
        return False
    return err_champion < err_challenger - margin
