"""Load generator + latency SLO probe for the serving daemon.

    python -m distributed_drift_detection_tpu loadgen synth:rialto,seed=0 \\
        --port 7007 --rows 4000 --rate 2000 --dir runs/live [...]

Replays a stream — an ``io.synth`` spec (``synth:rialto,...``) or a CSV
file — over the serve ingress at a target sustained rate, with
optional seeded dirty-row injection through the same
``resilience.faults.corrupt_lines`` helper the batch fault site uses
(``--dirty nan_cell:5:7`` corrupts 5 seeded rows), then tails the
daemon's verdict sidecar and reports **achieved rows/s plus p50/p99
row→verdict latency** as one JSON line — the SLO evidence ``bench.py
--serve`` records and the ``perf`` CLI tracks informationally.

``--wire v2`` replays the same rows as **binary columnar frames**
(``serve.wire``, ``--frame-rows`` rows each) instead of text lines —
the device-speed admission path. Latency attribution is identical
(verdict ``rows_through`` keys both protocols), ``--dirty`` corrupts
the same seeded stream positions via columnar stand-ins
(:func:`apply_dirty_frames`), and a multi-tenant replay deals the same
round-robin blocks with the tenant id carried in each frame header.

Tracing: ``--trace-sample R`` head-samples the replay at rate R — each
sampled row is preceded by a ``TRACE <trace_id> <span_id>`` wire line
(telemetry.tracing), so the daemon attaches its serving span chain to
the client's trace and the verdict record lists the trace ids. With
``--dir`` the loadgen also writes its own run log into the telemetry
directory with one root ``ingress`` span per sampled-and-covered row
(send → verdict observed), so the ``timeline`` CLI merges client and
daemon into one end-to-end trace.

Latency attribution: every verdict record carries ``rows_through`` — the
cumulative count of admitted rows up to and including its microbatch —
and rows are admitted in arrival order, so sent row *i*'s verdict is the
first record with ``rows_through > i``. Its latency is the verdict's
publication wall-clock minus the row's send wall-clock (same host for
generator and daemon in every supported deployment of this probe).
Under ``strict`` with dirty traffic rejected rows shift the mapping —
drive dirty SLO runs under ``quarantine``/``repair`` (rows keep their
positions; the loadgen default matches the daemon's).
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import sys
import time

import numpy as np

from . import wire


def load_source(
    spec: str, target_column: str = "target"
) -> tuple[np.ndarray, np.ndarray, int]:
    """Resolve a stream source to ``(X, y, num_classes)`` with labels
    re-indexed to ``0..C-1`` (the serve ingress contract — a daemon
    cannot re-index, so the generator does)."""
    if spec.startswith("synth:"):
        from ..io.synth import parse_synth

        X, y = parse_synth(spec[len("synth:"):])
    else:
        from ..io.stream import load_csv

        X, y = load_csv(spec, target_column)
    classes, y_idx = np.unique(y, return_inverse=True)
    return (
        np.ascontiguousarray(X, np.float32),
        y_idx.astype(np.int32),
        len(classes),
    )


def format_lines(X: np.ndarray, y: np.ndarray) -> list[str]:
    """Rows → protocol CSV lines (label last). ``repr(float(v))``
    round-trips every f32 exactly through the daemon's parser, so a
    clean replay is bit-identical to feeding the arrays directly."""
    return [
        ",".join(repr(float(v)) for v in row) + f",{int(label)}"
        for row, label in zip(X, y)
    ]


def apply_dirty(
    lines: list[str], spec: str
) -> list[tuple[int, int]]:
    """Apply one ``--dirty kind[:rows[:seed]]`` spec in place via
    ``resilience.faults.corrupt_lines``; returns the corrupted
    ``(row, column)`` pairs."""
    from ..resilience.faults import corrupt_lines

    parts = spec.split(":")
    kind = parts[0]
    rows = int(parts[1]) if len(parts) > 1 else 1
    seed = int(parts[2]) if len(parts) > 2 else 0
    return corrupt_lines(lines, kind, rows=rows, seed=seed, label_col=-1)


def apply_dirty_frames(
    X: np.ndarray, y: np.ndarray, spec: str
) -> list[tuple[int, int]]:
    """The ``--wire v2`` twin of :func:`apply_dirty`: corrupt the replay
    *arrays* in place with the SAME seeded row/column selection as
    ``resilience.faults.corrupt_lines`` (the shared
    ``corrupt_row_indices``/``corrupt_cell_column`` helpers), so a v1
    and a v2 replay of one ``--dirty`` spec dirty the same stream
    positions and their quarantine masks — hence their drift verdicts —
    stay bit-identical.

    A binary columnar frame cannot express text-only dirt, so two kinds
    use **columnar stand-ins** that hit the same contract clause class:
    ``bad_label`` (v1: non-integral label) and any dirt landing on the
    label column become an out-of-domain label (``-1``; i32 labels are
    integral by construction), and ``ragged_row`` becomes a whole-row
    NaN fill + bad label (a frame is rectangular by construction). Under
    ``quarantine``/``strict`` the affected rows resolve identically to
    v1 (masked / rejected at the same positions); under ``repair`` the
    v1 kinds may repair where the stand-ins quarantine — drive dirty
    cross-protocol parity runs under ``quarantine`` (the default).
    """
    from ..resilience.faults import (
        CORRUPTION_KINDS,
        corrupt_cell_column,
        corrupt_row_indices,
    )

    parts = spec.split(":")
    kind = parts[0]
    rows = int(parts[1]) if len(parts) > 1 else 1
    seed = int(parts[2]) if len(parts) > 2 else 0
    if kind not in CORRUPTION_KINDS:
        raise ValueError(
            f"unknown corruption kind {kind!r}; expected one of "
            f"{sorted(CORRUPTION_KINDS)}"
        )
    n = len(y)
    if n == 0:
        return []
    num_fields = X.shape[1] + 1  # corrupt_lines sees F+1 CSV fields
    label_col = X.shape[1]
    out: list[tuple[int, int]] = []
    for k, r in enumerate(corrupt_row_indices(kind, n, rows, seed)):
        if kind == "ragged_row":
            X[r, :] = np.nan
            y[r] = -1
            out.append((r, -1))
        elif kind == "bad_label":
            y[r] = -1
            out.append((r, label_col))
        else:  # nan_cell
            c = corrupt_cell_column(kind, seed, k, num_fields)
            if c == label_col:
                y[r] = -1
            else:
                X[r, c] = np.nan
            out.append((r, c))
    return out


def sample_traces(
    n: int, rate: float, seed: "int | None" = 0
) -> "dict[int, tuple[str, str]]":
    """Head-sample a replay: row index → fresh ``(trace_id, span_id)``
    root context for each sampled row. Empty at rate 0 (no work)."""
    if rate <= 0.0 or n <= 0:
        return {}
    from ..telemetry.tracing import HeadSampler

    s = HeadSampler(rate, seed=seed)
    return {i: s.new_context() for i in s.sample_block(n)}


def _stamp_lines(
    lines: list[str], trace_ctx: "dict[int, tuple[str, str]]"
) -> list[str]:
    """Prefix each sampled row's wire payload with its TRACE directive
    (one list element stays one data row — pacing math is unchanged)."""
    if not trace_ctx:
        return lines
    return [
        (
            f"TRACE {trace_ctx[i][0]} {trace_ctx[i][1]}\n{ln}"
            if i in trace_ctx
            else ln
        )
        for i, ln in enumerate(lines)
    ]


def _emit_client_spans(
    trace_log,
    trace_ctx: "dict[int, tuple[str, str]]",
    send_ts: np.ndarray,
    verdict_ts: "dict[int, float]",
) -> int:
    """Root ``ingress`` spans (send → verdict observed) for every
    sampled row the verdict stream covered; returns the count."""
    if trace_log is None or not trace_ctx:
        return 0
    from ..telemetry.tracing import emit_span

    n = 0
    for i in sorted(trace_ctx):
        end = verdict_ts.get(i)
        if end is None:
            continue
        tid, sid = trace_ctx[i]
        emit_span(
            trace_log,
            name="ingress",
            trace_id=tid,
            span_id=sid,
            parent_id=None,
            start_ts=float(send_ts[i]),
            dur_s=end - float(send_ts[i]),
            row=i,
        )
        n += 1
    return n


def adapt_attribution(
    verdict_records: "list[dict]", events: "list[dict]"
) -> dict:
    """Attribute refit latency from a replay's artifacts: verdict
    records carry each chunk's publication wall-clock, ``adaptation``
    events carry the trigger chunk and their own stamp — the delta is
    the drift→adaptation latency a client experiences. Returns the
    summary-JSON fields (Nones when nothing adapted)."""
    by_chunk: dict[int, float] = {}
    for r in verdict_records:
        by_chunk.setdefault(int(r["chunk"]), float(r["ts"]))
    lat_ms, row_spans = [], []
    for e in events:
        if e.get("type") != "adaptation":
            continue
        t0 = by_chunk.get(int(e["trigger_chunk"]))
        if t0 is not None:
            lat_ms.append((float(e["ts"]) - t0) * 1000.0)
        if e.get("rows_to_apply") is not None:
            row_spans.append(int(e["rows_to_apply"]))
    n = sum(1 for e in events if e.get("type") == "adaptation")
    return {
        "adaptations": n,
        "adapt_promoted": sum(
            1
            for e in events
            if e.get("type") == "adaptation" and e.get("promoted")
        ),
        "adapt_latency_ms_p50": (
            round(float(np.percentile(lat_ms, 50)), 2) if lat_ms else None
        ),
        "adapt_rows_to_apply_p50": (
            float(np.percentile(row_spans, 50)) if row_spans else None
        ),
    }


class _FleetVerdictTail:
    """Verdict tailing over a FLEET: every ``*.verdicts.jsonl`` under
    each given telemetry directory (one per backend daemon), discovered
    live — a failover's landing daemon may open its sidecar mid-replay.
    Merged per poll; per-tenant attribution joins on each record's
    GLOBAL tenant id, so one summary covers the whole fleet (``loadgen
    --router``)."""

    def __init__(self, dirs):
        self.dirs = list(dirs)
        self._tails: "dict[str, _VerdictTail]" = {}

    def poll(self) -> list[dict]:
        import glob as _glob

        out: list[dict] = []
        for d in self.dirs:
            for path in _glob.glob(os.path.join(d, "*.verdicts.jsonl")):
                tail = self._tails.get(path)
                if tail is None:
                    tail = self._tails[path] = _VerdictTail(path)
                out.extend(tail.poll())
        return out


class _VerdictTail:
    """Incremental verdict-sidecar reader (torn-tail tolerant: the offset
    only advances past complete lines, like ``telemetry.watch.LogTail``)."""

    def __init__(self, path: str):
        self.path = path
        self._offset = 0

    def poll(self) -> list[dict]:
        if not os.path.exists(self.path):
            return []
        with open(self.path, "rb") as fh:
            fh.seek(self._offset)
            blob = fh.read()
        end = blob.rfind(b"\n")
        if end < 0:
            return []
        chunk = blob[: end + 1]
        self._offset += end + 1
        out = []
        for line in chunk.decode("utf-8", errors="replace").splitlines():
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if isinstance(rec, dict) and rec.get("kind") == "verdict":
                out.append(rec)
        return out


def _connect(host: str, port: int, timeout: float) -> socket.socket:
    deadline = time.monotonic() + timeout
    while True:
        try:
            return socket.create_connection((host, port), timeout=5)
        except OSError:
            if time.monotonic() >= deadline:
                raise
            time.sleep(0.05)


def _send_rows(
    sock: socket.socket,
    lines: list[str],
    rate: float,
    batch: int = 256,
    label_lag: int = 0,
) -> np.ndarray:
    """Send data lines paced to ``rate`` rows/s (0 = as fast as the
    socket takes them); returns per-row send wall-clock stamps.

    ``label_lag`` is the delayed-labels replay mode (``--delayed-labels
    K``): a labeled row can only enter the wire once its label exists,
    and the label of row *i* "arrives" with the generation of row
    ``i + K`` — so row *i* ships at row ``i + K``'s pace slot, a
    constant lag of ``K / rate`` seconds between a feature vector's
    nominal arrival and its labeled admission. Pacing-only (needs
    ``rate > 0``); stream order is unchanged, so verdict attribution and
    the positional admission contract are untouched."""
    send_ts = np.empty(len(lines), np.float64)
    start = time.monotonic()
    i = 0
    while i < len(lines):
        if rate > 0:
            due = int((time.monotonic() - start) * rate) + 1 - label_lag
            if due <= i:
                time.sleep(min(0.002, 1.0 / rate))
                continue
            j = min(len(lines), i + min(batch, due - i))
        else:
            j = min(len(lines), i + batch)
        sock.sendall(("\n".join(lines[i:j]) + "\n").encode())
        send_ts[i:j] = time.time()
        i = j
    return send_ts


def _send_frames(
    sock: socket.socket,
    X: np.ndarray,
    y: np.ndarray,
    rate: float,
    frame_rows: int = 1024,
    label_lag: int = 0,
    tenant: int = 0,
) -> np.ndarray:
    """Send the replay as v2 binary frames of up to ``frame_rows`` rows,
    paced to ``rate`` rows/s (0 = as fast as the socket takes them);
    returns per-row send wall-clock stamps. The frame-batched twin of
    :func:`_send_rows` — same pacing math, same ``label_lag`` delayed-
    labels shift, so latency attribution is identical across protocols."""
    n = len(y)
    send_ts = np.empty(n, np.float64)
    start = time.monotonic()
    i = 0
    while i < n:
        if rate > 0:
            due = int((time.monotonic() - start) * rate) + 1 - label_lag
            if due <= i:
                time.sleep(min(0.002, 1.0 / rate))
                continue
            j = min(n, i + min(frame_rows, due - i))
        else:
            j = min(n, i + frame_rows)
        sock.sendall(wire.encode_frame(X[i:j], y[i:j], tenant=tenant))
        send_ts[i:j] = time.time()
        i = j
    return send_ts


def _stage_split(covering: "list[dict]") -> "dict | None":
    """Row→verdict latency split by serve stage, joined from the
    covering verdict records' own stage stamps (``record['lat_ms']``,
    written by the serve runner for every chunk): per-component
    p50/p99 ms over the covered rows, each row weighted by its
    covering record. ``None`` when no record carries stamps (a
    pre-observatory daemon's sidecar) — the summary stays
    end-to-end-only there, exactly as before. This makes client-side
    attribution cross-checkable against the daemon's busy accounting:
    the dominant component here should name the same stage the
    ``pipeline`` report blames."""
    stages: "dict[str, list[float]]" = {}
    for r in covering:
        lm = r.get("lat_ms")
        if not lm:
            continue
        for k, v in lm.items():
            stages.setdefault(k, []).append(float(v))
    if not stages:
        return None
    return {
        k: {
            "p50": round(float(np.percentile(v, 50)), 3),
            "p99": round(float(np.percentile(v, 99)), 3),
        }
        for k, v in sorted(stages.items())
    }


def _run_loadgen_tenants(
    host: str,
    port: int,
    lines: list[str],
    tenants: int,
    *,
    rate: float = 0.0,
    verdicts: "str | None" = None,
    timeout: float = 60.0,
    flush: bool = True,
    stop: bool = False,
    connect_timeout: float = 30.0,
    expect_rows: "int | None" = None,
    interleave: int = 64,
    trace_ctx: "dict[int, tuple[str, str]] | None" = None,
    trace_log=None,
    label_lag: int = 0,
    wire_version: str = "v1",
    arrays=None,
    fleet_dirs=None,
    weights=None,
) -> dict:
    """Multi-tenant replay: the stream is dealt round-robin (blocks of
    ``interleave`` rows) across T tenant slots over ONE connection, with
    ``TENANT k`` protocol lines routing each block — the interleaved
    traffic shape a real multi-tenant ingress sees. Latency attribution
    is per tenant: a verdict record's ``tenants[k].rows_through`` maps
    tenant k's sent rows exactly as ``rows_through`` does on a solo
    daemon; the pooled per-row latencies feed one p50/p99 pair (the SLO
    covers the plane, not one tenant). ``wire_version='v2'`` ships each
    dealt block as ONE binary frame carrying its tenant id (the frame
    header routes instead of a TENANT line) — identical dealing, so
    per-tenant streams match the v1 replay row for row.

    ``fleet_dirs`` is the router posture (``loadgen --router``): the
    replay's TENANT ids are GLOBAL (the router rewrites them to backend
    slots), verdict tailing merges every sidecar under each backend's
    telemetry directory (:class:`_FleetVerdictTail`), and attribution
    joins on each record entry's global ``id`` — a migrated tenant's
    verdicts continue its ``rows_through`` sequence from the landing
    daemon's sidecar, so one summary covers the whole fleet with the
    per-tenant latency math unchanged.

    ``weights`` (len T, positive) skews the dealing: blocks go to
    tenants by smooth weighted round-robin — fully deterministic (same
    weights → same dealing, so parity runs stay reproducible), with
    tenant t receiving a ``weights[t]/sum(weights)`` share of blocks.
    The Zipf-ish traffic split the history plane's hotness ranking is
    validated against. ``None`` = the uniform round-robin of old."""
    global_ids = fleet_dirs is not None
    if weights is not None:
        if len(weights) != tenants or any(w <= 0 for w in weights):
            raise ValueError(
                f"tenant weights must be {tenants} positive numbers, "
                f"got {weights!r}"
            )

    def _key(ent) -> int:
        # fleet join key: the record entry's GLOBAL tenant id (== the
        # slot index off-fleet; vacant spares carry id -1 → filtered)
        return int(ent.get("id", ent["tenant"])) if global_ids else int(
            ent["tenant"]
        )
    n_rows = len(arrays[1]) if wire_version == "v2" else len(lines)
    # Deal rows into tenant streams (round-robin blocks) and build the
    # wire segments: (tenant, [row indices]) in send order.
    streams: list[list[int]] = [[] for _ in range(tenants)]
    segments: list[tuple[int, list[int]]] = []
    wrr = [0.0] * tenants  # smooth-WRR credit (weights mode only)
    w_total = float(sum(weights)) if weights is not None else 0.0
    for base in range(0, n_rows, interleave):
        if weights is None:
            t = (base // interleave) % tenants
        else:
            # smooth weighted round-robin (nginx's): every tenant gains
            # its weight in credit, the richest takes the block and pays
            # the total back — deterministic, maximally interleaved
            for i in range(tenants):
                wrr[i] += float(weights[i])
            t = max(range(tenants), key=lambda i: (wrr[i], -i))
            wrr[t] -= w_total
        idx = list(range(base, min(base + interleave, n_rows)))
        streams[t].extend(idx)
        segments.append((t, idx))
    tail = (
        _FleetVerdictTail(fleet_dirs)
        if fleet_dirs
        else _VerdictTail(verdicts) if verdicts else None
    )
    baselines = [0] * tenants
    if tail is not None:
        for rec in tail.poll():
            for ent in rec.get("tenants") or []:
                k = _key(ent)
                if 0 <= k < tenants:
                    baselines[k] = max(
                        baselines[k], int(ent["rows_through"])
                    )
    stamped = (
        _stamp_lines(lines, trace_ctx or {}) if wire_version == "v1" else None
    )
    sock = _connect(host, port, connect_timeout)
    send_ts = np.empty(n_rows, np.float64)
    sent_so_far = 0
    try:
        t0 = time.monotonic()
        for t, idx in segments:
            if rate > 0:
                # label_lag: same delayed-labels pace shift as _send_rows
                while sent_so_far + label_lag > (time.monotonic() - t0) * rate:
                    time.sleep(min(0.002, 1.0 / rate))
            if wire_version == "v2":
                X, y = arrays
                sock.sendall(
                    wire.encode_frame(X[idx], y[idx], tenant=t)
                )
            else:
                payload = (
                    f"TENANT {t}\n"
                    + "\n".join(stamped[i] for i in idx)
                    + "\n"
                )
                sock.sendall(payload.encode())
            send_ts[idx] = time.time()
            sent_so_far += len(idx)
        sent_span = time.monotonic() - t0
        if flush:
            sock.sendall(
                wire.encode_flush() if wire_version == "v2" else b"FLUSH\n"
            )
        if stop:
            sock.sendall(
                wire.encode_stop() if wire_version == "v2" else b"STOP\n"
            )
    finally:
        sock.close()
    sent = n_rows
    expects = [b + len(s) for b, s in zip(baselines, streams)]
    # expect_rows (same contract as the solo path): override how many
    # TOTAL rows the verdict stream must cover before the probe stops
    # waiting — e.g. a strict-policy replay whose rejected rows can never
    # be covered.
    expect_total = (
        sum(baselines) + expect_rows if expect_rows is not None else None
    )
    records: list[dict] = []
    covered = list(baselines)
    timed_out = False

    def _pending() -> bool:
        if expect_total is not None:
            return sum(covered) < expect_total
        return any(c < e for c, e in zip(covered, expects))

    if tail is not None:
        deadline = time.monotonic() + timeout
        while _pending():
            fresh = tail.poll()
            if fresh:
                records.extend(fresh)
                for rec in fresh:
                    for ent in rec.get("tenants") or []:
                        k = _key(ent)
                        if 0 <= k < tenants:
                            covered[k] = max(
                                covered[k], int(ent["rows_through"])
                            )
                continue
            if time.monotonic() >= deadline:
                timed_out = True
                break
            time.sleep(0.02)
    lat_ms: list[float] = []
    per_tenant_covered = [0] * tenants
    verdict_ts: dict[int, float] = {}
    covering: list[dict] = []  # one record per covered row (stage split)
    if records:
        for t in range(tenants):
            entries = [
                (int(e["rows_through"]), float(r["ts"]), r)
                for r in records
                for e in (r.get("tenants") or [])
                if _key(e) == t
            ]
            if not entries or not streams[t]:
                continue
            entries.sort(key=lambda x: x[:2])
            throughs = np.array([x for x, _, _ in entries])
            ts = np.array([x for _, x, _ in entries])
            pos = baselines[t] + np.arange(len(streams[t]))
            idx = np.searchsorted(throughs, pos, side="right")
            ok = idx < len(entries)
            per_tenant_covered[t] = int(ok.sum())
            row_ids = np.asarray(streams[t])[ok]
            lat_ms.extend(
                ((ts[idx[ok]] - send_ts[row_ids]) * 1000.0).tolist()
            )
            covering.extend(entries[i][2] for i in idx[ok])
            if trace_ctx:
                for rid, vts in zip(row_ids, ts[idx[ok]]):
                    if int(rid) in trace_ctx:
                        verdict_ts[int(rid)] = float(vts)
    _emit_client_spans(trace_log, trace_ctx or {}, send_ts, verdict_ts)
    return {
        "rows_traced": len(trace_ctx or {}),
        "traces_covered": len(verdict_ts),
        "rows_sent": sent,
        "rows_covered": len(lat_ms),
        "tenants": tenants,
        "tenant_rows_sent": [len(s) for s in streams],
        "tenant_rows_covered": per_tenant_covered,
        "verdicts": len(records),
        "detections": sum(int(r["detections"]) for r in records),
        "achieved_rows_per_sec": (
            round(sent / sent_span, 1) if sent_span > 0 else None
        ),
        "target_rows_per_sec": rate or None,
        "p50_ms": (
            round(float(np.percentile(lat_ms, 50)), 2) if lat_ms else None
        ),
        "p99_ms": (
            round(float(np.percentile(lat_ms, 99)), 2) if lat_ms else None
        ),
        "mean_ms": round(float(np.mean(lat_ms)), 2) if lat_ms else None,
        "stage_ms": _stage_split(covering),
        "timeout": timed_out,
    }


def run_loadgen(
    host: str,
    port: int,
    lines: list[str],
    *,
    rate: float = 0.0,
    verdicts: "str | None" = None,
    timeout: float = 60.0,
    flush: bool = True,
    stop: bool = False,
    connect_timeout: float = 30.0,
    expect_rows: "int | None" = None,
    tenants: int = 1,
    trace_sample: float = 0.0,
    trace_seed: int = 0,
    trace_log=None,
    label_lag: int = 0,
    wire_version: str = "v1",
    arrays=None,
    frame_rows: int = 1024,
    fleet_dirs=None,
    tenant_weights=None,
) -> dict:
    """Drive one replay and measure the SLO (see module docstring).
    ``expect_rows`` overrides how many admitted rows the verdict stream
    must cover before the probe stops waiting (default: all sent).
    ``tenants > 1`` deals the stream round-robin across tenant slots of a
    multi-tenant daemon (``TENANT`` protocol lines) with per-tenant
    latency attribution — see :func:`_run_loadgen_tenants`.
    ``trace_sample``/``trace_seed`` head-sample the replay (TRACE wire
    stamps, telemetry.tracing); ``trace_log`` (an ``EventLog``) receives
    one root ``ingress`` span per sampled-and-covered row.
    ``label_lag`` replays with labels arriving K rows late (see
    :func:`_send_rows`) — the realistic shape adaptation refits are
    exercised under. ``wire_version='v2'`` replays as binary columnar
    frames of ``frame_rows`` rows (``serve.wire``): ``arrays=(X, y)``
    supplies the row data (``lines`` may be None), verdict attribution
    is unchanged (``rows_through`` keys both protocols identically).
    ``fleet_dirs`` (``--router``) replays through a router endpoint:
    TENANT ids are GLOBAL, verdicts are tailed from EVERY sidecar under
    each backend's telemetry directory and attribution joins on the
    records' global tenant ids — one summary for the whole fleet."""
    if wire_version not in ("v1", "v2"):
        raise ValueError(f"wire_version must be 'v1' or 'v2', got {wire_version!r}")
    if wire_version == "v2":
        if arrays is None:
            raise ValueError("wire_version='v2' needs arrays=(X, y)")
        if trace_sample > 0:
            # TRACE stamps are text-protocol lines; the v2 trace source
            # is the daemon-side sampler (ServeParams.trace_sample).
            raise ValueError(
                "client-side trace sampling needs wire_version='v1'"
            )
    n_rows = len(arrays[1]) if wire_version == "v2" else len(lines)
    trace_ctx = sample_traces(
        n_rows if wire_version == "v1" else 0, trace_sample, trace_seed
    )
    if tenant_weights is not None and tenants <= 1:
        raise ValueError("tenant_weights needs tenants > 1")
    if tenants > 1:
        return _run_loadgen_tenants(
            host, port, lines, tenants,
            rate=rate, verdicts=verdicts, timeout=timeout, flush=flush,
            stop=stop, connect_timeout=connect_timeout,
            expect_rows=expect_rows, trace_ctx=trace_ctx,
            trace_log=trace_log, label_lag=label_lag,
            wire_version=wire_version, arrays=arrays,
            fleet_dirs=fleet_dirs, weights=tenant_weights,
        )
    tail = (
        _FleetVerdictTail(fleet_dirs)
        if fleet_dirs
        else _VerdictTail(verdicts) if verdicts else None
    )
    baseline = 0
    if tail is not None:
        # Rows already verdicted by earlier traffic (a warm daemon):
        # this replay's row i sits at admitted position baseline + i.
        for rec in tail.poll():
            baseline = max(baseline, int(rec["rows_through"]))
    sock = _connect(host, port, connect_timeout)
    try:
        t0 = time.monotonic()
        if wire_version == "v2":
            send_ts = _send_frames(
                sock, arrays[0], arrays[1], rate,
                frame_rows=frame_rows, label_lag=label_lag,
            )
        else:
            send_ts = _send_rows(
                sock, _stamp_lines(lines, trace_ctx), rate,
                label_lag=label_lag,
            )
        sent_span = time.monotonic() - t0
        if flush:
            sock.sendall(
                wire.encode_flush() if wire_version == "v2" else b"FLUSH\n"
            )
        if stop:
            sock.sendall(
                wire.encode_stop() if wire_version == "v2" else b"STOP\n"
            )
    finally:
        sock.close()
    sent = n_rows
    expect = baseline + (expect_rows if expect_rows is not None else sent)
    records: list[dict] = []
    covered = baseline
    timed_out = False
    if tail is not None:
        deadline = time.monotonic() + timeout
        while covered < expect:
            fresh = tail.poll()
            if fresh:
                records.extend(fresh)
                covered = max(covered, *(int(r["rows_through"]) for r in fresh))
                continue
            if time.monotonic() >= deadline:
                timed_out = True
                break
            time.sleep(0.02)
    lat_ms: list[float] = []
    verdict_ts: dict[int, float] = {}
    covering: list[dict] = []  # one record per covered row (stage split)
    if records:
        recs = sorted(records, key=lambda r: int(r["rows_through"]))
        throughs = np.array([int(r["rows_through"]) for r in recs])
        ts = np.array([float(r["ts"]) for r in recs])
        pos = baseline + np.arange(sent)
        idx = np.searchsorted(throughs, pos, side="right")
        ok = idx < len(recs)
        lat_ms = ((ts[idx[ok]] - send_ts[ok]) * 1000.0).tolist()
        covering = [recs[i] for i in idx[ok]]
        if trace_ctx:
            covered_rows = np.nonzero(ok)[0]
            for rid, vts in zip(covered_rows, ts[idx[ok]]):
                if int(rid) in trace_ctx:
                    verdict_ts[int(rid)] = float(vts)
    _emit_client_spans(trace_log, trace_ctx, send_ts, verdict_ts)
    report = {
        "rows_sent": sent,
        "rows_traced": len(trace_ctx),
        # traces whose verdict the probe actually observed (== rows_traced
        # on a fully-covered replay); client root spans are emitted for
        # exactly these when a trace_log is given
        "traces_covered": len(verdict_ts),
        "rows_covered": len(lat_ms),
        "verdicts": len(records),
        "detections": sum(int(r["detections"]) for r in records),
        "achieved_rows_per_sec": (
            round(sent / sent_span, 1) if sent_span > 0 else None
        ),
        "target_rows_per_sec": rate or None,
        "p50_ms": round(float(np.percentile(lat_ms, 50)), 2) if lat_ms else None,
        "p99_ms": round(float(np.percentile(lat_ms, 99)), 2) if lat_ms else None,
        "mean_ms": round(float(np.mean(lat_ms)), 2) if lat_ms else None,
        # daemon-stamped stage split of the same covered rows — the
        # end-to-end percentiles above, attributed
        "stage_ms": _stage_split(covering),
        "timeout": timed_out,
    }
    return report


def main(argv=None) -> None:
    """``loadgen``: replay a stream at a target rate and report the SLO."""
    ap = argparse.ArgumentParser(
        prog="python -m distributed_drift_detection_tpu loadgen",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("source", help="synth:SPEC (io.synth.parse_synth) or a CSV path")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--rows", type=int, default=None,
                    help="cap the replay at N rows (default: the whole source)")
    ap.add_argument("--rate", type=float, default=0.0,
                    help="target rows/s (0 = as fast as the socket takes them)")
    ap.add_argument("--tenants", type=int, default=1,
                    help="deal the replay round-robin across N tenant "
                    "slots of a multi-tenant daemon (TENANT wire lines, "
                    "per-tenant latency attribution)")
    ap.add_argument("--tenant-weights", default=None, metavar="W0,W1,...",
                    help="skew the multi-tenant dealing: one positive "
                    "weight per tenant (len == --tenants), blocks dealt "
                    "by deterministic smooth weighted round-robin — e.g. "
                    "a Zipf-ish 8,4,2,1 hotness split for exercising "
                    "`history top-tenants` (default: uniform)")
    ap.add_argument("--wire", choices=("v1", "v2"), default="v1",
                    help="wire protocol: v1 = text lines (default), "
                    "v2 = binary columnar frames (serve.wire) — "
                    "frame-batched replay at device-feed rates, same "
                    "latency attribution")
    ap.add_argument("--frame-rows", type=int, default=1024,
                    help="rows per v2 frame (--wire v2; multi-tenant "
                    "replays deal interleave-sized frames instead)")
    ap.add_argument("--dirty", action="append", default=[],
                    metavar="KIND[:ROWS[:SEED]]",
                    help="seeded dirty-row injection (nan_cell|bad_label|"
                    "ragged_row), repeatable; --wire v2 corrupts the same "
                    "seeded stream positions with columnar stand-ins "
                    "(NaN cells / out-of-domain labels)")
    ap.add_argument("--router", action="store_true",
                    help="the endpoint is a tenant ROUTER (fleet front "
                    "daemon): --tenants deals GLOBAL tenant ids, --dir "
                    "(repeatable, one per backend daemon) names the "
                    "fleet's telemetry directories — every verdict "
                    "sidecar under them is tailed and per-tenant "
                    "rows_through attribution joins on global ids, so "
                    "one summary JSON covers the whole fleet")
    ap.add_argument("--verdicts", default=None,
                    help="verdict sidecar path (row→verdict latency source)")
    ap.add_argument("--dir", dest="telemetry_dir", action="append",
                    default=None,
                    help="telemetry directory: resolve the newest verdict "
                    "sidecar in it (repeatable with --router — one per "
                    "backend daemon)")
    ap.add_argument("--timeout", type=float, default=60.0,
                    help="max seconds to wait for verdict coverage")
    ap.add_argument("--stop", action="store_true",
                    help="send STOP after the replay (drain the daemon)")
    ap.add_argument("--delayed-labels", type=int, default=0, metavar="K",
                    help="labels arrive K rows after features: each row "
                    "ships at row i+K's pace slot (needs --rate), so "
                    "adaptation refits are exercised under realistic "
                    "label lag; refit latency is attributed in the "
                    "summary JSON when --dir is given")
    ap.add_argument("--trace-sample", type=float, default=0.0,
                    help="head-sample the replay at this rate (0..1): "
                    "sampled rows carry TRACE wire stamps and, with "
                    "--dir, root ingress spans land in a loadgen run log "
                    "for the timeline CLI (0 = off)")
    ap.add_argument("--trace-seed", type=int, default=0,
                    help="seed for the head-sampling decisions (reproducible "
                    "trace sets)")
    ap.add_argument("--target-column", default="target")
    args = ap.parse_args(argv)

    X, y, num_classes = load_source(args.source, args.target_column)
    if args.rows is not None:
        X, y = X[: args.rows], y[: args.rows]
    dirty_rows = 0
    if args.wire == "v2":
        if args.trace_sample > 0:
            ap.error(
                "--trace-sample needs --wire v1 (TRACE stamps are text "
                "protocol lines; use the daemon's --trace-sample for v2)"
            )
        X = np.ascontiguousarray(X, np.float32)
        y = np.ascontiguousarray(y, np.int32)
        for spec in args.dirty:
            dirty_rows += len(apply_dirty_frames(X, y, spec))
        lines = None
    else:
        lines = format_lines(X, y)
        for spec in args.dirty:
            dirty_rows += len(apply_dirty(lines, spec))
    dirs = list(args.telemetry_dir or [])
    if args.router:
        if not dirs:
            ap.error("--router needs --dir (one per backend daemon)")
        if args.verdicts:
            ap.error("--router tails every sidecar under --dir; "
                     "drop --verdicts")
    elif len(dirs) > 1:
        ap.error("multiple --dir needs --router (fleet verdict tailing)")
    verdicts = args.verdicts
    if verdicts is None and dirs and not args.router:
        from .runner import find_verdicts

        verdicts = find_verdicts(dirs[0])
        if verdicts is None:
            ap.error(f"no verdict sidecar under {dirs[0]}")
    trace_log = None
    if args.trace_sample > 0 and dirs:
        from ..telemetry.events import EventLog

        trace_log = EventLog.open_run(dirs[0], name="loadgen")
        trace_log.emit(
            "run_started",
            run_id=trace_log.run_id,
            config={"kind": "loadgen", "source": args.source,
                    "trace_sample": args.trace_sample},
        )
    if args.delayed_labels and args.rate <= 0:
        ap.error("--delayed-labels is a pacing mode and needs --rate > 0")
    tenant_weights = None
    if args.tenant_weights:
        if args.tenants <= 1:
            ap.error("--tenant-weights needs --tenants > 1")
        try:
            tenant_weights = [
                float(w) for w in args.tenant_weights.split(",")
            ]
        except ValueError:
            ap.error(f"--tenant-weights must be comma-separated numbers, "
                     f"got {args.tenant_weights!r}")
        if len(tenant_weights) != args.tenants or any(
            w <= 0 for w in tenant_weights
        ):
            ap.error(f"--tenant-weights needs {args.tenants} positive "
                     f"weights, got {args.tenant_weights!r}")
    t0 = time.monotonic()
    report = run_loadgen(
        args.host,
        args.port,
        lines,
        rate=args.rate,
        verdicts=verdicts,
        timeout=args.timeout,
        stop=args.stop,
        tenants=args.tenants,
        trace_sample=args.trace_sample,
        trace_seed=args.trace_seed,
        trace_log=trace_log,
        label_lag=args.delayed_labels,
        wire_version=args.wire,
        arrays=(X, y) if args.wire == "v2" else None,
        frame_rows=args.frame_rows,
        fleet_dirs=dirs if args.router else None,
        tenant_weights=tenant_weights,
    )
    report.update(
        source=args.source,
        wire=args.wire,
        features=int(X.shape[1]),
        classes=num_classes,
        dirty_rows=dirty_rows,
    )
    if args.router:
        report["router"] = True
        report["fleet_dirs"] = dirs
    if args.delayed_labels:
        report["label_lag_rows"] = args.delayed_labels
    if dirs:
        # Refit-latency attribution (adapt subsystem): join the daemon's
        # adaptation events against the verdict stream's publication
        # stamps. Every run log in the directory is scanned (the
        # loadgen's own --trace-sample client log would otherwise shadow
        # the daemon's as the newest); best-effort — a policy-free
        # daemon yields zero counts.
        import glob as _glob

        from ..telemetry import registry as _registry
        from ..telemetry.events import SchemaError, read_events

        events = []
        for p in (
            q for d in dirs for q in _glob.glob(os.path.join(d, "*.jsonl"))
        ):
            base = os.path.basename(p)
            if base == _registry.INDEX_NAME or base.endswith(
                _registry.SIDECAR_SUFFIXES
            ):
                continue
            try:
                events.extend(
                    e
                    for e in read_events(p, allow_partial_tail=True)
                    if e["type"] == "adaptation"
                )
            except (OSError, SchemaError, ValueError):
                continue
        if verdicts and os.path.exists(verdicts):
            from .runner import read_verdicts

            report.update(
                adapt_attribution(read_verdicts(verdicts), events)
            )
    if trace_log is not None:
        trace_log.emit(
            "run_completed",
            rows=report["rows_sent"],
            seconds=time.monotonic() - t0,
            detections=report["detections"],
        )
        trace_log.close()
        report["trace_log"] = trace_log.path
    print(json.dumps(report))
    raise SystemExit(2 if report["timeout"] else 0)


if __name__ == "__main__":
    main(sys.argv[1:])
