"""TCP line-protocol ingress for the serving daemon.

The wire contract (newline-delimited UTF-8, one row per line):

* ``v1,...,vF,label`` — CSV fields, label **last** (``F`` =
  ``ServeParams.num_features``);
* ``{"x": [v1, ..., vF], "y": label}`` or ``[v1, ..., vF, label]`` —
  JSON rows, normalized to the same fields at admission;
* ``TENANT k`` — route this connection's subsequent rows to tenant slot
  ``k`` of a multi-tenant daemon (``RunConfig.tenants > 1``; defaults to
  tenant 0, so single-tenant clients never need it). A malformed or
  out-of-range id is ordinary untrusted client input, not an internal
  failure: the connection gets an ``ERR`` line and is dropped — the
  daemon (and every other tenant's stream) keeps serving. Tenant
  isolation is the multi-tenant plane's point; only genuine
  admission-path failures poison the batcher;
* ``TRACE <trace_id> <span_id>`` — mark the **next** data row on this
  connection as head-sampled for end-to-end tracing
  (``telemetry.tracing``): the row's verdict joins back to the client's
  trace, and every serving stage attaches a child span to the run log.
  Ids are lowercase-hex tokens (malformed ones get the same ERR+drop as
  a bad TENANT id). Independently, a daemon-side sampler
  (``ServeParams.trace_sample``) can head-sample unstamped rows with
  fresh root traces; at rate 0 it does nothing;
* ``FLUSH`` — seal the current partial microbatch now (clients use it to
  close out a replay instead of waiting for the linger deadline);
* ``STOP`` — request a graceful drain (same path as SIGTERM: in-flight
  batches flush, the final checkpoint lands, the registry record flips
  to completed).

The server never acknowledges data lines (throughput; verdicts are
published through the run log + verdict sidecar, see ``serve.runner``).
The one response is ``ERR <reason>`` when ``data_policy='strict'``
rejects rows from this connection's traffic.

Handlers admit rows in *recv-sized blocks*: whatever complete lines one
``recv`` delivered go through ``AdmissionController.admit_lines`` as a
single block, so sanitize cost amortizes under load while a trickling
client still admits per line — the admission parser is block-vectorized
(``io.sanitize.parse_rows`` tiers), so bigger recv blocks parse at array
speed, which is why ``_RECV_BYTES`` is generous. An admission failure
(an armed ``serve.ingress`` fault, an unexpected bug) poisons the
batcher — the serve loop re-raises it and the daemon dies loudly rather
than serving around a broken ingress.
"""

from __future__ import annotations

import socketserver
import threading

# One recv per admission block: sized so a loaded ingress hands the
# vectorized admission parse thousands of rows at a time (a ~100-byte row
# → ~2.5k rows per block) instead of drip-feeding it.
_RECV_BYTES = 1 << 18


class _ProtocolReject(Exception):
    """Connection-local protocol violation (e.g. a bad TENANT id): drop
    THIS connection after the ERR reply, never the daemon."""


class _Handler(socketserver.BaseRequestHandler):
    def setup(self) -> None:
        super().setup()
        self._tenant = 0  # per-connection routing (the TENANT line)
        self._trace_next = None  # pending TRACE context for the next row

    def handle(self) -> None:
        buf = b""
        try:
            while True:
                try:
                    data = self.request.recv(_RECV_BYTES)
                except OSError:
                    break
                if not data:
                    break
                buf += data
                cut = buf.rfind(b"\n")
                if cut < 0:
                    continue
                block, buf = buf[:cut], buf[cut + 1 :]
                self._process(
                    block.decode("utf-8", errors="replace").split("\n")
                )
            if buf.strip():
                self._process([buf.decode("utf-8", errors="replace")])
        except _ProtocolReject:
            pass  # ERR already sent; close just this connection

    def _process(self, lines: list[str]) -> None:
        server: "IngressServer" = self.server  # type: ignore[assignment]
        block: list[str] = []
        marks: list[tuple] = []  # (block index, trace_id, span_id)
        for ln in lines:
            s = ln.strip()
            if not s:
                continue
            if s.startswith("TENANT"):
                # Any TENANT-prefixed line is a routing directive: no data
                # row starts with it (CSV rows open with a digit/sign,
                # JSON with {/[), so a malformed one ('TENANT', 'TENANT x')
                # must reject loudly here — falling through as a dirty
                # data row would leave every following row silently
                # routed to the PREVIOUS tenant's slot. Admit what
                # accumulated under the previous tenant first — blocks
                # are per-tenant by construction.
                self._admit(block, marks)
                block, marks = [], []
                try:
                    self._tenant = server.check_tenant(int(s[6:].strip()))
                except (ValueError, IndexError) as e:
                    # Untrusted client input: reject THIS connection
                    # (ERR + close), never the daemon — one client's
                    # typo must not take down the other tenants.
                    self._send(f"ERR {type(e).__name__}: {e}")
                    raise _ProtocolReject from e
            elif s.startswith("TRACE"):
                # Same no-data-row-starts-with-it argument as TENANT: a
                # malformed TRACE must reject here, or it would parse as
                # a dirty data row and silently shift positions.
                try:
                    self._trace_next = server.check_trace(s)
                except (ValueError, IndexError) as e:
                    self._send(f"ERR {type(e).__name__}: {e}")
                    raise _ProtocolReject from e
            elif s == "FLUSH":
                self._admit(block, marks)
                block, marks = [], []
                server.batcher.flush()
            elif s == "STOP":
                self._admit(block, marks)
                block, marks = [], []
                server.on_stop()
            else:
                if self._trace_next is not None:
                    marks.append((len(block), *self._trace_next))
                    self._trace_next = None
                block.append(s)
        self._admit(block, marks)

    def _admit(self, block: list[str], marks: "list[tuple] | None" = None) -> None:
        if not block:
            return
        server: "IngressServer" = self.server  # type: ignore[assignment]
        if server.sampler:
            # Daemon-side head sampling of unstamped rows: fresh root
            # traces, one decision batch per ingress block. Rate 0 makes
            # the sampler falsy — this branch costs one bool check.
            stamped = {i for i, *_ in marks} if marks else set()
            fresh = [
                (i, *server.sampler.new_context())
                for i in server.sampler.sample_block(len(block))
                if i not in stamped
            ]
            if fresh:
                marks = sorted((marks or []) + fresh)
        try:
            res = server.admission_for(self._tenant).admit_lines(
                block, traces=marks or None
            )
        except BaseException as e:
            # The daemon must die loudly on an ingress-path failure (the
            # armed serve.ingress fault is the rehearsal): poison the
            # batcher so the serve loop re-raises, tell the client, and
            # end this connection.
            server.batcher.poison(e)
            self._send(f"ERR {type(e).__name__}: {e}")
            raise
        if res.get("error"):
            self._send("ERR " + res["error"])

    def _send(self, line: str) -> None:
        try:
            self.request.sendall((line + "\n").encode())
        except OSError:
            pass  # client already gone; the counters carry the evidence


class IngressServer(socketserver.ThreadingTCPServer):
    """The listener: one daemon thread accepting, one per connection.

    ``on_stop`` is the runner's graceful-drain hook (the ``STOP``
    protocol line); :attr:`batcher`/:attr:`admissions` are shared with
    the serve loop. ``server_address`` after construction carries the
    bound port (``port=0`` requests an OS-assigned one).
    """

    daemon_threads = True
    allow_reuse_address = True

    def __init__(
        self, host: str, port: int, admissions, batcher, on_stop,
        sampler=None,
    ):
        super().__init__((host, port), _Handler)
        # One admission controller per tenant slot (the TENANT protocol
        # line routes); a solo daemon passes a 1-element list.
        self.admissions = list(admissions)
        self.batcher = batcher
        self.on_stop = on_stop
        # Daemon-side head sampler (telemetry.tracing.HeadSampler) for
        # rows the client did not TRACE-stamp; None/rate-0 = off.
        self.sampler = sampler
        self._thread: "threading.Thread | None" = None

    def admission_for(self, tenant: int):
        """The admission controller serving ``tenant`` (see TENANT line)."""
        return self.admissions[tenant]

    def check_tenant(self, tenant: int) -> int:
        """Validate a TENANT line's id against the daemon's tenant plane."""
        n = len(self.admissions)
        if not 0 <= tenant < n:
            raise ValueError(
                f"TENANT {tenant} out of range (daemon serves {n} tenant(s))"
            )
        return tenant

    def check_trace(self, line: str) -> "tuple[str, str]":
        """Parse + validate a ``TRACE <trace_id> <span_id>`` wire line
        (untrusted client input; raises ValueError on any malformation)."""
        from ..telemetry.tracing import check_trace_token

        parts = line.split()
        if len(parts) != 3:
            raise ValueError(
                f"TRACE line needs exactly 'TRACE <trace_id> <span_id>', "
                f"got {len(parts)} token(s)"
            )
        return check_trace_token(parts[1]), check_trace_token(parts[2])

    @property
    def port(self) -> int:
        return self.server_address[1]

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self.serve_forever, name="serve-ingress", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self.shutdown()
        self.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
