"""Readiness-based TCP ingress for the serving daemon: one event loop,
N connections, v1 text lines and v2 binary frames auto-detected.

Two wire protocols share every connection (docs/SERVING.md "Wire
protocol"):

* **v1 — newline-delimited UTF-8 text** (unchanged byte-for-byte from
  the original thread-per-connection ingress):

  - ``v1,...,vF,label`` — CSV fields, label **last**;
  - ``{"x": [..], "y": l}`` / ``[.., l]`` — JSON rows, normalized to the
    same fields at admission;
  - ``TENANT k`` — route this connection's subsequent v1 rows to tenant
    slot ``k``. A malformed or out-of-range id is untrusted client
    input: the connection gets an ``ERR`` line and is dropped — the
    daemon (and every other tenant's stream) keeps serving;
  - ``TRACE <trace_id> <span_id>`` — mark the **next** v1 data row on
    this connection as head-sampled for end-to-end tracing;
  - ``FLUSH`` / ``STOP`` — seal the partial microbatch / graceful drain.

* **v2 — length-prefixed binary columnar frames** (``serve.wire``): a
  16-byte header + one contiguous f32 feature block + i32 label vector.
  A frame carries its own tenant id and admits as a whole through the
  vectorized frame path (``AdmissionController.admit_frame``) — no text
  parse, no per-row Python. Zero-row control frames are the binary
  FLUSH/STOP twins.

Auto-detection costs one byte test per message boundary: every v1
message opens with an ASCII byte (< 0x80), the v2 magic's first wire
byte is 0xF2 — so the per-connection state machine routes each message
unambiguously and a single connection may interleave both freely.

The listener is a **single event loop** (``selectors``, epoll on Linux):
one thread multiplexing every connection through non-blocking sockets,
instead of one thread per connection. Per-connection state is a framing
state machine (buffered text bytes, or an in-flight frame whose payload
is filled by ``recv_into`` straight into its own buffer — the socket's
bytes land once in memory the admitted rows then alias, no intermediate
copy). Admission itself runs on ONE **admitter thread** behind a bounded
in-order work queue: the event loop does only I/O and framing, the
admitter does the vectorized sanitize + microbatch seals, so socket
drain and admission compute overlap as a two-stage pipeline (both
stages release the GIL for their heavy work — syscalls and numpy). One
admitter, not a pool: admission order is stream position, and the
shared-controller lock would serialize a pool anyway. Backpressure is
global by construction: a full work queue blocks the loop, a full
microbatcher queue blocks the admitter, and TCP pushes back on every
client — the daemon's admission rate, not its memory, is the limit.

Handlers admit v1 rows in *message-boundary blocks*: whatever complete
lines arrived together go through ``AdmissionController.admit_lines`` as
one block, so sanitize cost amortizes under load while a trickling
client still admits per line. The server never acknowledges data; the
one response is ``ERR <reason>`` (strict rejections, protocol
violations — the latter also close that connection). An admission-path
failure (an armed ``serve.ingress`` fault, an unexpected bug) poisons
the batcher — the serve loop re-raises it and the daemon dies loudly
rather than serving around a broken ingress.

Per-protocol accounting (``serve_ingress_frames_total{version=v1|v2}``
counts admitted v1 line blocks / v2 data frames;
``serve_ingress_decode_errors_total`` counts structurally invalid
frames, protocol-line violations and mid-frame disconnects) feeds
``/metrics``, the ``/statusz`` ingress section, and the ``top``
dashboard's WIRE column.
"""

from __future__ import annotations

import queue
import selectors
import socket
import threading

import numpy as np

from . import wire

# One recv per readiness event: sized so a loaded ingress hands the
# vectorized admission parse thousands of rows at a time (a ~100-byte v1
# row → ~2.5k rows per block) instead of drip-feeding it.
_RECV_BYTES = 1 << 18

#: The one-byte v2 protocol discriminator as a bytes needle (fast
#: C-level containment scans over text regions).
_MAGIC_BYTES = bytes([wire.MAGIC_BYTE])


class _ProtocolReject(Exception):
    """Connection-local protocol violation (a bad TENANT id, a malformed
    frame header): drop THIS connection after the ERR reply, never the
    daemon."""


class _Connection:
    """Per-connection framing state (the event loop owns the I/O)."""

    __slots__ = ("sock", "buf", "tenant", "trace_next", "pending")

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.buf = bytearray()  # unconsumed text/header bytes
        self.tenant = 0  # v1 per-connection routing (the TENANT line)
        self.trace_next = None  # pending TRACE context for the next v1 row
        # In-flight v2 frame: (header, payload bytearray, filled bytes).
        # While set, recv_into fills the payload buffer directly — the
        # socket's payload bytes land once, in memory the admitted rows
        # then alias (wire.payload_views).
        self.pending: "tuple | None" = None


class IngressServer:
    """The listener: ONE daemon thread multiplexing every connection.

    ``on_stop`` is the runner's graceful-drain hook (the ``STOP``
    protocol message, text or control frame); :attr:`batcher` /
    :attr:`admissions` are shared with the serve loop. ``port`` after
    construction carries the bound port (``port=0`` requests an
    OS-assigned one). ``metrics`` (a ``telemetry.metrics``
    ``MetricsRegistry``) adds the per-protocol ingress counters;
    ``max_frame_rows`` bounds a v2 header's declared row count
    (``ServeParams.max_frame_rows``).
    """

    def __init__(
        self, host: str, port: int, admissions, batcher, on_stop,
        sampler=None, metrics=None,
        max_frame_rows: int = wire.MAX_FRAME_ROWS,
        on_control=None,
    ):
        # One admission controller per tenant slot (the TENANT line and
        # the frame tenant field route); a solo daemon passes a
        # 1-element list.
        self.admissions = list(admissions)
        self.batcher = batcher
        self.on_stop = on_stop
        # Tenant-migration control hook (ServeRunner.request_control):
        # SAVETENANT/LOADTENANT wire lines land here, in wire order via
        # the work queue; None (solo embedders) rejects the lines.
        self.on_control = on_control
        # Daemon-side head sampler (telemetry.tracing.HeadSampler) for
        # rows the client did not TRACE-stamp; None/rate-0 = off.
        self.sampler = sampler
        # 0 = the codec default (ServeParams.max_frame_rows's sentinel;
        # wire.MAX_FRAME_ROWS stays the one copy of the constant).
        self.max_frame_rows = int(max_frame_rows) or wire.MAX_FRAME_ROWS
        # Per-protocol accounting (GIL-atomic ints; the ops plane reads
        # them from its own thread via stats()).
        self.frames_v1 = 0  # admitted v1 line blocks
        self.frames_v2 = 0  # admitted v2 data frames
        self.decode_errors = 0  # malformed frames / protocol lines
        self._c_frames = self._c_decode = None
        if metrics is not None:
            self._c_frames = metrics.counter(
                "serve_ingress_frames_total",
                help="Ingress messages admitted per wire protocol "
                "(v1 = text line blocks, v2 = binary data frames)",
            )
            self._c_decode = metrics.counter(
                "serve_ingress_decode_errors_total",
                help="Structurally invalid ingress messages (bad frame "
                "header, malformed protocol line, mid-frame disconnect)",
            )
        self._sel = selectors.DefaultSelector()
        self._listen = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listen.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listen.bind((host, port))
        self._listen.listen(128)
        self._listen.setblocking(False)
        self._conns: "dict[socket.socket, _Connection]" = {}
        self._stop_evt = threading.Event()
        self._thread: "threading.Thread | None" = None
        # The admitter pipeline stage: complete messages (closures) run
        # in arrival order on one worker thread, overlapping admission
        # compute with the loop's socket drain. Bounded: a slow admitter
        # backpressures the loop, and TCP backpressures the clients.
        self._work: "queue.Queue" = queue.Queue(maxsize=8)
        self._admitter: "threading.Thread | None" = None

    # -- shared lookups (also used by tests) ---------------------------------

    def admission_for(self, tenant: int):
        """The admission controller serving ``tenant``."""
        return self.admissions[tenant]

    def check_tenant(self, tenant: int) -> int:
        """Validate a tenant id (TENANT line or frame header field)
        against the daemon's tenant plane."""
        n = len(self.admissions)
        if not 0 <= tenant < n:
            raise ValueError(
                f"TENANT {tenant} out of range (daemon serves {n} tenant(s))"
            )
        return tenant

    def check_trace(self, line: str) -> "tuple[str, str]":
        """Parse + validate a ``TRACE <trace_id> <span_id>`` wire line
        (untrusted client input; raises ValueError on any malformation)."""
        from ..telemetry.tracing import check_trace_token

        parts = line.split()
        if len(parts) != 3:
            raise ValueError(
                f"TRACE line needs exactly 'TRACE <trace_id> <span_id>', "
                f"got {len(parts)} token(s)"
            )
        return check_trace_token(parts[1]), check_trace_token(parts[2])

    def stats(self) -> dict:
        """Per-protocol ingress accounting (the ``/statusz`` ingress
        section; rendered by ``top``'s WIRE column)."""
        return {
            "frames_v1": self.frames_v1,
            "frames_v2": self.frames_v2,
            "decode_errors": self.decode_errors,
            "connections": len(self._conns),
        }

    @property
    def port(self) -> int:
        return self._listen.getsockname()[1]

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self._sel.register(self._listen, selectors.EVENT_READ, None)
        self._admitter = threading.Thread(
            target=self._admit_worker, name="serve-admitter", daemon=True
        )
        self._admitter.start()
        self._thread = threading.Thread(
            target=self._run, name="serve-ingress", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop_evt.set()
        if self._thread is not None and self._thread is not threading.current_thread():
            # The loop notices the event within one select timeout. A
            # drain-time join may time out while the loop is blocked in a
            # backpressured put — the serve loop keeps consuming, so the
            # thread unwedges and exits on its own (it is a daemon
            # thread either way).
            self._thread.join(timeout=5)
        if self._admitter is not None:
            # Sentinel: drain queued admissions, then exit. NON-blocking:
            # stop() runs on the serve loop — the batcher's only consumer
            # — and the admitter may right now be wedged in a
            # backpressured batcher.push that only our caller's drain can
            # relieve. A blocking put on the full work queue here would
            # deadlock the whole drain; when the queue is full the
            # _stop_evt poll below is the admitter's exit path instead.
            try:
                self._work.put_nowait(None)
            except queue.Full:
                pass
            self._admitter.join(timeout=5)
            self._admitter = None

    def _admit_worker(self) -> None:
        """The admitter stage: run queued admissions in arrival order.
        An admission-path failure already poisoned the batcher inside its
        closure (the serve loop dies loudly); the worker keeps draining
        so the stop sentinel is always reachable. The get() polls so a
        stop() that could not enqueue its sentinel (full queue at drain
        time) still terminates the thread once the backlog drains."""
        while True:
            try:
                task = self._work.get(timeout=0.1)
            except queue.Empty:
                if self._stop_evt.is_set():
                    return
                continue
            if task is None:
                return
            try:
                task()
            except BaseException:
                # Evidence lives in the poisoned batcher + ERR replies;
                # every later admission raises the same poison and is
                # swallowed the same way while the daemon dies.
                pass

    def _run(self) -> None:
        try:
            while not self._stop_evt.is_set():
                for key, _ in self._sel.select(timeout=0.1):
                    if key.data is None:
                        self._accept()
                    else:
                        self._service(key.data)
        finally:
            for conn in list(self._conns.values()):
                self._close(conn)
            try:
                self._sel.unregister(self._listen)
            except (KeyError, ValueError):
                pass
            self._listen.close()
            self._sel.close()

    def _accept(self) -> None:
        while True:
            try:
                sock, _ = self._listen.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return  # listener closing under us (drain)
            sock.setblocking(False)
            conn = _Connection(sock)
            self._conns[sock] = conn
            self._sel.register(sock, selectors.EVENT_READ, conn)

    def _close(self, conn: _Connection) -> None:
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError):
            pass
        self._conns.pop(conn.sock, None)
        try:
            conn.sock.close()
        except OSError:
            pass

    # -- I/O -----------------------------------------------------------------

    def _service(self, conn: _Connection) -> None:
        try:
            if conn.pending is not None and not conn.buf:
                # Mid-frame: the socket's payload bytes land directly in
                # the frame's own buffer — no intermediate copy.
                header, payload, filled = conn.pending
                n = conn.sock.recv_into(memoryview(payload)[filled:])
                if n == 0:
                    self._eof(conn)
                    return
                filled += n
                if filled == len(payload):
                    conn.pending = None
                    self._finish_frame(conn, header, payload)
                else:
                    conn.pending = (header, payload, filled)
                return
            data = conn.sock.recv(_RECV_BYTES)
            if not data:
                self._eof(conn)
                return
            conn.buf += data
            self._consume(conn)
        except (BlockingIOError, InterruptedError):
            return
        except _ProtocolReject:
            self._close(conn)  # ERR already sent; just this connection
        except OSError:
            self._close(conn)  # peer went away mid-I/O
        except BaseException as e:
            # Genuine internal failure ON THE LOOP THREAD (a payload
            # allocation failing, a sampler bug — admissions themselves
            # run on the admitter thread and poison from their own
            # closures). Swallowing it would close the connection with
            # zero evidence while the daemon keeps serving; poison the
            # batcher instead so the serve loop dies loudly — the same
            # contract every admission-path failure honors.
            self.batcher.poison(e)
            self._close(conn)

    def _eof(self, conn: _Connection) -> None:
        """Peer closed its half: flush what can be flushed, then close."""
        try:
            if conn.pending is not None:
                # Mid-frame disconnect: the partial frame's rows were
                # never admitted — no misattribution possible — but the
                # stream was structurally cut, which is a decode error.
                conn.pending = None
                self._count_decode_error()
            elif conn.buf.strip():
                # A trailing v1 line without its newline (the original
                # thread-per-connection ingress admitted it too).
                if conn.buf[0] == wire.MAGIC_BYTE:
                    self._count_decode_error()  # truncated frame header
                else:
                    self._process_text(
                        conn,
                        [conn.buf.decode("utf-8", errors="replace")],
                    )
        except _ProtocolReject:
            pass
        finally:
            self._close(conn)

    def _consume(self, conn: _Connection) -> None:
        """Drain complete messages from ``conn.buf`` (the framing state
        machine; partial messages stay buffered)."""
        buf = conn.buf
        n = len(buf)
        pos = 0
        while pos < n:
            if conn.pending is not None:
                # Payload bytes that arrived in the same recv as the
                # header (or as trailing text): copy the overlap into the
                # frame buffer; steady-state payload traffic bypasses
                # this via the recv_into fast path in _service.
                header, payload, filled = conn.pending
                take = min(n - pos, len(payload) - filled)
                payload[filled : filled + take] = memoryview(buf)[
                    pos : pos + take
                ]
                filled += take
                pos += take
                if filled == len(payload):
                    conn.pending = None
                    self._finish_frame(conn, header, payload)
                else:
                    conn.pending = (header, payload, filled)
                continue
            if buf[pos] == wire.MAGIC_BYTE:
                if n - pos < wire.HEADER_SIZE:
                    # Partial header: validate the bytes already here so
                    # a garbage burst fails NOW (ERR + close) instead of
                    # silently waiting for a header that never completes.
                    avail = n - pos
                    if (
                        avail >= 2 and buf[pos + 1] != wire.MAGIC >> 8
                    ) or (avail >= 3 and buf[pos + 2] != wire.VERSION):
                        self._reject(
                            conn,
                            wire.WireError(
                                "bad frame magic/version in partial header"
                            ),
                        )
                    break  # plausible prefix — wait for more bytes
                try:
                    header = wire.decode_header(
                        memoryview(buf)[pos : pos + wire.HEADER_SIZE],
                        max_rows=self.max_frame_rows,
                    )
                except wire.WireError as e:
                    self._reject(conn, e)
                pos += wire.HEADER_SIZE
                if header.is_control:
                    # Through the work queue (like the FLUSH/STOP text
                    # lines): controls must act AFTER the admissions
                    # queued before them.
                    if header.flags & wire.FLAG_FLUSH:
                        self._work.put(self.batcher.flush)
                    if header.flags & wire.FLAG_STOP:
                        self._work.put(self.on_stop)
                    continue
                if header.payload_nbytes == 0:  # unreachable; defensive
                    continue
                # Contract validation BEFORE the payload buffer exists:
                # the decoder's geometry bounds alone still admit a
                # hostile header declaring max_rows × MAX_FRAME_FEATURES
                # (a quarter-terabyte allocation). The daemon's own row
                # contract is known right here, so a frame that cannot
                # possibly admit must be refused pre-allocation — that is
                # the documented no-OOM guarantee (config.max_frame_rows).
                try:
                    tenant = self.check_tenant(header.tenant)
                except (ValueError, IndexError) as e:
                    self._reject(conn, e)
                expect = self.admissions[tenant].num_features
                if header.features != expect:
                    self._reject(
                        conn,
                        wire.WireError(
                            f"frame declares {header.features} feature(s); "
                            f"this daemon serves {expect}"
                        ),
                    )
                # np.empty, not bytearray: the payload is overwritten
                # from the socket, so the zero-fill would be pure memset
                # waste at ingest rates.
                conn.pending = (
                    header, np.empty(header.payload_nbytes, np.uint8), 0
                )
                continue
            # Text region: batch every complete line up to the next
            # message boundary that opens a frame (v1 clients never send
            # one, so their whole recv block admits as a single batch —
            # byte-for-byte the original ingress semantics, and the same
            # bulk rfind + one decode + one split per recv block, not a
            # per-line Python loop — the v1 ingest ceiling must not move).
            cut = buf.rfind(b"\n", pos)
            if cut < 0:
                break  # partial trailing line
            chunk = bytes(buf[pos:cut])
            if _MAGIC_BYTES not in chunk:  # pure text — one C-level scan
                self._process_text(
                    conn, chunk.decode("utf-8", errors="replace").split("\n")
                )
                pos = cut + 1
                continue
            # Rare: a magic byte inside the complete-lines region. Only a
            # line that OPENS with it is a frame boundary — a mid-line
            # 0xF2 is ordinary (dirty) text, exactly like the original
            # per-line ingress. Admit text up to the first frame opener.
            raw = chunk.split(b"\n")
            stop = next(
                (i for i, rl in enumerate(raw) if rl[:1] == _MAGIC_BYTES),
                None,
            )
            if stop is None:
                self._process_text(
                    conn,
                    [rl.decode("utf-8", errors="replace") for rl in raw],
                )
                pos = cut + 1
                continue
            if stop:
                self._process_text(
                    conn,
                    [
                        rl.decode("utf-8", errors="replace")
                        for rl in raw[:stop]
                    ],
                )
            pos += sum(len(rl) + 1 for rl in raw[:stop])
            # buf[pos] is now the frame opener — the next iteration's
            # magic-byte branch parses it.
        del buf[:pos]

    # -- v2 frames -----------------------------------------------------------

    def _finish_frame(self, conn: _Connection, header, payload) -> None:
        """One complete data frame (tenant + feature count were validated
        in _consume, before the payload buffer was even allocated): queue
        the vectorized frame admission for the admitter stage."""
        admission = self.admission_for(header.tenant)
        X, y = wire.payload_views(header, payload)
        traces = None
        if self.sampler:
            # Daemon-side head sampling (fresh root traces) — frames
            # carry no TRACE stamps, so the daemon's sampler is the one
            # trace source on the v2 path. Decided here, on the loop
            # thread, so sampling order matches arrival order.
            traces = [
                (i, *self.sampler.new_context())
                for i in self.sampler.sample_block(header.rows)
            ] or None

        def task() -> None:
            try:
                res = admission.admit_frame(X, y, traces=traces)
            except BaseException as e:
                # The daemon must die loudly on an ingress-path failure
                # (the armed serve.ingress fault is the rehearsal):
                # poison the batcher so the serve loop re-raises, tell
                # the client, and end this connection.
                self.batcher.poison(e)
                self._send(conn, f"ERR {type(e).__name__}: {e}")
                raise
            self.frames_v2 += 1
            if self._c_frames is not None:
                self._c_frames.inc(version="v2")
            if res.get("error"):
                self._send(conn, "ERR " + res["error"])

        self._work.put(task)

    # -- v1 text lines (semantics unchanged from the threaded ingress) ------

    def _process_text(self, conn: _Connection, lines: list[str]) -> None:
        block: list[str] = []
        marks: list[tuple] = []  # (block index, trace_id, span_id)
        for ln in lines:
            s = ln.strip()
            if not s:
                continue
            if s.startswith("TENANT"):
                # Any TENANT-prefixed line is a routing directive: no data
                # row starts with it (CSV rows open with a digit/sign,
                # JSON with {/[), so a malformed one ('TENANT', 'TENANT x')
                # must reject loudly here — falling through as a dirty
                # data row would leave every following row silently
                # routed to the PREVIOUS tenant's slot. Admit what
                # accumulated under the previous tenant first — blocks
                # are per-tenant by construction.
                self._admit(conn, block, marks)
                block, marks = [], []
                try:
                    conn.tenant = self.check_tenant(int(s[6:].strip()))
                except (ValueError, IndexError) as e:
                    # Untrusted client input: reject THIS connection
                    # (ERR + close), never the daemon — one client's
                    # typo must not take down the other tenants.
                    self._reject(conn, e)
            elif s.startswith("TRACE"):
                # Same no-data-row-starts-with-it argument as TENANT: a
                # malformed TRACE must reject here, or it would parse as
                # a dirty data row and silently shift positions.
                try:
                    conn.trace_next = self.check_trace(s)
                except (ValueError, IndexError) as e:
                    self._reject(conn, e)
            elif s.startswith(("SAVETENANT", "LOADTENANT")):
                # Migration control lines (serve.router): `SAVETENANT
                # <slot> <path>` drains slot state into a solo-shaped
                # checkpoint, `LOADTENANT <slot> <path>` installs one.
                # Same no-data-row-starts-with-it argument as TENANT —
                # malformed control must reject loudly, never admit as a
                # dirty row. Admit what accumulated first (wire order),
                # then ride the work queue so the request lands strictly
                # after the admissions before it; the serve loop executes
                # it and replies OK/ERR on this connection.
                self._admit(conn, block, marks)
                block, marks = [], []
                parts = s.split(maxsplit=2)
                try:
                    if self.on_control is None:
                        raise ValueError(
                            "tenant control surface not enabled on this "
                            "daemon"
                        )
                    if len(parts) != 3:
                        raise ValueError(
                            f"{parts[0]} needs exactly "
                            f"'{parts[0]} <slot> <path>'"
                        )
                    op, slot, path = (
                        parts[0],
                        self.check_tenant(int(parts[1])),
                        parts[2],
                    )
                except (ValueError, IndexError) as e:
                    self._reject(conn, e)

                def ctrl(op=op, slot=slot, path=path, conn=conn):
                    self.on_control(
                        op, slot, path,
                        lambda line: self._send(conn, line),
                    )

                self._work.put(ctrl)
            elif s == "FLUSH":
                self._admit(conn, block, marks)
                block, marks = [], []
                # Through the work queue: the flush must seal AFTER the
                # rows queued before it have admitted.
                self._work.put(self.batcher.flush)
            elif s == "STOP":
                self._admit(conn, block, marks)
                block, marks = [], []
                self._work.put(self.on_stop)
            else:
                if conn.trace_next is not None:
                    marks.append((len(block), *conn.trace_next))
                    conn.trace_next = None
                block.append(s)
        self._admit(conn, block, marks)

    def _admit(
        self, conn: _Connection, block: list[str], marks=None
    ) -> None:
        if not block:
            return
        if self.sampler:
            # Daemon-side head sampling of unstamped rows: fresh root
            # traces, one decision batch per ingress block. Rate 0 makes
            # the sampler falsy — this branch costs one bool check.
            # Decided on the loop thread so order matches arrival.
            stamped = {i for i, *_ in marks} if marks else set()
            fresh = [
                (i, *self.sampler.new_context())
                for i in self.sampler.sample_block(len(block))
                if i not in stamped
            ]
            if fresh:
                marks = sorted((marks or []) + fresh)
        # Tenant routing resolves NOW (the TENANT line that set it was
        # processed in order on this thread); the sanitize + push runs on
        # the admitter stage.
        admission = self.admission_for(conn.tenant)
        traces = marks or None

        def task() -> None:
            try:
                res = admission.admit_lines(block, traces=traces)
            except BaseException as e:
                # Same loud-death contract as the frame path.
                self.batcher.poison(e)
                self._send(conn, f"ERR {type(e).__name__}: {e}")
                raise
            self.frames_v1 += 1
            if self._c_frames is not None:
                self._c_frames.inc(version="v1")
            if res.get("error"):
                self._send(conn, "ERR " + res["error"])

        self._work.put(task)

    # -- replies / rejection -------------------------------------------------

    def _reject(self, conn: _Connection, exc: BaseException) -> "None":
        """Protocol violation on ``conn``: count it, answer ``ERR``, and
        raise :class:`_ProtocolReject` (the loop closes the connection)."""
        self._count_decode_error()
        self._send(conn, f"ERR {type(exc).__name__}: {exc}")
        raise _ProtocolReject from exc

    def _count_decode_error(self) -> None:
        self.decode_errors += 1
        if self._c_decode is not None:
            self._c_decode.inc()

    def _send(self, conn: _Connection, line: str) -> None:
        try:
            conn.sock.sendall((line + "\n").encode())
        except (BlockingIOError, InterruptedError, OSError):
            pass  # client already gone/stalled; the counters carry the evidence
