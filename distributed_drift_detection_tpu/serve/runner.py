"""The always-on serving loop: AOT-prepared chunked detection over live
admitted traffic, with verdict publication, checkpointed state, and a
graceful drain.

    python -m distributed_drift_detection_tpu serve \\
        --features 27 --classes 10 --telemetry-dir runs/live [...]

One :class:`ServeRunner` owns the whole lifecycle:

* **prepare** — ``api.prepare_chunked`` resolves the RunConfig into an
  AOT-warmed :class:`~..engine.chunked.ChunkedDetector` (both chunk
  shapes compiled before the first row arrives; with
  ``RunConfig.compile_cache_dir`` a restarted daemon warm-starts from the
  persistent cache), and a checkpoint at ``ServeParams.checkpoint``
  restores the detector carry + stream position — the kill-and-resume
  contract.
* **serve** — sealed microbatches from the admission layer feed the
  detector through the donated ``place()`` double-buffer (chunk k+1's
  host→device upload dispatches while chunk k computes; pipeline depth
  drops to 1 when ``checkpoint_every == 1`` so every checkpoint describes
  exactly the published prefix). Each chunk's **verdict** — detection
  count, per-partition change positions, stream-position accounting — is
  appended to a ``<run-log>.verdicts.jsonl`` sidecar (flushed per line,
  torn-tail tolerant like every sink here), and the run log receives the
  same ``chunk_completed`` / ``heartbeat`` / ``drift_detected`` events a
  batch run would — so ``watch``, ``report`` and ``correlate`` work
  unchanged against a live service.
* **drain** — SIGTERM/SIGINT (or the protocol ``STOP`` line) stops the
  ingress, flushes the partial microbatch through the validity plane,
  publishes everything in flight, writes an atomic final checkpoint, and
  flips the registry record to ``completed``.
* **trace plane** (telemetry.tracing/.forensics) — head-sampled rows
  (client ``TRACE`` wire lines, or the daemon's own ``--trace-sample``)
  carry a trace context through admission → microbatcher → kernel →
  verdict: each stage attaches a child ``span`` event to the run log and
  the verdict record lists the chunk's trace ids, so a verdict joins
  back to its originating packet (render with the ``timeline`` CLI). On
  a drift verdict, ``--forensics`` (default on, needs a telemetry dir)
  extracts an evidence bundle host-side — error-rate trajectory,
  warn/drift thresholds, the detector window stats entering the firing
  chunk, pre/post context rows, sampled trace ids — into
  ``<run-log>.forensics/`` (render with the ``explain`` CLI; counted in
  ``/statusz``). Sampling off + forensics off leaves the hot path
  untouched.
* **ops plane** (``--ops-port``, telemetry.ops/.slo/.trace) — a threaded
  HTTP server exposes the **live** metrics registry (``/metrics``,
  byte-identical to the ``.prom`` exporter), a drain/poison/stall-aware
  health check (``/healthz``: 200 healthy or draining, 503 while an SLO
  alert fires or the ingress poisoned the batcher) and a JSON
  ``/statusz`` snapshot. Every published microbatch feeds the
  ``serve_row_latency_seconds{stage=...}`` histograms from the admission
  layer's per-row monotonic ingest stamps (admission/queue/device/
  collect/total), so live p50/p99 row→verdict latency needs no post-hoc
  sidecar tailing. A background SLO evaluator turns declarative rules
  (``--slo p99_ms=250`` ...) into schema-v1 ``alert`` events, and a
  bounded flight recorder dumps the last N events to
  ``<run-log>.flightrec.jsonl`` on a crash — never on a clean drain.

The ``serve.flush`` fault site fires at verdict publication —
``kind='raise'`` kills the daemon after a chunk's state advanced but
before its verdict/checkpoint landed (the crash the resume test
rehearses); ``torn_write`` tears the verdict sidecar's trailing line.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import threading
import time

import numpy as np

from ..config import (
    RunConfig,
    ServeParams,
    host_shuffle_seed,
    replace,
    telemetry_config_payload,
)
from ..resilience import faults
from .admission import AdmissionController, MicroBatcher

VERDICT_VERSION = 1

VERDICT_SUFFIX = ".verdicts.jsonl"


def reconcile_torn_tail(path: str) -> bool:
    """Drop a torn trailing line (no final newline) from an append-only
    JSONL sidecar; returns True when something was truncated.

    A crash mid-append leaves a partial last line — tolerable to every
    reader here (``allow_partial_tail``) *as long as it stays the last
    line*. A resumed daemon about to APPEND must remove it first, or the
    next record would concatenate into a permanently corrupt interior
    line no reader tolerates."""
    if not os.path.exists(path):
        return False
    with open(path, "rb+") as fh:
        data = fh.read()
        if not data or data.endswith(b"\n"):
            return False
        cut = data.rfind(b"\n")
        fh.truncate(cut + 1)
    return True


def find_verdicts(telemetry_dir: str) -> "str | None":
    """Newest verdict sidecar in a telemetry directory (mtime order) —
    how ``loadgen`` locates a live daemon's verdict stream."""
    paths = glob.glob(os.path.join(telemetry_dir, "*" + VERDICT_SUFFIX))
    return max(paths, key=os.path.getmtime) if paths else None


def read_verdicts(path: str, *, allow_partial_tail: bool = True) -> list[dict]:
    """Parse a verdict sidecar; tolerates one torn trailing line (the
    writer flushes per line — same crash/live-tail contract as the event
    log and quarantine sidecars)."""
    records = []
    with open(path) as fh:
        lines = fh.readlines()
    for lineno, line in enumerate(lines, 1):
        stripped = line.strip()
        if not stripped:
            continue
        try:
            rec = json.loads(stripped)
        except json.JSONDecodeError:
            if allow_partial_tail and lineno == len(lines):
                break
            raise ValueError(f"{path}:{lineno}: corrupt verdict record")
        if isinstance(rec, dict) and rec.get("kind") == "verdict":
            records.append(rec)
    return records


class ServeRunner:
    """Lifecycle owner of one serving daemon (see module docstring).

    ``keep_flags=True`` additionally accumulates every published chunk's
    host flag table — the in-process embedding tests use it for
    bit-parity against ``api.run``; a production daemon leaves it off
    (unbounded memory on an unbounded stream).
    """

    def __init__(
        self,
        cfg: RunConfig,
        params: ServeParams,
        *,
        keep_flags: bool = False,
        max_chunks: "int | None" = None,
    ):
        if params.num_features <= 0 or params.num_classes <= 0:
            raise ValueError(
                "ServeParams.num_features/num_classes must be explicit "
                f"(> 0), got {params.num_features}/{params.num_classes}"
            )
        self.cfg = replace(cfg, app_name=cfg.app_name or "serve")
        self.params = params
        self._stop = threading.Event()
        self._keep = [] if keep_flags else None
        self._max_chunks = max_chunks
        self.det = None
        # Multi-tenant serving (RunConfig.tenants > 1): the detector is
        # the stacked [T·P, CB, B] chunk program, the batcher the
        # per-tenant TenantMicroBatcher, and `admissions` holds one
        # AdmissionController per tenant (own running stats, own
        # quarantine sidecar, shared counters) — the ingress TENANT line
        # routes a connection's rows to its slot. `admission` stays the
        # single controller on a solo daemon (tenant 0's otherwise) so
        # existing drivers keep working.
        self.tenants = max(int(cfg.tenants), 1)
        # Global tenant identity per slot (ServeParams.tenant_ids; the
        # fleet posture): slot s serves global tenant tenant_ids[s] with
        # THAT tenant's solo seed + stripe shuffle seed; -1 = vacant
        # spare (masked, migration landing capacity). Mutable — a
        # LOADTENANT installs the shipped tenant's identity into the
        # landing slot.
        if params.tenant_ids:
            if len(params.tenant_ids) != self.tenants:
                raise ValueError(
                    f"{len(params.tenant_ids)} tenant_ids for "
                    f"{self.tenants} tenant slot(s)"
                )
            self.tenant_ids = [int(i) for i in params.tenant_ids]
            active = [i for i in self.tenant_ids if i >= 0]
            if len(set(active)) != len(active):
                raise ValueError(
                    f"duplicate global tenant ids in {self.tenant_ids}"
                )
        else:
            self.tenant_ids = list(range(self.tenants))
        self.batcher: "MicroBatcher | None" = None
        self.admission: "AdmissionController | None" = None
        self.admissions: "list[AdmissionController]" = []
        self._ingress = None
        self._log = None
        self._metrics = None
        self._lat_hist = None
        self._ops = None
        self._slo = None
        self._slo_stop = None
        self._slo_thread = None
        self._recorder = None
        self._incidents = None  # telemetry.incident.IncidentRecorder
        self._compile_info: dict = {}
        self._last_pub_mono: "float | None" = None
        self._loop_mono: "float | None" = None  # serve-loop liveness stamp
        # Wedged-stage breadcrumb: (stage name, mono stamp) set at every
        # stage boundary REUSING the boundary's existing clock read —
        # zero extra hot-loop clock calls. Mid-stall the busy counters
        # haven't been credited yet (they land when the stage *ends*),
        # so an incident capture needs this to name the stage the loop
        # is wedged IN, not the one that last finished.
        self._loop_stage: "tuple[str, float] | None" = None
        # Pipeline observatory (telemetry.pipeline): stage busy clock,
        # wall/rows gauges, per-chunk stage-span tracer. All None when
        # params.pipeline_metrics is off — every touch point guards.
        self._stage_clock = None
        self._wall_gauge = None
        self._rows_gauge = None
        # Per-tenant hotness series (params.tenant_series): rows counter
        # labeled by GLOBAL tenant id — the history plane's ranking food.
        self._tenant_rows = None
        self._chunk_tracer = None
        self._loop_start_mono: "float | None" = None
        self._inflight_n = 0
        self._verdict_fh = None
        self.verdicts_path: "str | None" = None
        self._sampler = None  # daemon-side head sampler (trace plane)
        self._rows_traced = 0  # rows whose serving span chain was emitted
        self._forensics = None  # telemetry.forensics.ForensicsExtractor
        self._adapt = None  # adapt.refit.AdaptationController
        self._flag_base = 0  # flag columns published == batches published
        self._published = 0  # chunks published this process
        self._ckpt_at = 0
        self._rows_published = 0
        self._detections = 0
        self._last_meta: "dict | None" = None
        # slot → (stream_row, rows_admitted) installed by a LOADTENANT
        # and not yet covered by a publish: _last_meta still describes
        # the PREVIOUS occupant there, so a SAVETENANT before the next
        # publish must use the restored accounting, not the stale meta
        # (else the shipped watermark under-claims and the router
        # re-feeds rows the carry already saw)
        self._restored_accounting: "dict[int, tuple[int, int]]" = {}
        self._t_start: "float | None" = None
        self.resumed_meta: "dict | None" = None
        # Tenant-migration control surface (SAVETENANT/LOADTENANT wire
        # lines, serve.router): requests queue here (admitter thread) and
        # the serve LOOP executes them once everything sealed before the
        # request has been published — carry surgery must never race a
        # feed, and a saved slot must describe exactly the published
        # prefix. Each entry carries the batcher's seal watermark at
        # request time; `_example` is the zero-row chunk restore_tenant
        # rebuilds a fresh plane from.
        self._control: "list[dict]" = []
        self._control_lock = threading.Lock()
        self._example = None
        # Pipeline depth: 2 = double-buffered (chunk k+1 uploads while k
        # computes); 1 when every chunk checkpoints, so the carry on disk
        # always describes exactly the published verdict prefix.
        self._depth = (
            1 if (params.checkpoint and params.checkpoint_every <= 1) else 2
        )

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> dict:
        """Open telemetry, restore state, AOT-prepare, start the ingress;
        returns the startup banner (host/port/artifact paths)."""
        from ..api import prepare_chunked
        from ..io.stream import stripe_chunk
        from ..telemetry import trace
        from ..telemetry.metrics import MetricsRegistry
        from ..telemetry.ops import FlightRecorder
        from ..telemetry.slo import SloEngine, parse_rules, start_evaluator

        cfg, params = self.cfg, self.params
        self._t_start = time.monotonic()
        # The registry is live regardless of telemetry persistence: the
        # ops plane scrapes it over HTTP; write_exports at drain still
        # requires a telemetry dir.
        self._metrics = MetricsRegistry()
        self._lat_hist = trace.latency_histogram(self._metrics)
        if params.pipeline_metrics:
            from ..telemetry.pipeline import (
                SERVE_ROWS_HELP,
                SERVE_ROWS_METRIC,
                SERVE_WALL_HELP,
                SERVE_WALL_METRIC,
                ServeStageClock,
            )

            self._stage_clock = ServeStageClock(self._metrics)
            self._wall_gauge = self._metrics.gauge(
                SERVE_WALL_METRIC, help=SERVE_WALL_HELP
            )
            self._rows_gauge = self._metrics.gauge(
                SERVE_ROWS_METRIC, help=SERVE_ROWS_HELP
            )
        if params.flightrec_events > 0:
            self._recorder = FlightRecorder(params.flightrec_events)
        if params.tenant_series:
            # Cardinality guard: per-tenant label values multiply every
            # scrape forever — refuse loudly rather than melt the store.
            from ..telemetry.history import (
                TENANT_ROWS_HELP,
                TENANT_ROWS_METRIC,
            )

            if self.tenants > params.tenant_series_max:
                raise ValueError(
                    f"--tenant-series refused: {self.tenants} tenants "
                    f"exceed tenant_series_max={params.tenant_series_max} "
                    "(raise the cap explicitly if you mean it)"
                )
            self._tenant_rows = self._metrics.counter(
                TENANT_ROWS_METRIC, help=TENANT_ROWS_HELP
            )
            for gid in self.tenant_ids:
                # pre-register every tenant at 0 so the series (and its
                # HELP line) is scrapeable before the first publish
                self._tenant_rows.inc(0.0, tenant=str(int(gid)))
        ident = None
        if cfg.telemetry_dir:
            from ..parallel.multihost import host_identity
            from ..telemetry.events import EventLog

            ident = host_identity()
            self._log = EventLog.open_run(
                cfg.telemetry_dir,
                name=cfg.resolved_app_name(),
                process_index=ident["process_index"],
            )
            if self._recorder is not None:
                self._log.tap = self._recorder.record
        stem = (
            os.path.splitext(self._log.path)[0]
            if self._log is not None
            else "serve"
        )
        self.verdicts_path = stem + VERDICT_SUFFIX

        self.det, compile_info = prepare_chunked(
            cfg,
            params.num_features,
            params.num_classes,
            chunk_batches=params.chunk_batches,
            # Slot s's detector seed is its GLOBAL tenant's solo seed
            # (identity mapping unless ServeParams.tenant_ids says
            # otherwise); a vacant spare keeps its positional seed — its
            # state is overwritten by the LOADTENANT that fills it.
            tenant_seeds=[
                cfg.seed + (s if self.tenant_ids[s] < 0 else self.tenant_ids[s])
                for s in range(self.tenants)
            ],
        )
        self._compile_info = dict(compile_info)
        example = stripe_chunk(
            np.zeros((0, params.num_features), np.float32),
            np.zeros((0,), np.int32),
            0,
            cfg.partitions,
            cfg.per_batch,
            params.chunk_batches,
        )
        if self.tenants > 1:
            from ..engine.loop import stack_tenants

            example = stack_tenants([example] * self.tenants)
        self._example = example
        resume = None
        if params.checkpoint and os.path.exists(params.checkpoint):
            resume = self.det.restore(params.checkpoint, example_chunk=example)
            if int(resume.get("tenants", 1)) != self.tenants:
                raise ValueError(
                    f"checkpoint {params.checkpoint} holds "
                    f"{resume.get('tenants', 1)} tenant(s); this daemon "
                    f"serves {self.tenants} — tenant planes must match "
                    "(migrate slots via ChunkedDetector.save_tenant)"
                )
            self.det.rows_done = int(resume.get("rows_done", 0))
            self._flag_base = int(resume.get("flag_cols", 0))
            self._published = int(resume.get("chunk_index", 0))
            self._ckpt_at = self._published
            self._rows_published = int(resume.get("rows_admitted", 0))
            self._detections = int(resume.get("detections", 0))
            self.resumed_meta = resume
        # A FRESH daemon starts a fresh verdict stream: truncate, so a
        # reused (untelemetered) path from an earlier run cannot leave a
        # non-monotone rows_through sequence behind. A resumed daemon
        # appends — its records continue the previous accounting — after
        # dropping any torn trailing line the crash left, so the resume
        # never manufactures a corrupt interior line.
        # (Telemetered daemons get unique per-run-log stems either way.)
        if resume is not None:
            reconcile_torn_tail(self.verdicts_path)
        self._verdict_fh = open(
            self.verdicts_path, "a" if resume is not None else "w"
        )
        if params.trace_sample > 0:
            from ..telemetry.tracing import HeadSampler

            self._sampler = HeadSampler(params.trace_sample, seed=cfg.seed)
        if (
            params.pipeline_metrics
            and params.trace_sample > 0
            and self._log is not None
        ):
            # Per-chunk stage spans on the trace plane: each sampled
            # chunk's feed/device/collect/publish windows share one
            # trace, laid out next to the row-level serving spans.
            from ..telemetry.tracing import ChunkTracer

            # seed offset: the row-tracing sampler above seeds its rng
            # with cfg.seed too — the same stream would mint identical
            # trace ids, welding chunk spans onto row traces
            self._chunk_tracer = ChunkTracer(
                self._log, params.trace_sample, seed=cfg.seed + 0x5EED
            )
        if params.forensics and self._log is not None:
            from ..telemetry.forensics import (
                FORENSICS_SUFFIX,
                ForensicsExtractor,
            )

            self._forensics = ForensicsExtractor(
                stem + FORENSICS_SUFFIX,
                run_id=self._log.run_id,
                detector_params={
                    "detector": cfg.detector,
                    **getattr(cfg, cfg.detector)._asdict(),
                },
                tenants=self.tenants,
                metrics=self._metrics,
            )
        if self.tenants > 1:
            from .admission import TenantMicroBatcher, _TenantSlot

            self.batcher = TenantMicroBatcher(
                self.tenants,
                cfg.partitions,
                cfg.per_batch,
                params.chunk_batches,
                num_features=params.num_features,
                # slot s stripes with its GLOBAL tenant's solo shuffle
                # seed (seed + tenant_ids[s]; identity mapping by
                # default) — the bit-parity contract with solo
                # daemons/batch runs, fleet-placement-invariant
                shuffle_seeds=[
                    host_shuffle_seed(self._slot_identity_cfg(s))
                    for s in range(self.tenants)
                ],
                linger_s=params.linger_s,
                # Serve meta is optional, like the solo path's .get()s: a
                # detector-plane checkpoint (ChunkedDetector.save carries
                # `tenants` but no batcher accounting) resumes detector
                # state with fresh positions, not a KeyError at startup.
                start_rows=(
                    [int(s) for s in resume["stream_rows"]]
                    if resume and "stream_rows" in resume
                    else None
                ),
                chunk_index=(
                    int(resume.get("chunk_index", 0)) if resume else 0
                ),
                rows_admitted=(
                    [int(r) for r in resume["t_rows_admitted"]]
                    if resume and "t_rows_admitted" in resume
                    else None
                ),
            )
            def _tenant_qpath(t: int) -> str:
                # Per-tenant sidecar: quarantine records must stay
                # attributable to the tenant that shipped the row — an
                # explicit path gets the same .t<k> suffix the derived
                # stem does, never one interleaved file for the plane.
                if cfg.quarantine_path:
                    root, ext = os.path.splitext(cfg.quarantine_path)
                    return f"{root}.t{t}{ext or '.jsonl'}"
                return stem + f".t{t}.quarantine.jsonl"

            self.admissions = [
                AdmissionController(
                    _TenantSlot(self.batcher, t),
                    params.num_features,
                    params.num_classes,
                    policy=cfg.data_policy,
                    quarantine_path=_tenant_qpath(t),
                    metrics=self._metrics,
                    source=f"ingress[t{t}]",
                )
                for t in range(self.tenants)
            ]
            self.admission = self.admissions[0]
        else:
            self.batcher = MicroBatcher(
                cfg.partitions,
                cfg.per_batch,
                params.chunk_batches,
                # a solo daemon serving one GLOBAL fleet tenant (single-
                # slot backend, tenant_ids=(g,)) stripes with that
                # tenant's identity; the default is host_shuffle_seed(cfg)
                shuffle_seed=host_shuffle_seed(self._slot_identity_cfg(0)),
                linger_s=params.linger_s,
                start_row=int(resume.get("stream_row", 0)) if resume else 0,
                chunk_index=(
                    int(resume.get("chunk_index", 0)) if resume else 0
                ),
                rows_admitted=(
                    int(resume.get("rows_admitted", 0)) if resume else 0
                ),
            )
            self.admission = AdmissionController(
                self.batcher,
                params.num_features,
                params.num_classes,
                policy=cfg.data_policy,
                quarantine_path=(
                    cfg.quarantine_path or stem + ".quarantine.jsonl"
                ),
                metrics=self._metrics,
            )
            self.admissions = [self.admission]
        # Adaptation plane (adapt/ subsystem): consume drift verdicts per
        # the per-tenant --on-drift policy. No spec (or all alert_only)
        # builds nothing at all — the policy-free daemon is byte-identical
        # to one that never imported the package.
        from ..adapt.policy import resolve_policies

        policies = resolve_policies(params.on_drift, self.tenants)
        if any(p.active for p in policies):
            from ..adapt.refit import ADAPT_STATE_SUFFIX, AdaptationController

            self._adapt = AdaptationController(
                self.det,
                policies,
                per_batch=cfg.per_batch,
                num_features=params.num_features,
                rows_per_chunk=self.batcher.rows_per_chunk,
                log=self._log,
                metrics=self._metrics,
                seed=cfg.seed,
            )
            if params.checkpoint and resume is not None:
                # mid-adaptation state (window buffers, probation
                # champions) resumes next to the detector carry
                self._adapt.restore(params.checkpoint + ADAPT_STATE_SUFFIX)
            # warm the adaptation programs before traffic (AOT posture)
            self._adapt.prepare(params.chunk_batches)
        if self._log is not None:
            from ..telemetry import registry as run_registry

            payload = telemetry_config_payload(cfg)
            self._log.emit(
                "run_started",
                run_id=self._log.run_id,
                config=payload,
                serve={
                    "chunk_batches": params.chunk_batches,
                    "linger_s": params.linger_s,
                    "checkpoint": params.checkpoint,
                    "resumed": resume is not None,
                },
                **(ident or {}),
            )
            self._log.emit(
                "compile_completed",
                cached=compile_info.get("cached", False),
                seconds=compile_info.get("build_seconds", 0.0),
                aot_seconds=compile_info.get("aot_seconds", 0.0),
                aot_shapes=compile_info.get("aot_shapes", 0),
            )
            run_registry.record(
                cfg.telemetry_dir,
                self._log.run_id,
                "running",
                kind="serve",
                config_digest=run_registry.config_digest(payload),
                log=os.path.basename(self._log.path),
                resumed=resume is not None,
                **(ident or {}),
            )
        if params.port is not None:
            from .ingress import IngressServer

            self._ingress = IngressServer(
                params.host,
                params.port,
                self.admissions,
                self.batcher,
                self.request_stop,
                sampler=self._sampler,
                metrics=self._metrics,
                max_frame_rows=params.max_frame_rows,
                on_control=self.request_control,
            )
            self._ingress.start()
        # SLO engine + evaluator thread: the judge must not live on the
        # serve loop — the loop being wedged is what stall_s detects.
        rules = parse_rules(params.slo)
        # metrics= exports slo_alert_active{rule} gauges: a scraper (the
        # collector, top) sees live alert state, not just the log tail.
        self._slo = SloEngine(rules, metrics=self._metrics)
        # Incident autopsy plane: alert-triggered cross-plane evidence
        # capture (telemetry.incident). Rides the SLO evaluator thread
        # via the engine's observer hook — zero serve-loop work, and the
        # verdict sidecars stay bit-identical with it on or off. Needs a
        # run log (the bundle root is the run-log stem); a log-less
        # embed simply has no incident plane.
        if params.incidents and self._log is not None:
            from ..telemetry.incident import IncidentRecorder

            self._incidents = IncidentRecorder(
                stem,
                flight=self._recorder,
                statusz_fn=self._statusz,
                pipeline_fn=self.pipeline_snapshot,
                verdicts_path=self.verdicts_path,
                store=params.incident_store or None,
                window_s=params.incident_window_s,
                metrics=self._metrics,
                max_bundles=params.incident_max,
            )
            self._slo.observer = self._incidents.on_transition
        if rules:
            self._slo_thread, self._slo_stop = start_evaluator(
                self._slo,
                self._slo_snapshot,
                self._log.emit if self._log is not None else None,
                params.slo_interval_s,
            )
        if params.ops_port is not None:
            from ..telemetry.ops import OpsServer

            self._ops = OpsServer(
                params.host,
                params.ops_port,
                metrics_fn=self._metrics.to_prometheus_text,
                health_fn=self._health,
                status_fn=self._statusz,
                incidentz_fn=(
                    self._incidents.incidentz
                    if self._incidents is not None
                    else None
                ),
            )
            self._ops.start()
            if self._log is not None and cfg.telemetry_dir:
                # Second "running" record carrying the now-bound ops
                # address: registry.runs() MERGES extras per run_id, so
                # this augments (not replaces) the first record — the
                # collector's --registry discovery scrapes this field.
                from ..telemetry import registry as run_registry

                run_registry.record(
                    cfg.telemetry_dir,
                    self._log.run_id,
                    "running",
                    kind="serve",
                    ops=f"{params.host}:{self._ops.port}",
                    **({"name": params.name} if params.name else {}),
                )
        return {
            "serving": True,
            "tenants": self.tenants,
            "tenant_ids": list(self.tenant_ids),
            "name": params.name or None,
            "host": params.host,
            # both wire protocols are always live on the socket — the
            # per-connection state machine auto-detects per message
            "wire": ["v1", "v2"] if self._ingress is not None else None,
            "port": self._ingress.port if self._ingress is not None else None,
            "ops_port": self._ops.port if self._ops is not None else None,
            "pid": os.getpid(),
            "run_log": self._log.path if self._log is not None else None,
            "verdicts": self.verdicts_path,
            "checkpoint": params.checkpoint or None,
            "resumed": resume is not None,
            "on_drift": (
                [p.on_drift for p in policies]
                if self._adapt is not None
                else None
            ),
        }

    def request_stop(self) -> None:
        """Graceful drain (signal handlers and the STOP line land here).
        Thread-safe and idempotent; the serve loop performs the drain."""
        self._stop.set()

    # -- tenant-migration control surface (serve.router) ---------------------

    def _slot_identity_cfg(self, slot: int) -> RunConfig:
        """The solo config of the GLOBAL tenant slot ``slot`` serves —
        ``config.tenant_configs``' ``seed + id`` convention, so a fleet
        daemon's slot is bit-identical to that tenant's solo run wherever
        the router places it. A vacant spare keeps its positional
        identity (masked until a LOADTENANT installs a real one)."""
        from ..config import tenant_dataset

        g = self.tenant_ids[slot]
        g = slot if g < 0 else g
        return replace(
            self.cfg,
            tenants=1,
            seed=self.cfg.seed + g,
            dataset=tenant_dataset(self.cfg.dataset, g),
        )

    def request_control(self, op: str, slot: int, path: str, reply) -> None:
        """Queue a ``SAVETENANT``/``LOADTENANT`` request (the ingress
        admitter thread lands here, strictly AFTER the admissions queued
        before the control line — wire order is stream order). The serve
        loop executes it once every chunk sealed before this moment has
        been published, so a saved slot describes exactly the published
        verdict prefix; ``reply`` receives the one ``OK``/``ERR`` line."""
        with self._control_lock:
            self._control.append(
                {
                    "op": op,
                    "slot": int(slot),
                    "path": path,
                    "reply": reply,
                    # Seals so far (continues across resume, like
                    # _published): the request may run only once these
                    # are all published.
                    "watermark": self.batcher.chunk_index,
                }
            )

    def _run_controls(self) -> None:
        """Execute every queued control that has become safe (serve-loop
        thread only; FIFO, stopping at the first not-yet-due request so
        wire order is preserved).

        Safe means: every chunk sealed before the request has been
        *published* (the migrating tenant's rows were all sealed by the
        router's FLUSH, so its verdicts are complete up to the shipped
        state) and the seal queue is empty (a sealed-but-unfed chunk
        would leave the batcher's positions ahead of the carry — an
        inconsistent snapshot). Chunks *in flight* (fed, unpublished)
        are consistent — the carry and the positions both include them —
        and their verdicts still publish from this daemon afterwards.
        The router quiesces its forwarding to this backend around a
        migration, so both conditions drain within a poll interval; an
        embedder driving controls under sustained traffic must quiesce
        the same way."""
        while self._control:
            with self._control_lock:
                if not self._control:
                    return
                ctl = self._control[0]
                if (
                    self._published < ctl["watermark"]
                    or self.batcher.depth()["queued_chunks"]
                ):
                    return
                self._control.pop(0)
            line = self._handle_control(ctl["op"], ctl["slot"], ctl["path"])
            try:
                ctl["reply"](line)
            except Exception:
                pass  # requester gone; the state change stands either way

    def _handle_control(self, op: str, slot: int, path: str) -> str:
        """One SAVETENANT/LOADTENANT, pipeline already drained past the
        watermark. Failures answer ``ERR`` and leave the daemon serving —
        a router retrying a migration must not kill the backend."""
        try:
            if not 0 <= slot < self.tenants:
                raise ValueError(
                    f"slot {slot} out of range (daemon serves "
                    f"{self.tenants} tenant(s))"
                )
            buffered = self.batcher.tenant_state(slot)["buffered"]
            if buffered:
                # Checked BEFORE any state moves: a slot snapshot under
                # buffered (unsealed) rows would record rows_admitted
                # ahead of the carry, and a load would orphan them — the
                # router FLUSHes (and quiesces) before either op.
                raise RuntimeError(
                    f"slot {slot} holds {buffered} buffered row(s); "
                    "FLUSH before SAVETENANT/LOADTENANT"
                )
            if op == "SAVETENANT":
                if self.det.carry is None:
                    raise RuntimeError(
                        "no detector state yet (slot never saw traffic)"
                    )
                rows_admitted = self._save_tenant_slot(slot, path)
                return f"OK SAVETENANT {slot} {rows_admitted}"
            if op == "LOADTENANT":
                meta = self.det.restore_tenant(
                    path, slot, example_chunk=self._example
                )
                rows_admitted = int(meta.get("rows_admitted", 0))
                # Identity, then positions: the landing slot stripes
                # subsequent rows with the SHIPPED tenant's shuffle seed
                # and answers to its global id. A checkpoint without
                # identity meta (ChunkedDetector.save_tenant outside a
                # fleet daemon) keeps the slot's own.
                if "shuffle_seed" in meta:
                    seed = meta["shuffle_seed"]
                    self.batcher.set_tenant_identity(
                        slot, None if seed is None else int(seed)
                    )
                if "tenant_id" in meta:
                    self.tenant_ids[slot] = int(meta["tenant_id"])
                self.batcher.set_tenant_state(
                    slot,
                    int(meta.get("stream_row", 0)),
                    rows_admitted,
                )
                self._restored_accounting[slot] = (
                    int(meta.get("stream_row", 0)),
                    rows_admitted,
                )
                return f"OK LOADTENANT {slot} {rows_admitted}"
            raise ValueError(f"unknown control op {op!r}")
        except Exception as e:
            return f"ERR {op} {slot} {type(e).__name__}: {e}"

    def _save_tenant_slot(self, slot: int, path: str) -> int:
        """Write slot ``slot`` as a solo-shaped checkpoint carrying its
        stream accounting (the migration currency); returns the slot's
        ``rows_admitted`` watermark.

        The accounting comes from the last PUBLISHED chunk's meta, like
        the plane checkpoint's — the carry describes exactly the
        published prefix, and a watermark ahead of it (the batcher's
        admitted-side counters run ahead whenever rows are sealed or in
        flight) would make the router re-send NOTHING for the gap and
        silently lose those rows' verdicts past the checkpoint. The
        batcher counters are only used before the first publish (a
        freshly-resumed daemon, where admitted == published by the
        resume contract)."""
        meta = self._last_meta
        span = self.batcher.rows_per_chunk
        restored = self._restored_accounting.get(slot)
        if restored is not None:
            # landed by LOADTENANT, nothing published since: _last_meta
            # still describes the slot's PREVIOUS occupant — the shipped
            # checkpoint's accounting is the restore's, verbatim
            start_row, rows_admitted = restored
        elif meta is not None:
            if self.tenants > 1:
                start_row = int(meta["t_start_row"][slot]) + span
                rows_admitted = int(meta["t_rows_through"][slot])
            else:
                start_row = int(meta["start_row"]) + span
                rows_admitted = int(meta["rows_through"])
        else:
            st = self.batcher.tenant_state(slot)
            start_row = int(st["start_row"])
            rows_admitted = int(st["rows_admitted"])
        ident = self._slot_identity_cfg(slot)
        extra = {
            "stream_row": start_row,
            "rows_admitted": rows_admitted,
            # The migration currency's identity half: the landing slot
            # must answer to this global tenant and stripe with its solo
            # shuffle seed — placement-invariant bit-parity.
            "tenant_id": int(self.tenant_ids[slot]),
            "shuffle_seed": host_shuffle_seed(ident),
        }
        if self.params.name:
            extra["daemon"] = self.params.name
        self.det.save_tenant(path, slot, extra_meta=extra)
        return rows_admitted

    # -- ops-plane surface (read-only; served from the ops/evaluator
    # -- threads, so everything here reads GIL-atomic scalars or takes the
    # -- owning structure's lock) ---------------------------------------------

    @property
    def metrics(self):
        """The live registry (ops scrape target; bench reads quantiles)."""
        return self._metrics

    def _adm_totals(self) -> dict:
        """Pooled admission accounting across the tenant plane (a solo
        daemon's list holds its one controller)."""
        out = {
            "rows_seen": 0, "rows_quarantined": 0,
            "rows_rejected": 0, "rows_repaired": 0,
        }
        for a in self.admissions:
            out["rows_seen"] += a.rows_seen
            out["rows_quarantined"] += a.rows_quarantined
            out["rows_rejected"] += a.rows_rejected
            out["rows_repaired"] += a.rows_repaired
        return out

    def _slo_snapshot(self) -> dict:
        """Rule kind → current value (None = not measurable right now)."""
        from ..telemetry.trace import hist_quantile

        now = time.monotonic()
        p99 = hist_quantile(self._lat_hist, 0.99, stage="total")
        verdict_age = None
        if self._last_pub_mono is not None and (
            self.batcher is not None
            and self.batcher.rows_admitted > self._rows_published
        ):
            # Output staleness only means anything while work is pending:
            # an idle daemon's last verdict ages by design.
            verdict_age = now - self._last_pub_mono
        quarantine_pct = None
        adm = self._adm_totals() if self.admissions else None
        if adm is not None and adm["rows_seen"] > 0:
            quarantine_pct = (
                100.0 * adm["rows_quarantined"] / adm["rows_seen"]
            )
        # Loop liveness, not event age: works without a run log too (an
        # ops-only daemon must still tell wedged from idle), and any
        # wedge — device sync, publish, emit — blocks the loop thread.
        stall = None if self._loop_mono is None else now - self._loop_mono
        return {
            "p99_ms": None if p99 is None else p99 * 1000.0,
            "verdict_age_s": verdict_age,
            "quarantine_pct": quarantine_pct,
            "stall_s": stall,
        }

    def _health(self) -> "tuple[int, dict]":
        """The ``/healthz`` contract: (HTTP status, JSON payload)."""
        alerts = self._slo.active() if self._slo is not None else []
        poisoned = (
            self.batcher.poisoned() if self.batcher is not None else None
        )
        healthy = not alerts and poisoned is None
        payload = {
            "status": (
                ("draining" if self._stop.is_set() else "serving")
                if healthy
                else "degraded"
            ),
            "run_id": self._log.run_id if self._log is not None else None,
            "alerts": alerts,
            "poisoned": None if poisoned is None else repr(poisoned),
        }
        if any(
            a.get("rule") in ("stall_s", "p99_ms")
            or str(a.get("rule", "")).startswith("burn_rate:")
            for a in alerts
        ):
            # A wedged/slow loop names its dominant stage right in the
            # health body — the one-curl diagnosis the observatory owes.
            snap = self.pipeline_snapshot()
            if snap is not None and snap.get("dominant_stage"):
                payload["bottleneck_stage"] = snap["dominant_stage"]
        return (200 if healthy else 503), payload

    def pipeline_snapshot(self) -> "dict | None":
        """The ``/statusz`` ``pipeline`` section (also bench's
        ``serve_pipeline_s`` source): per-stage busy seconds + shares
        since the loop started, serve-loop wall, coverage (busy/wall),
        and the named dominant stage. ``None`` when the observatory is
        off (``--no-pipeline-metrics``). The busy dict is copied BEFORE
        the wall stamp, so busy-sum ≤ wall holds even against the live
        loop thread."""
        if self._stage_clock is None:
            return None
        from ..telemetry.pipeline import attribute

        busy = dict(self._stage_clock.busy)
        now = time.monotonic()
        wall = (
            now - self._loop_start_mono
            if self._loop_start_mono is not None
            else 0.0
        )
        attr = attribute(busy, wall, self._rows_published)
        # The wedged-stage breadcrumb: mid-stall, busy counters lag (a
        # stage is only credited when it ENDS), so the dominant stage
        # can misname a live wedge. current_stage is where the loop is
        # right now and for how long — the incident diagnoser's primary
        # witness for a stall.
        cur = self._loop_stage
        return {
            "busy_s": {s: round(t, 4) for s, t in sorted(busy.items())},
            "wall_s": round(wall, 4),
            "shares": {
                s: c["share"] for s, c in attr["stages"].items()
            },
            "coverage": attr.get("coverage"),
            "dominant_stage": attr["dominant_stage"],
            "current_stage": (
                {"stage": cur[0], "for_s": round(now - cur[1], 4)}
                if cur is not None
                else None
            ),
        }

    def _statusz(self) -> dict:
        """The ``/statusz`` snapshot (one JSON dict, cheap to assemble)."""
        from ..telemetry.trace import hist_quantile

        now = time.monotonic()
        batcher = self.batcher
        adm = self._adm_totals()
        p50 = hist_quantile(self._lat_hist, 0.5, stage="total")
        p99 = hist_quantile(self._lat_hist, 0.99, stage="total")
        # Per-slot stream accounting: the router's rebalance signal (a
        # hot slot's rows_admitted grows fastest; a backlogged one shows
        # buffered rows) and the fleet dashboard's per-tenant view.
        tenant_detail = None
        if batcher is not None:
            tenant_detail = [
                {
                    "tenant": t,
                    "id": self.tenant_ids[t],
                    **batcher.tenant_state(t),
                }
                for t in range(self.tenants)
            ]
        return {
            "run_id": self._log.run_id if self._log is not None else None,
            "name": self.params.name or None,
            "pid": os.getpid(),
            "uptime_s": (
                round(now - self._t_start, 3)
                if self._t_start is not None
                else None
            ),
            "draining": self._stop.is_set(),
            "tenants": self.tenants,
            "tenant_detail": tenant_detail,
            "rows": {
                "ingress_seen": adm["rows_seen"],
                "admitted": (
                    batcher.rows_admitted if batcher is not None else 0
                ),
                "published": self._rows_published,
                "quarantined": adm["rows_quarantined"],
                "rejected": adm["rows_rejected"],
                "repaired": adm["rows_repaired"],
            },
            "chunks": {
                "published": self._published,
                "inflight": self._inflight_n,
                **(batcher.depth() if batcher is not None else {}),
            },
            # Per-protocol ingress accounting (frames_v1/frames_v2/
            # decode_errors/connections); None on socketless embeddings.
            "ingress": (
                self._ingress.stats() if self._ingress is not None else None
            ),
            "rows_per_sec": (
                round(self._rows_published / (now - self._t_start), 3)
                if self._t_start is not None and now > self._t_start
                else 0.0
            ),
            "pipeline": self.pipeline_snapshot(),
            "detections": self._detections,
            "last_verdict_age_s": (
                None
                if self._last_pub_mono is None
                else round(now - self._last_pub_mono, 3)
            ),
            "latency_ms": {
                "p50": None if p50 is None else round(p50 * 1000.0, 3),
                "p99": None if p99 is None else round(p99 * 1000.0, 3),
            },
            "compile": {
                **self._compile_info,
                "compile_cache_dir": self.cfg.compile_cache_dir or None,
            },
            "checkpoint": self.params.checkpoint or None,
            "resumed": self.resumed_meta is not None,
            "alerts": self._slo.active() if self._slo is not None else [],
            "tracing": {
                "sample_rate": self.params.trace_sample,
                "rows_traced": self._rows_traced,
            },
            "forensics": {
                "enabled": self._forensics is not None,
                "bundles": (
                    self._forensics.bundles_written
                    if self._forensics is not None
                    else 0
                ),
            },
            "adaptation": (
                self._adapt.snapshot() if self._adapt is not None else None
            ),
            # Incident autopsy plane: bundle count + open alerts; None
            # when the plane is off (--no-incidents or no run log). The
            # collector lifts "count" into the fleet history store.
            "incidents": (
                self._incidents.statusz_section()
                if self._incidents is not None
                else None
            ),
        }

    # -- the loop ------------------------------------------------------------

    def serve_forever(self) -> int:
        """Run until a drain completes; returns 0. Exceptions (poisoned
        ingress, armed faults, device failures) record ``failed`` in the
        registry and propagate — a crashed daemon must read as crashed."""
        import jax  # noqa: F401  (placed/fed chunks are device work)

        params = self.params
        inflight: list[tuple] = []
        last_hb = time.monotonic()
        stop_handled = False
        try:
            while True:
                self._loop_mono = time.monotonic()  # SLO stall_s stamp
                if self._loop_start_mono is None:
                    self._loop_start_mono = self._loop_mono
                if self._stop.is_set() and not stop_handled:
                    stop_handled = True
                    if self._ingress is not None:
                        self._ingress.stop()
                    self.batcher.flush()
                wait_start = time.monotonic()
                # Wedged-stage breadcrumbs (pipeline_snapshot's
                # current_stage): each boundary reuses the clock read it
                # already takes — no extra hot-loop time calls.
                self._loop_stage = ("seal_wait", wait_start)
                item = self.batcher.get(0.0 if inflight else params.poll_s)
                if self._stage_clock is not None:
                    # seal_wait = the loop blocked for input; folding it
                    # here (not at publish) keeps an idle loop honest.
                    now = time.monotonic()
                    self._stage_clock.add("seal_wait", now - wait_start)
                    self._wall_gauge.set(now - self._loop_start_mono)
                if item is not None:
                    # Forensics: copy the detector state ENTERING this
                    # chunk before the feed donates the carry (an async
                    # device-side copy of a few [P] scalars; materialized
                    # host-side at publish, when the chunk's compute is
                    # done anyway). None when forensics is off.
                    entry = self._capture_entry()
                    feed_start = time.monotonic()
                    self._loop_stage = ("feed", feed_start)
                    flags = self.det.feed(self.det.place(item.chunk))
                    # Row-tracing stamp: the chunk entered the device
                    # pipeline (queue stage ends, device stage begins).
                    item.meta["fed_mono"] = time.monotonic()
                    if self._stage_clock is not None:
                        # feed = place()+feed() dispatch (h2d + enqueue;
                        # the device wait is accounted at publish)
                        item.meta["_feed_start_mono"] = feed_start
                        self._stage_clock.add(
                            "feed", item.meta["fed_mono"] - feed_start
                        )
                    inflight.append(
                        (
                            flags,
                            item.meta,
                            entry,
                            # the chunk's numpy-backed host copy, kept only
                            # while forensics needs its context rows or the
                            # adaptation plane its post-drift window rows
                            (
                                item.chunk
                                if self._forensics is not None
                                or self._adapt is not None
                                else None
                            ),
                        )
                    )
                self._inflight_n = len(inflight)
                if inflight and (item is None or len(inflight) >= self._depth):
                    self._publish(*inflight.pop(0))
                    self._inflight_n = len(inflight)
                    if (
                        params.checkpoint
                        and self._published - self._ckpt_at
                        >= max(params.checkpoint_every, 1)
                    ):
                        # A checkpoint must describe exactly the published
                        # prefix, and the donated carry always reflects the
                        # last FED chunk — so drain the pipeline first (one
                        # deliberate bubble per checkpoint_every chunks;
                        # depth 1 makes this a no-op).
                        while inflight:
                            self._publish(*inflight.pop(0))
                            self._inflight_n = len(inflight)
                        self._save_checkpoint()
                        self._ckpt_at = self._published
                if self._control and not inflight:
                    # Migration controls (SAVETENANT/LOADTENANT): run
                    # once the pipeline has published past each request's
                    # seal watermark — never mid-feed.
                    self._run_controls()
                if (
                    self._log is not None
                    and time.monotonic() - last_hb >= params.heartbeat_s
                ):
                    self.det.emit_heartbeat(self._log)
                    last_hb = time.monotonic()
                if (
                    self._max_chunks is not None
                    and self._published >= self._max_chunks
                ):
                    self._stop.set()
                if stop_handled and not inflight and self.batcher.empty():
                    break
            self._finish()
            return 0
        except BaseException:
            self._fail()
            raise

    def _capture_entry(self):
        """Device-side copy of the detector state entering the next chunk
        (forensics evidence; the copy is dispatched BEFORE the next feed
        donates the carry, so the buffers are still live). ``None`` when
        forensics is off or no carry exists yet (a fresh plane's first
        chunk enters with init state — the bundle's window stats are
        simply absent there)."""
        if self._forensics is None or self.det.carry is None:
            return None
        import jax
        import jax.numpy as jnp

        return jax.tree.map(jnp.copy, self.det.carry.ddm)

    def _publish(self, flags, meta: dict, entry=None, chunk=None) -> None:
        """Collect one chunk's flags host-side and publish its verdict
        (the row→verdict latency endpoint)."""
        import jax

        pub_start = time.monotonic()  # loop blocks on the device sync here
        self._loop_stage = ("device", pub_start)
        host = jax.tree.map(np.asarray, flags)
        collected_mono = time.monotonic()  # device stage ends here
        self._loop_stage = ("collect", collected_mono)
        cg = np.asarray(host.change_global)
        changed = cg >= 0
        changes = [
            [int(p), int(b), int(cg[p, b])]
            for b, p in zip(*np.nonzero(changed.T))
        ]
        record = {
            "v": VERDICT_VERSION,
            "kind": "verdict",
            "ts": time.time(),
            # Fleet identity (serve --name): the join key a router-fronted
            # fleet's sidecar readers use against the placement journal.
            **({"daemon": self.params.name} if self.params.name else {}),
            "chunk": meta["chunk"],
            "start_row": meta["start_row"],
            "rows": meta["rows"],
            "rows_through": meta["rows_through"],
            "short": meta["short"],
            "flag_base": self._flag_base,
            "cols": int(cg.shape[1]),
            "detections": int(changed.sum()),
            "changes": changes,
        }
        if self.tenants > 1 or self.params.tenant_ids:
            # Per-tenant verdict attribution: the top-level `changes` keep
            # STACKED partition indices (tenant t's partitions are rows
            # t·P..(t+1)·P−1 of the plane); each tenant entry re-indexes
            # its own changes tenant-locally and carries its own
            # rows/rows_through accounting — the loadgen's per-tenant
            # latency attribution key. A SOLO daemon in fleet posture
            # (--tenant-ids g) emits the one entry too — the fleet
            # verdict tail joins on the entries' global ids, so a
            # single-tenant backend's verdicts must carry one (its solo
            # MicroBatcher meta lacks the t_* vectors; the whole-plane
            # accounting IS that tenant's).
            p_per = cg.shape[0] // self.tenants
            t_rows = meta.get("t_rows") or [meta["rows"]]
            t_through = meta.get("t_rows_through") or [meta["rows_through"]]
            t_start = meta.get("t_start_row") or [meta["start_row"]]
            record["tenants"] = [
                {
                    "tenant": t,
                    # global tenant identity (== t off-fleet): the key a
                    # router-fronted fleet's readers join on — a migrated
                    # tenant's verdicts continue under its OWN id in the
                    # landing daemon's sidecar
                    "id": int(self.tenant_ids[t]),
                    "rows": int(t_rows[t]),
                    "rows_through": int(t_through[t]),
                    "start_row": int(t_start[t]),
                    "detections": int(
                        changed[t * p_per : (t + 1) * p_per].sum()
                    ),
                    "changes": [
                        [int(p) - t * p_per, int(b), int(cg[p, b])]
                        for p, b, _ in changes
                        if t * p_per <= p < (t + 1) * p_per
                    ],
                }
                for t in range(self.tenants)
            ]
        trace_marks = meta.get("traces") or ()
        if trace_marks:
            # the sidecar verdict joins back to its originating packets
            record["traces"] = [m["trace_id"] for m in trace_marks]
        assembled_mono = time.monotonic()  # collect stage ends here
        # Set BEFORE the faults.fire below: a planted serve.flush stall
        # must read as publish-bound in the incident bundle.
        self._loop_stage = ("publish", assembled_mono)
        # Per-chunk latency split (admission/queue/device/collect), from
        # the stamps every seal already carries — present in BOTH
        # pipeline-metrics modes, so the sidecar schema never depends on
        # the instrumentation flag (bit-parity modulo ts/lat_ms). The
        # loadgen summary joins these to split client-observed latency.
        fed_m = meta.get("fed_mono", collected_mono)
        sealed_m = meta.get("sealed_mono", fed_m)
        lat = {
            "queue": fed_m - sealed_m,
            "device": collected_mono - fed_m,
            "collect": assembled_mono - collected_mono,
        }
        ing = meta.get("ingest_mono")
        if ing is not None and len(ing):
            lat = {"admission": sealed_m - float(np.mean(ing)), **lat}
        record["lat_ms"] = {
            k: round(max(v, 0.0) * 1000.0, 3) for k, v in lat.items()
        }
        line = json.dumps(record)
        # Fault-injection site (resilience.faults; no-op unless armed):
        # raise = die after the chunk's state advanced but before its
        # verdict landed; torn_write = tear the sidecar's trailing line.
        faults.fire(
            "serve.flush",
            fh=self._verdict_fh,
            payload=line,
            chunk=meta["chunk"],
        )
        self._verdict_fh.write(line + "\n")
        self._verdict_fh.flush()
        published_mono = time.monotonic()
        if self._lat_hist is not None:
            from ..telemetry.trace import observe_chunk_stages

            observe_chunk_stages(
                self._lat_hist,
                meta,
                fed_mono=meta.get("fed_mono", collected_mono),
                collected_mono=collected_mono,
                published_mono=published_mono,
            )
        self._last_pub_mono = published_mono
        self._flag_base += int(cg.shape[1])
        self._published += 1
        self._rows_published = int(meta["rows_through"])
        if self._tenant_rows is not None:
            # labeled by GLOBAL id (tenant_ids), same join key as the
            # verdict sidecar entries — a migrated tenant's rate follows
            # it across backends under one label value
            t_rows = meta.get("t_rows") or [meta["rows"]]
            for t in range(min(self.tenants, len(t_rows))):
                if int(t_rows[t]):
                    self._tenant_rows.inc(
                        float(int(t_rows[t])),
                        tenant=str(int(self.tenant_ids[t])),
                    )
        self._detections += int(changed.sum())
        self._last_meta = meta
        # any publish postdates every applied LOADTENANT (controls run
        # only on a drained pipeline), so its per-slot accounting now
        # covers the landed tenants — the restore overrides expire
        self._restored_accounting.clear()
        if self._keep is not None:
            self._keep.append(host)
        trace_ids: list = []
        if trace_marks and self._log is not None:
            from ..telemetry.tracing import emit_row_spans

            trace_ids = emit_row_spans(
                self._log,
                meta,
                collected_mono=collected_mono,
                published_mono=published_mono,
            )
            self._rows_traced += len(trace_ids)
        hooks_start = time.monotonic()  # publish stage ends here
        self._loop_stage = ("forensics", hooks_start)
        if self._forensics is not None and chunk is not None:
            entry_host = (
                jax.tree.map(np.asarray, entry) if entry is not None else None
            )
            self._forensics.on_publish(
                meta,
                host,
                chunk,
                entry_host,
                log=self._log,
                trace_ids=trace_ids,
            )
        forensics_done = time.monotonic()
        self._loop_stage = ("adapt", forensics_done)
        if self._adapt is not None:
            # the reaction arm: route this verdict through the per-tenant
            # policy — forensics above explains the drift, this acts on it
            self._adapt.on_chunk(meta, host, chunk)
        adapt_done = time.monotonic()
        if self._log is not None:
            from ..telemetry.events import emit_flag_events

            self.det.emit_chunk_event(
                self._log, meta["chunk"], host, self._metrics
            )
            self.det.emit_heartbeat(self._log)
            emit_flag_events(self._log, cg, np.asarray(host.forced_retrain), 0)
        if self._stage_clock is not None:
            # Fold the whole chunk's stage timings in one place, outside
            # the dispatch window: `device` is the loop BLOCKED on the
            # host sync (the pipelined overlap already subtracted —
            # busy-conservation needs loop-thread time, not device time).
            clk = self._stage_clock
            clk.add("device", collected_mono - pub_start)
            clk.add("collect", assembled_mono - collected_mono)
            clk.add("publish", hooks_start - assembled_mono)
            clk.add("forensics", forensics_done - hooks_start)
            clk.add("adapt", adapt_done - forensics_done)
            self._rows_gauge.set(self._rows_published)
            if self._loop_start_mono is not None:
                self._wall_gauge.set(
                    time.monotonic() - self._loop_start_mono
                )
        if self._chunk_tracer:
            # Stage spans ride the trace plane per sampled chunk; the
            # device span is the TRUE device window (fed→collected),
            # which overlaps the next chunk's feed at depth 2.
            ck = meta["chunk"]
            fs = meta.get("_feed_start_mono")
            fed_span = meta.get("fed_mono", pub_start)
            if fs is not None:
                self._chunk_tracer.span("serve.feed", ck, fs, fed_span)
            self._chunk_tracer.span(
                "serve.device", ck, fed_span, collected_mono
            )
            self._chunk_tracer.span(
                "serve.collect", ck, collected_mono, assembled_mono
            )
            self._chunk_tracer.span(
                "serve.publish", ck, assembled_mono, published_mono
            )

    def _save_checkpoint(self) -> None:
        if self.det.carry is None or self._last_meta is None:
            return
        from ..utils.checkpoint import save_checkpoint

        meta = self._last_meta
        extra = {}
        if self.tenants > 1:
            span = self.batcher.rows_per_chunk  # per-tenant grid span
            extra = {
                "tenants": self.tenants,
                "stream_rows": [
                    int(s) + span for s in meta["t_start_row"]
                ],
                "t_rows_admitted": [
                    int(r) for r in meta["t_rows_through"]
                ],
            }
        if self._adapt is not None:
            from ..adapt.refit import ADAPT_STATE_SUFFIX

            # adaptation state (window buffers, probation champions)
            # rides next to the carry — the mid-adaptation resume contract
            self._adapt.save(self.params.checkpoint + ADAPT_STATE_SUFFIX)
        if self.params.tenant_checkpoints:
            # Solo-shaped per-slot checkpoints next to the plane — the
            # migration currency (ChunkedDetector.save_tenant): a router
            # failing this daemon over LOADTENANTs these into survivors.
            # Atomic each, and written at the same drained-pipeline
            # moment as the plane, so slot and plane always agree.
            # Vacant spares (id -1) are skipped: masked state nobody can
            # land from, pure serialization waste on the checkpoint path.
            for t in range(self.tenants):
                if self.tenant_ids[t] < 0:
                    continue
                self._save_tenant_slot(
                    t, f"{self.params.checkpoint}.t{t}"
                )
        save_checkpoint(
            self.params.checkpoint,
            self.det.carry,
            meta={
                # flag columns == batches consumed (the first chunk's
                # batch_a microbatch emits no flag row), so the published
                # prefix and the carry agree by construction — checkpoints
                # are only written when nothing is in flight.
                "batches_done": self._flag_base,
                "partitions": self.det.partitions,
                "stream_row": meta["start_row"] + self.batcher.rows_per_chunk,
                "chunk_index": meta["chunk"] + 1,
                "rows_admitted": meta["rows_through"],
                "flag_cols": self._flag_base,
                "rows_done": self.det.rows_done,
                "detections": self._detections,
                **extra,
            },
        )

    def _stop_ops(self) -> None:
        """Tear down the ops plane (idempotent; both exit paths)."""
        if self._slo_stop is not None:
            self._slo_stop.set()
            self._slo_stop = None
        if self._slo_thread is not None:
            # Join before the final events land: a mid-evaluate alert
            # must not serialize AFTER run_completed ("last event" is a
            # schema contract) or race the log close.
            self._slo_thread.join(timeout=5)
            self._slo_thread = None
        if self._ops is not None:
            try:
                self._ops.stop()
            except Exception:
                pass
            self._ops = None

    def _finish(self) -> None:
        self._stop_ops()
        if self.params.checkpoint and self.det.carry is not None:
            self._save_checkpoint()
        elapsed = time.monotonic() - self._t_start
        if self._log is not None:
            from ..telemetry import registry as run_registry
            from ..telemetry.metrics import write_exports

            adm = self._adm_totals()
            self._log.emit(
                "run_completed",
                rows=self._rows_published,
                seconds=elapsed,
                detections=self._detections,
                chunks=self._published,
                rows_quarantined=adm["rows_quarantined"],
                rows_rejected=adm["rows_rejected"],
                **({"tenants": self.tenants} if self.tenants > 1 else {}),
            )
            run_registry.record(
                self.cfg.telemetry_dir,
                self._log.run_id,
                "completed",
                rows=self._rows_published,
                seconds=elapsed,
                detections=self._detections,
            )
            write_exports(
                self._metrics, os.path.splitext(self._log.path)[0]
            )
            self._log.close()
        self._close_files()

    def _fail(self) -> None:
        self._stop_ops()
        try:
            if self._ingress is not None:
                self._ingress.stop()
        except Exception:
            pass
        if self._log is not None:
            try:
                from ..telemetry import registry as run_registry

                run_registry.record(
                    self.cfg.telemetry_dir, self._log.run_id, "failed"
                )
            except Exception:
                pass  # best-effort crash evidence (api.run's posture)
            # Crash flight recorder: the last N events land next to the
            # log (dump() is best-effort — it must not mask the original
            # failure). A clean drain never writes this file.
            if self._recorder is not None:
                from ..telemetry.ops import FLIGHTREC_SUFFIX

                self._recorder.dump(
                    os.path.splitext(self._log.path)[0] + FLIGHTREC_SUFFIX
                )
            # Crash incident bundle: the full cross-plane autopsy (the
            # flight ring above plus pipeline/statusz/verdict-tail
            # evidence) — the crash-only dump, generalized. Best-effort:
            # it must never mask the original failure either.
            if self._incidents is not None:
                try:
                    self._incidents.capture_crash(sys.exc_info()[1])
                except Exception:
                    pass
            self._log.close()
        self._close_files()

    def _close_files(self) -> None:
        if self._verdict_fh is not None and not self._verdict_fh.closed:
            self._verdict_fh.close()
        for adm in self.admissions:
            adm.close()

    # -- test/bench surface --------------------------------------------------

    def flags(self):
        """Concatenated host flag tables of every published chunk
        (requires ``keep_flags=True``)."""
        from ..engine.loop import FlagRows

        assert self._keep is not None, "construct with keep_flags=True"
        if not self._keep:
            return None
        return FlagRows(
            *(np.concatenate(xs, axis=1) for xs in zip(*self._keep))
        )


def main(argv=None) -> None:
    """``serve``: run the online drift-serving daemon until drained."""
    import signal

    from ..config import DATA_POLICIES, DETECTOR_NAMES

    ap = argparse.ArgumentParser(
        prog="python -m distributed_drift_detection_tpu serve",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("--features", type=int, required=True,
                    help="feature count of every ingress row (label rides last)")
    ap.add_argument("--classes", type=int, required=True,
                    help="label domain size (labels must be 0..C-1)")
    ap.add_argument("--partitions", type=int, default=4)
    ap.add_argument("--per-batch", type=int, default=50)
    ap.add_argument("--tenants", type=int, default=1,
                    help="independent tenant streams in one compiled "
                    "kernel (wire: a TENANT k line routes a connection's "
                    "rows; per-tenant verdict attribution in the sidecar)")
    ap.add_argument("--chunk-batches", type=int, default=4,
                    help="microbatches per flushed chunk ([P,CB,B] grid)")
    ap.add_argument("--window", type=int, default=1,
                    help="speculative window width (explicit; no auto on a live stream)")
    ap.add_argument("--model", default="centroid")
    ap.add_argument("--detector", default="ddm", choices=DETECTOR_NAMES)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0,
                    help="TCP ingress port (0 = OS-assigned, see banner)")
    ap.add_argument("--linger-s", type=float, default=0.25,
                    help="max wait before a partial microbatch flushes short")
    ap.add_argument("--max-frame-rows", type=int, default=0,
                    help="wire-v2 decoder bound: a binary frame header "
                    "declaring more rows is refused (ERR + close) before "
                    "any allocation (0 = the codec default, "
                    "serve.wire.MAX_FRAME_ROWS)")
    ap.add_argument("--heartbeat-s", type=float, default=10.0)
    ap.add_argument("--data-policy", default="quarantine",
                    choices=DATA_POLICIES,
                    help="admission policy (serve default: quarantine; "
                    "strict rejects rows per connection, repair imputes "
                    "from running column means)")
    ap.add_argument("--telemetry-dir", default=None,
                    help="run log + verdict sidecar + registry directory")
    ap.add_argument("--checkpoint", default="",
                    help="detector-state checkpoint path (enables resume)")
    ap.add_argument("--checkpoint-every", type=int, default=1)
    ap.add_argument("--tenant-checkpoints", action="store_true",
                    help="also write one solo-shaped <checkpoint>.t<slot> "
                    "per tenant at every checkpoint — the router's "
                    "failover/migration currency (needs --checkpoint)")
    ap.add_argument("--name", default="",
                    help="fleet identity of this daemon: stamped into "
                    "every verdict record ('daemon') so a router-fronted "
                    "fleet's sidecars stay attributable per backend")
    ap.add_argument("--tenant-ids", default="",
                    help="comma-separated GLOBAL tenant id per slot "
                    "(len == --tenants; -1 = vacant spare for migration "
                    "landings): slot s serves global tenant ids[s] with "
                    "that tenant's solo seed/shuffle identity — the "
                    "fleet placement posture ('' = identity 0..T-1)")
    ap.add_argument("--mesh-tenants", type=int, default=0,
                    help="tenant-axis rows of a 2-D (tenant, partition) "
                    "device mesh: shard the stacked tenant plane over "
                    "devices (must divide --tenants and the device "
                    "count; 0 = single-device/1-D, the default)")
    ap.add_argument("--compile-cache-dir", default="",
                    help="persistent XLA cache (restart warm-start)")
    ap.add_argument("--no-shuffle", action="store_true",
                    help="disable the stripe-time per-microbatch shuffle")
    ap.add_argument("--max-chunks", type=int, default=None,
                    help="drain after N published microbatches (CI/tests)")
    ap.add_argument("--ops-port", type=int, default=None,
                    help="HTTP ops plane: /metrics, /healthz, /statusz "
                    "(0 = OS-assigned, see banner; omit = no ops server)")
    ap.add_argument("--slo", action="append", default=None,
                    metavar="KIND=THRESHOLD",
                    help="SLO alert rule (p99_ms|verdict_age_s|"
                    "quarantine_pct|stall_s) or a multi-window "
                    "burn_rate=SERIES:OBJECTIVE:FAST/SLOW:FACTOR pair, "
                    "repeatable; 'none' disables. Default: stall_s=60")
    ap.add_argument("--tenant-series", action="store_true",
                    help="export serve_tenant_rows_total{tenant=<global "
                    "id>} per-tenant rows counters on /metrics — the "
                    "history plane's hotness-ranking input (cardinality-"
                    "guarded by --tenant-series-max)")
    ap.add_argument("--tenant-series-max", type=int, default=512,
                    help="refuse --tenant-series beyond this many tenant "
                    "slots instead of flooding every scrape (default 512)")
    ap.add_argument("--slo-interval-s", type=float, default=1.0,
                    help="SLO evaluator cadence (its own thread)")
    ap.add_argument("--flightrec-events", type=int, default=256,
                    help="crash flight-recorder ring capacity (0 = off)")
    ap.add_argument("--no-pipeline-metrics", action="store_true",
                    help="disable the serve-pipeline observatory "
                    "(stage busy counters, /statusz pipeline section, "
                    "per-chunk stage spans); verdict sidecars are "
                    "bit-identical either way")
    ap.add_argument("--trace-sample", type=float, default=0.0,
                    help="daemon-side head-sampling rate (0..1) for rows "
                    "the client did not TRACE-stamp: sampled rows get the "
                    "full serving span chain in the run log (0 = off, "
                    "zero hot-path work; client TRACE lines always honored)")
    ap.add_argument("--no-forensics", action="store_true",
                    help="disable drift evidence bundles "
                    "(<run-log>.forensics/; on by default with a "
                    "telemetry dir)")
    ap.add_argument("--no-incidents", action="store_true",
                    help="disable the incident autopsy plane "
                    "(<run-log>.incidents/ bundles captured when an SLO "
                    "alert fires or the daemon crashes; on by default "
                    "with a telemetry dir — verdict sidecars are "
                    "bit-identical either way)")
    ap.add_argument("--incident-store", default="",
                    help="history-store directory (collector --store): "
                    "bundles also extract the recent fleet time-series "
                    "window + top-tenant ranking from it")
    ap.add_argument("--incident-window-s", type=float, default=120.0,
                    help="history window extracted into each bundle")
    ap.add_argument("--incident-max", type=int, default=32,
                    help="bundle cap per run (alert flapping must not "
                    "fill the disk; skipped captures are counted)")
    ap.add_argument("--on-drift", action="append", default=[],
                    metavar="[T=]POLICY[,k=v...]",
                    help="drift-reaction policy (adapt/ subsystem), "
                    "repeatable: alert_only (default — verdicts only "
                    "publish), retrain (refit on a post-drift window and "
                    "hot-swap at a chunk boundary), shadow "
                    "(champion/challenger: swap gated on measured "
                    "shadow-slice error). Prefix T= targets one tenant; "
                    "knobs: window_rows, cooldown_rows, margin, epsilon")
    args = ap.parse_args(argv)

    # Validate --on-drift at argv time (jax-free policy grammar): a bad
    # spec must fail here, not after the backend initialised.
    from ..adapt.policy import resolve_policies as _resolve_policies

    try:
        _resolve_policies(args.on_drift, args.tenants)
    except ValueError as e:
        ap.error(str(e))
    if args.tenant_checkpoints and not args.checkpoint:
        ap.error("--tenant-checkpoints needs --checkpoint (the plane stem)")
    tenant_ids: tuple = ()
    if args.tenant_ids:
        try:
            tenant_ids = tuple(
                int(s) for s in args.tenant_ids.split(",") if s.strip() != ""
            )
        except ValueError:
            ap.error(f"--tenant-ids must be comma-separated integers, "
                     f"got {args.tenant_ids!r}")
        if len(tenant_ids) != args.tenants:
            ap.error(f"--tenant-ids names {len(tenant_ids)} slot(s) but "
                     f"--tenants is {args.tenants}")

    # CLI-driven fault arming (DDD_FAULTS, the grid harness's pattern):
    # inert unless the env var is set. The ops-smoke CI job wedges the
    # serve loop with a serve.flush kind=stall this way and asserts the
    # SLO stall alert + /healthz flip without writing Python.
    armed = faults.arm_from_env()
    if armed:
        print(
            json.dumps({"armed_faults": armed}), file=sys.stderr, flush=True
        )

    cfg = RunConfig(
        model=args.model,
        detector=args.detector,
        partitions=args.partitions,
        per_batch=args.per_batch,
        tenants=args.tenants,
        window=args.window,
        seed=args.seed,
        shuffle_batches=not args.no_shuffle,
        data_policy=args.data_policy,
        telemetry_dir=args.telemetry_dir,
        compile_cache_dir=args.compile_cache_dir,
        mesh_tenant_devices=args.mesh_tenants,
        results_csv="",
    )
    params = ServeParams(
        num_features=args.features,
        num_classes=args.classes,
        host=args.host,
        port=args.port,
        chunk_batches=args.chunk_batches,
        linger_s=args.linger_s,
        max_frame_rows=args.max_frame_rows,
        checkpoint=args.checkpoint,
        checkpoint_every=args.checkpoint_every,
        tenant_checkpoints=args.tenant_checkpoints,
        tenant_ids=tenant_ids,
        name=args.name,
        heartbeat_s=args.heartbeat_s,
        ops_port=args.ops_port,
        slo=tuple(args.slo) if args.slo else ServeParams._field_defaults["slo"],
        slo_interval_s=args.slo_interval_s,
        tenant_series=args.tenant_series,
        tenant_series_max=args.tenant_series_max,
        flightrec_events=args.flightrec_events,
        trace_sample=args.trace_sample,
        pipeline_metrics=not args.no_pipeline_metrics,
        forensics=not args.no_forensics,
        on_drift=tuple(args.on_drift),
        incidents=not args.no_incidents,
        incident_store=args.incident_store,
        incident_window_s=args.incident_window_s,
        incident_max=args.incident_max,
    )
    runner = ServeRunner(cfg, params, max_chunks=args.max_chunks)
    banner = runner.start()
    print(json.dumps(banner), flush=True)
    # SIGTERM/SIGINT drain: flush in-flight batches, final atomic
    # checkpoint, registry → completed — then exit 0 (the smoke gate's
    # contract). A second signal falls through to the default handler.
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: runner.request_stop())
    raise SystemExit(runner.serve_forever())


if __name__ == "__main__":
    main(sys.argv[1:])
