"""Admission control for the serving daemon: sanitize → fixed-geometry
microbatches.

Two pieces, deliberately separate so each is testable without sockets:

* :class:`AdmissionController` — the data-plane gate. Every ingress line
  block is parsed with the tolerant row parser and contract-scanned
  (``io.sanitize.parse_rows`` / ``scan_matrix``), then resolved per the
  configured policy through the same ``io.sanitize.apply_block_policy``
  the streaming CSV reader uses — quarantined rows land in the sidecar
  and count ``ingest_quarantined_total``, exactly as in batch mode. Two
  serving-specific adaptations, both documented deviations from the
  batch loaders:

  - ``strict`` rejects the violating *rows* (dropped, counted, an error
    line back on the connection) instead of refusing the whole stream —
    a daemon that dies on one bad row is not a daemon;
  - ``repair`` imputes from **running** column means over the rows
    admitted so far (``io.sanitize.RunningColumnStats``) — full-column
    statistics do not exist on an unbounded stream.

  Admitted rows under ``quarantine``/``repair`` keep their stream
  *positions* (masked, padding-identical inside jit), so a dirty served
  stream produces flags bit-identical to the clean-masked batch run —
  the PR-5 acceptance, extended to the wire.

* :class:`MicroBatcher` — the geometry gate. Admitted rows accumulate in
  arrival order; a full ``[P, CB, B]`` grid seals immediately, a partial
  one seals when its oldest row has lingered past ``linger_s``. Sealing
  runs the rows through the one shared striper (``io.stream.stripe_chunk``
  with the RunConfig's host shuffle seed), so a short flush is *literally*
  the same chunk as a full grid with the tail masked — static shapes,
  nothing recompiles, and the serving path cannot drift from the batch
  path's placement. The stream position advances by the full grid span
  per seal (grid-slot semantics): under sustained load there are no gaps,
  and a lingering flush trades position density for latency, never
  correctness.
"""

from __future__ import annotations

import threading
import time
from typing import NamedTuple

import numpy as np

from ..io import sanitize
from ..io.stream import ChunkStriper
from ..resilience import faults


class FrameContractError(ValueError):
    """A v2 frame whose geometry disagrees with the daemon's row contract
    (feature count / shape). Connection-level protocol violation — the
    ingress validates before admission, so reaching this from the wire
    means an embedder bug."""


def _split_buffered(bufs, n_take: int, num_features: int):
    """One stream's buffered blocks → the seal's (take, rest) halves.

    ``bufs`` is the ``(X_list, y_list, ok_list, ts_list)`` quadruple a
    batcher accumulates per stream; the oldest ``n_take`` rows split off
    as ``(take_X, take_y, take_ok, take_ts)`` (``take_ok`` collapses to
    None when every taken row is valid) and the remainder is re-stashed
    in the same list form. The ONE copy of the take/rest mechanics the
    solo :class:`MicroBatcher` and per-tenant :class:`TenantMicroBatcher`
    seals share — the serve path's bit-parity contract rides on these
    exact semantics, so they must not be able to diverge. An empty
    stream yields a zero-row take (``num_features`` shapes its plane).
    """
    X_list, y_list, ok_list, ts_list = bufs
    if X_list:
        X = np.concatenate(X_list) if len(X_list) > 1 else X_list[0]
        y = np.concatenate(y_list) if len(y_list) > 1 else y_list[0]
        ts = np.concatenate(ts_list) if len(ts_list) > 1 else ts_list[0]
        ok = None
        if any(o is not None for o in ok_list):
            ok = np.concatenate(
                [
                    np.ones(len(a), bool) if o is None else o
                    for a, o in zip(X_list, ok_list)
                ]
            )
    else:
        X = np.zeros((0, num_features), np.float32)
        y = np.zeros((0,), np.int32)
        ts = np.zeros((0,), np.float64)
        ok = None
    take_X, rest_X = X[:n_take], X[n_take:]
    take_y, rest_y = y[:n_take], y[n_take:]
    take_ts, rest_ts = ts[:n_take], ts[n_take:]
    take_ok = rest_ok = None
    if ok is not None:
        take_ok, rest_ok = ok[:n_take], ok[n_take:]
        if take_ok.all():
            take_ok = None
    rest = (
        [rest_X] if len(rest_X) else [],
        [rest_y] if len(rest_X) else [],
        (
            [rest_ok]
            if len(rest_X) and rest_ok is not None
            else ([None] if len(rest_X) else [])
        ),
        [rest_ts] if len(rest_X) else [],
    )
    return (take_X, take_y, take_ok, take_ts), rest


def _take_marks(
    marks: "list[dict]", taken_before: int, n_take: int
) -> "tuple[list[dict], list[dict]]":
    """One stream's trace marks → the seal's (taken, rest) halves.

    ``marks`` hold absolute admitted positions; the seal covers
    ``[taken_before, taken_before + n_take)``. The ONE copy of the
    mark-partition mechanics the solo and tenant seals share (the same
    rule as :func:`_split_buffered` for the row planes) — taken marks
    come back position-rebased is the CALLER's job (it owns the seal's
    index base). Returns ``(taken, rest)``.
    """
    end = taken_before + n_take
    taken = [m for m in marks if m["pos"] < end]
    if not taken:
        return [], marks
    return taken, [m for m in marks if m["pos"] >= end]


class SealedChunk(NamedTuple):
    """One flushed microbatch: the striped ``[P, CB, B]`` chunk plus its
    accounting meta (``chunk`` index, ``start_row`` grid position,
    ``rows`` admitted into it, ``rows_through`` cumulative admitted rows
    up to and including it — the loadgen's latency-attribution key —
    ``short`` flag and seal wall-clock). For end-to-end row tracing
    (``telemetry.trace``) the meta also carries ``ingest_mono`` — one
    monotonic admission stamp per admitted row, in stream order — and
    ``sealed_mono``, the seal instant on the same clock; the serve loop
    turns these into the live ``serve_row_latency_seconds`` stages."""

    chunk: object  # engine.loop.Batches
    meta: dict


class MicroBatcher:
    """Thread-safe accumulation of admitted rows into fixed-geometry
    chunks with a max-linger deadline (see module docstring).

    Producers call :meth:`push` (ingress handler threads); the single
    consumer (the serve loop) calls :meth:`get`. :meth:`poison` carries a
    producer-side failure to the consumer — the daemon must die loudly,
    not serve around a broken ingress.
    """

    def __init__(
        self,
        partitions: int,
        per_batch: int,
        chunk_batches: int,
        *,
        shuffle_seed: "int | None" = None,
        linger_s: float = 0.25,
        start_row: int = 0,
        chunk_index: int = 0,
        rows_admitted: int = 0,
        max_queue: int = 64,
    ):
        self.partitions = partitions
        self.per_batch = per_batch
        self.chunk_batches = chunk_batches
        self.rows_per_chunk = partitions * per_batch * chunk_batches
        self.shuffle_seed = shuffle_seed
        # Pooled seal striper: same placement/shuffle/validity folding as
        # stripe_chunk — bit-identical, pinned by test — but the pad
        # staging buffers are reused across seals, so a sustained ingress
        # (the v2 frame path especially) seals with zero per-chunk
        # staging allocation.
        self._striper = ChunkStriper(
            partitions, per_batch, chunk_batches, shuffle_seed
        )
        self.linger_s = linger_s
        self.start_row = int(start_row)  # next chunk's grid position
        self.chunk_index = int(chunk_index)
        self.rows_admitted = int(rows_admitted)  # cumulative, incl. masked
        self.rows_sealed = 0  # cumulative rows sealed into chunks (this process)
        self._max_queue = max(1, max_queue)
        self._cv = threading.Condition()
        self._X: list[np.ndarray] = []
        self._y: list[np.ndarray] = []
        self._ok: list["np.ndarray | None"] = []
        self._ts: list[np.ndarray] = []  # per-row monotonic ingest stamps
        self._buffered = 0
        self._first_ts: "float | None" = None  # monotonic, oldest buffered row
        # Sampled-row trace marks (telemetry.tracing): [{"pos": absolute
        # admitted position, "trace_id", "parent_id"}], carried into the
        # covering seal's meta. Empty unless tracing is on — the untraced
        # path costs one falsy check per push.
        self._trace_marks: list[dict] = []
        self._queue: list[SealedChunk] = []
        self._error: "BaseException | None" = None

    def push(
        self,
        X: np.ndarray,
        y: np.ndarray,
        ok: "np.ndarray | None" = None,
        traces=None,
    ) -> None:
        """Admit a block of rows (arrival order = stream order). Blocks
        while the sealed-chunk queue is full (backpressure to ingress)."""
        X = np.ascontiguousarray(X, np.float32)
        y = np.ascontiguousarray(y, np.int32)
        if len(X) == 0:
            return
        # One ingest stamp per block (rows of one push arrived together),
        # taken BEFORE the backpressure wait below: under overload that
        # wait IS the latency a client experiences, and a post-wait stamp
        # would hide exactly the congestion the p99 SLO exists to catch.
        ingest_mono = time.monotonic()
        with self._cv:
            while len(self._queue) >= self._max_queue and self._error is None:
                self._cv.wait(0.1)
            if self._error is not None:
                raise self._error
            self._X.append(X)
            self._y.append(y)
            self._ok.append(None if ok is None else np.asarray(ok, bool))
            self._ts.append(np.full(len(X), ingest_mono, dtype=np.float64))
            if traces:
                base = self.rows_admitted
                self._trace_marks.extend(
                    {
                        "pos": base + int(i),
                        "trace_id": tid,
                        "parent_id": pid,
                    }
                    for i, tid, pid in traces
                )
            self._buffered += len(X)
            self.rows_admitted += len(X)
            if self._first_ts is None:
                self._first_ts = time.monotonic()
            while self._buffered >= self.rows_per_chunk:
                self._seal_locked(self.rows_per_chunk)
            self._cv.notify_all()

    def flush(self) -> None:
        """Seal the partial grid now (protocol ``FLUSH`` / drain)."""
        with self._cv:
            if self._buffered:
                self._seal_locked(self._buffered)
            self._cv.notify_all()

    def poison(self, exc: BaseException) -> None:
        """Fail the consumer: the next/blocked :meth:`get` raises ``exc``."""
        with self._cv:
            self._error = exc
            self._cv.notify_all()

    def empty(self) -> bool:
        with self._cv:
            return not self._queue and not self._buffered

    def poisoned(self) -> "BaseException | None":
        """The producer-side failure carried to the consumer, if any
        (ops-plane health surface; read-only)."""
        with self._cv:
            return self._error

    def depth(self) -> dict:
        """Queue occupancy for ``/statusz``: sealed chunks waiting for
        the serve loop + rows buffered toward the next seal."""
        with self._cv:
            return {
                "queued_chunks": len(self._queue),
                "buffered_rows": self._buffered,
                "rows_sealed": self.rows_sealed,
            }

    def tenant_state(self, tenant: int = 0) -> dict:
        """The stream-position accounting of slot ``tenant`` (a solo
        batcher has exactly slot 0) — what a migration checkpoint must
        carry so the landing daemon's verdicts continue this one's
        ``rows_through`` sequence without a gap."""
        if tenant != 0:
            raise ValueError(f"solo batcher has only tenant 0, not {tenant}")
        with self._cv:
            return {
                "start_row": self.start_row,
                "rows_admitted": self.rows_admitted,
                "buffered": self._buffered,
            }

    def set_tenant_state(
        self, tenant: int, start_row: int, rows_admitted: int
    ) -> None:
        """Install a shipped tenant's stream positions into slot
        ``tenant`` (the LOADTENANT landing half of a migration). Refuses
        while rows are buffered toward a seal — position surgery under a
        live buffer would mis-stripe every buffered row."""
        if tenant != 0:
            raise ValueError(f"solo batcher has only tenant 0, not {tenant}")
        with self._cv:
            if self._buffered:
                raise RuntimeError(
                    f"cannot install tenant state over {self._buffered} "
                    "buffered row(s); flush first"
                )
            self.start_row = int(start_row)
            self.rows_admitted = int(rows_admitted)

    def set_tenant_identity(
        self, tenant: int, shuffle_seed: "int | None"
    ) -> None:
        """Install a migrated tenant's stripe identity into slot
        ``tenant``: the slot stripes subsequent rows with the SHIPPED
        tenant's shuffle seed, so post-migration flags continue the
        tenant's own solo sequence bit-identically. Same empty-buffer
        guard as :meth:`set_tenant_state` — a seed swap under buffered
        rows would mis-stripe them."""
        if tenant != 0:
            raise ValueError(f"solo batcher has only tenant 0, not {tenant}")
        with self._cv:
            if self._buffered:
                raise RuntimeError(
                    f"cannot install tenant identity over {self._buffered} "
                    "buffered row(s); flush first"
                )
            self.shuffle_seed = shuffle_seed
            self._striper = ChunkStriper(
                self.partitions, self.per_batch, self.chunk_batches,
                shuffle_seed,
            )

    def get(self, timeout: float = 0.0) -> "SealedChunk | None":
        """Next sealed chunk, sealing a lingering partial when its
        deadline passed; ``None`` on timeout. Raises a poisoned error."""
        deadline = time.monotonic() + max(timeout, 0.0)
        with self._cv:
            while True:
                if self._error is not None:
                    raise self._error
                if self._queue:
                    item = self._queue.pop(0)
                    self._cv.notify_all()  # wake a backpressured producer
                    return item
                now = time.monotonic()
                if (
                    self._buffered
                    and self._first_ts is not None
                    and now - self._first_ts >= self.linger_s
                ):
                    self._seal_locked(self._buffered)
                    continue
                waits = [deadline - now]
                if self._buffered and self._first_ts is not None:
                    waits.append(self._first_ts + self.linger_s - now)
                wait = min(waits)
                if deadline - now <= 0:
                    return None
                self._cv.wait(max(wait, 0.001))

    def _seal_locked(self, n_take: int) -> None:
        take, rest = _split_buffered(
            (self._X, self._y, self._ok, self._ts),
            n_take,
            self._X[0].shape[1],  # solo seals always hold data
        )
        take_X, take_y, take_ok, take_ts = take
        chunk = self._striper.stripe(
            take_X, take_y, self.start_row, row_valid=take_ok
        )
        taken_before = self.rows_admitted - self._buffered
        meta = {
            "chunk": self.chunk_index,
            "start_row": self.start_row,
            "rows": int(n_take),
            "rows_through": int(taken_before + n_take),
            "short": n_take < self.rows_per_chunk,
            "sealed_ts": time.time(),
            # row-tracing stamps (telemetry.trace.observe_chunk_stages);
            # never serialized — _publish copies named scalars only
            "ingest_mono": take_ts,
            "sealed_mono": time.monotonic(),
        }
        if self._trace_marks:
            taken, self._trace_marks = _take_marks(
                self._trace_marks, taken_before, n_take
            )
            if taken:
                meta["traces"] = [
                    {
                        "idx": m["pos"] - taken_before,
                        "trace_id": m["trace_id"],
                        "parent_id": m["parent_id"],
                    }
                    for m in taken
                ]
        self._queue.append(SealedChunk(chunk, meta))
        self.rows_sealed += int(n_take)
        # Grid-slot semantics: the stream position always advances by the
        # full grid span, so the next seal stays aligned to P·B (the
        # stripe-time shuffle's invariance requirement) and a short flush
        # reads as a grid with a masked tail, never as a re-packed stream.
        self.start_row += self.rows_per_chunk
        self.chunk_index += 1
        self._X, self._y, self._ok, self._ts = rest
        self._buffered = len(rest[0][0]) if rest[0] else 0
        self._first_ts = time.monotonic() if self._buffered else None


class _TenantSlot:
    """The push surface one tenant's :class:`AdmissionController` sees:
    routes admitted rows into its slot of the shared
    :class:`TenantMicroBatcher` grid."""

    def __init__(self, batcher: "TenantMicroBatcher", tenant: int):
        self._batcher = batcher
        self._tenant = tenant

    def push(self, X, y, ok=None, traces=None) -> None:
        self._batcher.push(self._tenant, X, y, ok, traces)


class TenantMicroBatcher:
    """T independent per-tenant row accumulators sealing into ONE stacked
    ``[T·P, CB, B]`` grid — the serving half of the multi-tenant plane.

    Each tenant accumulates its own arrival-order stream and stripes into
    its own ``[P, CB, B]`` block with its own shuffle seed and its own
    stream position (grid-slot semantics per tenant, exactly
    :class:`MicroBatcher`'s); a seal stacks the T blocks on the leading
    axis (``engine.loop.stack_tenants``) so the serve loop feeds one
    chunk, one dispatch, for all tenants. Seal policy: a FULL grid seals
    as soon as every tenant has a full span buffered (the balanced
    sustained-load fast path — per-tenant content then equals T solo
    batchers', so served flags stay bit-identical to solo runs); a
    PARTIAL grid seals when the oldest buffered row has lingered past
    ``linger_s`` — each tenant contributes what it has, masked through
    the validity plane (ragged tenant traffic == ragged tenant lengths:
    masked rows read as padding inside jit, static shapes, zero
    recompiles). Every seal advances EVERY tenant's stream position by
    the full span, so tenant blocks stay aligned to the stripe shuffle's
    P·B invariant.

    Liveness under skew: a tenant whose buffer crosses
    ``max_buffer_spans`` spans forces a partial seal too (idle tenants
    contribute masked blocks), so one hot tenant's buffering — and its
    row latency — stays bounded even when the balanced full seal never
    fires.

    ``meta`` carries per-tenant accounting lists (``t_rows``,
    ``t_rows_through``, ``t_start_row``) next to the pooled totals, so
    the verdict sidecar can attribute per tenant
    (``serve.runner._publish``) and the loadgen's per-tenant latency
    mapping works. Interface-compatible with :class:`MicroBatcher` where
    the serve loop touches it (get/flush/poison/poisoned/empty/depth/
    rows_admitted); producers push via :meth:`push` with a tenant index
    (the per-tenant :class:`_TenantSlot` adapters the admission
    controllers hold).
    """

    def __init__(
        self,
        tenants: int,
        partitions: int,
        per_batch: int,
        chunk_batches: int,
        *,
        num_features: int,
        shuffle_seeds=None,  # per-tenant stripe seeds (None = unshuffled)
        linger_s: float = 0.25,
        start_rows=None,
        chunk_index: int = 0,
        rows_admitted=None,
        max_queue: int = 64,
        max_buffer_spans: int = 4,
    ):
        if tenants < 1:
            raise ValueError(f"tenants must be >= 1, got {tenants}")
        if max_buffer_spans < 1:
            raise ValueError(
                f"max_buffer_spans must be >= 1, got {max_buffer_spans}"
            )
        if num_features <= 0:
            # An idle tenant's block is a zero-row stripe — its feature
            # plane's width must be configuration, not inference.
            raise ValueError(
                f"num_features must be > 0, got {num_features}"
            )
        self.num_features = int(num_features)
        self.tenants = tenants
        self.partitions = partitions
        self.per_batch = per_batch
        self.chunk_batches = chunk_batches
        # Per-TENANT span; the stacked chunk carries tenants· this.
        self.rows_per_chunk = partitions * per_batch * chunk_batches
        if shuffle_seeds is None:
            shuffle_seeds = [None] * tenants
        if len(shuffle_seeds) != tenants:
            raise ValueError(
                f"{len(shuffle_seeds)} shuffle_seeds for {tenants} tenants"
            )
        self.shuffle_seeds = list(shuffle_seeds)
        # One pooled seal striper per tenant (each has its own shuffle
        # seed and staging pool) — see MicroBatcher.
        self._stripers = [
            ChunkStriper(partitions, per_batch, chunk_batches, s)
            for s in self.shuffle_seeds
        ]
        self.linger_s = linger_s
        self.start_rows = [
            int(s) for s in (start_rows or [0] * tenants)
        ]
        if len(self.start_rows) != tenants:
            raise ValueError(
                f"{len(self.start_rows)} start_rows for {tenants} tenants"
            )
        self.chunk_index = int(chunk_index)
        per_tenant_admitted = list(rows_admitted or [0] * tenants)
        if len(per_tenant_admitted) != tenants:
            raise ValueError(
                f"{len(per_tenant_admitted)} rows_admitted for {tenants} "
                "tenants"
            )
        self.tenant_rows_admitted = [int(r) for r in per_tenant_admitted]
        self.rows_sealed = 0  # cumulative rows sealed into chunks (this process)
        self._max_buffer_spans = int(max_buffer_spans)
        self._max_queue = max(1, max_queue)
        self._cv = threading.Condition()
        self._X = [[] for _ in range(tenants)]
        self._y = [[] for _ in range(tenants)]
        self._ok = [[] for _ in range(tenants)]
        self._ts = [[] for _ in range(tenants)]
        # per-tenant trace marks (same shape as MicroBatcher's, positions
        # absolute within that tenant's admitted stream)
        self._trace_marks: list[list[dict]] = [[] for _ in range(tenants)]
        self._buffered = [0] * tenants
        self._first_ts: "float | None" = None  # oldest buffered row, any tenant
        self._queue: list[SealedChunk] = []
        self._error: "BaseException | None" = None

    # -- MicroBatcher-compatible surface -------------------------------------

    @property
    def rows_admitted(self) -> int:
        return sum(self.tenant_rows_admitted)

    def push(self, tenant: int, X, y, ok=None, traces=None) -> None:
        """Admit a block of rows into ``tenant``'s stream (arrival order =
        that tenant's stream order). Blocks while the sealed queue is full
        (backpressure to ingress), like :class:`MicroBatcher`."""
        if not 0 <= tenant < self.tenants:
            raise ValueError(
                f"tenant {tenant} out of range 0..{self.tenants - 1}"
            )
        X = np.ascontiguousarray(X, np.float32)
        y = np.ascontiguousarray(y, np.int32)
        if len(X) == 0:
            return
        ingest_mono = time.monotonic()
        with self._cv:
            while len(self._queue) >= self._max_queue and self._error is None:
                self._cv.wait(0.1)
            if self._error is not None:
                raise self._error
            self._X[tenant].append(X)
            self._y[tenant].append(y)
            self._ok[tenant].append(None if ok is None else np.asarray(ok, bool))
            self._ts[tenant].append(
                np.full(len(X), ingest_mono, dtype=np.float64)
            )
            if traces:
                base = self.tenant_rows_admitted[tenant]
                self._trace_marks[tenant].extend(
                    {
                        "pos": base + int(i),
                        "trace_id": tid,
                        "parent_id": pid,
                    }
                    for i, tid, pid in traces
                )
            self._buffered[tenant] += len(X)
            self.tenant_rows_admitted[tenant] += len(X)
            if self._first_ts is None:
                self._first_ts = time.monotonic()
            while all(b >= self.rows_per_chunk for b in self._buffered):
                self._seal_locked(full=True)
            # Skew bound: under imbalanced traffic the all-tenants-full
            # seal never fires, and without this a hot tenant's buffer
            # (and its row latency) would grow without bound between
            # linger seals. A tenant crossing max_buffer_spans spans
            # forces a partial seal — idle tenants contribute masked
            # blocks, trading their position density for the hot
            # tenant's liveness, exactly like the linger deadline.
            # Balanced sustained load never reaches it (the full seal
            # above fires first), so the solo-parity fast path is
            # untouched.
            while (
                self._buffered[tenant]
                >= self._max_buffer_spans * self.rows_per_chunk
            ):
                self._seal_locked(full=False)
            self._cv.notify_all()

    def flush(self) -> None:
        with self._cv:
            # Seal until EVERY tenant's buffer is empty: a hot tenant may
            # hold several spans (the skew bound allows up to
            # max_buffer_spans), and the FLUSH/drain contract is "seal
            # buffered rows NOW", not one-span-per-linger.
            while any(self._buffered):
                self._seal_locked(full=False)
            self._cv.notify_all()

    def poison(self, exc: BaseException) -> None:
        with self._cv:
            self._error = exc
            self._cv.notify_all()

    def empty(self) -> bool:
        with self._cv:
            return not self._queue and not any(self._buffered)

    def poisoned(self) -> "BaseException | None":
        with self._cv:
            return self._error

    def depth(self) -> dict:
        with self._cv:
            return {
                "queued_chunks": len(self._queue),
                "buffered_rows": sum(self._buffered),
                "tenant_buffered_rows": list(self._buffered),
                "rows_sealed": self.rows_sealed,
            }

    def tenant_state(self, tenant: int) -> dict:
        """Slot ``tenant``'s stream-position accounting (the migration
        checkpoint's meta — see :meth:`MicroBatcher.tenant_state`)."""
        if not 0 <= tenant < self.tenants:
            raise ValueError(
                f"tenant {tenant} out of range 0..{self.tenants - 1}"
            )
        with self._cv:
            return {
                "start_row": self.start_rows[tenant],
                "rows_admitted": self.tenant_rows_admitted[tenant],
                "buffered": self._buffered[tenant],
            }

    def set_tenant_state(
        self, tenant: int, start_row: int, rows_admitted: int
    ) -> None:
        """Install a shipped tenant's stream positions into slot
        ``tenant`` (LOADTENANT). The slot's own buffer must be empty —
        the OTHER tenants' buffers are untouched and irrelevant (their
        positions are their own)."""
        if not 0 <= tenant < self.tenants:
            raise ValueError(
                f"tenant {tenant} out of range 0..{self.tenants - 1}"
            )
        with self._cv:
            if self._buffered[tenant]:
                raise RuntimeError(
                    f"cannot install tenant {tenant} state over "
                    f"{self._buffered[tenant]} buffered row(s); flush first"
                )
            self.start_rows[tenant] = int(start_row)
            self.tenant_rows_admitted[tenant] = int(rows_admitted)

    def set_tenant_identity(
        self, tenant: int, shuffle_seed: "int | None"
    ) -> None:
        """Install a migrated tenant's stripe identity into slot
        ``tenant`` (see :meth:`MicroBatcher.set_tenant_identity`): the
        slot's striper rebuilds with the SHIPPED shuffle seed. The
        slot's own buffer must be empty; other tenants are untouched."""
        if not 0 <= tenant < self.tenants:
            raise ValueError(
                f"tenant {tenant} out of range 0..{self.tenants - 1}"
            )
        with self._cv:
            if self._buffered[tenant]:
                raise RuntimeError(
                    f"cannot install tenant {tenant} identity over "
                    f"{self._buffered[tenant]} buffered row(s); flush first"
                )
            self.shuffle_seeds[tenant] = shuffle_seed
            self._stripers[tenant] = ChunkStriper(
                self.partitions, self.per_batch, self.chunk_batches,
                shuffle_seed,
            )

    def get(self, timeout: float = 0.0) -> "SealedChunk | None":
        deadline = time.monotonic() + max(timeout, 0.0)
        with self._cv:
            while True:
                if self._error is not None:
                    raise self._error
                if self._queue:
                    item = self._queue.pop(0)
                    self._cv.notify_all()
                    return item
                now = time.monotonic()
                if (
                    any(self._buffered)
                    and self._first_ts is not None
                    and now - self._first_ts >= self.linger_s
                ):
                    self._seal_locked(full=False)
                    continue
                waits = [deadline - now]
                if any(self._buffered) and self._first_ts is not None:
                    waits.append(self._first_ts + self.linger_s - now)
                wait = min(waits)
                if deadline - now <= 0:
                    return None
                self._cv.wait(max(wait, 0.001))

    def _seal_locked(self, full: bool) -> None:
        from ..engine.loop import stack_tenants

        span = self.rows_per_chunk
        blocks, ts_parts = [], []
        t_rows, t_through, t_start = [], [], []
        traces: list[dict] = []
        seal_offset = 0  # index base into the tenant-major ingest array
        any_short = False
        for t in range(self.tenants):
            n_take = span if full else min(self._buffered[t], span)
            take, rest = _split_buffered(
                (self._X[t], self._y[t], self._ok[t], self._ts[t]),
                n_take,
                self.num_features,
            )
            take_X, take_y, take_ok, take_ts = take
            blocks.append(
                self._stripers[t].stripe(
                    take_X, take_y, self.start_rows[t], row_valid=take_ok
                )
            )
            ts_parts.append(take_ts)
            taken_before = self.tenant_rows_admitted[t] - self._buffered[t]
            if self._trace_marks[t]:
                taken, self._trace_marks[t] = _take_marks(
                    self._trace_marks[t], taken_before, n_take
                )
                traces.extend(
                    {
                        "idx": seal_offset + m["pos"] - taken_before,
                        "trace_id": m["trace_id"],
                        "parent_id": m["parent_id"],
                        "tenant": t,
                    }
                    for m in taken
                )
            seal_offset += n_take
            t_rows.append(int(n_take))
            t_through.append(int(taken_before + n_take))
            t_start.append(self.start_rows[t])
            any_short = any_short or n_take < span
            # Grid-slot semantics PER TENANT: every tenant's position
            # advances by the full span each seal, so blocks stay aligned.
            self.start_rows[t] += span
            self._X[t], self._y[t], self._ok[t], self._ts[t] = rest
            self._buffered[t] = len(rest[0][0]) if rest[0] else 0
        chunk = stack_tenants(blocks) if self.tenants > 1 else blocks[0]
        meta = {
            "chunk": self.chunk_index,
            "start_row": t_start[0],
            "rows": int(sum(t_rows)),
            "rows_through": int(sum(t_through)),
            "short": any_short,
            "sealed_ts": time.time(),
            "tenants": self.tenants,
            "t_rows": t_rows,
            "t_rows_through": t_through,
            "t_start_row": t_start,
            # row-tracing stamps: tenant-major concatenation, matching the
            # stacked grid's leading-axis order
            "ingest_mono": np.concatenate(ts_parts) if ts_parts else None,
            "sealed_mono": time.monotonic(),
        }
        if traces:
            meta["traces"] = traces
        self._queue.append(SealedChunk(chunk, meta))
        self.rows_sealed += int(sum(t_rows))
        self.chunk_index += 1
        self._first_ts = time.monotonic() if any(self._buffered) else None


def _json_field(v) -> str:
    """One JSON row value → one CSV field. Non-numeric values become a
    comma-free non-numeric token, so they reach the contract scan as a
    dirty CELL (quarantinable) instead of crashing the normalizer — a
    daemon must never die on one malformed row."""
    try:
        return repr(float(v))
    except (TypeError, ValueError):
        return str(v).replace(",", ";") or "''"


def _json_line_to_csv(line: str) -> str:
    """Normalize a JSON row (``{"x": [...], "y": l}`` or ``[f..., l]``)
    to the CSV field form the shared parser consumes; malformed JSON is
    returned as-is so it flows through the contract scan like any other
    dirty line (one parse path, one policy)."""
    import json

    try:
        obj = json.loads(line)
    except json.JSONDecodeError:
        return line
    if isinstance(obj, dict):
        fields = list(obj.get("x") or []) + [obj.get("y")]
    elif isinstance(obj, list):
        fields = obj
    else:
        return line
    return ",".join(_json_field(v) for v in fields)


class AdmissionController:
    """The per-block sanitize → push gate (see module docstring).

    ``num_features`` fixes the ingress line contract: every row carries
    exactly ``num_features + 1`` comma-separated fields with the label
    LAST (or the JSON forms, normalized to the same fields). Labels must
    already be integral and in ``0..num_classes-1`` — a daemon cannot
    re-index classes the way the one-shot loader does; out-of-range
    labels are contract violations handled by the policy.
    """

    def __init__(
        self,
        batcher: MicroBatcher,
        num_features: int,
        num_classes: int,
        *,
        policy: str = "quarantine",
        quarantine_path: "str | None" = None,
        metrics=None,
        source: str = "ingress",
    ):
        sanitize.check_policy(policy)
        self.batcher = batcher
        self.num_features = int(num_features)
        self.num_classes = int(num_classes)
        self.columns = self.num_features + 1
        self.tcol = self.num_features  # label last — the line contract
        self.policy = policy
        self.source = source
        self._stats = (
            sanitize.RunningColumnStats(self.columns)
            if policy == "repair"
            else None
        )
        self._writer = (
            sanitize.QuarantineWriter(quarantine_path, policy)
            if quarantine_path and policy != "strict"
            else None
        )
        self.rows_seen = 0  # ingress data rows consumed (admitted+rejected)
        self.rows_rejected = 0
        self.rows_quarantined = 0
        self.rows_repaired = 0
        # One admission at a time: handler threads (one per connection)
        # share this controller, and the absolute-row accounting, running
        # stats, counters and lazy sidecar writer all assume sequential
        # blocks. Admission order across connections is arbitrary anyway
        # (the network already interleaves), so serializing loses nothing.
        self._lock = threading.Lock()
        self._c_rows = self._c_quar = self._c_rej = None
        if metrics is not None:
            self._c_rows = metrics.counter(
                "ingest_rows_total", help="Stream rows admitted at ingress"
            )
            self._c_quar = metrics.counter(
                sanitize.QUARANTINE_METRIC, help=sanitize.QUARANTINE_METRIC_HELP
            )
            self._c_rej = metrics.counter(
                "serve_rejected_total",
                help="Ingress rows refused under data_policy=strict",
            )

    def close(self) -> None:
        if self._writer is not None:
            self._writer.close()

    def admit_lines(self, lines: list[str], traces=None) -> dict:
        """Sanitize + admit one block of protocol data lines; returns the
        block's accounting (``error`` is the strict-rejection message for
        the connection, None otherwise). Thread-safe (serialized).

        ``traces`` marks head-sampled rows (telemetry.tracing):
        ``[(line_index, trace_id, parent_span_id), ...]`` — indices into
        ``lines``. Marks follow their rows through the policy (a
        strict-rejected row's mark is dropped with it; quarantined rows
        keep their positions and their marks) into the batcher, which
        carries them to the covering seal's meta."""
        with self._lock:
            return self._admit_lines_locked(lines, traces)

    def _parse_block(
        self, lines: list[str]
    ) -> tuple[np.ndarray, list[sanitize.RowIssue]]:
        """Fast-path-then-fallback parse, the batch readers' shape: a
        vectorized parse serves the (overwhelmingly common) clean block;
        the tolerant parser runs only when it refuses — and that parser
        is itself tier-vectorized (``sanitize.parse_rows``: whole-block →
        per-row → per-cell), so a dirty block still parses its clean rows
        in batched ``np.asarray`` calls rather than a per-cell Python
        loop. Ingress hands whole recv-blocks here (``serve.ingress``),
        which is what makes the batching real under load. NaN/Inf parse
        fine on the fast path and are caught by the matrix scan like
        everywhere else."""
        import io as _io

        try:
            arr = np.loadtxt(
                _io.StringIO("\n".join(lines)),
                delimiter=",",
                dtype=np.float32,
                ndmin=2,
            )
            if arr.shape == (len(lines), self.columns):
                return arr, []
        except ValueError:
            pass
        return sanitize.parse_rows(lines, self.columns)

    def _admit_lines_locked(self, lines: list[str], traces=None) -> dict:
        if traces:
            # Re-anchor marks across the blank-line filter below so a
            # mark keeps pointing at ITS row (ingress never sends blanks,
            # but direct embedders may).
            kept = [i for i, ln in enumerate(lines) if ln.strip()]
            remap = {orig: new for new, orig in enumerate(kept)}
            traces = [
                (remap[i], tid, pid)
                for i, tid, pid in traces
                if i in remap
            ]
        lines = [
            _json_line_to_csv(ln) if ln.lstrip()[:1] in "{[" else ln
            for ln in lines
            if ln.strip()
        ]
        # Fault-injection site (resilience.faults; no-op unless armed):
        # corruption kinds mutate the live protocol lines — dirty traffic
        # by seeded injection; raise/timeout poison the batcher upstream
        # (the ingress handler routes the exception there).
        faults.fire(
            "serve.ingress",
            lines=lines,
            label_col=self.tcol,
            rows_seen=self.rows_seen,
        )
        if not lines:
            return {"rows": 0, "admitted": 0, "error": None}
        arr, issues = self._parse_block(lines)
        flagged = frozenset(i.row for i in issues)
        issues = issues + sanitize.scan_matrix(arr, self.tcol, flagged=flagged)
        base = self.rows_seen
        self.rows_seen += len(arr)
        return self._admit_block_locked(
            arr, issues, base, traces, rows=len(lines)
        )

    def admit_frame(self, X, y, traces=None) -> dict:
        """Admit one v2 binary frame: columnar ``[n, F]`` f32 features +
        ``[n]`` i32 labels (``serve.wire``), skipping the text parse
        entirely. The overwhelmingly common clean frame admits with two
        vectorized scans (finite cells, label domain) and **zero
        copies** — the payload views push straight into the batcher,
        which stripes them through its pooled staging buffers; a dirty
        frame assembles the combined matrix once and flows through the
        SAME ``scan_matrix`` → policy tail as text admission, so
        strict/quarantine/repair semantics (positions, sidecar records,
        counters, error text) are identical between the protocols.
        Thread-safe (serialized), like :meth:`admit_lines`."""
        with self._lock:
            return self._admit_frame_locked(
                np.asarray(X), np.asarray(y), traces
            )

    def _admit_frame_locked(self, X, y, traces=None) -> dict:
        n = len(y)
        if X.ndim != 2 or X.shape != (n, self.num_features):
            raise FrameContractError(
                f"frame shape {X.shape}/{y.shape} does not match the "
                f"daemon's contract of {self.num_features} feature(s) "
                "per row"
            )
        # Fault-injection site (resilience.faults; no-op unless armed):
        # raise/timeout poison the batcher upstream exactly like the text
        # path. The corruption kinds mutate text lines and are a no-op
        # here — seed v2 dirt client-side (loadgen --wire v2 --dirty).
        faults.fire("serve.ingress", rows_seen=self.rows_seen, frame_rows=n)
        if n == 0:
            return {"rows": 0, "admitted": 0, "error": None}
        base = self.rows_seen
        self.rows_seen += n
        # Clean fast path: labels integral by wire construction, so the
        # whole contract collapses to two vectorized checks. num_classes
        # bounds the label far below the 2^24 f32-exactness clause.
        clean = bool(
            ((y >= 0) & (y < self.num_classes)).all()
        ) and bool(np.isfinite(X).all())
        if clean:
            if self._stats is not None:
                # Running repair stats want every admitted row as
                # evidence (the one combined-matrix copy the repair
                # policy pays; quarantine/strict daemons skip it).
                arr = np.empty((n, self.columns), np.float32)
                arr[:, : self.num_features] = X
                arr[:, self.tcol] = y
                self._stats.update(arr, None)
            if self._c_rows is not None:
                self._c_rows.inc(n)
            self.batcher.push(X, y, None, traces or None)
            return {"rows": n, "admitted": n, "error": None}
        # Dirty frame (rare): assemble the combined matrix once and run
        # the one shared policy tail — bit-identical semantics to text.
        arr = np.empty((n, self.columns), np.float32)
        arr[:, : self.num_features] = X
        arr[:, self.tcol] = y
        issues = sanitize.scan_matrix(arr, self.tcol)
        return self._admit_block_locked(arr, issues, base, traces, rows=n)

    def _admit_block_locked(
        self, arr, issues, base: int, traces, *, rows: int
    ) -> dict:
        """The shared policy tail: label-domain clause + strict/
        quarantine/repair resolution + stats/counters + batcher push.
        One copy of these semantics — the v1 text and v2 frame paths must
        not be able to drift apart."""
        # Serving-only contract clause: the label domain is configuration
        # (no re-indexing pass exists on a live stream). Checked on the
        # ROUNDED label — np.round is exactly what the repair policy will
        # apply, so a label that would round out of the domain (e.g. 9.6
        # at 10 classes) is an unrepairable violation here, never an
        # out-of-range index handed to the engine.
        y = arr[:, self.tcol]
        with np.errstate(invalid="ignore"):
            y_r = np.round(y)
            in_range = np.isfinite(y) & (y_r >= 0) & (y_r < self.num_classes)
        for r in np.nonzero(~in_range)[0]:
            # Appended even when the row already carries another issue: a
            # repairable one (non-integral label) must not shadow this
            # UNREPAIRABLE violation, or repair would round the label
            # straight out of the engine's index domain.
            issues.append(
                sanitize.RowIssue(
                    int(r),
                    self.tcol,
                    f"label {float(y[r])!r} outside the configured "
                    f"class domain 0..{self.num_classes - 1}",
                )
            )
        issues.sort(key=lambda i: (i.row, -1 if i.column is None else i.column))

        error = None
        ok = None
        if self.policy == "repair" and issues:
            arr, issues, repaired = sanitize.repair_rows(
                arr, issues, self.tcol, self._stats
            )
            self.rows_repaired += repaired
        if self.policy == "strict":
            if issues:
                bad = sorted({i.row for i in issues})
                first = issues[0]
                error = (
                    f"rejected {len(bad)} row(s); first: data row "
                    f"{base + first.row}"
                    + ("" if first.column is None else f", column {first.column}")
                    + f": {first.reason}"
                )
                self.rows_rejected += len(bad)
                if self._c_rej is not None:
                    self._c_rej.inc(len(bad))
                keep = np.ones(len(arr), bool)
                keep[bad] = False
                if traces:
                    # rejected rows vanish (no stream position) — their
                    # marks go with them; survivors shift down
                    new_idx = np.cumsum(keep) - 1
                    traces = [
                        (int(new_idx[i]), tid, pid)
                        for i, tid, pid in traces
                        if keep[i]
                    ]
                arr = arr[keep]
        else:
            arr, ok = sanitize.apply_block_policy(
                arr,
                issues,
                path=self.source,
                policy=self.policy,
                base_row=base,
                writer=self._writer,
            )
            if ok is not None:
                n_bad = int((~ok).sum())
                self.rows_quarantined += n_bad
                if self._c_quar is not None:
                    self._c_quar.inc(n_bad)
        if self._stats is not None and len(arr):
            self._stats.update(arr, ok)
        admitted = len(arr)
        if admitted:
            if self._c_rows is not None:
                self._c_rows.inc(admitted)
            self.batcher.push(
                arr[:, : self.num_features],
                arr[:, self.tcol].astype(np.int32),
                ok,
                traces or None,
            )
        return {"rows": rows, "admitted": admitted, "error": error}
