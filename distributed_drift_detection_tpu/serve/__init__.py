"""Always-on online drift serving (ROADMAP item 2).

The batch pipeline is load → run → exit; this package turns the same
engines into a long-lived service:

* :mod:`.ingress` — one readiness-based event loop multiplexing every
  connection; v1 text lines (CSV/JSON rows, ``FLUSH``/``STOP`` controls)
  and v2 binary columnar frames auto-detected per message;
* :mod:`.wire` — the v2 frame codec (length-prefixed binary columnar
  frames: the wire twin of the ``[P, CB, B]`` grid);
* :mod:`.admission` — sanitize-at-admission (the PR-5
  ``strict|quarantine|repair`` contract on live traffic) + the
  fixed-geometry :class:`~.admission.MicroBatcher` with a max-linger
  deadline — short batches pad through the validity plane, so shapes
  stay static and nothing recompiles;
* :mod:`.runner` — the AOT-prepared serving loop over the donated
  double-buffered :class:`~..engine.chunked.ChunkedDetector`, verdict
  sidecar + schema-v1 telemetry, checkpointed state, graceful SIGTERM
  drain;
* :mod:`.loadgen` — stream replay at a target rows/s with seeded dirty
  injection and the p50/p99 row→verdict latency SLO report.

Lazy exports (PEP 562): importing the package pulls no jax — the CLIs
decide what they need.
"""

from __future__ import annotations

_EXPORTS = {
    "AdmissionController": ".admission",
    "MicroBatcher": ".admission",
    "TenantMicroBatcher": ".admission",
    "SealedChunk": ".admission",
    "IngressServer": ".ingress",
    "ServeRunner": ".runner",
    "find_verdicts": ".runner",
    "read_verdicts": ".runner",
    "run_loadgen": ".loadgen",
    # fleet layer (tenant router over N daemons)
    "TenantRouter": ".router",
    "BackendSpec": ".router",
    "HashRing": ".router",
    "plan_fleet": ".router",
    # wire protocol v2 (binary columnar frames)
    "WireError": ".wire",
    "encode_frame": ".wire",
    "decode_frame": ".wire",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    if name in _EXPORTS:
        import importlib

        mod = importlib.import_module(_EXPORTS[name], __name__)
        return getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
