"""Wire protocol v2: length-prefixed binary columnar frames.

The v1 serve protocol ships one CSV/JSON text row per line — admission
parses text row by row, orders of magnitude behind the device. A v2
**frame** is the wire twin of one span of the ``[P, CB, B]`` grid: a
fixed 16-byte header followed by a columnar payload — the whole feature
block as one contiguous little-endian f32 matrix, then the label vector
as i32 — so the daemon admits thousands of rows with a handful of
vectorized numpy calls and **zero text parsing**.

Frame layout (all little-endian)::

    offset  size  field
    ------  ----  -----------------------------------------------------
         0     2  magic     0xDDF2  (first wire byte 0xF2 — non-ASCII,
                            so a byte at a message boundary tells v2
                            frames from v1 text lines unambiguously)
         2     1  version   2
         3     1  flags     0 = data frame; FLAG_FLUSH / FLAG_STOP mark
                            a zero-row CONTROL frame (the binary twins
                            of the FLUSH / STOP text lines)
         4     4  tenant    u32 tenant slot (0 on solo daemons)
         8     4  rows      u32 row count  (>= 1 for data frames)
        12     4  features  u32 feature count (must equal the daemon's
                            --features; label is NOT counted)
        16     …  payload   rows*features f32 feature block (row-major)
                            followed by rows i32 labels

Auto-detection: v1 data rows start with an ASCII digit/sign/``{``/``[``
and v1 controls with an ASCII letter, so the first byte of any v1
message is < 0x80. The magic's first wire byte (0xF2) can therefore
never open a text message — the ingress checks one byte at each message
boundary and routes to the right decoder; one connection may freely mix
text lines and frames.

The decoder validates structure without copying payload bytes:
:func:`decode_header` reads the fixed header from a ``memoryview`` and
bounds-checks the declared geometry (an oversized ``rows``/``features``
is a :class:`WireError` *before* any allocation happens — a malicious
or corrupt header must not OOM the daemon), and :func:`payload_views`
wraps the payload buffer with ``np.frombuffer`` — the returned arrays
alias the buffer, no copy. Everything here is jax-free stdlib + numpy.
"""

from __future__ import annotations

import struct
from typing import NamedTuple

import numpy as np

#: u16 little-endian — first byte on the wire is 0xF2 (non-ASCII).
MAGIC = 0xDDF2
MAGIC_BYTE = MAGIC & 0xFF  # 0xF2, the one-byte protocol discriminator
VERSION = 2

_HEADER = struct.Struct("<HBBIII")
HEADER_SIZE = _HEADER.size  # 16

#: Control-frame flags (zero-row frames; the binary FLUSH/STOP twins).
FLAG_FLUSH = 0x01
FLAG_STOP = 0x02
_KNOWN_FLAGS = FLAG_FLUSH | FLAG_STOP

#: Decoder bounds: a header declaring more than this is malformed, not
#: merely large — the daemon must refuse it before allocating anything.
#: (``max_rows`` is overridable per daemon via ServeParams.max_frame_rows.)
MAX_FRAME_ROWS = 1 << 20
MAX_FRAME_FEATURES = 1 << 16


class WireError(ValueError):
    """A structurally invalid v2 frame (bad magic/version, out-of-bounds
    geometry, zero-row data frame, unknown flags). Connection-local: the
    ingress answers ``ERR`` and drops that connection, never the daemon."""


class FrameHeader(NamedTuple):
    """The decoded fixed header of one v2 frame."""

    version: int
    flags: int
    tenant: int
    rows: int
    features: int

    @property
    def is_control(self) -> bool:
        return self.rows == 0 and self.flags != 0

    @property
    def payload_nbytes(self) -> int:
        return self.rows * self.features * 4 + self.rows * 4

    @property
    def frame_nbytes(self) -> int:
        return HEADER_SIZE + self.payload_nbytes


def decode_header(
    buf, *, max_rows: int = MAX_FRAME_ROWS, max_features: int = MAX_FRAME_FEATURES
) -> FrameHeader:
    """Decode + validate the 16-byte header at the start of ``buf``.

    ``buf`` is any buffer-protocol object holding at least
    :data:`HEADER_SIZE` bytes; nothing is copied. Raises
    :class:`WireError` on any structural violation.
    """
    magic, version, flags, tenant, rows, features = _HEADER.unpack_from(buf)
    if magic != MAGIC:
        raise WireError(f"bad frame magic 0x{magic:04X} (expected 0x{MAGIC:04X})")
    if version != VERSION:
        raise WireError(f"unsupported wire version {version} (expected {VERSION})")
    if flags & ~_KNOWN_FLAGS:
        raise WireError(f"unknown frame flags 0x{flags:02X}")
    if flags:
        # Control frame: geometry must be zero — a flagged frame that
        # also declares rows is ambiguous, and ambiguity on an untrusted
        # wire is an error, not a guess.
        if rows or features:
            raise WireError(
                f"control frame (flags 0x{flags:02X}) declares geometry "
                f"rows={rows} features={features}; control frames are empty"
            )
        return FrameHeader(version, flags, tenant, rows, features)
    if rows == 0:
        raise WireError("zero-row data frame (empty frames carry control flags)")
    if rows > max_rows:
        raise WireError(f"frame declares {rows} rows (max {max_rows})")
    if features == 0:
        raise WireError("data frame declares zero features")
    if features > max_features:
        raise WireError(
            f"frame declares {features} features (max {max_features})"
        )
    return FrameHeader(version, flags, tenant, rows, features)


def payload_views(
    header: FrameHeader, payload
) -> "tuple[np.ndarray, np.ndarray]":
    """``(X [rows, features] f32, y [rows] i32)`` views over ``payload``.

    Zero-copy: the arrays alias the buffer (``np.frombuffer``). The
    caller owns the buffer's lifetime — the ingress hands each frame its
    own buffer, filled straight from the socket, so the views stay valid
    for as long as the admitted rows do.
    """
    n, f = header.rows, header.features
    if len(payload) != header.payload_nbytes:
        raise WireError(
            f"payload holds {len(payload)} byte(s); header declares "
            f"{header.payload_nbytes}"
        )
    X = np.frombuffer(payload, dtype="<f4", count=n * f).reshape(n, f)
    y = np.frombuffer(payload, dtype="<i4", count=n, offset=n * f * 4)
    return X, y


def decode_frame(
    buf, *, max_rows: int = MAX_FRAME_ROWS, max_features: int = MAX_FRAME_FEATURES
):
    """Decode one frame from the head of ``buf``.

    Returns ``(header, X, y, consumed_bytes)`` for a complete data frame
    (``X``/``y`` are zero-copy views into ``buf``), ``(header, None,
    None, consumed)`` for a control frame, or ``None`` when ``buf``
    holds a valid but incomplete prefix (wait for more bytes). Raises
    :class:`WireError` on malformed input. The streaming ingress keeps
    its own incremental state machine; this whole-buffer form is the
    reference decoder the tests and fuzzers drive.
    """
    mv = memoryview(buf)
    if len(mv) == 0:
        return None
    if mv[0] != MAGIC_BYTE:
        raise WireError(
            f"bad frame magic: first byte 0x{mv[0]:02X} (expected "
            f"0x{MAGIC_BYTE:02X})"
        )
    if len(mv) < HEADER_SIZE:
        # Partial header: everything present so far must still look like
        # a frame (second magic byte, version), else fail now.
        if len(mv) >= 2 and mv[1] != (MAGIC >> 8):
            raise WireError("bad frame magic (second byte)")
        if len(mv) >= 3 and mv[2] != VERSION:
            raise WireError(f"unsupported wire version {mv[2]}")
        return None
    header = decode_header(mv, max_rows=max_rows, max_features=max_features)
    total = header.frame_nbytes
    if len(mv) < total:
        return None
    if header.is_control:
        return header, None, None, HEADER_SIZE
    X, y = payload_views(header, mv[HEADER_SIZE:total])
    return header, X, y, total


def encode_frame(X, y, *, tenant: int = 0, flags: int = 0) -> bytes:
    """Encode one data frame (client side — ``loadgen --wire v2``).

    ``X`` is ``[rows, features]`` (cast to f32), ``y`` ``[rows]`` (cast
    to i32); rows must be >= 1.
    """
    X = np.ascontiguousarray(X, "<f4")
    y = np.ascontiguousarray(y, "<i4")
    if X.ndim != 2 or y.ndim != 1 or len(X) != len(y):
        raise ValueError(
            f"frame wants X [rows, features] and y [rows]; got "
            f"{X.shape} / {y.shape}"
        )
    if len(y) == 0:
        raise ValueError("cannot encode a zero-row data frame")
    header = _HEADER.pack(
        MAGIC, VERSION, flags, tenant, X.shape[0], X.shape[1]
    )
    return header + X.tobytes() + y.tobytes()


def encode_control(flags: int, *, tenant: int = 0) -> bytes:
    """Encode a control frame (``FLAG_FLUSH`` / ``FLAG_STOP``)."""
    if not flags or flags & ~_KNOWN_FLAGS:
        raise ValueError(f"control flags must be FLUSH/STOP, got 0x{flags:02X}")
    return _HEADER.pack(MAGIC, VERSION, flags, tenant, 0, 0)


def encode_flush() -> bytes:
    """The binary twin of the ``FLUSH`` text line."""
    return encode_control(FLAG_FLUSH)


def encode_stop() -> bytes:
    """The binary twin of the ``STOP`` text line."""
    return encode_control(FLAG_STOP)
