"""Tenant router: one front daemon placing tenants across N serving
daemons, with live migration (ROADMAP item 1, fleet layer).

    python -m distributed_drift_detection_tpu router --port 0 \\
        --backend 127.0.0.1:7007:7008 --backend 127.0.0.1:7017:7018 \\
        --telemetry-dir runs/fleet [...]

One compiled tenant plane (PR 9) caps at one process on one host's
devices; a fleet is N such daemons behind this router. Clients speak the
existing v1/v2 wire protocols with **global** tenant ids; the router
owns the ``global tenant → (backend, slot)`` placement and rewrites each
message's tenant routing (the ``TENANT`` line, or 4 header bytes of a v2
frame) on the way through — backends see only their own slot indices and
stay bit-identical to solo daemons.

**Placement** is consistent hashing (:class:`HashRing`): stable under
fleet growth, and a dead backend's tenants re-place WITHOUT disturbing
anyone else's placement. :func:`plan_fleet` computes the initial
assignment the operator starts each backend with (``serve --tenant-ids
g0,g1,...,-1`` — trailing ``-1`` slots are vacant spares, the landing
capacity migrations need; slot counts are compiled into each backend's
kernel, so failover capacity is provisioned up front, not grown).

**Liveness**: a health thread polls each backend's ops-plane
``/healthz`` (the PR-8 stall contract — 200 *or* 503 mean alive; only a
dead socket means dead) and any data-path send/EOF failure reports the
same way. After ``health_fails`` consecutive misses the backend is
declared dead and its tenants fail over.

**Migration** (drain → ship → resume; flags bit-identical across the
move) uses the serve daemons' SAVETENANT/LOADTENANT control surface and
the solo-shaped per-tenant checkpoints:

* *graceful* (``migrate_tenant``, rebalance): quiesce the tenant (the
  event loop buffers its rows instead of forwarding), FLUSH the source
  and wait until the slot's admitted rows match the router's forwarded
  count, ``SAVETENANT`` → ship the checkpoint (shared filesystem) →
  ``LOADTENANT`` into a vacant slot elsewhere, re-send any delta from
  the per-tenant replay buffer, resume. The vacated slot becomes new
  landing capacity.
* *failover* (dead backend): each orphaned tenant re-places from its
  LAST checkpoint (``<checkpoint>.t<slot>``, written by ``serve
  --tenant-checkpoints``); the landing reply reports the checkpoint's
  ``rows_admitted`` watermark and the router re-sends every buffered row
  past it — no verdict is lost past the checkpoint, and rows in the gap
  (buffer overrun) are counted loudly in the journal, never silently.

**Rebalance**: ``--rebalance-every`` polls the backends' ``/statusz``
per-tenant stream accounting (the ops plane's own rebalance signal) and
migrates the hottest tenant off the hottest backend when the
max/min row-rate ratio exceeds ``--rebalance-ratio`` (and somewhere has
a vacant slot). Off by default — placement changes are journaled either
way (``router.journal.jsonl``).

The router is jax-free (stdlib + numpy): it moves bytes and 4-byte
header rewrites, never rows through a kernel. Its own ops plane
(``--ops-port``) serves ``/healthz``, ``/metrics`` and a ``/statusz``
the ``top`` dashboard renders next to the backends'.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import selectors
import socket
import struct
import sys
import threading
import time
import urllib.error
import urllib.request
from collections import deque

from . import wire

JOURNAL_NAME = "router.journal.jsonl"

#: Default per-tenant replay-buffer cap (rows). The buffer must cover
#: the worst-case gap between a backend's last per-tenant checkpoint and
#: its death — checkpoint_every chunks of the serving grid, plus
#: whatever was in flight.
REPLAY_BUFFER_ROWS = 1 << 16


# ---------------------------------------------------------------------------
# consistent-hash placement
# ---------------------------------------------------------------------------


class HashRing:
    """Consistent hashing over backend names (md5 ring, ``vnodes``
    virtual points per backend): ``place(key)`` is stable under fleet
    growth, and excluding a dead backend moves ONLY its keys."""

    def __init__(self, names, vnodes: int = 64):
        names = list(names)
        if not names:
            raise ValueError("a hash ring needs at least one backend")
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate backend names: {names}")
        self.names = names
        self._ring: list[tuple[int, str]] = sorted(
            (self._point(f"{name}#{v}"), name)
            for name in names
            for v in range(vnodes)
        )

    @staticmethod
    def _point(key: str) -> int:
        return int.from_bytes(hashlib.md5(key.encode()).digest()[:8], "big")

    def place(self, key, exclude=()) -> str:
        """The backend owning ``key`` (first ring point clockwise of the
        key's hash), skipping ``exclude``\\ d (dead) backends."""
        excluded = set(exclude)
        alive = [n for n in self.names if n not in excluded]
        if not alive:
            raise RuntimeError("no live backend to place on")
        h = self._point(str(key))
        # bisect over the precomputed ring; walk past excluded points
        lo, hi = 0, len(self._ring)
        while lo < hi:
            mid = (lo + hi) // 2
            if self._ring[mid][0] < h:
                lo = mid + 1
            else:
                hi = mid
        for k in range(len(self._ring)):
            point, name = self._ring[(lo + k) % len(self._ring)]
            if name not in excluded:
                return name
        raise RuntimeError("unreachable: ring exhausted")  # pragma: no cover


def plan_fleet(
    tenants: int, backends, spares: int = 1
) -> "dict[str, list[int]]":
    """Initial placement: global tenants ``0..tenants-1`` dealt over
    ``backends`` by the ring, each backend padded with ``spares`` vacant
    ``-1`` slots (migration landing capacity). The result is each
    daemon's ``--tenant-ids`` list — and every backend gets at least one
    slot even when the ring assigns it no tenants (a kernel needs T >= 1).
    """
    names = list(backends)
    ring = HashRing(names)
    assign: dict[str, list[int]] = {n: [] for n in names}
    for g in range(tenants):
        assign[ring.place(g)].append(g)
    return {
        n: ids + [-1] * max(spares, 1 if not ids else spares)
        for n, ids in assign.items()
    }


# ---------------------------------------------------------------------------
# backends
# ---------------------------------------------------------------------------


class BackendSpec:
    """``host:port:ops_port`` (a ``serve`` daemon's data + ops ports)."""

    def __init__(self, spec: str):
        parts = spec.split(":")
        if len(parts) != 3:
            raise ValueError(
                f"backend spec {spec!r} must be host:port:ops_port"
            )
        self.host = parts[0]
        self.port = int(parts[1])
        self.ops_port = int(parts[2])

    def __repr__(self):
        return f"{self.host}:{self.port}:{self.ops_port}"


class _Backend:
    """One serving daemon as the router sees it: identity + slot table
    discovered from its ``/statusz``, a persistent data connection, a
    lazy control connection, and liveness accounting."""

    def __init__(self, spec: BackendSpec):
        self.spec = spec
        self.name = ""  # discovered (serve --name, or host:port)
        self.slot_ids: list[int] = []  # global id per slot; -1 = vacant
        self.checkpoint = ""  # the daemon's plane-checkpoint stem
        self.tenant_checkpoints = False
        self.alive = True
        self.health_fails = 0
        self.rows_forwarded = 0
        self.sock: "socket.socket | None" = None
        self.send_lock = threading.Lock()
        self._ctrl: "socket.socket | None" = None
        self._ctrl_buf = b""
        self._ctrl_lock = threading.Lock()

    # -- discovery -----------------------------------------------------------

    def statusz(self, timeout: float = 5.0) -> dict:
        url = f"http://{self.spec.host}:{self.spec.ops_port}/statusz"
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return json.load(r)

    def metrics_text(self, timeout: float = 5.0) -> str:
        url = f"http://{self.spec.host}:{self.spec.ops_port}/metrics"
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.read().decode()

    def healthz(self, timeout: float = 2.0) -> bool:
        """True while the daemon ANSWERS — 200 and 503 both mean alive
        (503 is an SLO alert, the daemon's own problem); only a dead
        socket means dead."""
        url = f"http://{self.spec.host}:{self.spec.ops_port}/healthz"
        try:
            with urllib.request.urlopen(url, timeout=timeout):
                return True
        except urllib.error.HTTPError:
            return True  # it answered; 503 = alerting, not dead
        except (urllib.error.URLError, OSError):
            return False

    def discover(self, connect_timeout: float = 30.0) -> None:
        """Resolve identity + slot table from the live daemon (retries
        until ``connect_timeout`` — the fleet may still be compiling)."""
        deadline = time.monotonic() + connect_timeout
        last: "Exception | None" = None
        while time.monotonic() < deadline:
            try:
                s = self.statusz()
                break
            except (urllib.error.URLError, OSError, ValueError) as e:
                last = e
                time.sleep(0.2)
        else:
            raise RuntimeError(
                f"backend {self.spec} unreachable: {last}"
            )
        self.name = s.get("name") or f"{self.spec.host}:{self.spec.port}"
        detail = s.get("tenant_detail") or []
        ids = [int(t["id"]) for t in detail]
        if not ids:
            # a solo daemon's slot table is its one (global) tenant
            ids = [0] if s.get("tenants", 1) == 1 else list(
                range(int(s["tenants"]))
            )
        self.slot_ids = ids
        self.checkpoint = s.get("checkpoint") or ""
        self.sock = socket.create_connection(
            (self.spec.host, self.spec.port), timeout=10
        )
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.sock.setblocking(False)

    # -- data path -----------------------------------------------------------

    def send(self, payload: bytes) -> None:
        """One whole wire message to the daemon (thread-safe; the event
        loop and the migration thread both land here). Raises OSError on
        a dead peer — the caller reports the death."""
        with self.send_lock:
            sock = self.sock
            if sock is None:
                raise OSError(f"backend {self.name} has no data connection")
            # sendall on a non-blocking socket raises on a FULL buffer,
            # not just a dead peer — spin the short waits out.
            view = memoryview(payload)
            while view:
                try:
                    n = sock.send(view)
                    view = view[n:]
                except (BlockingIOError, InterruptedError):
                    time.sleep(0.001)

    # -- control path (SAVETENANT / LOADTENANT / FLUSH acks) -----------------

    def control(self, line: str, timeout: float = 120.0) -> str:
        """One control request → its ``OK``/``ERR`` reply line, over a
        dedicated connection (data-path ERR chatter must never
        interleave with a migration's replies). Any failure mid-exchange
        tears the connection down — a reply still in flight after a
        timeout must never be read as the NEXT request's answer (an
        off-by-one reply stream would mis-attribute every migration ack
        after it)."""
        with self._ctrl_lock:
            try:
                if self._ctrl is None:
                    self._ctrl = socket.create_connection(
                        (self.spec.host, self.spec.port), timeout=10
                    )
                    self._ctrl.setsockopt(
                        socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
                    )
                self._ctrl.settimeout(timeout)
                self._ctrl.sendall((line + "\n").encode())
                while b"\n" not in self._ctrl_buf:
                    chunk = self._ctrl.recv(4096)
                    if not chunk:
                        raise OSError(
                            f"backend {self.name} closed the control "
                            "connection"
                        )
                    self._ctrl_buf += chunk
                reply, _, self._ctrl_buf = self._ctrl_buf.partition(b"\n")
                return reply.decode(errors="replace").strip()
            except OSError:
                if self._ctrl is not None:
                    try:
                        self._ctrl.close()
                    except OSError:
                        pass
                    self._ctrl = None
                self._ctrl_buf = b""
                raise

    def close(self) -> None:
        for attr in ("sock", "_ctrl"):
            s = getattr(self, attr)
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass
                setattr(self, attr, None)


# ---------------------------------------------------------------------------
# rebalance planning (pure — the auto thread and the tests share it)
# ---------------------------------------------------------------------------


def plan_rebalance(
    backend_rates: "dict[str, float]",
    tenant_rates: "dict[str, dict[int, float]]",
    vacancies: "dict[str, int]",
    ratio: float = 2.0,
) -> "tuple[int, str, str] | None":
    """``(tenant, src, dst)`` when the fleet is imbalanced, else None.

    ``backend_rates`` maps backend → recent rows/s, ``tenant_rates``
    backend → {global tenant: recent rows/s}, ``vacancies`` backend →
    vacant slot count. Imbalanced means the hottest backend's rate
    exceeds the coolest's by ``ratio`` (a cold fleet never rebalances),
    the hottest backend serves more than one tenant (moving its only
    tenant moves the imbalance), and the coolest has a vacant slot."""
    rated = {n: r for n, r in backend_rates.items() if r is not None}
    if len(rated) < 2:
        return None
    hot = max(rated, key=rated.get)
    cold = min(rated, key=rated.get)
    if hot == cold or rated[hot] < ratio * max(rated[cold], 1e-9):
        return None
    movable = tenant_rates.get(hot) or {}
    if len(movable) < 2 or not vacancies.get(cold):
        return None
    return max(movable, key=movable.get), hot, cold


# ---------------------------------------------------------------------------
# the router
# ---------------------------------------------------------------------------


class TenantRouter:
    """Lifecycle owner of one router daemon (see module docstring).

    In-process embedding (tests, ``bench --fleet``)::

        router = TenantRouter([BackendSpec("127.0.0.1:7007:7008"), ...])
        banner = router.start()        # discovers backends, binds the port
        ...                            # clients connect to banner["port"]
        router.migrate_tenant(3, "b2") # graceful drain → ship → resume
        router.stop()
    """

    def __init__(
        self,
        backends,
        *,
        host: str = "127.0.0.1",
        port: "int | None" = 0,
        ops_port: "int | None" = None,
        telemetry_dir: "str | None" = None,
        name: str = "router",
        health_interval_s: float = 1.0,
        health_fails: int = 3,
        failover: bool = True,
        replay_rows: int = REPLAY_BUFFER_ROWS,
        rebalance_every_s: float = 0.0,
        rebalance_ratio: float = 2.0,
        connect_timeout: float = 60.0,
        max_frame_rows: int = wire.MAX_FRAME_ROWS,
    ):
        self.backends = [
            _Backend(b if isinstance(b, BackendSpec) else BackendSpec(b))
            for b in backends
        ]
        if not self.backends:
            raise ValueError("a router needs at least one backend")
        self.host = host
        self.port = port
        self.ops_port = ops_port
        self.name = name
        self.telemetry_dir = telemetry_dir
        self.health_interval_s = health_interval_s
        self.health_fails = max(int(health_fails), 1)
        self.failover = failover
        self.replay_rows = int(replay_rows)
        self.rebalance_every_s = rebalance_every_s
        self.rebalance_ratio = rebalance_ratio
        self.connect_timeout = connect_timeout
        # reject oversized client frames at the ROUTER's edge: a frame
        # the backends would refuse must not reach the shared persistent
        # data connection (the backend answers a protocol reject by
        # closing it, which reads as a dead backend → failover churn);
        # set this to the MINIMUM of the backends' --max-frame-rows
        self.max_frame_rows = int(max_frame_rows)

        # Routing state — one lock guards the placement table, tenant
        # quiesce states, replay buffers and counters. Data-socket sends
        # happen OUTSIDE it (per-backend send locks order the bytes).
        self._lock = threading.RLock()
        self.place: "dict[int, tuple[_Backend, int]]" = {}
        self._state: "dict[int, str]" = {}  # active | quiesced | orphaned
        self._buffer: "dict[int, deque]" = {}  # replay entries
        self._buffered_rows: "dict[int, int]" = {}
        self._pending: "dict[int, list]" = {}  # held while quiesced
        self._pending_rows: "dict[int, int]" = {}
        self._pending_overflowed: "set[int]" = set()
        self.rows_forwarded: "dict[int, int]" = {}
        self.frames_v1 = 0  # v1 text blocks forwarded
        self.frames_v2 = 0  # v2 frames forwarded
        self.decode_errors = 0
        self.backend_errors = 0  # ERR lines backends sent on the data path
        self.migrations = 0
        self.failovers = 0
        self.rows_lost = 0  # failover gaps past the replay buffer

        self._sel: "selectors.DefaultSelector | None" = None
        self._lsock: "socket.socket | None" = None
        self._stop = threading.Event()
        self._draining = False
        self._dead_q: "deque[_Backend]" = deque()
        self._threads: list[threading.Thread] = []
        self._journal_fh = None
        self._journal_lock = threading.Lock()
        self._ops = None
        self._t_start: "float | None" = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> dict:
        """Discover the fleet, bind the client port, start the event
        loop + health (+ rebalance) threads; returns the banner dict."""
        for b in self.backends:
            b.discover(self.connect_timeout)
        names = [b.name for b in self.backends]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate backend names: {names}")
        self.ring = HashRing(names)
        self._by_name = {b.name: b for b in self.backends}
        with self._lock:
            for b in self.backends:
                for slot, g in enumerate(b.slot_ids):
                    if g < 0:
                        continue
                    if g in self.place:
                        other = self.place[g][0].name
                        raise ValueError(
                            f"global tenant {g} served by both "
                            f"{other} and {b.name}"
                        )
                    self.place[g] = (b, slot)
                    self._state[g] = "active"
                    self._buffer[g] = deque()
                    self._buffered_rows[g] = 0
                    self._pending[g] = []
                    self._pending_rows[g] = 0
                    self.rows_forwarded[g] = 0
        if self.telemetry_dir:
            os.makedirs(self.telemetry_dir, exist_ok=True)
            self._journal_fh = open(
                os.path.join(self.telemetry_dir, JOURNAL_NAME), "a"
            )
        self._journal(
            "fleet_started",
            backends=[
                {"name": b.name, "spec": repr(b.spec), "slots": b.slot_ids}
                for b in self.backends
            ],
            placements={
                str(g): [b.name, s] for g, (b, s) in self.place.items()
            },
        )
        self._lsock = socket.create_server(
            (self.host, self.port or 0), backlog=128
        )
        self._lsock.setblocking(False)
        self.port = self._lsock.getsockname()[1]
        self._sel = selectors.DefaultSelector()
        self._sel.register(self._lsock, selectors.EVENT_READ, ("accept",))
        for b in self.backends:
            self._sel.register(b.sock, selectors.EVENT_READ, ("backend", b))
        self._t_start = time.monotonic()
        loop = threading.Thread(
            target=self._run_loop, name="router-loop", daemon=True
        )
        health = threading.Thread(
            target=self._run_health, name="router-health", daemon=True
        )
        self._threads = [loop, health]
        if self.rebalance_every_s > 0:
            self._threads.append(
                threading.Thread(
                    target=self._run_rebalance,
                    name="router-rebalance",
                    daemon=True,
                )
            )
        for t in self._threads:
            t.start()
        if self.ops_port is not None:
            self._ops = self._start_ops()
        return {
            "router": True,
            "name": self.name,
            "host": self.host,
            "port": self.port,
            "ops_port": self._ops.port if self._ops is not None else None,
            "backends": {
                b.name: {
                    "spec": repr(b.spec),
                    "slots": list(b.slot_ids),
                }
                for b in self.backends
            },
            "tenants": sorted(self.place),
            "journal": (
                os.path.join(self.telemetry_dir, JOURNAL_NAME)
                if self.telemetry_dir
                else None
            ),
        }

    def stop(self) -> None:
        """Tear the router down (backends are NOT stopped — they drain
        via the wire STOP broadcast or their own SIGTERM)."""
        self._stop.set()
        for t in self._threads:
            t.join(timeout=10)
        if self._ops is not None:
            self._ops.stop()
        if self._sel is not None:
            self._sel.close()
        if self._lsock is not None:
            self._lsock.close()
        for b in self.backends:
            b.close()
        if self._journal_fh is not None:
            self._journal_fh.close()
            self._journal_fh = None

    # -- journal -------------------------------------------------------------

    def _journal(self, event: str, **fields) -> None:
        rec = {"ts": time.time(), "event": event, **fields}
        with self._journal_lock:
            if self._journal_fh is not None:
                self._journal_fh.write(json.dumps(rec) + "\n")
                self._journal_fh.flush()

    # -- the event loop ------------------------------------------------------

    def _run_loop(self) -> None:
        while not self._stop.is_set():
            events = self._sel.select(timeout=0.1)
            for key, _ in events:
                kind = key.data[0]
                if kind == "accept":
                    self._accept()
                elif kind == "backend":
                    self._read_backend(key.data[1])
                else:
                    self._read_client(key)

    def _accept(self) -> None:
        try:
            sock, _ = self._lsock.accept()
        except OSError:
            return
        sock.setblocking(False)
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        state = {
            "sock": sock,
            "buf": bytearray(),
            "tenant": None,  # current v1 global tenant
            "trace": None,  # pending TRACE line for the next data row
        }
        self._sel.register(sock, selectors.EVENT_READ, ("client", state))

    def _close_client(self, state) -> None:
        try:
            self._sel.unregister(state["sock"])
        except (KeyError, ValueError):
            pass
        try:
            state["sock"].close()
        except OSError:
            pass

    def _read_backend(self, b: _Backend) -> None:
        """Drain a backend's data-path replies (ERR chatter — counted,
        journaled once, never forwarded: the client/backend row mapping
        is gone by the time an async ERR surfaces). EOF off-drain means
        the backend died."""
        try:
            chunk = b.sock.recv(65536)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            chunk = b""
        if not chunk:
            try:
                self._sel.unregister(b.sock)
            except (KeyError, ValueError):
                pass
            if not self._draining and b.alive:
                self._report_dead(b, "data connection EOF")
            return
        errs = chunk.count(b"ERR")
        if errs:
            self.backend_errors += errs
            self._journal(
                "backend_err",
                backend=b.name,
                sample=chunk[:200].decode(errors="replace"),
            )

    def _read_client(self, key) -> None:
        state = key.data[1]
        try:
            chunk = state["sock"].recv(1 << 20)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            chunk = b""
        if not chunk:
            self._close_client(state)
            return
        state["buf"] += chunk
        try:
            self._drain_client(state)
        except _Reject as e:
            self.decode_errors += 1
            try:
                state["sock"].sendall(f"ERR {e}\n".encode())
            except OSError:
                pass
            self._close_client(state)

    def _drain_client(self, state) -> None:
        """Consume every complete message in the client buffer, routing
        each to its tenant's backend. Consecutive v1 data rows for one
        tenant coalesce into ONE replay entry (one ``TENANT`` prefix,
        one lock pass, one backend send) — per-row dispatch made the
        router the v1 bottleneck. The batch never outlives this drain
        pass (flushed on tenant switch, frame/control boundary, reject,
        and return), so wire order is preserved exactly."""
        buf = state["buf"]
        batch: "list[str]" = []
        batch_g: "int | None" = None

        def flush() -> None:
            nonlocal batch, batch_g
            if batch:
                self._route_rows(batch_g, batch)
                batch = []
            batch_g = None

        try:
            while buf:
                if buf[0] == wire.MAGIC_BYTE:
                    if len(buf) < wire.HEADER_SIZE:
                        return  # incomplete header
                    try:
                        # Header only, decoded from an immutable copy: the
                        # router never builds payload views over the live
                        # buffer (an exported view would make the
                        # `del buf[:consumed]` resize below a BufferError),
                        # and it never needs the columns — it forwards the
                        # frame bytes whole, rewriting 4 header bytes.
                        header = wire.decode_header(
                            bytes(buf[: wire.HEADER_SIZE]),
                            max_rows=self.max_frame_rows,
                        )
                    except wire.WireError as e:
                        raise _Reject(f"WireError: {e}") from e
                    consumed = header.frame_nbytes
                    if len(buf) < consumed:
                        return  # incomplete frame
                    flush()
                    frame = bytes(buf[:consumed])
                    del buf[:consumed]
                    if header.is_control:
                        self._broadcast_control(header.flags)
                    else:
                        self._route_frame(header.tenant, frame, header.rows)
                    continue
                nl = buf.find(b"\n")
                if nl < 0:
                    if len(buf) > (1 << 20):
                        raise _Reject("unterminated text line > 1 MiB")
                    return
                line = bytes(buf[:nl]).decode(errors="replace").strip()
                del buf[: nl + 1]
                if not line:
                    continue
                if line.startswith("TENANT"):
                    try:
                        g = int(line[6:].strip())
                    except ValueError as e:
                        raise _Reject(
                            f"malformed TENANT line {line!r}"
                        ) from e
                    if g not in self.place:
                        raise _Reject(f"unknown global tenant {g}")
                    if batch_g is not None and g != batch_g:
                        flush()
                    state["tenant"] = g
                elif line.startswith("TRACE"):
                    state["trace"] = line  # rides with its next data row
                elif line == "FLUSH":
                    flush()
                    self._broadcast_control(wire.FLAG_FLUSH)
                elif line == "STOP":
                    flush()
                    self._broadcast_control(wire.FLAG_STOP)
                elif line.startswith(("SAVETENANT", "LOADTENANT")):
                    # migration is the ROUTER's job — a client must not
                    # reach around the placement table
                    raise _Reject("tenant control lines are router-internal")
                else:
                    g = state["tenant"]
                    if g is None:
                        # solo convention: an un-TENANTed client speaks to
                        # the fleet's lowest global tenant (one-tenant
                        # fleets feel like one daemon)
                        g = min(self.place, default=None)
                        if g is None:
                            raise _Reject("fleet serves no tenants")
                        state["tenant"] = g
                    if batch_g is not None and g != batch_g:
                        flush()
                    batch_g = g
                    if state["trace"] is not None:
                        batch.append(state["trace"])
                        state["trace"] = None
                    batch.append(line)
        finally:
            flush()

    # -- routing + the replay buffer -----------------------------------------

    def _route_rows(self, g: int, lines: "list[str]") -> None:
        """Route a block of v1 text lines (data rows + TRACE stamps) for
        global tenant ``g``."""
        rows = sum(1 for ln in lines if not ln.startswith("TRACE"))
        self._dispatch(g, ("v1", lines, rows))

    def _route_frame(self, g: int, frame: bytes, rows: int) -> None:
        if g not in self.place:
            raise _Reject(f"unknown global tenant {g}")
        self._dispatch(g, ("v2", frame, rows))

    def _dispatch(self, g: int, entry) -> None:
        """Forward one replay entry when the tenant is active; hold it
        while quiesced/orphaned (the resume flushes holds in order).
        Bookkeeping — the replay buffer and the forwarded counters —
        happens at FORWARD time only, so the buffer's tail always ends
        exactly at ``rows_forwarded`` (the invariant the failover
        re-send indexes by)."""
        with self._lock:
            if self._state[g] != "active":
                self._pending[g].append(entry)
                self._pending_rows[g] = self._pending_rows.get(g, 0) + entry[2]
                # a quiesce is transient (bounded by the drain timeout),
                # but an ORPHANED tenant may never resume — cap its hold
                # at the replay-buffer bound like _buffer_entry, counting
                # every dropped row in rows_lost (loud, never silent)
                held = self._pending[g]
                if self._state[g] == "orphaned":
                    dropped = 0
                    while (
                        len(held) > 1
                        and self._pending_rows[g] - held[0][2]
                        >= self.replay_rows
                    ):
                        n = held.pop(0)[2]
                        self._pending_rows[g] -= n
                        dropped += n
                    if dropped:
                        self.rows_lost += dropped
                        if g not in self._pending_overflowed:
                            self._pending_overflowed.add(g)
                            self._journal(
                                "pending_overflow", tenant=g,
                                dropped_rows=dropped,
                            )
                return
            b, slot = self.place[g]
            self._account(g, b, entry)
        self._send_entry(b, slot, entry)

    def _account(self, g: int, b: _Backend, entry) -> None:
        """Forward-time bookkeeping (call under the lock)."""
        self._buffer_entry(g, entry)
        self.rows_forwarded[g] += entry[2]
        b.rows_forwarded += entry[2]
        if entry[0] == "v1":
            self.frames_v1 += 1
        else:
            self.frames_v2 += 1

    def _buffer_entry(self, g: int, entry) -> None:
        """Append to the replay buffer, trimming the oldest WHOLE entries
        past the cap (call under the lock)."""
        buf = self._buffer[g]
        buf.append(entry)
        self._buffered_rows[g] += entry[2]
        while (
            len(buf) > 1
            and self._buffered_rows[g] - buf[0][2] >= self.replay_rows
        ):
            self._buffered_rows[g] -= buf.popleft()[2]

    def _send_entry(self, b: _Backend, slot: int, entry) -> None:
        """One buffered entry → the backend's wire (slot rewrite +
        send). Send failures report the backend dead; the row is already
        buffered, so the failover re-sends it."""
        kind, payload, rows = entry
        try:
            if kind == "v1":
                b.send(
                    (f"TENANT {slot}\n" + "\n".join(payload) + "\n").encode()
                )
            else:
                out = bytearray(payload)
                struct.pack_into("<I", out, 4, slot)
                b.send(bytes(out))
        except OSError as e:
            self._report_dead(b, f"send failed: {e}")

    def _broadcast_control(self, flags: int) -> None:
        if flags & wire.FLAG_STOP:
            # a STOP must not overtake rows held for quiesced tenants —
            # the backends would drain and exit before the resume
            # flushes the holds. Wait (bounded) for in-flight
            # migrations/failovers to resume, then count anything still
            # held (orphans never resume) LOUDLY as lost.
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                with self._lock:
                    busy = any(
                        st == "quiesced" for st in self._state.values()
                    )
                if not busy:
                    break
                time.sleep(0.05)
            with self._lock:
                dropped = 0
                for g, held in self._pending.items():
                    if held:
                        dropped += self._pending_rows.get(g, 0)
                        self._pending[g] = []
                        self._pending_rows[g] = 0
                if dropped:
                    self.rows_lost += dropped
                    self._journal("stop_dropped_pending", rows=dropped)
            self._draining = True
            self._journal("fleet_stop")
        line = b""
        if flags & wire.FLAG_FLUSH:
            line += b"FLUSH\n"
        if flags & wire.FLAG_STOP:
            line += b"STOP\n"
        for b in self.backends:
            if not b.alive:
                continue
            try:
                b.send(line)
            except OSError as e:
                self._report_dead(b, f"send failed: {e}")

    # -- liveness + failover -------------------------------------------------

    def _report_dead(self, b: _Backend, why: str) -> None:
        """Mark a backend dead (any thread) and queue its failover for
        the health thread — the event loop must keep moving the other
        tenants' bytes while orphans re-place."""
        with self._lock:
            if not b.alive:
                return
            b.alive = False
        self._journal("backend_dead", backend=b.name, why=why)
        self._dead_q.append(b)

    def _run_health(self) -> None:
        while not self._stop.is_set():
            while self._dead_q:
                dead = self._dead_q.popleft()
                if self.failover:
                    self._failover(dead)
                else:
                    self._orphan_all(dead)
            for b in self.backends:
                if not b.alive or self._draining:
                    continue
                if b.healthz(timeout=max(self.health_interval_s, 1.0)):
                    b.health_fails = 0
                else:
                    b.health_fails += 1
                    if b.health_fails >= self.health_fails:
                        self._report_dead(
                            b,
                            f"healthz missed {b.health_fails} polls",
                        )
            self._stop.wait(self.health_interval_s)

    def _orphan_all(self, dead: _Backend) -> None:
        with self._lock:
            for g, (b, _) in list(self.place.items()):
                if b is dead:
                    self._state[g] = "orphaned"
                    self._journal("orphaned", tenant=g, backend=dead.name)

    def _failover(self, dead: _Backend) -> None:
        """Re-place every tenant of a dead backend from its last
        per-tenant checkpoint, re-sending buffered rows past each
        checkpoint's watermark. Tenants that cannot land (no checkpoint,
        no vacancy) stay ``orphaned`` — loudly, in the journal and
        /statusz — while everyone else keeps serving."""
        with self._lock:
            orphans = [
                (g, slot)
                for g, (b, slot) in self.place.items()
                if b is dead
            ]
            for g, _ in orphans:
                self._state[g] = "quiesced"
        for g, slot in orphans:
            try:
                first = self.ring.place(
                    g, exclude=[b.name for b in self.backends if not b.alive]
                )
            except RuntimeError:
                self._mark_orphaned(g, "no live backend")
                continue
            ckpt = f"{dead.checkpoint}.t{slot}" if dead.checkpoint else ""
            if not ckpt or not os.path.exists(ckpt):
                self._mark_orphaned(
                    g, f"no per-tenant checkpoint at {ckpt or '<none>'}"
                )
                continue
            # the ring's pick first, then every other live backend —
            # a tenant orphans only when NO survivor can land it, not
            # merely when the hash's favourite is full
            order = [first] + [
                b.name
                for b in self.backends
                if b.alive and b.name != first
            ]
            errs = []
            for dst_name in order:
                dst = self._by_name[dst_name]
                if not dst.alive:
                    continue
                err = self._land(
                    g, dst, ckpt, src_name=dead.name, kind="failover"
                )
                if err is None:
                    break
                errs.append(f"{dst_name}: {err}")
            else:
                self._mark_orphaned(g, "; ".join(errs) or "no live backend")
        self.failovers += 1

    def _mark_orphaned(self, g: int, why: str) -> None:
        with self._lock:
            self._state[g] = "orphaned"
        self._journal("orphaned", tenant=g, why=why)

    def _claim_vacant(self, dst: _Backend) -> "int | None":
        with self._lock:
            for s, gid in enumerate(dst.slot_ids):
                if gid < 0:
                    dst.slot_ids[s] = -2  # claimed, not yet landed
                    return s
        return None

    def _land(
        self, g: int, dst: _Backend, ckpt: str, *, src_name: str, kind: str
    ) -> "str | None":
        """LOADTENANT ``ckpt`` into a vacant slot of ``dst``, re-send
        buffered rows past the checkpoint's watermark, resume ``g``.
        The tenant must already be quiesced. Returns None on success,
        else the failure reason — the CALLER decides what failure means
        (failover orphans the tenant; migration resumes it at its
        still-live source)."""
        vslot = self._claim_vacant(dst)
        if vslot is None:
            return f"no vacant slot on {dst.name}"
        try:
            reply = dst.control(f"LOADTENANT {vslot} {ckpt}")
        except OSError as e:
            self._report_dead(dst, f"control failed: {e}")
            reply = f"ERR LOADTENANT {vslot} {type(e).__name__}: {e}"
        if not reply.startswith("OK LOADTENANT"):
            with self._lock:
                if dst.slot_ids[vslot] == -2:
                    dst.slot_ids[vslot] = -1  # unclaim
            return f"landing failed: {reply}"
        watermark = int(reply.split()[-1])
        with self._lock:
            dst.slot_ids[vslot] = g
            self.place[g] = (dst, vslot)
        gap, resent = self._resend_from(g, dst, vslot, watermark)
        self._resume(g, dst, vslot)
        self._journal(
            kind,
            tenant=g,
            src=src_name,
            dst=dst.name,
            slot=vslot,
            checkpoint=ckpt,
            watermark=watermark,
            resent_rows=resent,
            lost_rows=gap,
        )
        if kind == "migrated":
            self.migrations += 1
        return None

    def _resend_from(
        self, g: int, dst: _Backend, slot: int, watermark: int
    ) -> "tuple[int, int]":
        """Re-send tenant ``g``'s buffered rows with tenant-local index
        >= ``watermark`` to its new home; returns ``(lost, resent)`` row
        counts. ``lost`` > 0 means the buffer no longer reaches back to
        the checkpoint — journaled by the caller, counted here."""
        with self._lock:
            entries = list(self._buffer[g])
            start = self.rows_forwarded[g] - self._buffered_rows[g]
        gap = max(start - watermark, 0)
        if gap:
            self.rows_lost += gap
        pos, resent = start, 0
        for entry in entries:
            kind, payload, rows = entry
            lo = max(watermark - pos, 0)
            pos += rows
            if lo >= rows:
                continue
            if lo:
                entry = self._slice_entry(entry, lo)
            self._send_entry(dst, slot, entry)
            resent += rows - lo
        with self._lock:
            self.rows_forwarded[g] = max(self.rows_forwarded[g], watermark)
            dst.rows_forwarded += resent
        return gap, resent

    @staticmethod
    def _slice_entry(entry, lo: int):
        """Drop the first ``lo`` rows of a replay entry (the checkpoint
        already covers them)."""
        kind, payload, rows = entry
        if kind == "v1":
            # count data rows past TRACE stamps; keep a stamp only with
            # its row
            out, seen, trace = [], 0, None
            for ln in payload:
                if ln.startswith("TRACE"):
                    trace = ln
                    continue
                if seen >= lo:
                    if trace is not None:
                        out.append(trace)
                    out.append(ln)
                trace = None
                seen += 1
            return ("v1", out, rows - lo)
        header, X, y, _ = wire.decode_frame(payload)
        return ("v2", wire.encode_frame(X[lo:], y[lo:], tenant=0), rows - lo)

    def _resume(self, g: int, b: _Backend, slot: int) -> None:
        """Quiesced → active: flush rows held while the tenant moved,
        THEN flip active — a row routed mid-drain must never overtake
        the held ones."""
        while True:
            with self._lock:
                held = self._pending[g]
                if not held:
                    self._state[g] = "active"
                    return
                self._pending[g] = []
                self._pending_rows[g] = 0
                for entry in held:
                    self._account(g, b, entry)
            for entry in held:
                self._send_entry(b, slot, entry)

    # -- graceful migration + rebalance --------------------------------------

    def migrate_tenant(
        self, g: int, dst_name: str, *, drain_timeout: float = 60.0
    ) -> bool:
        """Live-migrate tenant ``g`` to backend ``dst_name``: quiesce →
        FLUSH + drain the source slot → SAVETENANT → LOADTENANT into a
        vacant slot → re-send any delta → resume. Flags are
        bit-identical across the move (the slot's full identity — global
        id, stream seed, stripe shuffle seed, positions — ships in the
        checkpoint). Returns True on success; failure resumes the tenant
        at its source, serving uninterrupted."""
        dst = self._by_name.get(dst_name)
        if dst is None or not dst.alive:
            raise ValueError(f"no live backend named {dst_name!r}")
        with self._lock:
            if g not in self.place:
                raise ValueError(f"unknown global tenant {g}")
            src, slot = self.place[g]
            if src is dst:
                return True
            if self._state[g] != "active":
                raise RuntimeError(
                    f"tenant {g} is {self._state[g]}; cannot migrate"
                )
            self._state[g] = "quiesced"
            forwarded = self.rows_forwarded[g]
        try:
            # Drain: everything the router forwarded must be ADMITTED
            # (sealed into the batcher's accounting) before the save, so
            # the checkpoint's watermark equals our forwarded count and
            # the delta re-send is empty.
            src.send(b"FLUSH\n")
            deadline = time.monotonic() + drain_timeout
            while time.monotonic() < deadline:
                try:
                    detail = (src.statusz().get("tenant_detail") or [])
                except (urllib.error.URLError, OSError, ValueError):
                    break
                st = detail[slot] if slot < len(detail) else None
                if (
                    st is not None
                    and int(st["rows_admitted"]) >= forwarded
                    and int(st["buffered"]) == 0
                ):
                    break
                time.sleep(0.05)
            ship = self._ship_path(g)
            reply = src.control(f"SAVETENANT {slot} {ship}")
            if not reply.startswith("OK SAVETENANT"):
                raise RuntimeError(f"source refused the save: {reply}")
            err = self._land(
                g, dst, ship, src_name=src.name, kind="migrated"
            )
            if err is None:
                with self._lock:
                    src.slot_ids[slot] = -1  # vacated: new landing capacity
                return True
            raise RuntimeError(err)  # → resume at the source below
        except (OSError, RuntimeError) as e:
            self._journal(
                "migration_failed", tenant=g, src=src.name,
                dst=dst_name, why=str(e),
            )
            self._resume(g, src, slot)  # serve on, from the source
            return False

    def _ship_path(self, g: int) -> str:
        base = self.telemetry_dir or "."
        return os.path.join(base, f"migrate.t{g}.ckpt")

    def _run_rebalance(self) -> None:
        prev: "dict[str, tuple[float, int, dict[int, int]]]" = {}
        while not self._stop.wait(self.rebalance_every_s):
            if self._draining:
                continue
            self.rebalance_once(prev)

    def rebalance_once(self, prev: "dict | None" = None) -> "tuple | None":
        """One rebalance evaluation over the backends' /statusz stream
        accounting; migrates and returns ``(tenant, src, dst)`` when the
        fleet is imbalanced, else None. ``prev`` carries the last poll's
        counters between calls (rates need two samples)."""
        if prev is None:
            prev = {}
        now = time.monotonic()
        rates: "dict[str, float]" = {}
        tenant_rates: "dict[str, dict[int, float]]" = {}
        vacancies: "dict[str, int]" = {}
        for b in self.backends:
            if not b.alive:
                continue
            try:
                s = b.statusz()
            except (urllib.error.URLError, OSError, ValueError):
                continue
            rows = int((s.get("rows") or {}).get("admitted") or 0)
            detail = {
                int(t["id"]): int(t["rows_admitted"])
                for t in s.get("tenant_detail") or []
                if int(t["id"]) >= 0
            }
            with self._lock:
                vacancies[b.name] = sum(1 for g in b.slot_ids if g == -1)
            last = prev.get(b.name)
            if last is not None and now > last[0]:
                dt = now - last[0]
                rates[b.name] = (rows - last[1]) / dt
                tenant_rates[b.name] = {
                    g: (r - last[2].get(g, 0)) / dt
                    for g, r in detail.items()
                }
            prev[b.name] = (now, rows, detail)
        move = plan_rebalance(
            rates, tenant_rates, vacancies, self.rebalance_ratio
        )
        if move is None:
            return None
        g, src, dst = move
        self._journal("rebalance", tenant=g, src=src, dst=dst)
        try:
            if self.migrate_tenant(g, dst):
                return move
        except (ValueError, RuntimeError) as e:
            # the plan raced a failover/quiesce or the destination died
            # since the poll — skip this round, never kill the
            # rebalance thread
            self._journal(
                "rebalance_skipped", tenant=g, dst=dst, why=str(e)
            )
        return None

    # -- ops plane -----------------------------------------------------------

    def status(self) -> dict:
        with self._lock:
            placements = {
                str(g): {
                    "backend": b.name,
                    "slot": s,
                    "state": self._state[g],
                    "rows_forwarded": self.rows_forwarded[g],
                }
                for g, (b, s) in sorted(self.place.items())
            }
            backends = [
                {
                    "name": b.name,
                    "spec": repr(b.spec),
                    "alive": b.alive,
                    "rows_forwarded": b.rows_forwarded,
                    "slots": list(b.slot_ids),
                }
                for b in self.backends
            ]
            total = sum(self.rows_forwarded.values())
        dead = [b["name"] for b in backends if not b["alive"]]
        orphaned = [
            g for g, p in placements.items() if p["state"] == "orphaned"
        ]
        now = time.monotonic()
        return {
            "router": True,
            "run_id": self.name,
            "name": self.name,
            "pid": os.getpid(),
            "uptime_s": (
                round(now - self._t_start, 3)
                if self._t_start is not None
                else None
            ),
            "draining": self._draining,
            "tenants": len(placements),
            # the fields the `top` dashboard's StatuszSource renders —
            # a router row reads like a daemon serving the whole fleet
            "rows": {"published": total, "admitted": total},
            "detections": None,
            "ingress": {
                "frames_v1": self.frames_v1,
                "frames_v2": self.frames_v2,
                "decode_errors": self.decode_errors,
            },
            "backend_errors": self.backend_errors,
            "migrations": self.migrations,
            "failovers": self.failovers,
            "rows_lost": self.rows_lost,
            "alerts": (
                [{"rule": f"backend_dead:{n}"} for n in dead]
                + [{"rule": f"orphaned:{g}"} for g in orphaned]
            ),
            "backends": backends,
            "placements": placements,
        }

    def fleetz(self) -> dict:
        """The merged fleet view (``/fleetz``): scrape every live
        backend's ``/statusz`` (falling back to its ``/metrics`` for
        the busy map when the pipeline section is absent) and fold
        into summed rows/s, max per-stage busy share, and per-backend
        bottleneck stages. Computed on request, outside the router
        lock — a slow backend stalls the scrape, never the data path."""
        from ..telemetry.pipeline import aggregate_fleet, backend_snapshot

        with self._lock:
            backends = list(self.backends)
        snaps = []
        for b in backends:
            status = metrics = None
            if b.alive:
                try:
                    status = b.statusz(timeout=2.0)
                    if not (status.get("pipeline") or {}).get("busy_s"):
                        metrics = b.metrics_text(timeout=2.0)
                except (urllib.error.URLError, OSError, ValueError):
                    status = metrics = None
            snaps.append(
                backend_snapshot(
                    b.name or repr(b.spec),
                    status,
                    metrics,
                    # ops address rides into the fleetz row: the history
                    # collector's --fleetz discovery scrapes it
                    ops=f"{b.spec.host}:{b.spec.ops_port}",
                )
            )
        return aggregate_fleet(snaps)

    def _health(self) -> "tuple[int, dict]":
        with self._lock:
            alive = [b.name for b in self.backends if b.alive]
            dead = [b.name for b in self.backends if not b.alive]
            orphaned = [
                g for g, st in self._state.items() if st == "orphaned"
            ]
        healthy = bool(alive) and not orphaned
        return (
            200 if healthy else 503,
            {
                "status": "ok" if healthy else "degraded",
                "alive": alive,
                "dead": dead,
                "orphaned": orphaned,
            },
        )

    def _start_ops(self):
        from ..telemetry.ops import OpsServer

        ops = OpsServer(
            self.host,
            self.ops_port or 0,
            metrics_fn=self._metrics_text,
            health_fn=self._health,
            status_fn=self.status,
            fleetz_fn=self.fleetz,
        )
        ops.start()
        return ops

    def _metrics_text(self) -> str:
        from ..telemetry.pipeline import fleet_metrics_lines

        # fleet_* series ride the router's scrape: aggregate first
        # (its own backend scrapes), THEN take the lock for the
        # router-local counters.
        fleet_lines = fleet_metrics_lines(self.fleetz())
        with self._lock:
            lines = [
                "# TYPE router_rows_forwarded_total counter",
                *(
                    f'router_rows_forwarded_total{{backend="{b.name}"}} '
                    f"{b.rows_forwarded}"
                    for b in self.backends
                ),
                "# TYPE router_backend_alive gauge",
                *(
                    f'router_backend_alive{{backend="{b.name}"}} '
                    f"{int(b.alive)}"
                    for b in self.backends
                ),
                "# TYPE router_migrations_total counter",
                f"router_migrations_total {self.migrations}",
                "# TYPE router_rows_lost_total counter",
                f"router_rows_lost_total {self.rows_lost}",
            ]
        return "\n".join(lines + fleet_lines) + "\n"


class _Reject(Exception):
    """Protocol violation on a CLIENT connection: ERR + close that
    connection, never the router."""


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def main(argv=None) -> None:
    """``router``: the fleet front daemon (see module docstring)."""
    ap = argparse.ArgumentParser(
        prog="python -m distributed_drift_detection_tpu router",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("--backend", action="append", default=[],
                    metavar="HOST:PORT:OPS_PORT", required=True,
                    help="one serving daemon (repeatable; data port + "
                    "ops port)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0,
                    help="client-facing data port (0 = OS-assigned; "
                    "printed in the banner)")
    ap.add_argument("--ops-port", type=int, default=None,
                    help="router ops plane (/healthz /metrics /statusz); "
                    "omitted = no ops server, 0 = OS-assigned")
    ap.add_argument("--name", default="router")
    ap.add_argument("--telemetry-dir", default=None,
                    help="placement journal (router.journal.jsonl) + "
                    "migration checkpoint staging")
    ap.add_argument("--health-interval", type=float, default=1.0,
                    help="seconds between backend /healthz polls")
    ap.add_argument("--health-fails", type=int, default=3,
                    help="consecutive missed polls before a backend is "
                    "declared dead")
    ap.add_argument("--no-failover", action="store_true",
                    help="mark a dead backend's tenants orphaned instead "
                    "of re-placing them from checkpoints")
    ap.add_argument("--replay-buffer", type=int,
                    default=REPLAY_BUFFER_ROWS, metavar="ROWS",
                    help="per-tenant replay-buffer rows (must cover the "
                    "worst checkpoint→death gap for lossless failover)")
    ap.add_argument("--rebalance-every", type=float, default=0.0,
                    metavar="S",
                    help="poll the fleet's per-tenant stream accounting "
                    "every S seconds and migrate the hottest tenant off "
                    "an imbalanced backend (0 = off)")
    ap.add_argument("--rebalance-ratio", type=float, default=2.0,
                    help="max/min backend row-rate ratio that triggers a "
                    "rebalance migration")
    ap.add_argument("--connect-timeout", type=float, default=60.0,
                    help="seconds to wait for every backend's ops plane "
                    "at startup (fleets compile before they answer)")
    ap.add_argument("--max-frame-rows", type=int,
                    default=wire.MAX_FRAME_ROWS, metavar="N",
                    help="reject client v2 frames declaring more rows at "
                    "the router's edge; set to the minimum of the "
                    "backends' --max-frame-rows so an oversized frame "
                    "never reaches (and closes) a shared backend "
                    "connection")
    args = ap.parse_args(argv)

    router = TenantRouter(
        [BackendSpec(b) for b in args.backend],
        host=args.host,
        port=args.port,
        ops_port=args.ops_port,
        telemetry_dir=args.telemetry_dir,
        name=args.name,
        health_interval_s=args.health_interval,
        health_fails=args.health_fails,
        failover=not args.no_failover,
        replay_rows=args.replay_buffer,
        rebalance_every_s=args.rebalance_every,
        rebalance_ratio=args.rebalance_ratio,
        connect_timeout=args.connect_timeout,
        max_frame_rows=args.max_frame_rows,
    )
    banner = router.start()
    print(json.dumps(banner), flush=True)

    import signal

    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    try:
        while not stop.is_set():
            stop.wait(0.5)
    finally:
        router.stop()


if __name__ == "__main__":
    main(sys.argv[1:])
