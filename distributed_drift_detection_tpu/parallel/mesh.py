"""Partition parallelism over a TPU device mesh.

The reference's distribution stack (C3+C8, ``DDM_Process.py:58-72,216-226``)
is: ship the whole dataframe to a Spark cluster, hash-shuffle on a
``device_id`` column, run one independent Python worker per group, collect at
the end. Here the same data-parallel strategy is expressed the TPU way
(SURVEY.md §2 "TPU mapping"):

* intra-chip: ``vmap`` of the compiled partition loop over the partition axis;
* inter-chip: a 1-D ``jax.sharding.Mesh`` over the ``'partitions'`` axis with
  ``NamedSharding`` — XLA splits the vmapped program across devices with no
  communication during the stream (the loop is embarrassingly parallel,
  matching the reference's zero worker↔worker traffic);
* the end-of-run merge ("all devices find the same changes",
  ``DDM_Process.py:89-92,258``) becomes an actual collective: a cross-
  partition **drift vote** — for each microbatch step, the fraction of
  partitions that flagged a change — reduced with ``psum`` semantics
  (``jnp.sum`` over the sharded partition axis, which XLA lowers to an
  all-reduce over ICI).

Spark's RPC upload (``:222``) becomes ``jax.device_put`` against the sharding;
its ``toPandas()`` collect (``:258``) becomes a device→host gather of the
tiny flag table.
"""

from __future__ import annotations

import re
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import DDMParams
from ..engine.loop import (
    Batches,
    FlagRows,
    IndexedBatches,
    PackedIndexedBatches,
    expand_packed,
    make_partition_runner,
)
from ..models.base import Model

PARTITION_AXIS = "partitions"

#: Second mesh axis of the fleet-scale tenant plane (ROADMAP item 1): a
#: 2-D ``(tenants, partitions)`` mesh spreads the stacked ``[T·P, ...]``
#: tenant plane over BOTH axes — whole tenants land on tenant-axis rows,
#: each tenant's partitions spread along the partition axis. The flattened
#: leading axis (``q = t·P + p``) shards over the flattened mesh
#: (``PartitionSpec((TENANT_AXIS, PARTITION_AXIS))``), so the device
#: order is tenant-major exactly like the stacked grid itself.
TENANT_AXIS = "tenants"


def make_mesh(
    num_devices: int = 0, devices=None, *, tenant_devices: int = 0
) -> Mesh:
    """Device mesh over the partition (data-parallel) axis — optionally
    2-D over ``(tenant, partition)``.

    ``num_devices = 0`` uses every visible device. Partition counts must be a
    multiple of the mesh size (the striper already produces equal-sized
    partition grids, mirroring the reference's ≤1-row imbalance tolerance).

    ``tenant_devices > 1`` grows the tenant axis (ROADMAP item 1): the
    devices reshape to ``[tenant_devices, rest]`` named
    ``(TENANT_AXIS, PARTITION_AXIS)`` so a stacked multi-tenant plane
    shards whole tenants across tenant-axis rows. ``0``/``1`` keeps the
    historical 1-D partition mesh (every existing caller).
    """
    if devices is None:
        devices = jax.devices()
    if num_devices:
        devices = devices[:num_devices]
    devices = np.asarray(devices)
    if tenant_devices and tenant_devices > 1:
        if devices.size % tenant_devices:
            raise ValueError(
                f"{devices.size} device(s) do not split into a "
                f"{tenant_devices}-row tenant axis"
            )
        return Mesh(
            devices.reshape(tenant_devices, -1),
            (TENANT_AXIS, PARTITION_AXIS),
        )
    return Mesh(devices, (PARTITION_AXIS,))


def plane_axes(mesh: Mesh):
    """The mesh axis name(s) the flattened ``(tenant·partition)`` leading
    axis shards over: ``(TENANT_AXIS, PARTITION_AXIS)`` on a 2-D tenant
    mesh (the leading array axis splits over both, tenant-major — exactly
    the stacked grid's own layout), plain ``PARTITION_AXIS`` on the
    historical 1-D mesh."""
    if TENANT_AXIS in mesh.axis_names:
        return (TENANT_AXIS, PARTITION_AXIS)
    return PARTITION_AXIS


def plane_sharding(mesh: Mesh, rows: int | None = None) -> NamedSharding:
    """The canonical sharding of a plane-major array (leading axis = the
    flattened ``tenant·partition`` stack; a solo run's plane is just its
    ``P`` partitions).

    When ``rows`` (the leading-axis width) is given, validates
    divisibility by the mesh size — the invariant every plane-major
    engine shares, on either mesh rank.
    """
    if rows is not None and rows % mesh.devices.size:
        raise ValueError(
            f"leading axis of {rows} row(s) not divisible by the "
            f"{mesh.devices.size}-device mesh "
            f"(shape {dict(zip(mesh.axis_names, mesh.devices.shape))})"
        )
    return NamedSharding(mesh, P(plane_axes(mesh)))


def partition_sharding(mesh: Mesh, partitions: int | None = None) -> NamedSharding:
    """The canonical partition-axis sharding for ``mesh`` (historical
    name; since the tenant mesh landed this is :func:`plane_sharding` —
    the partition axis of a solo run IS its plane)."""
    return plane_sharding(mesh, partitions)


def match_partition_rules(rules, tree, *, mesh: "Mesh | None" = None):
    """Per-leaf ``regex → PartitionSpec`` resolution over a pytree (the
    SNIPPETS.md [1] pattern, with the replication fallback of [3]).

    ``rules`` is an ordered ``[(pattern, PartitionSpec), ...]``; each leaf
    is named by its ``/``-joined key path (``params/centroids``,
    ``ddm/p_min``, ``a_X``...) and takes the FIRST matching rule's spec
    (``re.search`` semantics). Two fallbacks make the tree total:

    * scalar leaves (``ndim == 0`` or one element) replicate (``P()``) —
      a scalar cannot shard, and partitioning it is never what a rule
      meant;
    * a leaf no rule matches replicates too, *loudly is the caller's
      choice*: pass a catch-all ``(".*", spec)`` tail to make unmatched
      leaves impossible instead.

    Returns a pytree of ``PartitionSpec`` mirroring ``tree`` — or of
    ``NamedSharding`` when ``mesh`` is given (ready for ``device_put`` /
    ``jit`` shardings).
    """
    compiled = [(re.compile(pat), spec) for pat, spec in rules]

    def name_of(path) -> str:
        parts = []
        for k in path:
            for attr in ("name", "key", "idx"):
                v = getattr(k, attr, None)
                if v is not None:
                    parts.append(str(v))
                    break
            else:
                parts.append(str(k))
        return "/".join(parts)

    def spec_for(path, leaf):
        shape = getattr(leaf, "shape", ())
        if len(shape) == 0 or int(np.prod(shape)) == 1:
            return P()  # never partition scalars
        name = name_of(path)
        for pat, spec in compiled:
            if pat.search(name) is not None:
                return spec
        return P()  # replication fallback

    specs = jax.tree_util.tree_map_with_path(spec_for, tree)
    if mesh is None:
        return specs
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def plane_rules(mesh: Mesh):
    """The default partition-rule tree for a plane-major state pytree
    (the :class:`~..engine.loop.LoopCarry` every engine carries): every
    named leaf family shards its leading ``tenant·partition`` axis over
    the mesh's plane axes, with a catch-all tail so nothing silently
    replicates. Scalars still fall back to replication inside
    :func:`match_partition_rules`.

    Today every family maps to the SAME spec — the carry is plane-major
    by construction, so the tree currently reduces to its catch-all.
    The named rules are the placement seam (host-replicated collect
    scratch, tenant-replicated model side-state, …) kept so a future
    divergence is one line here, not a new mechanism; they are not
    evidence of per-family differences that exist now."""
    spec = P(plane_axes(mesh))
    return (
        (r"params/|^params$", spec),  # model state, one block per slice
        (r"ddm|^state", spec),  # detector state pytree
        (r"^a_[Xyw]$", spec),  # carried batch_a planes
        (r"^retrain$", spec),
        (r"^key$", spec),  # per-(tenant, partition) PRNG keys
        (r".*", spec),  # plane-major by construction: catch-all
    )


def plane_shardings(mesh: Mesh, tree):
    """Per-leaf ``NamedSharding`` tree for a plane-major state pytree —
    :func:`match_partition_rules` over :func:`plane_rules`. The carry
    placement :class:`~..engine.chunked.ChunkedDetector` and the fleet
    tests use; works on real arrays and on shape-struct templates."""
    return match_partition_rules(plane_rules(mesh), tree, mesh=mesh)


class MeshRunResult(NamedTuple):
    flags: FlagRows  # leaves [P, NB-1]
    drift_vote: jax.Array  # [NB-1] f32: fraction of partitions flagging change
    # The five flag leaves stacked into one i32 [5, P, NB-1] array: the
    # device→host link of the remote-TPU path is latency-bound (~0.1 s per
    # transfer regardless of size), so the collect phase fetches this single
    # array instead of five leaves. Unpack with :func:`unpack_flags`.
    packed: jax.Array
    # Device-compacted detection table ``[capacity + 1, 7]`` i32 (None when
    # compaction is off — RunConfig.collect='full' / validate=True). Drift
    # is rare, so almost every slot of the packed plane is sentinel fill;
    # this table carries only the flagged slots — columns (partition,
    # batch, warning_local, warning_global, change_local, change_global,
    # forced_retrain), sentinel-filled rows with partition = −1, and the
    # TOTAL flagged-slot count embedded in the extra last row so overflow
    # detection and the payload ride one d2h transfer. Rebuild the full
    # host table with :func:`expand_flag_table`; a count beyond capacity
    # means the table is partial — fall back to ``packed``
    # (:func:`host_flags` does, loudly).
    compact: "jax.Array | None" = None


def auto_compact_capacity(partitions: int, flag_rows: int) -> int:
    """Default compacted-table capacity for a ``[P, NBF]`` flag plane.

    ~P·NBF/8 entries (floor 64), clamped to the slot count: at 28 B/entry
    vs the plane's 20 B/slot the table stays ~5.7× smaller than the plane
    while overflow needs >12.5% of ALL slots flagged — far denser than any
    planted-drift stream (headline geometry flags ~1-3% of slots). At the
    clamp the table covers every slot, so overflow is impossible.
    """
    slots = max(int(partitions) * int(flag_rows), 1)
    return min(max(64, slots // 8), slots)


def compact_flag_table(flags: FlagRows, capacity: int) -> jax.Array:
    """The in-jit compaction epilogue: ``FlagRows [P, NBF]`` → dense
    ``[capacity + 1, 7]`` i32 table (see :attr:`MeshRunResult.compact`).

    A slot is *flagged* when any leaf is non-sentinel (a warning, a change,
    or a forced retrain — by ``engine.loop``'s construction the global
    columns are derived from the locals, so the three tests cover all
    five). ``jnp.nonzero(size=...)`` is the segment compaction: static
    output shape, first ``min(n, capacity)`` flagged slots in row-major
    order, −1 fill beyond them.
    """
    k = int(capacity)
    p, nbf = flags.change_local.shape
    flagged = (
        (flags.warning_local >= 0)
        | (flags.change_local >= 0)
        | flags.forced_retrain
    )
    flat = flagged.ravel()
    n = jnp.sum(flat, dtype=jnp.int32)  # true count — may exceed capacity
    (pos,) = jnp.nonzero(flat, size=k, fill_value=-1)
    ok = pos >= 0
    safe = jnp.maximum(pos, 0)

    def take(leaf):
        return jnp.where(ok, leaf.ravel()[safe].astype(jnp.int32), -1)

    entries = jnp.stack(
        [
            jnp.where(ok, (pos // nbf).astype(jnp.int32), -1),
            jnp.where(ok, (pos % nbf).astype(jnp.int32), -1),
            take(flags.warning_local),
            take(flags.warning_global),
            take(flags.change_local),
            take(flags.change_global),
            take(flags.forced_retrain),
        ],
        axis=1,
    )  # [K, 7]
    counter = jnp.concatenate([n[None], jnp.zeros(6, jnp.int32)])[None]
    return jnp.concatenate([entries, counter], axis=0)


def expand_flag_table(
    table: np.ndarray, partitions: int, flag_rows: int
) -> FlagRows | None:
    """Host-side inverse of :func:`compact_flag_table`: scatter the table's
    entries back into a sentinel-initialised ``[P, NBF]`` flag plane —
    bit-identical to :func:`unpack_flags` of the full plane (tested).
    Returns ``None`` when the embedded count exceeds the table's capacity:
    the table is then partial and only the full plane holds the truth.
    """
    table = np.asarray(table)
    capacity = table.shape[0] - 1
    n_events = int(table[-1, 0])
    if n_events > capacity:
        return None
    entries = table[:capacity]
    entries = entries[entries[:, 0] >= 0]
    shape = (int(partitions), int(flag_rows))
    leaves = [np.full(shape, -1, np.int32) for _ in range(4)]
    forced = np.zeros(shape, bool)
    pq, bq = entries[:, 0], entries[:, 1]
    for col, leaf in enumerate(leaves, start=2):
        leaf[pq, bq] = entries[:, col]
    forced[pq, bq] = entries[:, 6] != 0
    return FlagRows(*leaves, forced)


def host_flags(result: MeshRunResult) -> tuple[FlagRows, dict]:
    """The collect phase's device→host step: host ``FlagRows`` plus a
    provenance dict (``mode``, ``events``, ``overflow``).

    Compacted runners ship the small table in one latency-bound transfer;
    a table overflow (more flagged slots than capacity — a stream flagging
    >12.5% of all slots at the auto capacity) falls back to fetching the
    full packed plane and says so via ``RuntimeWarning`` — the contract is
    *never truncate silently*. Full-plane runners (``collect='full'``,
    ``validate=True``) skip straight to the plane.
    """
    if result.compact is not None:
        _, p, nbf = result.packed.shape  # geometry is shape metadata: free
        table = np.asarray(result.compact)  # ONE small d2h transfer
        n_events = int(table[-1, 0])
        flags = expand_flag_table(table, p, nbf)
        if flags is not None:
            return flags, {
                "mode": "compact", "events": n_events, "overflow": False,
            }
        import warnings

        warnings.warn(
            f"compacted flag table overflowed ({n_events} flagged slots > "
            f"capacity {table.shape[0] - 1}); falling back to the full "
            "flag plane — raise RunConfig.collect_capacity or use "
            "collect='full' for this stream",
            RuntimeWarning,
            stacklevel=2,
        )
        return unpack_flags(np.asarray(result.packed)), {
            "mode": "full", "events": n_events, "overflow": True,
        }
    return unpack_flags(np.asarray(result.packed)), {
        "mode": "full", "events": None, "overflow": False,
    }


def split_tenant_flags(
    flags: FlagRows, tenants: int, flag_cols=None
) -> "list[FlagRows]":
    """Tenant-aware view of a stacked ``[T·P, NBF]`` flag plane: per-tenant
    ``FlagRows`` slices ``[P, NBF]`` (or ``[P, flag_cols[t]]`` when the
    per-tenant flag widths are given — ragged tenants' padded trailing
    columns are pure sentinel and dropped).

    This is the tenant half of the collect story: :func:`host_flags`
    already ships the stacked plane as ONE device→host transfer —
    O(detections) bytes under compaction, since the compacted table's
    entries carry stacked-partition indices that decompose as
    ``tenant = q // P`` — and this split is free host-side slicing, so a
    T-tenant collect costs one transfer + O(detections) per tenant, never
    T transfers. Works on host numpy or device arrays (pure indexing).
    """
    tp = flags.change_global.shape[0]
    if tenants < 1 or tp % tenants:
        raise ValueError(
            f"stacked flag plane of {tp} rows does not split into "
            f"{tenants} tenants"
        )
    p = tp // tenants
    out = []
    for t in range(tenants):
        sl = FlagRows(
            *(getattr(flags, f)[t * p : (t + 1) * p] for f in FlagRows._fields)
        )
        if flag_cols is not None:
            w = int(flag_cols[t])
            sl = FlagRows(*(leaf[:, :w] for leaf in sl))
        out.append(sl)
    return out


def tenant_drift_vote(flags: FlagRows) -> np.ndarray:
    """One tenant's cross-partition drift vote — the fraction of its
    partitions flagging change per microbatch step, f32, matching the
    device reduction's dtype and arithmetic (``finish_mesh_run``). The
    multi-tenant collect computes this per tenant host-side: a vote pooled
    across tenants would be meaningless (tenants are independent streams).
    """
    changed = (np.asarray(flags.change_global) >= 0).astype(np.float32)
    return changed.sum(axis=0, dtype=np.float32) / np.float32(
        changed.shape[0]
    )


def finish_mesh_run(
    flags: FlagRows, compact_capacity: int = 0
) -> MeshRunResult:
    """The end-of-run merge shared by every runner: cross-partition drift
    vote (lowers to an ICI all-reduce when the partition axis is
    device-sharded — the psum merge of SURVEY §2) + the packed single-array
    collect form. ``compact_capacity > 0`` additionally fuses the
    segment-compaction epilogue (:func:`compact_flag_table`) so collect can
    ship O(detections) bytes instead of the plane."""
    changed = (flags.change_global >= 0).astype(jnp.float32)  # [P, NB-1]
    vote = jnp.sum(changed, axis=0) / changed.shape[0]
    packed = jnp.stack(
        [getattr(flags, f).astype(jnp.int32) for f in FlagRows._fields]
    )
    compact = (
        compact_flag_table(flags, compact_capacity)
        if compact_capacity
        else None
    )
    return MeshRunResult(
        flags=flags, drift_vote=vote, packed=packed, compact=compact
    )


_BOOL_FLAGS = frozenset({"forced_retrain"})


def unpack_flags(packed: np.ndarray) -> FlagRows:
    """Rebuild host-side :class:`FlagRows` from ``MeshRunResult.packed``."""
    return FlagRows(**{
        name: packed[i].astype(bool) if name in _BOOL_FLAGS else packed[i]
        for i, name in enumerate(FlagRows._fields)
    })


def make_mesh_runner(
    model: Model,
    ddm_params: DDMParams,
    mesh: Mesh | None,
    *,
    shuffle: bool = True,
    retrain_error_threshold: float | None = None,
    window: int = 1,
    indexed: bool = False,
    packed: bool = False,
    detector=None,
    rotations: int = 1,
    compact_capacity: int = 0,
):
    """Build ``run(batches, keys) -> MeshRunResult``, jitted over the mesh.

    ``batches`` leaves carry a leading partition axis ``[P, ...]`` sharded
    over the mesh; ``keys`` is ``[P]`` of PRNG keys. With ``mesh=None`` the
    same program runs single-device (one chip still vmaps over partitions).

    ``window > 1`` selects the speculative window engine (``engine.window``)
    — same flags, ~10× fewer sequential steps; ``window = 1`` is the
    batch-per-step sequential scan. ``indexed=True`` builds the runner for
    :class:`IndexedBatches` (compressed stream: row table replicated across
    the mesh, index planes sharded; requires ``window > 1``).
    ``packed=True`` (implies ``indexed``) accepts
    :class:`PackedIndexedBatches` and synthesizes the geometry planes
    in-jit (``expand_packed``) before the engines see them — the engines
    and their flags are identical, only the host→device transfer shrinks.
    ``rotations`` is the window engine's speculation depth
    (``engine.window.make_window_span``); it requires ``window > 1``
    (rejected otherwise, matching ``ChunkedDetector``).
    ``compact_capacity > 0`` fuses the segment-compaction epilogue into the
    program (:func:`compact_flag_table`): ``MeshRunResult.compact`` then
    carries the dense detection table the collect phase ships instead of
    the packed plane (:func:`host_flags`); flags are untouched.
    """
    from ..models.base import require_shardable

    require_shardable(model, mesh)
    packed_mode = packed
    indexed = indexed or packed_mode
    if window == 0:
        raise ValueError(
            "window=0 (auto) needs stream geometry and is resolved by "
            "api.prepare (config.auto_window); pass an explicit width here"
        )
    if indexed and window <= 1:
        raise ValueError("indexed batches require the window engine (window > 1)")
    if window <= 1 and rotations != 1:
        # Same contract as ChunkedDetector: the knob only exists on the
        # window engine, and silently ignoring it (or an invalid 0) would
        # make RunConfig(window=1, window_rotations=...) a no-op surface.
        raise ValueError(
            "rotations only applies to the window engine (window > 1)"
        )
    if window > 1:
        from ..engine.window import make_window_runner

        run_one = make_window_runner(
            model,
            ddm_params,
            window=window,
            shuffle=shuffle,
            retrain_error_threshold=retrain_error_threshold,
            detector=detector,
            rotations=rotations,
        )
    else:
        run_one = make_partition_runner(
            model,
            ddm_params,
            shuffle=shuffle,
            retrain_error_threshold=retrain_error_threshold,
            detector=detector,
        )
    if indexed:
        # Row table replicated (None axes), index planes partition-major.
        batch_axes = IndexedBatches(None, None, 0, 0, 0)
    else:
        batch_axes = Batches(0, 0, 0, 0)
    vmapped = jax.vmap(run_one, in_axes=(batch_axes, 0))

    def run(batches, keys: jax.Array) -> MeshRunResult:
        if packed_mode:
            # Synthesize the geometry planes on device: 1-byte perms in,
            # int32 rows + validity mask out — engines see the exact
            # IndexedBatches the host striper would have built.
            batches = expand_packed(batches)
        return finish_mesh_run(
            vmapped(batches, keys), compact_capacity=compact_capacity
        )

    if mesh is None:
        return jax.jit(run)

    data_sharding = plane_sharding(mesh)
    replicated = NamedSharding(mesh, P())
    if packed_mode:
        in_batches = PackedIndexedBatches(
            base_X=replicated, base_y=replicated,
            idx=data_sharding, perm=data_sharding, n_rows=replicated,
        )
    elif indexed:
        in_batches = IndexedBatches(
            replicated, replicated, data_sharding, data_sharding, data_sharding
        )
    else:
        in_batches = Batches(*(data_sharding,) * 4)
    out_sharding = MeshRunResult(
        flags=FlagRows(*(data_sharding,) * len(FlagRows._fields)),
        drift_vote=replicated,  # replicated after the all-reduce
        packed=NamedSharding(mesh, P(None, plane_axes(mesh))),
        # The compacted table is tiny and its nonzero-compaction already
        # gathered across shards — replicate it like the vote.
        compact=replicated if compact_capacity else None,
    )
    return jax.jit(
        run, in_shardings=(in_batches, data_sharding), out_shardings=out_sharding
    )


def shard_batches(batches, keys: jax.Array, mesh: Mesh | None):
    """Host→device placement of the striped stream (the ``:222`` upload).

    :class:`Batches` planes are partition-sharded; an :class:`IndexedBatches`
    row table is replicated to every device (it is tiny — the whole point of
    the compressed form) while its index planes are partition-sharded.
    """
    if mesh is None:
        return jax.device_put(batches), jax.device_put(keys)
    sh = plane_sharding(mesh)
    rep = NamedSharding(mesh, P())
    if isinstance(batches, PackedIndexedBatches):
        placed = PackedIndexedBatches(
            base_X=jax.device_put(batches.base_X, rep),
            base_y=jax.device_put(batches.base_y, rep),
            idx=jax.device_put(batches.idx, sh),
            perm=jax.device_put(batches.perm, sh),
            n_rows=jax.device_put(batches.n_rows, rep),
        )
        return placed, jax.device_put(keys, sh)
    if isinstance(batches, IndexedBatches):
        placed = IndexedBatches(
            base_X=jax.device_put(batches.base_X, rep),
            base_y=jax.device_put(batches.base_y, rep),
            idx=jax.device_put(batches.idx, sh),
            rows=jax.device_put(batches.rows, sh),
            valid=jax.device_put(batches.valid, sh),
        )
        return placed, jax.device_put(keys, sh)
    return jax.device_put(batches, sh), jax.device_put(keys, sh)
