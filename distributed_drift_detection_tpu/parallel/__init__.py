from . import multihost
from .mesh import (
    PARTITION_AXIS,
    MeshRunResult,
    auto_compact_capacity,
    compact_flag_table,
    expand_flag_table,
    host_flags,
    make_mesh,
    make_mesh_runner,
    partition_sharding,
    shard_batches,
    unpack_flags,
)

__all__ = [
    "PARTITION_AXIS",
    "multihost",
    "partition_sharding",
    "unpack_flags",
    "MeshRunResult",
    "auto_compact_capacity",
    "compact_flag_table",
    "expand_flag_table",
    "host_flags",
    "make_mesh",
    "make_mesh_runner",
    "shard_batches",
]
