from .mesh import (
    PARTITION_AXIS,
    MeshRunResult,
    make_mesh,
    make_mesh_runner,
    shard_batches,
)

__all__ = [
    "PARTITION_AXIS",
    "MeshRunResult",
    "make_mesh",
    "make_mesh_runner",
    "shard_batches",
]
