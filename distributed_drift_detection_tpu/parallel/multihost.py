"""Multi-host (DCN) scaling of the partition mesh.

The reference scales out by pointing more Spark executors at a standalone
master over the network (``DDM_Process.py:6,61-72``; SURVEY.md §2 "Distributed
communication backend"). The TPU-native equivalent spans *hosts*: each host
owns a TPU slice-piece, JAX's runtime carries collectives over ICI within a
slice and DCN across slices, and the control plane is
``jax.distributed.initialize`` instead of a Spark master URL.

The stream workload makes this easy: partitions never communicate during the
loop (embarrassingly parallel, matching the reference's zero worker↔worker
traffic), so the only cross-host traffic is the end-of-run drift-vote
all-reduce and flag gather — a few KB over DCN.

Usage on an N-host pod (same program on every host, e.g. via the TPU VM
launcher)::

    from distributed_drift_detection_tpu.parallel import multihost

    multihost.initialize()              # DCN control plane (env-signalled)
    mesh = multihost.global_mesh()      # 1-D mesh over ALL hosts' devices
    batches = stripe_partitions(stream, partitions, per_batch)
    sl = multihost.host_partition_slice(partitions, mesh)
    local, lkeys = multihost.local_stripe(batches, keys, sl)
    db, dk = multihost.shard_batches_global(local, lkeys, mesh, partitions)
    runner = make_mesh_runner(model, ddm, mesh, ...)
    out = runner(db, dk)                # flags gathered across hosts

Each host feeds only its own partitions (``host_partition_slice``), so the
host→device upload scales with 1/num_hosts — the analog of the reference
having each executor read its own stripe rather than the driver shipping the
whole dataframe (its 512 MB RPC ceiling, ``DDM_Process.py:70``).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .mesh import PARTITION_AXIS, Mesh


# Environment variables whose presence signals a multi-process launch (JAX's
# own coordinator override, or a cluster manager that sets the coordinator
# explicitly).
_DCN_ENV_SIGNALS = (
    "JAX_COORDINATOR_ADDRESS",
    "COORDINATOR_ADDRESS",
    "MEGASCALE_COORDINATOR_ADDRESS",
)


def _multiprocess_signalled() -> bool:
    import os

    if any(os.environ.get(v) for v in _DCN_ENV_SIGNALS):
        return True
    # TPU pod metadata: set on every TPU VM; signals a *pod* only when it
    # lists more than one worker hostname.
    hosts = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    return len([h for h in hosts.split(",") if h.strip()]) > 1


# Launcher rank/world-size variables, most specific first: our own explicit
# convention, then the cluster managers jax's own autodetection reads.
_RANK_ENV = ("JAX_PROCESS_ID", "SLURM_PROCID", "OMPI_COMM_WORLD_RANK")
_WORLD_ENV = ("JAX_PROCESS_COUNT", "SLURM_NTASKS", "OMPI_COMM_WORLD_SIZE")


def host_identity() -> dict:
    """This process's fleet identity: ``{hostname, process_index,
    process_count}`` — the ``run_started`` extras that let
    ``telemetry.correlate`` merge one multi-host run's N per-process logs.

    Jax-init-safe, same rule as :func:`initialize`: querying
    ``jax.process_index()`` on a process that has not yet initialized a
    backend would *create* one locally and make a later
    ``jax.distributed.initialize`` impossible — an identity probe must
    never decide the process's cluster fate. Resolution order:

    1. The **distributed control plane**, when it is already up
       (``jax.distributed.initialize`` ran): its process id/count are
       authoritative and readable without touching any backend — this is
       the pod window between ``multihost.initialize()`` and the first
       device op, where a backend probe alone would misreport ``(0, 1)``.
    2. A **live backend** (``jax.process_index()``), which at that point
       is a harmless read.
    3. The **launcher environment** (our explicit
       ``JAX_PROCESS_ID``/``JAX_PROCESS_COUNT`` convention, else the
       SLURM/OpenMPI rank variables jax's own cluster autodetection
       reads), falling back to the single-process identity ``(0, 1)``.
    """
    import os
    import socket

    ident = {
        "hostname": socket.gethostname(),
        "process_index": 0,
        "process_count": 1,
    }
    dist = _distributed_identity()
    if dist is not None:
        ident["process_index"], ident["process_count"] = dist
        return ident
    if _backend_initialized():
        ident["process_index"] = int(jax.process_index())
        ident["process_count"] = int(jax.process_count())
        return ident
    for key, names in (("process_index", _RANK_ENV),
                       ("process_count", _WORLD_ENV)):
        for var in names:
            val = os.environ.get(var, "")
            if val.strip().isdigit():
                ident[key] = int(val)
                break
    return ident


def fleet_worker_identity() -> dict:
    """Identity extras a sweep-fleet worker agent (``sched/worker.py``)
    stamps onto its scheduler hello: :func:`host_identity` plus the pid.
    One copy of the contract, so the scheduler's ``/statusz`` worker rows
    and the per-cell ``run_started`` extras (written by ``api.run``
    through the same :func:`host_identity`) name workers consistently —
    ``correlate`` then groups a scheduler-run sweep's per-worker logs
    exactly like a pod's per-process logs."""
    import os

    return {**host_identity(), "pid": os.getpid()}


def _distributed_identity() -> "tuple[int, int] | None":
    """``(process_id, num_processes)`` from jax's distributed runtime
    state when the control plane is initialized, else ``None``. Reads the
    private global state because there is no public backend-free probe;
    an unknown internals layout reads as 'not initialized' so the probe
    stays harmless."""
    try:
        from jax._src.distributed import global_state

        if global_state.client is None:
            return None
        return int(global_state.process_id), int(global_state.num_processes)
    except Exception:
        return None


def _backend_initialized() -> bool:
    """Whether any XLA backend is already live in this process — without
    creating one (same private-internals caveat as
    :func:`_distributed_identity`)."""
    try:
        from jax._src import xla_bridge

        return bool(xla_bridge._backends)
    except Exception:
        return False


def initialize(**kwargs) -> None:
    """Start the DCN control plane (single-process safe).

    Thin wrapper over :func:`jax.distributed.initialize` with one rule: the
    decision to go distributed is made **before touching any JAX API**
    (querying the backend would initialise it locally and make a later
    distributed init impossible). With explicit kwargs
    (``coordinator_address``/``num_processes``/``process_id``) or any of the
    coordinator environment signals present, initialization runs and errors
    **propagate** — a misconfigured pod must fail loudly, not degrade into N
    silent single-host runs. With neither, this is a no-op: a single-process
    run (CPU tests, one chip) whose local backend is the whole "cluster",
    the analog of the reference's local Spark mode.

    On managed pods whose launcher relies on JAX's cluster autodetection
    without setting any of the signal variables, call
    ``initialize(coordinator_address=...)`` explicitly (or export
    ``JAX_COORDINATOR_ADDRESS``).
    """
    if not kwargs and not _multiprocess_signalled():
        return
    jax.distributed.initialize(**kwargs)


def global_mesh(tenant_devices: int = 0) -> Mesh:
    """Partition mesh over every device of every host — 1-D classically,
    2-D ``(tenants, partitions)`` when ``tenant_devices > 1`` (ROADMAP
    item 1: the fleet-scale tenant plane spread over a pod). Device order
    is host-major either way, so :func:`host_partition_slice` — which
    slices the FLATTENED plane axis — works unchanged: a host's share of
    the stacked ``[T·P, ...]`` plane is still the contiguous row range
    its devices own."""
    from .mesh import make_mesh

    return make_mesh(tenant_devices=tenant_devices)


def host_partition_slice(partitions: int, mesh: Mesh) -> slice:
    """The contiguous range of partition indices this host must feed.

    Partitions are laid out contiguously over the mesh's device order, so a
    host's share is ``partitions * (local devices / global devices)``
    starting at its first addressable device's position.
    """
    devices = list(mesh.devices.flat)
    n = len(devices)
    if partitions % n:
        raise ValueError(f"{partitions} partitions not divisible by {n} devices")
    per_dev = partitions // n
    local = [i for i, d in enumerate(devices) if d.process_index == jax.process_index()]
    if not local:
        return slice(0, 0)
    if local != list(range(local[0], local[0] + len(local))):
        raise ValueError("host's devices are not contiguous in the mesh")
    return slice(local[0] * per_dev, (local[-1] + 1) * per_dev)


def local_stripe(batches, keys: jax.Array, sl: slice):
    """Slice the host's own partitions out of host-striped arrays.

    Sharded planes are cut to ``sl``; an :class:`IndexedBatches` row table is
    replicated, so it passes through whole.
    """
    from ..engine.loop import IndexedBatches, PackedIndexedBatches

    if isinstance(batches, PackedIndexedBatches):
        return (
            PackedIndexedBatches(
                base_X=batches.base_X,
                base_y=batches.base_y,
                idx=batches.idx[sl],
                perm=batches.perm[sl],
                n_rows=batches.n_rows,
            ),
            keys[sl],
        )
    if isinstance(batches, IndexedBatches):
        return (
            IndexedBatches(
                base_X=batches.base_X,
                base_y=batches.base_y,
                idx=batches.idx[sl],
                rows=batches.rows[sl],
                valid=batches.valid[sl],
            ),
            keys[sl],
        )
    return jax.tree.map(lambda x: x[sl], batches), keys[sl]


def shard_batches_global(
    batches, keys: jax.Array, mesh: Mesh, partitions: int | None = None
):
    """Multi-host upload: each host contributes its own partition stripe.

    Builds globally-sharded arrays from *process-local* data via
    :func:`jax.make_array_from_process_local_data` — the DCN-era replacement
    for the reference's whole-dataframe RPC upload. In multi-host runs the
    sharded planes (``batches`` grids, ``keys``) must be **this host's
    stripe only** (cut with :func:`host_partition_slice` +
    :func:`local_stripe`); replicated planes (the compressed-stream row
    table) are the full arrays on every host. Pass the global ``partitions``
    count explicitly so hosts that contribute zero partitions still agree on
    the global shape.

    On a single process this degenerates to ``parallel.shard_batches``.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    if jax.process_count() == 1:
        from .mesh import shard_batches

        return shard_batches(batches, keys, mesh)

    from .mesh import plane_axes

    sharded = NamedSharding(mesh, P(plane_axes(mesh)))
    replicated = NamedSharding(mesh, P())
    if partitions is None:
        n_local = sum(
            1 for d in mesh.devices.flat
            if d.process_index == jax.process_index()
        )
        if n_local == 0:
            raise ValueError(
                "this process addresses no devices in the mesh; pass the "
                "global `partitions` count explicitly"
            )
        ratio = mesh.devices.size // n_local

    def put(x, sharding):
        # Typed PRNG keys travel as their uint32 key data.
        is_key = jnp.issubdtype(getattr(x, "dtype", None), jax.dtypes.prng_key)
        impl = jax.random.key_impl(x) if is_key else None
        x = np.asarray(jax.random.key_data(x) if is_key else x)
        global_shape = x.shape
        if sharding is sharded:
            parts = partitions if partitions is not None else x.shape[0] * ratio
            global_shape = (parts, *x.shape[1:])
        out = jax.make_array_from_process_local_data(sharding, x, global_shape)
        return jax.random.wrap_key_data(out, impl=impl) if is_key else out

    from ..engine.loop import IndexedBatches, PackedIndexedBatches

    if isinstance(batches, PackedIndexedBatches):
        placed = PackedIndexedBatches(
            base_X=put(batches.base_X, replicated),
            base_y=put(batches.base_y, replicated),
            idx=put(batches.idx, sharded),
            perm=put(batches.perm, sharded),
            n_rows=put(batches.n_rows, replicated),
        )
    elif isinstance(batches, IndexedBatches):
        placed = IndexedBatches(
            base_X=put(batches.base_X, replicated),
            base_y=put(batches.base_y, replicated),
            idx=put(batches.idx, sharded),
            rows=put(batches.rows, sharded),
            valid=put(batches.valid, sharded),
        )
    else:
        placed = jax.tree.map(lambda x: put(x, sharded), batches)
    return placed, put(keys, sharded)
