"""ctypes binding to the native C++ ingest library (``native/ddd_native.cc``).

The reference's host data plane is Spark's JVM + Arrow; ours is a small C++
shared library for the parsing-bound part of ingest (CSV → row-major f32 at
memory speed, multithreaded, file read + line-indexed exactly once). Falls
back transparently to the NumPy path when the library is absent or the data
is malformed (strict parser — bad fields never silently become zeros); a
failed build is attempted at most once per process.

The C++ sources live at the repo root (``native/``) and ship in sdists
(MANIFEST.in); wheel installs have no ``native/`` directory and use the
NumPy fallback — by design, since the deployment target (TPU hosts running
a source checkout) always has the sources.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
_LIB_PATH = os.path.abspath(os.path.join(_NATIVE_DIR, "libddd_native.so"))

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_lib_tried = False


def _load_library() -> ctypes.CDLL | None:
    global _lib, _lib_tried
    with _lock:
        if _lib is not None or _lib_tried:
            return _lib
        _lib_tried = True
        # Always invoke make: its .cc dependency makes this a cheap no-op
        # when the library is current, and rebuilds a stale .so whose symbol
        # set predates this binding (binding such a library would raise).
        # An inter-process file lock serializes the build — concurrent first
        # loads (grid workers, pytest-xdist) must not dlopen a half-written
        # .so another process is regenerating in place.
        try:
            import fcntl

            lock_path = os.path.join(
                os.path.abspath(_NATIVE_DIR), ".build.lock"
            )
            with open(lock_path, "w") as lock_fh:
                fcntl.flock(lock_fh, fcntl.LOCK_EX)
                try:
                    subprocess.run(
                        ["make", "-s", "-C", os.path.abspath(_NATIVE_DIR)],
                        check=True,
                        capture_output=True,
                        timeout=120,
                    )
                finally:
                    fcntl.flock(lock_fh, fcntl.LOCK_UN)
        except (subprocess.SubprocessError, OSError, ImportError):
            pass  # no toolchain / read-only checkout: try the existing .so
        if not os.path.exists(_LIB_PATH):
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            return None
        try:
            lib.ddd_parse_block  # noqa: B018 — probe the newest symbol
        except AttributeError:
            return None  # stale library that make could not refresh
        lib.ddd_csv_open.argtypes = [ctypes.c_char_p]
        lib.ddd_csv_open.restype = ctypes.c_void_p
        for fn in (lib.ddd_csv_rows, lib.ddd_csv_cols):
            fn.argtypes = [ctypes.c_void_p]
            fn.restype = ctypes.c_int64
        lib.ddd_csv_read.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_float)]
        lib.ddd_csv_read.restype = ctypes.c_int64
        lib.ddd_csv_close.argtypes = [ctypes.c_void_p]
        lib.ddd_csv_close.restype = None
        lib.ddd_parse_block.argtypes = [
            ctypes.c_char_p,
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_int64,
        ]
        lib.ddd_parse_block.restype = ctypes.c_int64
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load_library() is not None


def load_csv_native(path: str) -> np.ndarray | None:
    """Parse a numeric CSV (header + rows) to ``[rows, cols]`` f32, or None
    if the native library is unavailable or any field is malformed (the
    caller then falls back to the NumPy path, which raises with a message)."""
    lib = _load_library()
    if lib is None:
        return None
    handle = lib.ddd_csv_open(path.encode())
    if not handle:
        return None
    try:
        rows = lib.ddd_csv_rows(handle)
        cols = lib.ddd_csv_cols(handle)
        out = np.empty((rows, cols), np.float32)
        status = lib.ddd_csv_read(
            handle, out.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
        )
        if status != 0:
            return None
        return out
    finally:
        lib.ddd_csv_close(handle)


def parse_block(block: bytes, cols: int) -> np.ndarray:
    """Parse a block of complete CSV data rows (no header) to ``[n, cols]``
    f32. Native multithreaded parser when available, NumPy fallback
    otherwise; raises ``ValueError`` on malformed data either way."""
    if not block:
        return np.empty((0, cols), np.float32)
    lib = _load_library()
    if lib is not None:
        max_rows = block.count(b"\n") + (0 if block.endswith(b"\n") else 1)
        out = np.empty((max_rows, cols), np.float32)
        n = lib.ddd_parse_block(
            block,
            len(block),
            cols,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            max_rows,
        )
        if n >= 0:
            return out[:n]
        # fall through: NumPy raises with a useful message
    import io as _io

    arr = np.loadtxt(_io.BytesIO(block), delimiter=",", dtype=np.float32, ndmin=2)
    if arr.shape[1] != cols:
        raise ValueError(f"expected {cols} columns, got {arr.shape[1]}")
    return arr
