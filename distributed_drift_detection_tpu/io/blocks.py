"""Line-aligned byte-range planning for parallel CSV ingest — jax-free.

The host data plane consumes CSV files in bounded blocks. Until r10 the
block boundaries were a side effect of serial ``read(block_bytes)`` calls
carrying partial lines forward; parallel ingest needs the boundaries
*planned up front* so N workers can parse disjoint, line-aligned ranges
of one ``mmap`` concurrently while the consumer reassembles results in
submission order (row order is then preserved exactly — the parallel
pipeline's determinism contract, ``io.feeder.csv_chunks``).

This module is the ONE boundary rule shared by every consumer — the
streaming feeder (``io.feeder.csv_chunks``, any worker count), and the
jax-free ``doctor --jobs`` parallel contract scan (``io.sanitize``) —
so two paths can never disagree about which bytes form a block. Pure
stdlib; no numpy, no jax (``doctor`` must run wherever the data lands).
"""

from __future__ import annotations

import mmap


def line_block_ranges(
    buf, start: int, block_bytes: int
) -> list[tuple[int, int]]:
    """Split ``buf[start:]`` into contiguous ``(lo, hi)`` byte ranges of
    ~``block_bytes`` each, every boundary landing just after a ``\\n``.

    Invariants (the parallel-parse determinism contract):

    * ranges are contiguous and disjoint: ``ranges[i][1] == ranges[i+1][0]``,
      covering ``start..len(buf)`` exactly;
    * every ``hi`` except possibly the last sits one past a newline, so a
      block always holds complete lines (the last block may lack a trailing
      newline — parsers handle the final partial line);
    * a single line longer than ``block_bytes`` extends its block to the
      line's end (the serial reader's carry semantics, planned ahead).

    ``buf`` is anything sliceable with ``find``/``rfind`` (an ``mmap``, a
    ``bytes``); the planner touches only bytes near each boundary, so
    planning a multi-GB file costs a handful of page faults per block.
    """
    if block_bytes <= 0:
        raise ValueError(f"block_bytes must be > 0, got {block_bytes}")
    n = len(buf)
    ranges: list[tuple[int, int]] = []
    lo = start
    while lo < n:
        hi = min(lo + block_bytes, n)
        if hi < n:
            nl = buf.rfind(b"\n", lo, hi)
            if nl < 0:
                # No newline inside the window: one over-long line —
                # extend to its terminating newline (or EOF).
                nl = buf.find(b"\n", hi)
                hi = n if nl < 0 else nl + 1
            else:
                hi = nl + 1
        ranges.append((lo, hi))
        lo = hi
    return ranges


def open_mapped(path: str) -> "tuple[object, mmap.mmap | bytes, int]":
    """Open ``path`` for block-range ingest: ``(file handle, buffer,
    data_start)`` where ``buffer`` is a read-only ``mmap`` of the whole
    file (falling back to an in-memory read where mmap is unavailable —
    e.g. an empty or special file) and ``data_start`` is the offset of
    the first data row (just past the header line). The caller owns both
    the handle and the buffer (``close()`` each; ``bytes`` fallback has a
    no-op close via duck typing at the call sites)."""
    fh = open(path, "rb")
    header_line = fh.readline()
    data_start = len(header_line)
    try:
        buf: "mmap.mmap | bytes" = mmap.mmap(
            fh.fileno(), 0, access=mmap.ACCESS_READ
        )
    except (ValueError, OSError):
        fh.seek(0)
        buf = fh.read()
    return fh, buf, data_start
