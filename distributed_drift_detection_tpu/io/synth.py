"""Synthetic drift-stream generators.

The reference's only stream synthesis is volume-scaling a CSV (C2,
``DDM_Process.py:38-55``). For scale tests beyond the shipped data
(BASELINE.json config #4: "Synthetic SEA/HYPERPLANE generator, 1e9 rows,
abrupt+gradual drifts — sustained-throughput soak") this module provides
classic stream-benchmark generators plus the planted-prototype stream used
throughout the test suite. All generators are seeded and chunk-friendly
(generate any ``[start, stop)`` row range deterministically), so the chunked
engine can stream unbounded data without materialising it.

Every generator returns (or fills) ``X [N,F] f32`` and ``y [N] i32`` with
known drift positions; :func:`as_stream` wraps them into a
:class:`~..io.stream.StreamData` with the concept spacing the delay metric
needs.
"""

from __future__ import annotations

import numpy as np

from ..utils.prng import row_uniforms as _row_uniforms
from .stream import StreamData


def planted_prototypes(
    seed: int,
    concepts: int = 40,
    rows_per_concept: int = 100,
    features: int = 21,
    noise: float = 0.05,
    label_flip: float = 0.0,
) -> StreamData:
    """Concept k = noisy copies of prototype k, labelled k — the same
    geometry as a volume-scaled outdoorStream (C2: sorted by target, equal
    concepts)."""
    rng = np.random.default_rng(seed)
    protos = rng.normal(size=(concepts, features)).astype(np.float32) * 3.0
    X = np.concatenate(
        [
            protos[k]
            + noise * rng.normal(size=(rows_per_concept, features)).astype(np.float32)
            for k in range(concepts)
        ]
    ).astype(np.float32)
    y = np.repeat(np.arange(concepts, dtype=np.int32), rows_per_concept)
    if label_flip:
        flip = rng.random(len(y)) < label_flip
        y[flip] = rng.integers(0, concepts, flip.sum()).astype(np.int32)
    return StreamData(X, y, concepts, rows_per_concept)


def rialto_like_xy(
    seed: int = 0,
    classes: int = 10,
    rows_per_class: int = 8225,
    features: int = 27,
    class_sep: float = 1.6,
    within_rank: int = 6,
    label_noise: float = 0.02,
) -> tuple[np.ndarray, np.ndarray]:
    """Synthetic stand-in for the reference's second benchmark dataset.

    ``rialto.csv`` is referenced throughout the reference (27 features per
    ``DDM_Process.py:33``; dataset switch in ``Plot Results.ipynb`` cell 2)
    but is absent from the repo as a large blob (SURVEY.md C16) — the real
    Rialto-bridge stream is 82,250 rows × 27 features × 10 classes. This
    generator reproduces that geometry: 10 class clusters in 27-d with
    low-rank anisotropic within-class covariance (colour-histogram-like
    correlated features) and a little label noise, so classifiers are good
    but not perfect and DDM sees a realistic error floor. Defaults give the
    real dataset's shape; rows are emitted class-interleaved (unsorted) and
    flow through the same C2 pipeline (``synthesize_stream``: mult → shuffle
    → sort-by-target) as a loaded CSV.
    """
    rng = np.random.default_rng(seed)
    protos = rng.normal(size=(classes, features)).astype(np.float32) * class_sep
    # Low-rank within-class factors: correlated feature noise per class.
    factors = rng.normal(size=(classes, within_rank, features)).astype(np.float32)
    n = classes * rows_per_class
    y = np.tile(np.arange(classes, dtype=np.int32), rows_per_class)
    z = rng.normal(size=(n, within_rank)).astype(np.float32)
    X = (
        protos[y]
        + np.einsum("nr,nrf->nf", z, factors[y]) * 0.4
        + 0.15 * rng.normal(size=(n, features)).astype(np.float32)
    )
    flip = rng.random(n) < label_noise
    y = y.copy()
    y[flip] = rng.integers(0, classes, int(flip.sum())).astype(np.int32)
    return X.astype(np.float32), y


def planted_prototypes_xy(
    seed: int = 0,
    concepts: int = 8,
    rows_per_concept: int = 400,
    features: int = 7,
    noise: float = 0.05,
    label_flip: float = 0.0,
) -> tuple[np.ndarray, np.ndarray]:
    """Raw ``(X, y)`` of :func:`planted_prototypes` — registered as the
    ``synth:prototypes`` spec so stream replays (``loadgen``, the CI
    trace-smoke job) can drive a concept-sorted stream with *planted*
    drift boundaries over the wire: every concept switch is a guaranteed
    distribution change the detectors fire on."""
    s = planted_prototypes(
        seed, concepts=concepts, rows_per_concept=rows_per_concept,
        features=features, noise=noise, label_flip=label_flip,
    )
    return s.X, s.y


def _class_protos(rng, classes: int, features: int, sep: float) -> np.ndarray:
    return rng.normal(size=(classes, features)).astype(np.float32) * sep


def gradual_drift_xy(
    seed: int = 0,
    concepts: int = 4,
    rows_per_concept: int = 1000,
    features: int = 12,
    classes: int = 8,
    transition: int = 200,
    noise: float = 1.0,
    class_sep: float = 1.0,
) -> tuple[np.ndarray, np.ndarray]:
    """Gradual-drift stream: per-concept class prototypes with a linear
    mixing band at every boundary (``synth:gradual``).

    Unlike ``prototypes`` (one class per concept, labels = concept id),
    every concept here holds all ``classes`` classes interleaved — the
    label domain is fixed at ``0..classes-1`` for the whole stream, which
    is exactly the serving ingress contract — and a concept switch
    *redraws the class prototypes*, so a model fitted on the old concept
    mispredicts the new one and the detectors fire on real error drift.
    The last ``transition`` rows before each boundary sample from the
    NEXT concept's prototypes with linearly ramping probability (the
    classic gradual-drift shape: the new concept bleeds in, it does not
    snap), so detection delay and adaptation are exercised on a boundary
    that has no single true row. Registered for wire replay like
    ``prototypes`` — the adaptation plane's proving stream.
    """
    if not 0 <= transition <= rows_per_concept:
        raise ValueError(
            f"transition must be in [0, rows_per_concept], got {transition}"
        )
    rng = np.random.default_rng(seed)
    protos = np.stack(
        [_class_protos(rng, classes, features, class_sep) for _ in range(concepts)]
    )  # [K, C, F]
    n = concepts * rows_per_concept
    y = rng.integers(0, classes, n).astype(np.int32)
    rows = np.arange(n)
    k = rows // rows_per_concept
    pos = rows % rows_per_concept
    ramp = (
        np.clip(
            (pos - (rows_per_concept - transition)) / transition, 0.0, 1.0
        )
        if transition
        else np.zeros(n)
    )
    use_next = (rng.random(n) < ramp) & (k < concepts - 1)
    eff = np.where(use_next, np.minimum(k + 1, concepts - 1), k)
    X = protos[eff, y] + noise * rng.normal(size=(n, features)).astype(
        np.float32
    )
    return X.astype(np.float32), y


def recurring_drift_xy(
    seed: int = 0,
    concepts: int = 6,
    rows_per_concept: int = 1000,
    features: int = 12,
    classes: int = 8,
    period: int = 2,
    noise: float = 1.0,
    class_sep: float = 1.0,
) -> tuple[np.ndarray, np.ndarray]:
    """Recurring (seasonal) drift stream: concept ``k`` reuses prototype
    set ``k % period`` from a fixed seasonal pool (``synth:recurring``).

    The same multi-class geometry as :func:`gradual_drift_xy` (fixed
    label domain, redrawn prototypes = real error drift at every abrupt
    boundary), but the concepts *cycle*: season A returns after season
    B, so an adaptive model that merely chases the newest window meets a
    distribution it has seen — and discarded — before. The stream the
    champion/challenger plane is proven on: a demoted challenger and a
    returning season are the cases a pure swap-on-drift policy gets
    wrong.
    """
    if period < 1:
        raise ValueError(f"period must be >= 1, got {period}")
    rng = np.random.default_rng(seed)
    pool = np.stack(
        [_class_protos(rng, classes, features, class_sep) for _ in range(period)]
    )  # [S, C, F]
    n = concepts * rows_per_concept
    y = rng.integers(0, classes, n).astype(np.int32)
    k = np.arange(n) // rows_per_concept
    eff = (k % period).astype(np.int64)
    X = pool[eff, y] + noise * rng.normal(size=(n, features)).astype(
        np.float32
    )
    return X.astype(np.float32), y


_SYNTH_REGISTRY = {
    "rialto": rialto_like_xy,
    "prototypes": planted_prototypes_xy,
    "gradual": gradual_drift_xy,
    "recurring": recurring_drift_xy,
}


def parse_synth(spec: str) -> tuple[np.ndarray, np.ndarray]:
    """Resolve a ``synth:`` dataset spec to raw ``(X, y)``.

    Spec grammar: ``name[,key=value]...`` — e.g. ``rialto`` or
    ``rialto,seed=1,rows_per_class=100``. Only class-concept generators are
    registered here (the C2 pipeline sorts by target, which is only
    meaningful for class-as-concept streams; SEA/hyperplane streams carry
    their own drift structure and are consumed via :func:`sea_stream` /
    :func:`hyperplane_stream` instead).
    """
    name, _, rest = spec.partition(",")
    try:
        fn = _SYNTH_REGISTRY[name]
    except KeyError:
        raise ValueError(
            f"unknown synthetic dataset {name!r}; known: {sorted(_SYNTH_REGISTRY)}"
        ) from None
    kw = {}
    if rest:
        for item in rest.split(","):
            if not item.strip():
                continue
            k, sep, v = item.partition("=")
            if not sep:
                raise ValueError(
                    f"bad synth spec item {item!r}; expected key=value "
                    f"(spec grammar: name[,key=value]...)"
                )
            try:
                num = int(v)
            except ValueError:
                try:
                    num = float(v)
                except ValueError:
                    raise ValueError(
                        f"bad synth spec value {item!r}; values must be numeric"
                    ) from None
            kw[k.strip()] = num
    return fn(**kw)


# SEA concept thresholds (Street & Kim 2001): label = f0 + f1 <= theta.
_SEA_THETAS = (8.0, 9.0, 7.0, 9.5)


def sea_chunk(seed: int, start: int, stop: int, drift_every: int, noise: float = 0.0):
    """Rows [start, stop) of an endless SEA stream with abrupt drifts.

    Features ~ U[0,10)^3; the concept of block ``row // drift_every`` cycles
    through the four SEA thresholds. ``noise`` flips that fraction of labels.
    Chunk-exact: deterministic per (seed, row) regardless of chunking.
    """
    n = stop - start
    rows = np.arange(start, stop, dtype=np.int64)
    u = _row_uniforms(seed, start, n, per_row=4, stream_id=0)
    X = (u[:, :3] * 10.0).astype(np.float32)
    theta = np.asarray(_SEA_THETAS, np.float32)[(rows // drift_every) % len(_SEA_THETAS)]
    y = (X[:, 0] + X[:, 1] <= theta).astype(np.int32)
    if noise:
        y[u[:, 3] < noise] ^= 1
    return X, y


def hyperplane_chunk(
    seed: int,
    start: int,
    stop: int,
    features: int = 10,
    drift_every: int = 0,
    rotate_scale: float = 0.0,
):
    """Rows [start, stop) of a rotating-hyperplane stream (Hulten et al.).

    label = (w_c · x > 0.5·Σw_c) with weights w_c per concept block
    (``drift_every`` > 0 → abrupt redraws) and an optional gradual rotation
    (``rotate_scale`` > 0 adds a smooth per-row drift term). Chunk-exact like
    :func:`sea_chunk`.
    """
    n = stop - start
    rows = np.arange(start, stop, dtype=np.int64)
    X = _row_uniforms(seed, start, n, per_row=features, stream_id=1).astype(np.float32)

    if drift_every > 0:
        blocks = rows // drift_every
        uniq = np.unique(blocks)
        # weights per concept block, deterministic in (seed, block)
        w = np.stack(
            [_row_uniforms(seed, int(b), 1, features, stream_id=2)[0] for b in uniq]
        ).astype(np.float32)
        w_rows = w[np.searchsorted(uniq, blocks)]
    else:
        base = _row_uniforms(seed, 0, 1, features, stream_id=2)[0].astype(np.float32)
        w_rows = np.broadcast_to(base, (n, features)).copy()

    if rotate_scale:
        phase = (rows[:, None] * rotate_scale).astype(np.float32)
        w_rows = w_rows + 0.3 * np.sin(phase + np.arange(features, dtype=np.float32))

    margin = (X * w_rows).sum(1) - 0.5 * w_rows.sum(1)
    y = (margin > 0).astype(np.int32)
    return X, y


def as_stream(X: np.ndarray, y: np.ndarray, drift_every: int) -> StreamData:
    """Wrap generated arrays as a StreamData with known concept spacing."""
    return StreamData(
        X=np.ascontiguousarray(X, np.float32),
        y=np.ascontiguousarray(y, np.int32),
        num_classes=int(y.max()) + 1,
        dist_between_changes=drift_every,
    )


def sea_stream(seed: int, n_rows: int, drift_every: int, noise: float = 0.0) -> StreamData:
    X, y = sea_chunk(seed, 0, n_rows, drift_every, noise)
    return as_stream(X, y, drift_every)


def hyperplane_stream(
    seed: int, n_rows: int, features: int = 10, drift_every: int = 0
) -> StreamData:
    X, y = hyperplane_chunk(seed, 0, n_rows, features, drift_every)
    return as_stream(X, y, drift_every or n_rows)
