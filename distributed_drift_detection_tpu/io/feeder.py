"""Host→device chunk feeder for the streaming engine.

Replaces the reference's one-shot driver upload (``spark.createDataFrame`` of
the entire dataset, ``DDM_Process.py:222``) with an incremental feed: a
chunk-exact generator (``io.synth``) or an in-memory stream is cut into
fixed-shape ``[P, CB, B]`` chunks whose striping matches the batch API's
``stripe_partitions`` exactly, so chunked and one-shot runs see identical
per-partition streams. JAX async dispatch overlaps the NumPy assembly and
host→device copy of chunk N+1 with device compute of chunk N (the
double-buffering called for by SURVEY.md §7 "host-feed bandwidth").
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterator

import numpy as np

from ..engine.loop import Batches
from .stream import stripe_chunk


def _ingest_counters(metrics):
    """(rows, chunks) counters for a feed path; ``(None, None)`` without a
    registry — callers guard on None so the disabled path costs nothing."""
    if metrics is None:
        return None, None
    return (
        metrics.counter(
            "ingest_rows_total", help="Stream rows striped into chunks"
        ),
        metrics.counter(
            "ingest_chunks_total", help="Fixed-shape [P,CB,B] chunks emitted"
        ),
    )


def _quarantine_counter(metrics):
    """The quarantine counter, name/help shared with api.run through
    ``io.sanitize.QUARANTINE_METRIC`` (one constant, one series)."""
    from .sanitize import QUARANTINE_METRIC, QUARANTINE_METRIC_HELP

    return metrics.counter(QUARANTINE_METRIC, help=QUARANTINE_METRIC_HELP)


def chunk_stream_arrays(
    X: np.ndarray,
    y: np.ndarray,
    partitions: int,
    per_batch: int,
    chunk_batches: int,
    start_row: int = 0,
    shuffle_seed: int | None = None,
    feature_dtype=np.float32,
    metrics=None,
    row_valid: np.ndarray | None = None,
) -> Iterator[Batches]:
    """Chunk an in-memory stream; rows are global positions + start_row.

    ``feature_dtype`` is the transport dtype of the feature plane
    (``stripe_chunk``): ``ml_dtypes.bfloat16`` halves host→device bytes
    for transport-bound feeds, at the cost of bf16 feature rounding.
    ``metrics`` (a :class:`..telemetry.metrics.MetricsRegistry`) counts
    ``ingest_rows_total`` / ``ingest_chunks_total`` as the feed progresses.
    ``row_valid`` ([n] bool — a quarantine mask from ``io.sanitize``, or
    any caller mask) is sliced per chunk and folded into each chunk's
    validity plane (``stripe_chunk``), so the chunked engine sees masked
    rows as padding exactly like the one-shot path; the mask adds
    ``ingest_quarantined_total`` to the metric set.
    """
    n, f = X.shape
    p, b, cb = partitions, per_batch, chunk_batches
    c_rows, c_chunks = _ingest_counters(metrics)
    c_quar = None
    if metrics is not None and row_valid is not None:
        c_quar = _quarantine_counter(metrics)
    rows_per_chunk = p * b * cb
    for s in range(0, n, rows_per_chunk):
        e = min(s + rows_per_chunk, n)
        rv = None if row_valid is None else row_valid[s:e]
        if c_rows is not None:
            c_rows.inc(e - s)
            c_chunks.inc()
            if c_quar is not None:
                c_quar.inc(int((~np.asarray(rv, bool)).sum()))
        yield stripe_chunk(
            X[s:e], y[s:e], s + start_row, p, b, cb, shuffle_seed,
            feature_dtype=feature_dtype, row_valid=rv,
        )


def generator_chunks(
    chunk_fn: Callable[[int, int], tuple[np.ndarray, np.ndarray]],
    total_rows: int,
    partitions: int,
    per_batch: int,
    chunk_batches: int,
    shuffle_seed: int | None = None,
    feature_dtype=np.float32,
    metrics=None,
) -> Iterator[Batches]:
    """Chunks from a chunk-exact generator ``chunk_fn(start, stop) -> (X, y)``
    (e.g. ``functools.partial(sea_chunk, seed, drift_every=...)`` adapted to
    (start, stop)). Generates only one chunk of rows at a time — 1e9-row
    soaks never materialise the stream. ``metrics`` counts ingest progress
    (see :func:`chunk_stream_arrays`).
    """
    p, b, cb = partitions, per_batch, chunk_batches
    c_rows, c_chunks = _ingest_counters(metrics)
    rows_per_chunk = p * b * cb
    for s in range(0, total_rows, rows_per_chunk):
        e = min(s + rows_per_chunk, total_rows)
        X, y = chunk_fn(s, e)
        if c_rows is not None:
            c_rows.inc(e - s)
            c_chunks.inc()
        yield stripe_chunk(
            X, y, s, p, b, cb, shuffle_seed, feature_dtype=feature_dtype
        )


class _Stop:
    pass


def prefetch_chunks(chunks: Iterator, depth: int = 2, metrics=None) -> Iterator:
    """Run a chunk iterator in a background thread, ``depth`` chunks ahead.

    JAX async dispatch already overlaps *device* compute with the caller's
    *next* host-side chunk assembly — but the assembly itself (CSV parse,
    generator math, striping) runs serially with the feed loop's Python.
    This wrapper moves it to a producer thread with a bounded queue, so host
    construction of chunk N+k proceeds while the main thread is feeding
    chunk N (the double-buffered feed of SURVEY.md §7 "host-feed
    bandwidth", generalized to depth-k).

    Exceptions in the producer propagate to the consumer. Abandoning the
    returned iterator (break / exception / GC) stops the producer thread
    promptly — its queue puts are timeout-guarded against a cancellation
    event that the consumer sets on close, so no chunks stay pinned.

    ``metrics`` (a :class:`..telemetry.metrics.MetricsRegistry`) records
    ``prefetch_chunks_total`` (delivered to the consumer) and the
    ``prefetch_queue_depth`` gauge sampled at each delivery — a depth
    pinned at 0 means the consumer is feed-bound, at ``depth`` means
    device-bound (the SURVEY §7 overlap question, answerable per run).
    """
    c_total = g_depth = None
    if metrics is not None:
        c_total = metrics.counter(
            "prefetch_chunks_total", help="Chunks delivered by the prefetcher"
        )
        g_depth = metrics.gauge(
            "prefetch_queue_depth", help="Prefetch queue depth at delivery"
        )
    q: queue.Queue = queue.Queue(maxsize=max(1, depth))
    stop = threading.Event()

    def put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def produce():
        try:
            for item in chunks:
                if not put(item):
                    return
            put(_Stop)
        except BaseException as e:  # propagate into the consumer
            put(e)

    threading.Thread(target=produce, daemon=True).start()

    def consume():
        try:
            while True:
                item = q.get()
                if item is _Stop:
                    return
                if isinstance(item, BaseException):
                    raise item
                if c_total is not None:
                    c_total.inc()
                    g_depth.set(q.qsize())
                yield item
        finally:
            stop.set()

    return consume()


def csv_chunks(
    path: str,
    partitions: int,
    per_batch: int,
    chunk_batches: int,
    *,
    target_column: str = "target",
    shuffle_seed: int | None = None,
    block_bytes: int = 16 << 20,
    feature_dtype=np.float32,
    metrics=None,
    data_policy: str | None = None,
    quarantine_path: str | None = None,
) -> Iterator[Batches]:
    """Stream a CSV file from disk as striped chunks, without materialising it.

    The one-shot path (``io.stream.load_csv``) parses the whole file — right
    for the reference's scale, impossible for multi-hundred-GB streams. This
    reader consumes the file in bounded byte blocks (carrying partial lines
    across block edges), parses each with the native multithreaded parser
    (``io.native.parse_block``; NumPy fallback), and yields the same
    ``[P, CB, B]`` chunks as :func:`chunk_stream_arrays` — host memory stays
    O(block + chunk) regardless of file size. Compose with
    :func:`prefetch_chunks` to overlap the parse with device compute.

    Labels are not re-indexed — for class labels outside ``0..C-1``, remap
    before modelling (the one-shot loader's re-indexing needs a full pass,
    which a stream cannot afford by design). They parse through float32
    (exact for integers up to 2^24); larger label ids raise rather than
    silently round.

    ``metrics`` counts ``ingest_rows_total`` / ``ingest_chunks_total`` plus
    ``ingest_bytes_total`` (file bytes parsed) for the disk path.

    ``data_policy`` (None = trusting parse, the exact historical
    behaviour) applies the stream contract per block (``io.sanitize``):
    ``'strict'`` raises a structured ``StreamContractError`` naming
    file/row/column on the first violation; ``'quarantine'`` masks
    violating rows into each chunk's validity plane (padding-identical
    inside jit), appends them to the ``quarantine_path`` sidecar, and
    counts ``ingest_quarantined_total``. ``'repair'`` is rejected — mean
    imputation needs full-column statistics a single-pass stream cannot
    have; use the one-shot loader for repair.
    """
    p, b, cb = partitions, per_batch, chunk_batches
    c_rows, c_chunks = _ingest_counters(metrics)
    c_bytes = (
        metrics.counter("ingest_bytes_total", help="CSV bytes parsed")
        if metrics is not None
        else None
    )
    c_quar = None
    sanitize = None
    writer = None
    if data_policy is not None:
        from . import sanitize

        sanitize.check_policy(data_policy)
        if data_policy == "repair":
            raise ValueError(
                "data_policy='repair' needs full-stream column statistics; "
                "the streaming reader supports 'strict' and 'quarantine' — "
                "use io.sanitize.load_csv_sane for repair"
            )
        if data_policy == "quarantine":
            writer = sanitize.QuarantineWriter(
                quarantine_path or (path + ".quarantine.jsonl"), data_policy
            )
            if metrics is not None:
                c_quar = _quarantine_counter(metrics)
    rows_per_chunk = p * b * cb
    from .native import parse_block

    with open(path, "rb") as fh:
        header = fh.readline().decode().strip().split(",")
        if sanitize is not None:
            tcol = sanitize.validate_header(header, target_column, path)
        elif target_column not in header:
            raise ValueError(
                f"{path}: target column {target_column!r} not in header; "
                f"columns found: {header}"
            )
        else:
            tcol = header.index(target_column)
        cols = len(header)
        mask = np.ones(cols, bool)
        mask[tcol] = False
        rows_parsed = 0  # absolute data-row index for sidecar records
        rows_valid = 0  # contract-passing rows seen (all-dirty guard)

        def parse(block_bytes_: bytes) -> tuple[np.ndarray, "np.ndarray | None"]:
            """One block → (matrix, ok-mask | None), contract applied."""
            nonlocal rows_parsed, rows_valid
            if sanitize is None:
                arr = parse_block(block_bytes_, cols)
                rows_parsed += len(arr)
                return arr, None
            try:
                arr = parse_block(block_bytes_, cols)
                issues = []
            except ValueError:
                lines = block_bytes_.decode(errors="replace").splitlines()
                arr, issues = sanitize.parse_rows(lines, cols)
            issues = issues + sanitize.scan_matrix(
                arr, tcol, header,
                flagged=frozenset(i.row for i in issues),
            )
            base = rows_parsed
            rows_parsed += len(arr)
            arr, ok = sanitize.apply_block_policy(
                arr, issues, path=path, policy=data_policy,
                base_row=base, writer=writer, header=header,
            )
            if ok is None:
                rows_valid += len(arr)
            else:
                rows_valid += int(ok.sum())
                if c_quar is not None:
                    c_quar.inc(int((~ok).sum()))
            return arr, ok

        parts: list[np.ndarray] = []
        ok_parts: list["np.ndarray | None"] = []
        buffered = 0
        start_row = 0
        carry = b""

        def emit(start, n_take):
            data = np.concatenate(parts) if len(parts) > 1 else parts[0]
            take, rest = data[:n_take], data[n_take:]
            ok = None
            ok_rest = None
            if any(o is not None for o in ok_parts):
                ok_all = np.concatenate(
                    [
                        np.ones(len(a), bool) if o is None else o
                        for a, o in zip(parts, ok_parts)
                    ]
                )
                ok, ok_rest = ok_all[:n_take], ok_all[n_take:]
                if ok.all():
                    ok = None
                if ok_rest is not None and not len(ok_rest):
                    ok_rest = None
            labels = take[:, tcol]
            valid_labels = labels if ok is None else labels[ok]
            if valid_labels.size and np.abs(valid_labels).max() >= 2**24:
                raise ValueError(
                    "label ids at or above 2^24 are not exactly representable "
                    "on the float32 parse path; re-encode the target column"
                )
            chunk = stripe_chunk(
                take[:, mask],
                labels.astype(np.int32),
                start,
                p, b, cb,
                shuffle_seed,
                feature_dtype=feature_dtype,
                row_valid=ok,
            )
            if c_rows is not None:
                c_rows.inc(len(take))
                c_chunks.inc()
            return chunk, rest, ok_rest

        try:
            while True:
                block = fh.read(block_bytes)
                if not block:
                    break
                if c_bytes is not None:
                    c_bytes.inc(len(block))
                block = carry + block
                cut = block.rfind(b"\n")
                if cut < 0:
                    carry = block
                    continue
                carry, block = block[cut + 1:], block[: cut + 1]
                arr, ok = parse(block)
                parts.append(arr)
                ok_parts.append(ok)
                buffered += len(arr)
                while buffered >= rows_per_chunk:
                    chunk, rest, ok_rest = emit(start_row, rows_per_chunk)
                    yield chunk
                    start_row += rows_per_chunk
                    parts = [rest] if len(rest) else []
                    ok_parts = [ok_rest] if len(rest) else []
                    buffered = len(rest)
            if carry:
                arr, ok = parse(carry)
                parts.append(arr)
                ok_parts.append(ok)
                buffered += len(arr)
            if buffered:
                chunk, _, _ = emit(start_row, buffered)
                yield chunk
            # Degenerate-stream guard, matching the whole-file path
            # (apply_policy raises the same on a fully-dirty file): a
            # run that quarantined EVERY row must not read as success.
            if sanitize is not None and rows_parsed and not rows_valid:
                raise sanitize.StreamContractError(
                    path,
                    reason=(
                        f"all {rows_parsed} data rows violate the stream "
                        "contract; nothing left to quarantine around"
                    ),
                    total=rows_parsed,
                )
        finally:
            if writer is not None:
                writer.close()
