"""Host→device chunk feeder for the streaming engine.

Replaces the reference's one-shot driver upload (``spark.createDataFrame`` of
the entire dataset, ``DDM_Process.py:222``) with an incremental feed: a
chunk-exact generator (``io.synth``) or an in-memory stream is cut into
fixed-shape ``[P, CB, B]`` chunks whose striping matches the batch API's
``stripe_partitions`` exactly, so chunked and one-shot runs see identical
per-partition streams. JAX async dispatch overlaps the NumPy assembly and
host→device copy of chunk N+1 with device compute of chunk N (the
double-buffering called for by SURVEY.md §7 "host-feed bandwidth").
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterator

import numpy as np

from ..engine.loop import Batches
from .stream import stripe_chunk


def chunk_stream_arrays(
    X: np.ndarray,
    y: np.ndarray,
    partitions: int,
    per_batch: int,
    chunk_batches: int,
    start_row: int = 0,
    shuffle_seed: int | None = None,
) -> Iterator[Batches]:
    """Chunk an in-memory stream; rows are global positions + start_row."""
    n, f = X.shape
    p, b, cb = partitions, per_batch, chunk_batches
    rows_per_chunk = p * b * cb
    for s in range(0, n, rows_per_chunk):
        e = min(s + rows_per_chunk, n)
        yield stripe_chunk(X[s:e], y[s:e], s + start_row, p, b, cb, shuffle_seed)


def generator_chunks(
    chunk_fn: Callable[[int, int], tuple[np.ndarray, np.ndarray]],
    total_rows: int,
    partitions: int,
    per_batch: int,
    chunk_batches: int,
    shuffle_seed: int | None = None,
) -> Iterator[Batches]:
    """Chunks from a chunk-exact generator ``chunk_fn(start, stop) -> (X, y)``
    (e.g. ``functools.partial(sea_chunk, seed, drift_every=...)`` adapted to
    (start, stop)). Generates only one chunk of rows at a time — 1e9-row
    soaks never materialise the stream.
    """
    p, b, cb = partitions, per_batch, chunk_batches
    rows_per_chunk = p * b * cb
    for s in range(0, total_rows, rows_per_chunk):
        e = min(s + rows_per_chunk, total_rows)
        X, y = chunk_fn(s, e)
        yield stripe_chunk(X, y, s, p, b, cb, shuffle_seed)


class _Stop:
    pass


def prefetch_chunks(chunks: Iterator, depth: int = 2) -> Iterator:
    """Run a chunk iterator in a background thread, ``depth`` chunks ahead.

    JAX async dispatch already overlaps *device* compute with the caller's
    *next* host-side chunk assembly — but the assembly itself (CSV parse,
    generator math, striping) runs serially with the feed loop's Python.
    This wrapper moves it to a producer thread with a bounded queue, so host
    construction of chunk N+k proceeds while the main thread is feeding
    chunk N (the double-buffered feed of SURVEY.md §7 "host-feed
    bandwidth", generalized to depth-k).

    Exceptions in the producer propagate to the consumer. Abandoning the
    returned iterator (break / exception / GC) stops the producer thread
    promptly — its queue puts are timeout-guarded against a cancellation
    event that the consumer sets on close, so no chunks stay pinned.
    """
    q: queue.Queue = queue.Queue(maxsize=max(1, depth))
    stop = threading.Event()

    def put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def produce():
        try:
            for item in chunks:
                if not put(item):
                    return
            put(_Stop)
        except BaseException as e:  # propagate into the consumer
            put(e)

    threading.Thread(target=produce, daemon=True).start()

    def consume():
        try:
            while True:
                item = q.get()
                if item is _Stop:
                    return
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            stop.set()

    return consume()
