"""Host→device chunk feeder for the streaming engine.

Replaces the reference's one-shot driver upload (``spark.createDataFrame`` of
the entire dataset, ``DDM_Process.py:222``) with an incremental feed: a
chunk-exact generator (``io.synth``) or an in-memory stream is cut into
fixed-shape ``[P, CB, B]`` chunks whose striping matches the batch API's
``stripe_partitions`` exactly, so chunked and one-shot runs see identical
per-partition streams. JAX async dispatch overlaps the NumPy assembly and
host→device copy of chunk N+1 with device compute of chunk N (the
double-buffering called for by SURVEY.md §7 "host-feed bandwidth").
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterator

import numpy as np

from ..engine.loop import Batches
from .stream import stripe_chunk


def _ingest_counters(metrics):
    """(rows, chunks) counters for a feed path; ``(None, None)`` without a
    registry — callers guard on None so the disabled path costs nothing."""
    if metrics is None:
        return None, None
    return (
        metrics.counter(
            "ingest_rows_total", help="Stream rows striped into chunks"
        ),
        metrics.counter(
            "ingest_chunks_total", help="Fixed-shape [P,CB,B] chunks emitted"
        ),
    )


def chunk_stream_arrays(
    X: np.ndarray,
    y: np.ndarray,
    partitions: int,
    per_batch: int,
    chunk_batches: int,
    start_row: int = 0,
    shuffle_seed: int | None = None,
    feature_dtype=np.float32,
    metrics=None,
) -> Iterator[Batches]:
    """Chunk an in-memory stream; rows are global positions + start_row.

    ``feature_dtype`` is the transport dtype of the feature plane
    (``stripe_chunk``): ``ml_dtypes.bfloat16`` halves host→device bytes
    for transport-bound feeds, at the cost of bf16 feature rounding.
    ``metrics`` (a :class:`..telemetry.metrics.MetricsRegistry`) counts
    ``ingest_rows_total`` / ``ingest_chunks_total`` as the feed progresses.
    """
    n, f = X.shape
    p, b, cb = partitions, per_batch, chunk_batches
    c_rows, c_chunks = _ingest_counters(metrics)
    rows_per_chunk = p * b * cb
    for s in range(0, n, rows_per_chunk):
        e = min(s + rows_per_chunk, n)
        if c_rows is not None:
            c_rows.inc(e - s)
            c_chunks.inc()
        yield stripe_chunk(
            X[s:e], y[s:e], s + start_row, p, b, cb, shuffle_seed,
            feature_dtype=feature_dtype,
        )


def generator_chunks(
    chunk_fn: Callable[[int, int], tuple[np.ndarray, np.ndarray]],
    total_rows: int,
    partitions: int,
    per_batch: int,
    chunk_batches: int,
    shuffle_seed: int | None = None,
    feature_dtype=np.float32,
    metrics=None,
) -> Iterator[Batches]:
    """Chunks from a chunk-exact generator ``chunk_fn(start, stop) -> (X, y)``
    (e.g. ``functools.partial(sea_chunk, seed, drift_every=...)`` adapted to
    (start, stop)). Generates only one chunk of rows at a time — 1e9-row
    soaks never materialise the stream. ``metrics`` counts ingest progress
    (see :func:`chunk_stream_arrays`).
    """
    p, b, cb = partitions, per_batch, chunk_batches
    c_rows, c_chunks = _ingest_counters(metrics)
    rows_per_chunk = p * b * cb
    for s in range(0, total_rows, rows_per_chunk):
        e = min(s + rows_per_chunk, total_rows)
        X, y = chunk_fn(s, e)
        if c_rows is not None:
            c_rows.inc(e - s)
            c_chunks.inc()
        yield stripe_chunk(
            X, y, s, p, b, cb, shuffle_seed, feature_dtype=feature_dtype
        )


class _Stop:
    pass


def prefetch_chunks(chunks: Iterator, depth: int = 2, metrics=None) -> Iterator:
    """Run a chunk iterator in a background thread, ``depth`` chunks ahead.

    JAX async dispatch already overlaps *device* compute with the caller's
    *next* host-side chunk assembly — but the assembly itself (CSV parse,
    generator math, striping) runs serially with the feed loop's Python.
    This wrapper moves it to a producer thread with a bounded queue, so host
    construction of chunk N+k proceeds while the main thread is feeding
    chunk N (the double-buffered feed of SURVEY.md §7 "host-feed
    bandwidth", generalized to depth-k).

    Exceptions in the producer propagate to the consumer. Abandoning the
    returned iterator (break / exception / GC) stops the producer thread
    promptly — its queue puts are timeout-guarded against a cancellation
    event that the consumer sets on close, so no chunks stay pinned.

    ``metrics`` (a :class:`..telemetry.metrics.MetricsRegistry`) records
    ``prefetch_chunks_total`` (delivered to the consumer) and the
    ``prefetch_queue_depth`` gauge sampled at each delivery — a depth
    pinned at 0 means the consumer is feed-bound, at ``depth`` means
    device-bound (the SURVEY §7 overlap question, answerable per run).
    """
    c_total = g_depth = None
    if metrics is not None:
        c_total = metrics.counter(
            "prefetch_chunks_total", help="Chunks delivered by the prefetcher"
        )
        g_depth = metrics.gauge(
            "prefetch_queue_depth", help="Prefetch queue depth at delivery"
        )
    q: queue.Queue = queue.Queue(maxsize=max(1, depth))
    stop = threading.Event()

    def put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def produce():
        try:
            for item in chunks:
                if not put(item):
                    return
            put(_Stop)
        except BaseException as e:  # propagate into the consumer
            put(e)

    threading.Thread(target=produce, daemon=True).start()

    def consume():
        try:
            while True:
                item = q.get()
                if item is _Stop:
                    return
                if isinstance(item, BaseException):
                    raise item
                if c_total is not None:
                    c_total.inc()
                    g_depth.set(q.qsize())
                yield item
        finally:
            stop.set()

    return consume()


def csv_chunks(
    path: str,
    partitions: int,
    per_batch: int,
    chunk_batches: int,
    *,
    target_column: str = "target",
    shuffle_seed: int | None = None,
    block_bytes: int = 16 << 20,
    feature_dtype=np.float32,
    metrics=None,
) -> Iterator[Batches]:
    """Stream a CSV file from disk as striped chunks, without materialising it.

    The one-shot path (``io.stream.load_csv``) parses the whole file — right
    for the reference's scale, impossible for multi-hundred-GB streams. This
    reader consumes the file in bounded byte blocks (carrying partial lines
    across block edges), parses each with the native multithreaded parser
    (``io.native.parse_block``; NumPy fallback), and yields the same
    ``[P, CB, B]`` chunks as :func:`chunk_stream_arrays` — host memory stays
    O(block + chunk) regardless of file size. Compose with
    :func:`prefetch_chunks` to overlap the parse with device compute.

    Labels are not re-indexed — for class labels outside ``0..C-1``, remap
    before modelling (the one-shot loader's re-indexing needs a full pass,
    which a stream cannot afford by design). They parse through float32
    (exact for integers up to 2^24); larger label ids raise rather than
    silently round.

    ``metrics`` counts ``ingest_rows_total`` / ``ingest_chunks_total`` plus
    ``ingest_bytes_total`` (file bytes parsed) for the disk path.
    """
    p, b, cb = partitions, per_batch, chunk_batches
    c_rows, c_chunks = _ingest_counters(metrics)
    c_bytes = (
        metrics.counter("ingest_bytes_total", help="CSV bytes parsed")
        if metrics is not None
        else None
    )
    rows_per_chunk = p * b * cb
    from .native import parse_block

    with open(path, "rb") as fh:
        header = fh.readline().decode().strip().split(",")
        tcol = header.index(target_column)
        cols = len(header)
        mask = np.ones(cols, bool)
        mask[tcol] = False

        parts: list[np.ndarray] = []
        buffered = 0
        start_row = 0
        carry = b""

        def emit(arr_list, start, n_take):
            data = np.concatenate(arr_list) if len(arr_list) > 1 else arr_list[0]
            take, rest = data[:n_take], data[n_take:]
            labels = take[:, tcol]
            if labels.size and np.abs(labels).max() >= 2**24:
                raise ValueError(
                    "label ids at or above 2^24 are not exactly representable "
                    "on the float32 parse path; re-encode the target column"
                )
            chunk = stripe_chunk(
                take[:, mask],
                labels.astype(np.int32),
                start,
                p, b, cb,
                shuffle_seed,
                feature_dtype=feature_dtype,
            )
            if c_rows is not None:
                c_rows.inc(len(take))
                c_chunks.inc()
            return chunk, rest

        while True:
            block = fh.read(block_bytes)
            if not block:
                break
            if c_bytes is not None:
                c_bytes.inc(len(block))
            block = carry + block
            cut = block.rfind(b"\n")
            if cut < 0:
                carry = block
                continue
            carry, block = block[cut + 1:], block[: cut + 1]
            arr = parse_block(block, cols)
            parts.append(arr)
            buffered += len(arr)
            while buffered >= rows_per_chunk:
                chunk, rest = emit(parts, start_row, rows_per_chunk)
                yield chunk
                start_row += rows_per_chunk
                parts, buffered = ([rest] if len(rest) else []), len(rest)
        if carry:
            parts.append(parse_block(carry, cols))
            buffered += len(parts[-1])
        if buffered:
            chunk, _ = emit(parts, start_row, buffered)
            yield chunk
