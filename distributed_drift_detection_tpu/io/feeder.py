"""Host→device chunk feeder for the streaming engine.

Replaces the reference's one-shot driver upload (``spark.createDataFrame`` of
the entire dataset, ``DDM_Process.py:222``) with an incremental feed: a
chunk-exact generator (``io.synth``) or an in-memory stream is cut into
fixed-shape ``[P, CB, B]`` chunks whose striping matches the batch API's
``stripe_partitions`` exactly, so chunked and one-shot runs see identical
per-partition streams. JAX async dispatch overlaps the NumPy assembly and
host→device copy of chunk N+1 with device compute of chunk N (the
double-buffering called for by SURVEY.md §7 "host-feed bandwidth").
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterator

import numpy as np

from ..engine.loop import Batches
from .stream import stripe_chunk


def _ingest_counters(metrics):
    """(rows, chunks) counters for a feed path; ``(None, None)`` without a
    registry — callers guard on None so the disabled path costs nothing."""
    if metrics is None:
        return None, None
    return (
        metrics.counter(
            "ingest_rows_total", help="Stream rows striped into chunks"
        ),
        metrics.counter(
            "ingest_chunks_total", help="Fixed-shape [P,CB,B] chunks emitted"
        ),
    )


def _quarantine_counter(metrics):
    """The quarantine counter, name/help shared with api.run through
    ``io.sanitize.QUARANTINE_METRIC`` (one constant, one series)."""
    from .sanitize import QUARANTINE_METRIC, QUARANTINE_METRIC_HELP

    return metrics.counter(QUARANTINE_METRIC, help=QUARANTINE_METRIC_HELP)


def chunk_stream_arrays(
    X: np.ndarray,
    y: np.ndarray,
    partitions: int,
    per_batch: int,
    chunk_batches: int,
    start_row: int = 0,
    shuffle_seed: int | None = None,
    feature_dtype=np.float32,
    metrics=None,
    row_valid: np.ndarray | None = None,
) -> Iterator[Batches]:
    """Chunk an in-memory stream; rows are global positions + start_row.

    ``feature_dtype`` is the transport dtype of the feature plane
    (``stripe_chunk``): ``ml_dtypes.bfloat16`` halves host→device bytes
    for transport-bound feeds, at the cost of bf16 feature rounding.
    ``metrics`` (a :class:`..telemetry.metrics.MetricsRegistry`) counts
    ``ingest_rows_total`` / ``ingest_chunks_total`` as the feed progresses.
    ``row_valid`` ([n] bool — a quarantine mask from ``io.sanitize``, or
    any caller mask) is sliced per chunk and folded into each chunk's
    validity plane (``stripe_chunk``), so the chunked engine sees masked
    rows as padding exactly like the one-shot path; the mask adds
    ``ingest_quarantined_total`` to the metric set.
    """
    n, f = X.shape
    p, b, cb = partitions, per_batch, chunk_batches
    c_rows, c_chunks = _ingest_counters(metrics)
    c_quar = None
    if metrics is not None and row_valid is not None:
        c_quar = _quarantine_counter(metrics)
    rows_per_chunk = p * b * cb
    for s in range(0, n, rows_per_chunk):
        e = min(s + rows_per_chunk, n)
        rv = None if row_valid is None else row_valid[s:e]
        if c_rows is not None:
            c_rows.inc(e - s)
            c_chunks.inc()
            if c_quar is not None:
                c_quar.inc(int((~np.asarray(rv, bool)).sum()))
        yield stripe_chunk(
            X[s:e], y[s:e], s + start_row, p, b, cb, shuffle_seed,
            feature_dtype=feature_dtype, row_valid=rv,
        )


def generator_chunks(
    chunk_fn: Callable[[int, int], tuple[np.ndarray, np.ndarray]],
    total_rows: int,
    partitions: int,
    per_batch: int,
    chunk_batches: int,
    shuffle_seed: int | None = None,
    feature_dtype=np.float32,
    metrics=None,
) -> Iterator[Batches]:
    """Chunks from a chunk-exact generator ``chunk_fn(start, stop) -> (X, y)``
    (e.g. ``functools.partial(sea_chunk, seed, drift_every=...)`` adapted to
    (start, stop)). Generates only one chunk of rows at a time — 1e9-row
    soaks never materialise the stream. ``metrics`` counts ingest progress
    (see :func:`chunk_stream_arrays`).
    """
    p, b, cb = partitions, per_batch, chunk_batches
    c_rows, c_chunks = _ingest_counters(metrics)
    rows_per_chunk = p * b * cb
    for s in range(0, total_rows, rows_per_chunk):
        e = min(s + rows_per_chunk, total_rows)
        X, y = chunk_fn(s, e)
        if c_rows is not None:
            c_rows.inc(e - s)
            c_chunks.inc()
        yield stripe_chunk(
            X, y, s, p, b, cb, shuffle_seed, feature_dtype=feature_dtype
        )


class _Stop:
    pass


def prefetch_chunks(chunks: Iterator, depth: int = 2, metrics=None) -> Iterator:
    """Run a chunk iterator in a background thread, ``depth`` chunks ahead.

    JAX async dispatch already overlaps *device* compute with the caller's
    *next* host-side chunk assembly — but the assembly itself (CSV parse,
    generator math, striping) runs serially with the feed loop's Python.
    This wrapper moves it to a producer thread with a bounded queue, so host
    construction of chunk N+k proceeds while the main thread is feeding
    chunk N (the double-buffered feed of SURVEY.md §7 "host-feed
    bandwidth", generalized to depth-k).

    Exceptions in the producer propagate to the consumer. Abandoning the
    returned iterator (break / exception / GC) stops the producer thread
    promptly — its queue puts are timeout-guarded against a cancellation
    event that the consumer sets on close, so no chunks stay pinned.

    ``metrics`` (a :class:`..telemetry.metrics.MetricsRegistry`) records
    ``prefetch_chunks_total`` (delivered to the consumer) and the
    ``prefetch_queue_depth`` gauge sampled at each delivery — a depth
    pinned at 0 means the consumer is feed-bound, at ``depth`` means
    device-bound (the SURVEY §7 overlap question, answerable per run).
    """
    c_total = g_depth = None
    if metrics is not None:
        c_total = metrics.counter(
            "prefetch_chunks_total", help="Chunks delivered by the prefetcher"
        )
        g_depth = metrics.gauge(
            "prefetch_queue_depth", help="Prefetch queue depth at delivery"
        )
    q: queue.Queue = queue.Queue(maxsize=max(1, depth))
    stop = threading.Event()

    def put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def produce():
        try:
            for item in chunks:
                if not put(item):
                    return
            put(_Stop)
        except BaseException as e:  # propagate into the consumer
            put(e)

    threading.Thread(target=produce, daemon=True).start()

    def consume():
        try:
            while True:
                item = q.get()
                if item is _Stop:
                    return
                if isinstance(item, BaseException):
                    raise item
                if c_total is not None:
                    c_total.inc()
                    g_depth.set(q.qsize())
                yield item
        finally:
            stop.set()

    return consume()


#: Stage names of the host-ingest pipeline, in data-flow order. ``read``
#: (mmap block materialisation + page faults) and ``parse`` (native/tolerant
#: CSV → f32 matrix + contract scan) run in the worker pool; ``sanitize``
#: (policy application, sidecar writes, running repair stats) and ``stripe``
#: (span assembly → [P, CB, B] grid) run sequentially in the consumer —
#: determinism lives there; ``upload`` is accounted by the chunk engine
#: (``ChunkedDetector.run``) around its place/feed dispatches.
PIPELINE_STAGES = ("read", "parse", "sanitize", "stripe", "upload")

STAGE_BUSY_METRIC = "ingest_stage_busy_seconds_total"
STAGE_BUSY_HELP = (
    "Cumulative busy seconds per host-ingest pipeline stage (parallel "
    "stages sum across workers, so read/parse can exceed wall-clock)"
)


class StageClock:
    """Per-stage busy-seconds accounting for the ingest pipeline.

    Accumulates locally (``.busy`` — bench reads it directly) and, when a
    metrics registry is given, mirrors into the
    ``ingest_stage_busy_seconds_total{stage=...}`` counter. Single-writer:
    workers *return* their timings and the sequential consumer folds them
    in, so the registry never sees concurrent writes.
    """

    def __init__(self, metrics=None):
        self.busy: dict[str, float] = {}
        self._c = (
            metrics.counter(STAGE_BUSY_METRIC, help=STAGE_BUSY_HELP)
            if metrics is not None
            else None
        )

    def add(self, stage: str, seconds: float) -> None:
        if seconds < 0:  # clock skew paranoia; counters reject negatives
            return
        self.busy[stage] = self.busy.get(stage, 0.0) + seconds
        if self._c is not None:
            self._c.inc(seconds, stage=stage)


def stage_breakdown(metrics, ndigits: int = 4) -> dict[str, float]:
    """The per-stage busy-seconds map a registry accumulated
    (``STAGE_BUSY_METRIC`` samples → ``{stage: seconds}``) — the ONE
    extraction bench.py's chunked rider and the ``chunked`` CLI share, so
    the artifact's ``pipeline_s`` and the CLI summary cannot drift."""
    c = metrics.counter(STAGE_BUSY_METRIC)
    return {
        dict(key)["stage"]: round(v, ndigits)
        for key, v in sorted(c.values.items())
    }


def resolve_ingest_workers(workers: int | None) -> int:
    """0/None = auto: one parse worker per core up to 4 — past that the
    native parser saturates host memory bandwidth and extra threads only
    steal cycles from the stripe/feed stages (measured; bench.py's
    --ingest-workers sweeps it). Explicit values pass through (min 1)."""
    if workers is None or int(workers) <= 0:
        import os

        return max(1, min(4, os.cpu_count() or 1))
    return int(workers)


def csv_chunks(
    path: str,
    partitions: int,
    per_batch: int,
    chunk_batches: int,
    *,
    target_column: str = "target",
    shuffle_seed: int | None = None,
    block_bytes: int = 16 << 20,
    feature_dtype=np.float32,
    metrics=None,
    data_policy: str | None = None,
    quarantine_path: str | None = None,
    workers: int = 1,
    num_classes: int | None = None,
    tracer=None,
) -> Iterator[Batches]:
    """Stream a CSV file from disk as striped chunks, without materialising it.

    The one-shot path (``io.stream.load_csv``) parses the whole file — right
    for the reference's scale, impossible for multi-hundred-GB streams. This
    reader consumes the file as line-aligned byte blocks over an ``mmap``
    (``io.blocks.line_block_ranges`` — ONE boundary rule for every worker
    count), parses each with the native multithreaded parser
    (``io.native.parse_block``; NumPy fallback), and yields the same
    ``[P, CB, B]`` chunks as :func:`chunk_stream_arrays` — host memory stays
    O(workers · block + chunk) regardless of file size. Compose with
    :func:`prefetch_chunks` to overlap the whole assembly with device
    compute.

    ``workers`` (0 = auto, :func:`resolve_ingest_workers`) is the parse
    fan-out: blocks are submitted to a thread pool in file order and the
    results consumed **in submission order**, so any worker count yields
    bit-identical chunks, flags, and sidecar contents to ``workers=1``
    (pinned by test + the CI ``ingest-smoke`` job). The pipeline stages:
    read+parse+scan run per block in the pool (the native parser releases
    the GIL, so the fan-out is real parallelism); policy application
    (ordered sidecar writes, running repair statistics) and striping
    (:class:`~.stream.ChunkStriper`, pooled staging buffers) stay
    sequential in the consumer — determinism lives there; in-flight depth
    is bounded at ``workers + 2`` blocks.

    Labels are not re-indexed — for class labels outside ``0..C-1``, remap
    before modelling (the one-shot loader's re-indexing needs a full pass,
    which a stream cannot afford by design). They parse through float32
    (exact for integers up to 2^24); larger label ids raise rather than
    silently round.

    ``metrics`` counts ``ingest_rows_total`` / ``ingest_chunks_total`` /
    ``ingest_bytes_total`` plus the pipeline gauges:
    ``ingest_stage_busy_seconds_total{stage=read|parse|sanitize|stripe}``
    (busy seconds; parallel stages sum across workers),
    ``ingest_parse_queue_depth`` (parsed-but-unconsumed blocks, sampled
    per consumed block — pinned at 0 means the pool is starving the
    consumer, near ``workers + 2`` means parse outruns the
    sanitize/stripe stages), and ``ingest_workers``.

    ``data_policy`` (None = trusting parse, the exact historical
    behaviour) applies the stream contract per block (``io.sanitize``):
    ``'strict'`` raises a structured ``StreamContractError`` naming
    file/row/column on the first violation (in row order, any worker
    count); ``'quarantine'`` masks violating rows into each chunk's
    validity plane (padding-identical inside jit), appends them to the
    ``quarantine_path`` sidecar, and counts ``ingest_quarantined_total``;
    ``'repair'`` imputes non-finite feature cells from **running** column
    means over the rows admitted so far (``io.sanitize.RunningColumnStats``
    / ``repair_rows`` — the serve-admission semantics), quarantining what
    it cannot fix. Streaming repair deliberately differs from the one-shot
    loader's repair, which imputes from *whole-file* means: a single-pass
    stream only has its past, so early blocks impute from less evidence
    (before any, the canonical 0.0 fill) — same rows repaired, possibly
    different imputed values; use ``io.sanitize.load_csv_sane`` when
    whole-file means matter.

    ``num_classes`` is repair's label-domain guard (serve admission's
    clause): the one-shot loader can round a non-integral label and
    re-index afterwards, but a stream never re-indexes — so a label that
    repair would round **out of the engine's ``0..C-1`` index domain**
    must be quarantined, never admitted. Pass the model's class count
    (the ``chunked`` CLI's ``--classes`` does) to allow in-domain
    rounding; with the default ``None`` the domain is unknown and
    non-integral labels are conservatively quarantined rather than
    rounded (the only repair semantics that can never hand the engine a
    fabricated out-of-range class index). Other policies never consult
    it — labels are not re-indexed or domain-checked on the trusting/
    strict/quarantine paths, exactly as before.

    ``tracer`` (a :class:`..telemetry.tracing.ChunkTracer`) emits one
    ``ingest`` span per head-sampled chunk — the host-assembly wall
    (read/parse/sanitize/stripe) that produced it, the ingest twin of
    the engine's ``kernel`` span (share one tracer instance and the two
    stages of one chunk share a trace). Falsy tracers cost one check
    per chunk.
    """
    workers = resolve_ingest_workers(workers)
    if data_policy is not None:
        from . import sanitize as _s

        _s.check_policy(data_policy)
    return _csv_chunk_pipeline(
        path, partitions, per_batch, chunk_batches, target_column,
        shuffle_seed, block_bytes, feature_dtype, metrics, data_policy,
        quarantine_path, workers, num_classes, tracer,
    )


def _csv_chunk_pipeline(
    path, partitions, per_batch, chunk_batches, target_column, shuffle_seed,
    block_bytes, feature_dtype, metrics, data_policy, quarantine_path, workers,
    num_classes, tracer=None,
) -> Iterator[Batches]:
    """Generator body of :func:`csv_chunks` (split out so argument
    validation happens at call time, not first ``next()``)."""
    import time

    from .blocks import line_block_ranges, open_mapped
    from .native import parse_block
    from .stream import ChunkStriper

    p, b, cb = partitions, per_batch, chunk_batches
    c_rows, c_chunks = _ingest_counters(metrics)
    c_bytes = g_depth = None
    if metrics is not None:
        c_bytes = metrics.counter("ingest_bytes_total", help="CSV bytes parsed")
        g_depth = metrics.gauge(
            "ingest_parse_queue_depth",
            help="Parsed-but-unconsumed blocks at each consumed block "
            "(0 = parse-bound, near workers+2 = consumer-bound)",
        )
        metrics.gauge(
            "ingest_workers", help="Configured ingest parse workers"
        ).set(workers)
    clock = StageClock(metrics)
    c_quar = None
    sanitize = None
    writer = None
    run_stats = None
    if data_policy is not None:
        from . import sanitize

        if data_policy in ("quarantine", "repair"):
            # repair quarantines what it cannot fix, like the whole-file
            # path — both policies own a sidecar.
            writer = sanitize.QuarantineWriter(
                quarantine_path or (path + ".quarantine.jsonl"), data_policy
            )
            if metrics is not None:
                c_quar = _quarantine_counter(metrics)
    rows_per_chunk = p * b * cb

    fh, buf, data_start = open_mapped(path)
    ex = None
    try:
        header = bytes(buf[:data_start]).decode().strip().split(",")
        if sanitize is not None:
            tcol = sanitize.validate_header(header, target_column, path)
        elif target_column not in header:
            raise ValueError(
                f"{path}: target column {target_column!r} not in header; "
                f"columns found: {header}"
            )
        else:
            tcol = header.index(target_column)
        cols = len(header)
        mask = np.ones(cols, bool)
        mask[tcol] = False
        if data_policy == "repair":
            run_stats = sanitize.RunningColumnStats(cols)
        ranges = line_block_ranges(buf, data_start, block_bytes)

        def parse_job(lo: int, hi: int):
            """Worker-side stage: materialise + parse + contract-scan one
            block. Pure w.r.t. pipeline state — safe at any fan-out; all
            ordering-sensitive work stays in the consumer below."""
            t0 = time.perf_counter()
            block = buf[lo:hi]  # the read stage: copy-out + page faults
            t1 = time.perf_counter()
            if sanitize is None:
                arr, issues = parse_block(block, cols), []
            else:
                try:
                    arr, issues = parse_block(block, cols), []
                except ValueError:
                    lines = block.decode(errors="replace").splitlines()
                    arr, issues = sanitize.parse_rows(lines, cols)
                issues = issues + sanitize.scan_matrix(
                    arr, tcol, header,
                    flagged=frozenset(i.row for i in issues),
                )
            return arr, issues, (t1 - t0, time.perf_counter() - t1), hi - lo

        def results():
            """Ordered fan-out: results arrive in submission order no
            matter which worker finishes first."""
            if workers <= 1:
                for lo, hi in ranges:
                    yield parse_job(lo, hi)
                return
            from collections import deque
            from concurrent.futures import ThreadPoolExecutor

            nonlocal ex
            ex = ThreadPoolExecutor(
                max_workers=workers, thread_name_prefix="ddd-ingest"
            )
            depth = workers + 2  # bounded in-flight blocks
            inflight: deque = deque()
            nxt = 0
            while nxt < len(ranges) and len(inflight) < depth:
                inflight.append(ex.submit(parse_job, *ranges[nxt]))
                nxt += 1
            while inflight:
                fut = inflight.popleft()
                if nxt < len(ranges):
                    inflight.append(ex.submit(parse_job, *ranges[nxt]))
                    nxt += 1
                if g_depth is not None:
                    # READY backlog, not occupancy (occupancy is pinned at
                    # the bound by construction): parsed-but-unconsumed
                    # blocks — 0 = the pool is starving the consumer
                    # (parse-bound), near the bound = parse outruns the
                    # sanitize/stripe stages.
                    g_depth.set(sum(f.done() for f in inflight))
                yield fut.result()

        rows_parsed = 0  # absolute data-row index for sidecar records
        rows_valid = 0  # contract-passing rows seen (all-dirty guard)
        striper = ChunkStriper(p, b, cb, shuffle_seed, feature_dtype)
        parts: list[np.ndarray] = []
        ok_parts: list["np.ndarray | None"] = []
        buffered = 0
        start_row = 0
        chunk_idx = 0  # emitted chunks (the tracer's span key)
        t_chunk_mono = time.monotonic()  # assembly start of the next chunk

        def emit(start, n_take):
            data = np.concatenate(parts) if len(parts) > 1 else parts[0]
            take, rest = data[:n_take], data[n_take:]
            ok = None
            ok_rest = None
            if any(o is not None for o in ok_parts):
                ok_all = np.concatenate(
                    [
                        np.ones(len(a), bool) if o is None else o
                        for a, o in zip(parts, ok_parts)
                    ]
                )
                ok, ok_rest = ok_all[:n_take], ok_all[n_take:]
                if ok.all():
                    ok = None
                if ok_rest is not None and not len(ok_rest):
                    ok_rest = None
            labels = take[:, tcol]
            valid_labels = labels if ok is None else labels[ok]
            if valid_labels.size and np.abs(valid_labels).max() >= 2**24:
                raise ValueError(
                    "label ids at or above 2^24 are not exactly representable "
                    "on the float32 parse path; re-encode the target column"
                )
            chunk = striper.stripe(
                take[:, mask], labels.astype(np.int32), start, row_valid=ok
            )
            if c_rows is not None:
                c_rows.inc(len(take))
                c_chunks.inc()
            return chunk, rest, ok_rest

        for arr, issues, (read_s, parse_s), nbytes in results():
            clock.add("read", read_s)
            clock.add("parse", parse_s)
            if c_bytes is not None:
                c_bytes.inc(nbytes)
            t0 = time.perf_counter()
            ok = None
            if sanitize is not None:
                base = rows_parsed
                if data_policy == "repair" and issues:
                    # Streaming repair: impute from the running means over
                    # rows admitted in PRIOR blocks (serve-admission
                    # semantics — the whole-file loader uses full-column
                    # means instead; see the csv_chunks docstring). The
                    # label-domain guard runs first: rounding must never
                    # fabricate a class index outside 0..num_classes-1
                    # (or any rounded label at all when the domain is
                    # unknown) on a path that never re-indexes.
                    issues = sanitize.demote_unroundable_labels(
                        issues, arr, tcol, num_classes
                    )
                    arr, issues, _ = sanitize.repair_rows(
                        arr, issues, tcol, run_stats
                    )
                arr, ok = sanitize.apply_block_policy(
                    arr, issues, path=path, policy=data_policy,
                    base_row=base, writer=writer, header=header,
                )
                if run_stats is not None and len(arr):
                    run_stats.update(arr, ok)
                rows_parsed += len(arr)
                if ok is None:
                    rows_valid += len(arr)
                else:
                    rows_valid += int(ok.sum())
                    if c_quar is not None:
                        c_quar.inc(int((~ok).sum()))
            else:
                rows_parsed += len(arr)
            clock.add("sanitize", time.perf_counter() - t0)
            parts.append(arr)
            ok_parts.append(ok)
            buffered += len(arr)
            while buffered >= rows_per_chunk:
                t0 = time.perf_counter()
                chunk, rest, ok_rest = emit(start_row, rows_per_chunk)
                clock.add("stripe", time.perf_counter() - t0)
                if tracer:
                    tracer.span(
                        "ingest", chunk_idx, t_chunk_mono, time.monotonic(),
                        rows=rows_per_chunk,
                    )
                yield chunk
                chunk_idx += 1
                t_chunk_mono = time.monotonic()
                start_row += rows_per_chunk
                parts = [rest] if len(rest) else []
                ok_parts = [ok_rest] if len(rest) else []
                buffered = len(rest)
        if buffered:
            t0 = time.perf_counter()
            chunk, _, _ = emit(start_row, buffered)
            clock.add("stripe", time.perf_counter() - t0)
            if tracer:
                tracer.span(
                    "ingest", chunk_idx, t_chunk_mono, time.monotonic(),
                    rows=buffered,
                )
            yield chunk
        # Degenerate-stream guard, matching the whole-file path
        # (apply_policy raises the same on a fully-dirty file): a
        # run that quarantined EVERY row must not read as success.
        if sanitize is not None and rows_parsed and not rows_valid:
            raise sanitize.StreamContractError(
                path,
                reason=(
                    f"all {rows_parsed} data rows violate the stream "
                    "contract; nothing left to quarantine around"
                ),
                total=rows_parsed,
            )
    finally:
        if ex is not None:
            # Drop queued blocks, wait out the (block-bounded) running
            # ones — workers must not touch the mmap after it closes.
            ex.shutdown(wait=True, cancel_futures=True)
        if writer is not None:
            writer.close()
        close = getattr(buf, "close", None)
        if close is not None:
            close()
        fh.close()
