"""Ingest-time stream-contract validation — the data plane's robustness layer.

The reference assumes a pristine numeric CSV (``DDM_Process.py:33-35``): a
non-numeric cell crashes the load, a ragged row silently falls back to a
different parser, and a single NaN feature poisons the DDM error statistics
for the rest of the stream (f32 NaN propagates through ``ops/ddm.py`` so
the detector never — or always — fires). At the ROADMAP's serving scale,
malformed rows are the *dominant* failure mode, and they are not transient:
retrying a poisoned stream (PR 4's resilience layer) burns the retry budget
and still yields garbage. This module gives the data plane the same
closed-loop treatment the process plane already has — detect bad rows,
quarantine them, keep the detector's statistics exactly what they would
have been on the clean stream.

The **stream contract** (what ``doctor`` and the loaders enforce):

* header: named columns, unique, containing the target column;
* every data row has exactly ``len(header)`` comma-separated fields;
* every cell parses as a finite float;
* the target column holds integral labels exact in f32 (``|y| < 2^24``).

Three **policies** decide what a violation does
(``RunConfig(data_policy=...)`` / ``--data-policy``):

=============  ==========================================================
``strict``     raise a structured :class:`StreamContractError` naming
               file / row / column / reason (the default: fail loudly,
               never compute on garbage)
``quarantine`` drop the row — append it with its reason to a
               ``quarantine.jsonl`` sidecar and carry it *positionally*
               as a masked row, so downstream striping folds it into the
               existing ``[P, NB, B]`` validity plane and inside jit it
               is indistinguishable from padding (static shapes, no
               recompiles, bit-identical flags to the clean stream with
               those rows masked — the headline acceptance)
``repair``     impute finite column means for NaN feature cells and
               clamp (round) non-integral labels; rows that cannot be
               repaired (ragged, non-finite label) are quarantined
=============  ==========================================================

Pure numpy + stdlib — **no jax** — so the ``doctor`` CLI and the
quarantine sidecar reader run wherever the data lands (the same
jax-free contract as ``telemetry.report`` / ``resilience.heal`` plan
mode). The ``stream.load`` fault site (``resilience.faults``) injects
deterministic corruption (``nan_cell`` / ``bad_label`` / ``ragged_row``)
through the same loader, so this path is exercised by seeded injection,
not by hoping for dirty data.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import NamedTuple

import numpy as np

from ..resilience import faults

#: Valid ``RunConfig.data_policy`` values (mirrored in ``config.py`` for
#: jax-free CLI validation).
POLICIES = ("strict", "quarantine", "repair")

SIDECAR_VERSION = 1

#: The one name/help of the quarantine counter — registered by the
#: feeder (per-chunk masking) and api.run (per-run total); a single
#: constant so the metric can never fork into two series over a typo.
QUARANTINE_METRIC = "ingest_quarantined_total"
QUARANTINE_METRIC_HELP = "Stream rows masked out by the quarantine policy"


class StreamContractError(ValueError):
    """A stream violated the ingest contract under ``data_policy='strict'``.

    Structured: ``file`` / ``row`` (0-based data-row index, header
    excluded) / ``column`` (0-based index or None for row-level issues)
    / ``reason`` ride as attributes; the message names all of them plus
    the total violation count, so the first log line is the diagnosis.
    """

    def __init__(
        self,
        file: str,
        row: "int | None" = None,
        column: "int | None" = None,
        reason: str = "stream contract violated",
        column_name: "str | None" = None,
        total: int = 1,
    ):
        self.file = file
        self.row = row
        self.column = column
        self.column_name = column_name
        self.reason = reason
        self.total = total
        where = file
        if row is not None:
            where += f", data row {row}"
        if column is not None:
            col = f"column {column}"
            if column_name is not None:
                col += f" ({column_name!r})"
            where += f", {col}"
        more = f" (+{total - 1} more violation(s))" if total > 1 else ""
        super().__init__(f"{where}: {reason}{more}")


class RowIssue(NamedTuple):
    """One contract violation, pinned to a data row (0-based, header
    excluded) and optionally a column. ``repairable`` marks issues the
    ``repair`` policy can fix in place (NaN feature cell, non-integral
    label); ragged rows and non-finite labels are not."""

    row: int
    column: "int | None"
    reason: str
    repairable: bool = False


class QuarantineReport(NamedTuple):
    """What sanitizing one stream did — carried on ``StreamData`` and
    surfaced as the ``rows_quarantined`` telemetry event +
    ``ingest_quarantined_total`` counter."""

    policy: str
    rows_quarantined: int
    rows_repaired: int
    sidecar: "str | None"
    issues: tuple  # tuple[RowIssue, ...] (first _MAX_REPORT, for messages)


_MAX_REPORT = 32  # issues carried on the report (the sidecar has them all)


def check_policy(policy: str) -> str:
    if policy not in POLICIES:
        raise ValueError(
            f"unknown data_policy {policy!r}; expected one of {POLICIES}"
        )
    return policy


def validate_header(
    header: list[str], target_column: str, path: str
) -> int:
    """Validate the header row; returns the target column index.

    Header problems are never row-quarantinable — without a trustworthy
    header nothing downstream can be aligned — so they raise
    :class:`StreamContractError` under every policy.
    """
    names = [h.strip() for h in header]
    if any(not n for n in names):
        raise StreamContractError(
            path, reason=f"header has empty column name(s): {names}"
        )
    dupes = sorted({n for n in names if names.count(n) > 1})
    if dupes:
        raise StreamContractError(
            path, reason=f"header has duplicate column name(s): {dupes}"
        )
    if target_column not in names:
        raise StreamContractError(
            path,
            reason=(
                f"target column {target_column!r} not in header; "
                f"columns found: {names}"
            ),
        )
    return names.index(target_column)


def parse_rows(
    lines: list[str], num_columns: int
) -> tuple[np.ndarray, list[RowIssue]]:
    """Tolerant CSV parse: ``[n, num_columns]`` f32 + issues.

    The dirty-path complement of the fast parsers (``io.native`` /
    ``np.loadtxt``), which reject the whole file on one bad cell: here a
    ragged row becomes a row-level issue (its cells are NaN), a
    non-numeric cell becomes a cell-level issue (that cell is NaN), and
    everything parseable parses. Blank lines are skipped (matching
    ``np.loadtxt``).

    Three vectorized tiers, coarsest first, so the per-cell Python loop
    runs only over rows that actually contain a dirty cell: (1) every
    rectangular row's fields convert in ONE ``np.asarray`` call — the
    overwhelmingly common shape of a dirty *block* (a handful of bad rows
    in thousands of clean ones) when only raggedness broke the fast path;
    (2) on failure, per-row array conversion; (3) per-cell ``float`` for
    the rows tier 2 refused. All tiers parse text → float64 → f32 (the
    same correctly-rounded double parse, so a cell's value is identical
    whichever tier lands it). Serve admission batches each recv-block
    through here (``serve.admission``), so the ingress daemon rides the
    same vectorization.
    """
    rows = [ln for ln in lines if ln.strip()]
    out = np.zeros((len(rows), num_columns), np.float32)
    issues: list[RowIssue] = []
    split = [line.split(",") for line in rows]
    rect: list[int] = []  # rows with the right field count
    for r, fields in enumerate(split):
        if len(fields) != num_columns:
            issues.append(
                RowIssue(
                    r,
                    None,
                    f"ragged row: {len(fields)} field(s), expected "
                    f"{num_columns}",
                )
            )
            out[r] = np.nan
        else:
            rect.append(r)

    def _cells(r: int) -> None:
        for c, tok in enumerate(split[r]):
            try:
                out[r, c] = float(tok)
            except ValueError:
                # Cell-level: the cell is NaN after this, so the repair
                # policy can impute it (unless it is the label column —
                # apply_policy demotes unrepairable label cells there).
                issues.append(
                    RowIssue(
                        r, c, f"non-numeric cell {tok.strip()!r}",
                        repairable=True,
                    )
                )
                out[r, c] = np.nan

    if rect:
        flat = [tok for r in rect for tok in split[r]]
        try:
            out[rect] = np.asarray(flat, np.float64).reshape(
                len(rect), num_columns
            )
        except ValueError:
            for r in rect:
                try:
                    out[r] = np.asarray(split[r], np.float64)
                except ValueError:
                    _cells(r)
    issues.sort(key=lambda i: (i.row, -1 if i.column is None else i.column))
    return out, issues


def scan_matrix(
    raw: np.ndarray,
    tcol: int,
    header: "list[str] | None" = None,
    flagged: frozenset = frozenset(),
) -> list[RowIssue]:
    """Contract-scan a parsed ``[n, cols]`` matrix: non-finite feature
    cells (repairable), non-finite labels, non-integral labels
    (repairable), labels beyond f32 integer exactness. Rows already in
    ``flagged`` (text-level issues) are skipped — one issue per cause.
    """
    issues: list[RowIssue] = []
    n, cols = raw.shape
    finite = np.isfinite(raw)
    y = raw[:, tcol]
    y_ok = finite[:, tcol]
    bad_feat = ~finite
    bad_feat[:, tcol] = False
    for r in np.nonzero(bad_feat.any(axis=1))[0]:
        if int(r) in flagged:
            continue
        c = int(np.nonzero(bad_feat[r])[0][0])
        issues.append(
            RowIssue(int(r), c, "non-finite feature value", repairable=True)
        )
    for r in np.nonzero(~y_ok)[0]:
        if int(r) in flagged:
            continue
        issues.append(RowIssue(int(r), tcol, "non-finite label"))
    with np.errstate(invalid="ignore"):
        nonint = y_ok & (y != np.round(y))
        toobig = y_ok & (np.abs(y) >= 2.0**24)
    for r in np.nonzero(nonint)[0]:
        if int(r) in flagged:
            continue
        issues.append(
            RowIssue(
                int(r), tcol, f"non-integral label {float(y[r])!r}",
                repairable=True,
            )
        )
    for r in np.nonzero(toobig)[0]:
        if int(r) in flagged:
            continue
        issues.append(
            RowIssue(
                int(r),
                tcol,
                "label at or above 2^24 is not exactly representable in "
                "f32; re-encode the target column",
            )
        )
    issues.sort(key=lambda i: (i.row, -1 if i.column is None else i.column))
    return issues


def scan_csv(
    path: str, target_column: str = "target", *, jobs: int = 1
) -> tuple[list[RowIssue], int]:
    """Full jax-free contract scan of a CSV: ``(issues, data_rows)``.

    The ``doctor`` CLI's engine — header validation raises, row/cell
    violations are returned. Always uses the tolerant parser (this is a
    diagnostic pass, not the hot ingest path).

    ``jobs > 1`` splits the data region into that many line-aligned byte
    ranges (the SAME splitter the parallel ingest pipeline uses —
    ``io.blocks.line_block_ranges``) and scans them in a thread pool;
    block results are rebased to absolute data-row indices and folded in
    block order, so the returned issue list — and hence the doctor CLI's
    printed violation order — is identical to the serial scan's (pinned
    by test).
    """
    jobs = max(1, int(jobs))
    with open(path) as fh:
        header = fh.readline().rstrip("\n").rstrip("\r").split(",")
        tcol = validate_header(header, target_column, path)
        if jobs == 1:
            lines = fh.read().splitlines()

    def scan_lines(block_lines: list[str]) -> tuple[int, list[RowIssue]]:
        raw, found = parse_rows(block_lines, len(header))
        found = found + scan_matrix(
            raw, tcol, header, flagged=frozenset(i.row for i in found)
        )
        return len(raw), found

    if jobs == 1:
        scanned = [scan_lines(lines)]
    else:
        from concurrent.futures import ThreadPoolExecutor

        from .blocks import line_block_ranges, open_mapped

        fh, buf, data_start = open_mapped(path)
        try:
            span = len(buf) - data_start
            block_bytes = max(1, -(-span // jobs))
            ranges = line_block_ranges(buf, data_start, block_bytes)
            with ThreadPoolExecutor(max_workers=jobs) as ex:
                scanned = list(
                    ex.map(
                        lambda r: scan_lines(
                            buf[r[0] : r[1]].decode().splitlines()
                        ),
                        ranges,
                    )
                )
        finally:
            close = getattr(buf, "close", None)
            if close is not None:
                close()
            fh.close()

    issues: list[RowIssue] = []
    total = 0
    for n_rows, found in scanned:  # block order == file order
        issues.extend(i._replace(row=total + i.row) for i in found)
        total += n_rows
    issues.sort(key=lambda i: (i.row, -1 if i.column is None else i.column))
    return issues, total


def mask_rows(
    X: np.ndarray, y: np.ndarray, row_ok: np.ndarray
) -> tuple[np.ndarray, np.ndarray]:
    """Canonicalize masked rows: features to 0.0, labels to the smallest
    valid label — deterministic fill, **the single normalization both
    the quarantine path and any clean-stream-with-rows-masked comparison
    share** (``io.stream.synthesize_stream`` applies it), so the two are
    bit-identical by construction. The label fill keeps masked rows at a
    stable position under the sort-by-target; their content never
    reaches compute (validity weight 0, and the striper re-zeros them to
    the padding fill on device)."""
    row_ok = np.asarray(row_ok, bool)
    if not row_ok.any():
        raise ValueError(
            "every row is masked/quarantined; no valid rows remain"
        )
    X = np.where(row_ok[:, None], X, X.dtype.type(0))
    y = np.where(row_ok, y, y[row_ok].min())
    return X, y


class QuarantineWriter:
    """Append-only ``quarantine.jsonl`` sidecar: one JSON line per
    quarantined row (``v``, ``file``, ``row``, ``column``,
    ``column_name``, ``reason``, ``policy``), opened lazily — a clean
    load leaves no artifact — and flushed per line, mirroring the
    telemetry sink's crash contract (a torn trailing line is tolerated
    by :func:`read_quarantine`, never a torn interior)."""

    def __init__(self, path: str, policy: str):
        self.path = path
        self.policy = policy
        self.rows = 0
        self._fh = None

    def append(
        self, file: str, issue: RowIssue, header: "list[str] | None" = None
    ) -> None:
        if self._fh is None:
            parent = os.path.dirname(self.path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            self._fh = open(self.path, "a")
        name = (
            header[issue.column]
            if header is not None and issue.column is not None
            else None
        )
        self._fh.write(
            json.dumps(
                {
                    "v": SIDECAR_VERSION,
                    "file": file,
                    "row": issue.row,
                    "column": issue.column,
                    "column_name": name,
                    "reason": issue.reason,
                    "policy": self.policy,
                }
            )
            + "\n"
        )
        self._fh.flush()
        self.rows += 1

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None


def read_quarantine(
    path: str, *, allow_partial_tail: bool = False
) -> list[dict]:
    """Parse a quarantine sidecar; ``allow_partial_tail=True`` tolerates
    exactly one torn **trailing** line — the same crash/live-tail
    contract as ``telemetry.events.read_events`` (the sidecar is flushed
    per line, so a crash mid-append can tear only the last one)."""
    records = []
    with open(path) as fh:
        lines = fh.readlines()
    for lineno, line in enumerate(lines, 1):
        stripped = line.strip()
        if not stripped:
            continue
        try:
            rec = json.loads(stripped)
        except json.JSONDecodeError as e:
            if allow_partial_tail and lineno == len(lines):
                break
            raise ValueError(f"{path}:{lineno}: not JSON ({e})") from None
        if not isinstance(rec, dict) or "row" not in rec:
            raise ValueError(
                f"{path}:{lineno}: not a quarantine record: {stripped[:80]}"
            )
        records.append(rec)
    return records


class SanitizedCSV(NamedTuple):
    """``load_csv_sane``'s result: features/labels plus the row-validity
    mask (``None`` = every row clean) and the quarantine report."""

    X: np.ndarray  # [N, F] f32; quarantined rows canonicalized (mask_rows)
    y: np.ndarray  # [N] i64
    row_ok: "np.ndarray | None"  # [N] bool, None = all valid
    report: "QuarantineReport | None"


def apply_policy(
    raw: np.ndarray,
    issues: list[RowIssue],
    tcol: int,
    *,
    path: str,
    policy: str,
    quarantine_path: "str | None" = None,
    header: "list[str] | None" = None,
) -> tuple[np.ndarray, "np.ndarray | None", "QuarantineReport | None"]:
    """Resolve contract issues per policy on a parsed ``[n, cols]``
    matrix. Returns ``(matrix, row_ok | None, report | None)`` — the
    matrix is repaired in ``repair`` mode; quarantined rows are left for
    the caller to canonicalize via :func:`mask_rows`."""
    check_policy(policy)
    if not issues:
        return raw, None, None
    if policy == "strict":
        first = issues[0]
        raise StreamContractError(
            path,
            row=first.row,
            column=first.column,
            column_name=(
                header[first.column]
                if header is not None and first.column is not None
                else None
            ),
            reason=first.reason,
            total=len(issues),
        )

    repaired_rows: set[int] = set()
    drop: list[RowIssue] = []
    if policy == "repair":
        # A "repairable" issue on the *label* column is only fixable when
        # the parsed value is still finite (non-integral → round); a
        # non-numeric/NaN label has nothing to clamp — quarantine the row.
        with np.errstate(invalid="ignore"):
            label_finite = np.isfinite(raw[:, tcol])
        bad_rows = {
            i.row
            for i in issues
            if not i.repairable
            or (i.column == tcol and not label_finite[i.row])
        }
        fixable = [i for i in issues if i.repairable and i.row not in bad_rows]
        drop = [i for i in issues if i.row in bad_rows]
        if fixable:
            ok = np.ones(len(raw), bool)
            ok[sorted(bad_rows)] = False
            feat_finite = np.isfinite(raw) & ok[:, None]
            label_rows = {i.row for i in fixable if i.column == tcol}
            feat_rows = {i.row for i in fixable if i.column != tcol}
            for r in sorted(label_rows):
                raw[r, tcol] = np.round(raw[r, tcol])
            for r in sorted(feat_rows):
                # Impute EVERY non-finite feature cell of the row, not
                # just the first one scan_matrix reported — a row with
                # two NaN cells must leave repair fully finite, or the
                # survivor poisons the f32 detector statistics (the
                # exact failure this module exists to prevent).
                for c in np.nonzero(~np.isfinite(raw[r]))[0]:
                    if c == tcol:
                        continue
                    col = raw[feat_finite[:, c], c]
                    raw[r, c] = col.mean() if col.size else 0.0
            repaired_rows = label_rows | feat_rows
    else:  # quarantine
        drop = issues

    row_ok = None
    writer = None
    dropped_rows: list[int] = []
    if drop:
        row_ok = np.ones(len(raw), bool)
        seen: set[int] = set()
        if quarantine_path:
            writer = QuarantineWriter(quarantine_path, policy)
        try:
            for i in drop:
                row_ok[i.row] = False
                if writer is not None and i.row not in seen:
                    writer.append(path, i, header)
                seen.add(i.row)
        finally:
            if writer is not None:
                writer.close()
        dropped_rows = sorted(seen)
        if not row_ok.any():
            raise StreamContractError(
                path,
                reason=(
                    f"all {len(raw)} data rows violate the stream "
                    "contract; nothing left to quarantine around"
                ),
                total=len(issues),
            )
    report = QuarantineReport(
        policy=policy,
        rows_quarantined=len(dropped_rows),
        rows_repaired=len(repaired_rows),
        sidecar=writer.path if writer is not None else None,
        issues=tuple(issues[:_MAX_REPORT]),
    )
    return raw, row_ok, report


def apply_block_policy(
    arr: np.ndarray,
    issues: list[RowIssue],
    *,
    path: str,
    policy: str,
    base_row: int = 0,
    writer: "QuarantineWriter | None" = None,
    header: "list[str] | None" = None,
) -> tuple[np.ndarray, "np.ndarray | None"]:
    """Streaming (per-block) policy application — the single home of the
    strict-raise and quarantine-write semantics for block readers
    (``io.feeder.csv_chunks``), so they cannot drift from the whole-file
    :func:`apply_policy`. Issues carry block-local row indices;
    ``base_row`` rebases them to absolute data-row indices for the error
    and the sidecar. Returns ``(arr, ok | None)`` with quarantined rows
    zeroed to the padding fill. Under ``policy='repair'`` the caller runs
    :func:`repair_rows` first (streaming running-mean imputation — the
    feeder and serve admission both do) and hands the *remaining*
    unrepairable issues here, which fall through to the quarantine
    branch below exactly like the whole-file repair's drop list.
    """
    if not issues:
        return arr, None
    issues = sorted(
        issues, key=lambda i: (i.row, -1 if i.column is None else i.column)
    )
    if policy == "strict":
        first = issues[0]
        raise StreamContractError(
            path,
            row=base_row + first.row,
            column=first.column,
            column_name=(
                header[first.column]
                if header is not None and first.column is not None
                else None
            ),
            reason=first.reason,
            total=len(issues),
        )
    ok = np.ones(len(arr), bool)
    seen: set[int] = set()
    for i in issues:
        ok[i.row] = False
        if writer is not None and i.row not in seen:
            writer.append(path, i._replace(row=base_row + i.row), header)
        seen.add(i.row)
    # Padding-canonical fill (the stripe re-checks, but no NaN should
    # survive past the parser either way).
    arr = np.where(ok[:, None], arr, np.float32(0))
    return arr, ok


class RunningColumnStats:
    """Running finite-cell column means — the streaming complement of the
    whole-file ``repair`` policy's full-column statistics.

    ``apply_policy``'s repair imputes each NaN feature cell with the mean
    of its column's finite cells, which needs the whole file up front —
    exactly what a long-lived ingest daemon cannot have. This accumulator
    gives the serve admission path (``serve.admission``) the same repair
    semantics over the *rows admitted so far*: per-column running
    sum/count of finite cells, updated block by block, queried for the
    imputation means. Before any evidence a column's mean is 0.0 — the
    same canonical fill masked rows carry, so an imputed cell can never
    introduce a value the clean pipeline could not."""

    def __init__(self, num_columns: int):
        self._sum = np.zeros(num_columns, np.float64)
        self._count = np.zeros(num_columns, np.int64)

    def update(self, arr: np.ndarray, row_ok: "np.ndarray | None" = None) -> None:
        """Fold a block's finite cells in (rows with ``row_ok == False``
        are excluded — quarantined content must not steer the means)."""
        finite = np.isfinite(arr)
        if row_ok is not None:
            finite = finite & np.asarray(row_ok, bool)[:, None]
        self._sum += np.where(finite, arr, 0.0).sum(axis=0, dtype=np.float64)
        self._count += finite.sum(axis=0)

    def means(self) -> np.ndarray:
        """Per-column finite means (f32); 0.0 where no evidence yet."""
        return (self._sum / np.maximum(self._count, 1)).astype(np.float32)


def demote_unroundable_labels(
    issues: list[RowIssue],
    arr: np.ndarray,
    tcol: int,
    num_classes: "int | None",
) -> list[RowIssue]:
    """Label-domain guard for **streaming** repair (the serve-admission
    clause, ``serve.admission``): flip a label-column repairable issue
    (non-integral finite label) to unrepairable when rounding it could
    leave the engine's ``0..C-1`` index domain — checked on the ROUNDED
    value, exactly what repair would store. With ``num_classes`` None the
    domain is unknowable, so every such label demotes: the one-shot
    loader re-indexes labels after repair, a single-pass stream never
    does, and a fabricated out-of-range class index must never reach the
    engine. Feature-cell issues pass through untouched."""
    with np.errstate(invalid="ignore"):
        y_r = np.round(arr[:, tcol])
    out = []
    for i in issues:
        if i.repairable and i.column == tcol:
            in_domain = (
                num_classes is not None
                and np.isfinite(y_r[i.row])
                and 0 <= y_r[i.row] < num_classes
            )
            if not in_domain:
                out.append(i._replace(repairable=False))
                continue
        out.append(i)
    return out


def repair_rows(
    arr: np.ndarray,
    issues: list[RowIssue],
    tcol: int,
    stats: RunningColumnStats,
) -> tuple[np.ndarray, list[RowIssue], int]:
    """Streaming (per-block) repair: the running-stats twin of
    ``apply_policy``'s whole-file repair branch.

    Repairable issues are fixed in place — non-integral finite labels are
    rounded, non-finite feature cells imputed from ``stats`` (the means
    over rows admitted *so far*, not the whole stream — the documented
    semantic difference from the one-shot loader's repair) — and every
    non-finite feature cell of a fixable row is imputed, not just the
    reported one (same all-cells rule as ``apply_policy``). Rows that
    cannot be repaired (ragged, non-finite label) come back as the
    remaining issues for the caller to quarantine via
    :func:`apply_block_policy`. Returns ``(arr, remaining, repaired_rows)``.
    """
    if not issues:
        return arr, [], 0
    with np.errstate(invalid="ignore"):
        label_finite = np.isfinite(arr[:, tcol])
    bad_rows = {
        i.row
        for i in issues
        if not i.repairable or (i.column == tcol and not label_finite[i.row])
    }
    fixable = sorted(
        {i.row for i in issues if i.repairable and i.row not in bad_rows}
    )
    means = stats.means() if fixable else None
    for r in fixable:
        if label_finite[r] and arr[r, tcol] != np.round(arr[r, tcol]):
            arr[r, tcol] = np.round(arr[r, tcol])
        for c in np.nonzero(~np.isfinite(arr[r]))[0]:
            if c != tcol:
                arr[r, c] = means[c]
    remaining = [i for i in issues if i.row in bad_rows]
    return arr, remaining, len(fixable)


def _fast_parse(path: str, header: list[str]) -> "np.ndarray | None":
    """The clean-stream fast path: native multithreaded parser, NumPy
    fallback; ``None`` when the data is malformed (caller falls to the
    tolerant parser). A native/NumPy column-count disagreement with the
    header raises via ``io.stream.load_csv``'s satellite contract — here
    it simply reads as malformed and the tolerant path diagnoses it."""
    from .native import load_csv_native

    raw = load_csv_native(path)
    if raw is not None and raw.shape[1] == len(header):
        return raw
    try:
        arr = np.loadtxt(
            path, delimiter=",", skiprows=1, dtype=np.float32, ndmin=2
        )
    except ValueError:
        return None
    return arr if arr.shape[1] == len(header) else None


def load_csv_sane(
    path: str,
    target_column: str = "target",
    *,
    policy: str = "strict",
    quarantine_path: "str | None" = None,
) -> SanitizedCSV:
    """Load a CSV under the stream contract (the policy-aware twin of
    ``io.stream.load_csv``).

    Clean files ride the fast parsers and pay one finite/label scan; the
    tolerant row parser runs only when the fast path refuses the data.
    The ``stream.load`` fault site fires here (``resilience.faults`` —
    no-op unless armed): corruption kinds mutate the raw text lines
    before parsing, so injected dirt flows through exactly the machinery
    real dirt would.
    """
    check_policy(policy)
    with open(path) as fh:
        header = fh.readline().rstrip("\n").rstrip("\r").split(",")
    tcol = validate_header(header, target_column, path)

    raw = None
    issues: list[RowIssue] = []
    if faults.armed("stream.load") is not None:
        with open(path) as fh:
            fh.readline()
            lines = fh.read().splitlines()
        faults.fire("stream.load", lines=lines, label_col=tcol, path=path)
        raw, issues = parse_rows(lines, len(header))
    else:
        raw = _fast_parse(path, header)
        if raw is None:
            with open(path) as fh:
                fh.readline()
                lines = fh.read().splitlines()
            raw, issues = parse_rows(lines, len(header))
    issues = issues + scan_matrix(
        raw, tcol, header, flagged=frozenset(i.row for i in issues)
    )
    issues.sort(key=lambda i: (i.row, -1 if i.column is None else i.column))

    raw, row_ok, report = apply_policy(
        raw,
        issues,
        tcol,
        path=path,
        policy=policy,
        quarantine_path=quarantine_path,
        header=header,
    )
    fmask = np.ones(len(header), bool)
    fmask[tcol] = False
    X = raw[:, fmask]
    yf = raw[:, tcol]
    if row_ok is not None:
        X, yf = mask_rows(X, yf, row_ok)
    return SanitizedCSV(X, yf.astype(np.int64), row_ok, report)


def main(argv=None) -> None:
    """``doctor``: jax-free stream-contract check of CSV inputs.

    Exit 0 = every file satisfies the contract; 1 = violations found
    (each printed as ``file, data row R, column C (name): reason``);
    2 = usage / unreadable input. The scriptable pre-flight for sweeps:
    run it over the dataset before burning accelerator time.
    """
    ap = argparse.ArgumentParser(
        prog="python -m distributed_drift_detection_tpu doctor",
        description=(
            "Validate CSV stream inputs against the ingest contract "
            "(numeric cells, finite values, rectangular rows, label "
            "domain) without touching jax. Exit 0 = clean, 1 = dirty."
        ),
    )
    ap.add_argument("csv", nargs="+", help="CSV path(s) to validate")
    ap.add_argument(
        "--target-column",
        default="target",
        help="label column name (default: target)",
    )
    ap.add_argument(
        "--max-report",
        type=int,
        default=20,
        help="violations printed per file (the count is always exact)",
    )
    ap.add_argument(
        "--jobs",
        type=int,
        default=1,
        help="parallel scan blocks per file (line-aligned byte ranges, "
        "the ingest pipeline's splitter); violation output ordering is "
        "identical to the serial scan (default: 1)",
    )
    args = ap.parse_args(argv)

    dirty = False
    for path in args.csv:
        if path.startswith("synth:"):
            print(f"{path}: synthetic spec, nothing to validate")
            continue
        try:
            issues, n = scan_csv(path, args.target_column, jobs=args.jobs)
        except StreamContractError as e:
            print(f"{path}: {e}")
            dirty = True
            continue
        except OSError as e:
            # exit 2 = environment error, distinct from 1 = dirty data
            # (the docstring's contract a gating script branches on)
            print(f"doctor: cannot read {path}: {e}", file=sys.stderr)
            raise SystemExit(2)
        if not issues:
            print(f"{path}: OK ({n} data rows)")
            continue
        dirty = True
        bad_rows = len({i.row for i in issues})
        print(
            f"{path}: {len(issues)} violation(s) across {bad_rows} of "
            f"{n} data rows"
        )
        for i in issues[: args.max_report]:
            col = "" if i.column is None else f", column {i.column}"
            print(f"  data row {i.row}{col}: {i.reason}")
        if len(issues) > args.max_report:
            print(f"  ... {len(issues) - args.max_report} more")
    raise SystemExit(1 if dirty else 0)


if __name__ == "__main__":
    main(sys.argv[1:])
