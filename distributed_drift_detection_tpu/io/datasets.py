"""Benchmark-dataset helpers (reference C16).

The reference names two benchmarks: ``outdoorStream.csv`` (committed, 4,000
rows × 21 features × 40 classes) and ``rialto.csv`` — referenced throughout
(``DDM_Process.py:33`` sets 27 features for it; ``Plot Results.ipynb``
cell 2 switches datasets) but absent from its repo as a large blob
(``.MISSING_LARGE_BLOBS``). Both are expected as numeric CSVs whose header
names the feature columns ``"0".."N-1"`` plus a ``"target"`` column
(``DDM_Process.py:33-35``); :func:`..io.stream.load_csv` consumes exactly
that schema, so a real ``rialto.csv`` runs unchanged via
``RunConfig(dataset="/path/to/rialto.csv")``.

The real dataset is the **Rialto Bridge Timelapse** stream (Losing, Hammer &
Wersing 2016, "KNN Classifier with Self Adjusting Memory for Heterogeneous
Concept Drift", ICDM): 82,250 rows × 27 colour-histogram features × 10
classes (buildings around Venice's Rialto bridge photographed across 20
days). Its canonical public mirror — the authors' ``driftDatasets``
repository (github.com/vlosing/driftDatasets, ``realWorld/rialto/``) —
ships it as a *pair* of whitespace-separated files (``rialto.data``
features, ``rialto.labels`` integer labels), not as the single CSV the
reference expects. :func:`convert_data_labels_to_csv` performs that
conversion; see the README "The rialto dataset" section for the end-to-end
recipe and for what the committed ``synth:rialto`` stand-in does and does
not reproduce.
"""

from __future__ import annotations

import numpy as np


def convert_data_labels_to_csv(
    data_path: str, labels_path: str, out_csv: str
) -> tuple[int, int]:
    """``(X.data, y.labels)`` pair → the reference's single-CSV schema.

    Writes ``out_csv`` with header ``0,1,…,F-1,target`` (the exact schema
    ``DDM_Process.py:33-35`` declares and ``io.stream.load_csv`` parses).
    Features are written with full float precision; labels as integers.
    Returns ``(rows, features)``.
    """
    # ndmin pins the rank: without it a one-row file of F features loads as
    # shape (F,) and would be misread as F single-feature rows.
    X = np.loadtxt(data_path, dtype=np.float64, ndmin=2)
    y = np.loadtxt(labels_path, dtype=np.int64, ndmin=1)
    if len(X) != len(y):
        raise ValueError(
            f"{data_path} has {len(X)} rows but {labels_path} has {len(y)}"
        )
    return _write_schema_csv(X, y, out_csv)


def _write_schema_csv(X, y, out_csv: str) -> tuple[int, int]:
    """Write ``(X, y)`` in the reference's CSV schema (header
    ``0..F-1,target``, full-precision floats, integer labels)."""
    n, f = X.shape
    header = ",".join([*map(str, range(f)), "target"])
    with open(out_csv, "w") as fh:
        fh.write(header + "\n")
        for i in range(n):
            fh.write(
                ",".join(repr(float(v)) for v in X[i]) + f",{int(y[i])}\n"
            )
    return n, f


def rialto_fixture_csv(
    out_csv: str, rows_per_class: int = 20, seed: int = 0
) -> tuple[int, int]:
    """A tiny CSV in the real rialto schema (header ``0..26,target``, 10
    classes) for loader tests — geometry-faithful, content synthetic."""
    from .synth import rialto_like_xy

    X, y = rialto_like_xy(seed=seed, rows_per_class=rows_per_class)
    return _write_schema_csv(X, y, out_csv)
