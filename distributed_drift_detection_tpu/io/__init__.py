"""Data-plane package: loaders, synthesis, striping, feeding, sanitizing.

Exports resolve **lazily** (PEP 562): ``io.sanitize`` is jax-free by
contract (the ``doctor`` CLI and the quarantine-sidecar reader must run
wherever the data lands), but ``io.stream``/``io.feeder`` import the
engine types and hence jax — an eager ``__init__`` would drag jax into
every ``from .io.sanitize import ...``. Attribute access is unchanged
for callers; only the import cost moved.
"""

_EXPORTS = {
    # datasets
    "convert_data_labels_to_csv": ".datasets",
    "rialto_fixture_csv": ".datasets",
    # blocks (jax-free)
    "line_block_ranges": ".blocks",
    # feeder
    "chunk_stream_arrays": ".feeder",
    "csv_chunks": ".feeder",
    "generator_chunks": ".feeder",
    "prefetch_chunks": ".feeder",
    "resolve_ingest_workers": ".feeder",
    # sanitize (jax-free)
    "QuarantineReport": ".sanitize",
    "StreamContractError": ".sanitize",
    "load_csv_sane": ".sanitize",
    "read_quarantine": ".sanitize",
    "scan_csv": ".sanitize",
    # stream
    "ChunkStriper": ".stream",
    "StreamData": ".stream",
    "load_csv": ".stream",
    "load_stream": ".stream",
    "materialize_batches": ".stream",
    "stripe_partitions": ".stream",
    "stripe_partitions_indexed": ".stream",
    "stripe_partitions_packed": ".stream",
    "synthesize_stream": ".stream",
    # synth
    "as_stream": ".synth",
    "hyperplane_chunk": ".synth",
    "hyperplane_stream": ".synth",
    "planted_prototypes": ".synth",
    "sea_chunk": ".synth",
    "sea_stream": ".synth",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name):
    if name in _EXPORTS:
        import importlib

        module = importlib.import_module(_EXPORTS[name], __name__)
        value = getattr(module, name)
        globals()[name] = value  # cache: next access skips __getattr__
        return value
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(__all__))
