from .datasets import convert_data_labels_to_csv, rialto_fixture_csv
from .feeder import (
    chunk_stream_arrays,
    csv_chunks,
    generator_chunks,
    prefetch_chunks,
)
from .stream import (
    StreamData,
    load_csv,
    load_stream,
    materialize_batches,
    stripe_partitions,
    stripe_partitions_indexed,
    stripe_partitions_packed,
    synthesize_stream,
)
from .synth import (
    as_stream,
    hyperplane_chunk,
    hyperplane_stream,
    planted_prototypes,
    sea_chunk,
    sea_stream,
)

__all__ = [
    "chunk_stream_arrays",
    "convert_data_labels_to_csv",
    "rialto_fixture_csv",
    "csv_chunks",
    "generator_chunks",
    "prefetch_chunks",
    "StreamData",
    "load_csv",
    "load_stream",
    "materialize_batches",
    "stripe_partitions",
    "stripe_partitions_indexed",
    "stripe_partitions_packed",
    "synthesize_stream",
    "as_stream",
    "hyperplane_chunk",
    "hyperplane_stream",
    "planted_prototypes",
    "sea_chunk",
    "sea_stream",
]
