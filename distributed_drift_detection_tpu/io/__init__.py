from .stream import StreamData, load_csv, load_stream, stripe_partitions, synthesize_stream

__all__ = [
    "StreamData",
    "load_csv",
    "load_stream",
    "stripe_partitions",
    "synthesize_stream",
]
