"""Stream loading, synthesis and partitioning (reference C2 + C8 data path).

``load_stream`` + ``synthesize_stream`` reproduce the reference's stream
construction (``DDM_Process.py:38-55``): load a CSV of numeric features plus a
``target`` column; scale volume by ``mult_data`` (fraction-sample when < 1,
duplicate ×N + shuffle otherwise); sort by ``target`` so each class label is
one planted "concept"; derive ``dist_between_changes = rows // classes``.

Deliberate deviations (SURVEY.md quirk register):

* Shuffles are seeded (the reference's ``sample(frac=1)`` at ``:49`` is not).
* Feature count is inferred from the file (quirk #5 — ``NUMBER_OF_FEATURES``).
* Global row ids are **positions in the sorted stream** (0..N-1). The
  reference stamps ``full_df_row_number = df.index`` *after* sorting
  (``:220``), i.e. pre-sort CSV row ids — an artifact that makes its delay
  metric (``changes % dist_between_changes``, ``:253-256``) meaningless for
  ``mult_data > 1``. Positional ids keep the metric exact at every scale
  while matching it exactly at ``mult_data = 1`` (where the CSV is already
  target-sorted).

``stripe_partitions`` reproduces the reference's placement (C8, ``:225-226``):
row *i* of the stream goes to partition ``i % P`` — every partition sees a
1/P-thinned copy of the same stream with the same concept boundaries — then
pads each partition to a rectangular ``[P, NB, B]`` microbatch grid with a
validity plane (TPU arrays are rectangular; the reference's last ragged batch
becomes masked padding).
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

from ..engine.loop import Batches


class StreamData(NamedTuple):
    """A prepared drift stream (host-side, numpy)."""

    X: np.ndarray  # [N, F] f32
    y: np.ndarray  # [N] i32, labels re-indexed to 0..C-1
    num_classes: int
    dist_between_changes: int  # rows // classes (C2, :55)

    @property
    def num_rows(self) -> int:
        return len(self.y)

    @property
    def num_features(self) -> int:
        return self.X.shape[1]


def load_csv(path: str, target_column: str = "target") -> tuple[np.ndarray, np.ndarray]:
    """Load a numeric CSV with a named target column.

    Uses the native multithreaded C++ parser (``io.native``) when available
    — parsing-bound ingest at memory speed — with a NumPy fallback.
    """
    with open(path) as fh:
        header = fh.readline().strip().split(",")
    tcol = header.index(target_column)

    from .native import load_csv_native

    raw = load_csv_native(path)
    if raw is None or raw.shape[1] != len(header):
        raw = np.loadtxt(path, delimiter=",", skiprows=1, dtype=np.float32)
    mask = np.ones(len(header), bool)
    mask[tcol] = False
    return raw[:, mask], raw[:, tcol].astype(np.int64)


def synthesize_stream(
    X: np.ndarray,
    y: np.ndarray,
    mult_data: float = 1.0,
    seed: int = 0,
    standardize: bool = True,
) -> StreamData:
    """Volume-scale, shuffle, sort-by-target — the C2 semantics, seeded."""
    rng = np.random.default_rng(seed)
    n = len(y)
    if mult_data < 1.0:
        take = rng.permutation(n)[: max(1, int(round(n * mult_data)))]
        X, y = X[take], y[take]
    else:
        reps = int(mult_data)
        idx = rng.permutation(n * reps) % n
        X, y = X[idx], y[idx]

    order = np.argsort(y, kind="stable")  # :51, stable like pandas sort_values
    X, y = X[order], y[order]

    classes, y_idx = np.unique(y, return_inverse=True)
    if standardize:
        mu = X.mean(axis=0)
        sd = X.std(axis=0)
        X = (X - mu) / np.where(sd > 0, sd, 1.0)

    return StreamData(
        X=np.ascontiguousarray(X, np.float32),
        y=y_idx.astype(np.int32),
        num_classes=len(classes),
        dist_between_changes=len(y) // len(classes),
    )


def load_stream(
    path: str, mult_data: float = 1.0, seed: int = 0, standardize: bool = True
) -> StreamData:
    X, y = load_csv(path)
    return synthesize_stream(X, y, mult_data, seed, standardize)


def stripe_chunk(
    X: np.ndarray,
    y: np.ndarray,
    start_row: int,
    partitions: int,
    per_batch: int,
    nb: int,
    shuffle_seed: int | None = None,
) -> Batches:
    """Pad + row-stripe one contiguous span of the stream into ``[P, NB, B]``.

    Row ``start_row + i`` goes to partition ``(start_row + i) % P`` at the
    next slot (C8 ``:225`` placement); ``start_row`` must be a multiple of
    P·B so striping is chunking-invariant. The single implementation shared
    by the one-shot path (:func:`stripe_partitions`) and the chunk feeder
    (``io.feeder``) — their bit-exact agreement is a correctness contract
    (see ``tests/test_chunked.py``).

    ``shuffle_seed`` applies the reference's per-microbatch shuffle
    (``batch.sample(frac=1)``, ``DDM_Process.py:187,190``) **on the host at
    stripe time** instead of inside the compiled loop: each batch is visited
    exactly once, so a pre-shuffle is semantically identical to the engine's
    in-jit shuffle while costing zero device time. Chunking-invariant
    (counter-based PRNG keyed on the absolute batch slot).
    """
    n = len(y)
    p, b = partitions, per_batch
    padded = p * nb * b
    assert shuffle_seed is None or start_row % (p * b) == 0, (
        "stripe-time shuffle needs start_row aligned to partitions*per_batch "
        "(all regular chunk boundaries are); pass shuffle_seed=None otherwise"
    )

    def pad(arr, fill):
        out = np.full((padded, *arr.shape[1:]), fill, arr.dtype)
        out[:n] = arr
        return out

    rows = start_row + np.arange(padded, dtype=np.int64)
    valid = np.arange(padded) < n

    if shuffle_seed is None:
        def stripe(arr):
            # padded position i → partition i % P, slot i // P  (C8 :225)
            return np.ascontiguousarray(
                arr.reshape(nb * b, p, *arr.shape[1:]).swapaxes(0, 1)
            ).reshape(p, nb, b, *arr.shape[1:])
    else:
        # Per-batch permutation keyed on the absolute batch slot (slot-major
        # id ``abs_slot * P + partition`` is contiguous within a chunk),
        # composed with the stripe into one gather: striped[p, s, j] =
        # padded[(s*B + j)*P + p], so the shuffled element is
        # padded[(s*B + perm[p, s, j])*P + p].
        from ..utils.prng import row_uniforms

        start_slot = start_row // (p * b)
        u = row_uniforms(shuffle_seed, start_slot * p, nb * p, b, stream_id=3)
        perms = np.argsort(u.reshape(nb, p, b), axis=-1).swapaxes(0, 1)
        slot = np.arange(nb, dtype=np.int64)[None, :, None]
        part = np.arange(p, dtype=np.int64)[:, None, None]
        gather = (slot * b + perms) * p + part  # [P, NB, B]

        def stripe(arr):
            return arr[gather]

    return Batches(
        X=stripe(pad(np.asarray(X, np.float32), 0.0)),
        y=stripe(pad(np.asarray(y, np.int32), 0)),
        rows=stripe(rows.astype(np.int32)),
        valid=stripe(valid),
    )


def stripe_partitions(
    stream: StreamData,
    partitions: int,
    per_batch: int,
    shuffle_seed: int | None = None,
) -> Batches:
    """Row-stripe the whole stream over P partitions (one-shot path).

    Returns :class:`Batches` with leading partition axis: ``X [P, NB, B, F]``,
    ``y/rows/valid [P, NB, B]``. ``rows`` holds global stream positions so the
    delay metric (global position % concept length) works per the reference's
    intent. ``shuffle_seed``: see :func:`stripe_chunk`.
    """
    n = stream.num_rows
    per_part = -(-n // partitions)  # ceil: partition sizes differ by ≤ 1 (C8)
    nb = -(-per_part // per_batch)
    return stripe_chunk(
        stream.X, stream.y, 0, partitions, per_batch, nb, shuffle_seed
    )
