"""Stream loading, synthesis and partitioning (reference C2 + C8 data path).

``load_stream`` + ``synthesize_stream`` reproduce the reference's stream
construction (``DDM_Process.py:38-55``): load a CSV of numeric features plus a
``target`` column; scale volume by ``mult_data`` (fraction-sample when < 1,
duplicate ×N + shuffle otherwise); sort by ``target`` so each class label is
one planted "concept"; derive ``dist_between_changes = rows // classes``.

Deliberate deviations (SURVEY.md quirk register):

* Shuffles are seeded (the reference's ``sample(frac=1)`` at ``:49`` is not).
* Feature count is inferred from the file (quirk #5 — ``NUMBER_OF_FEATURES``).
* Global row ids are **positions in the sorted stream** (0..N-1). The
  reference stamps ``full_df_row_number = df.index`` *after* sorting
  (``:220``), i.e. pre-sort CSV row ids — an artifact that makes its delay
  metric (``changes % dist_between_changes``, ``:253-256``) meaningless for
  ``mult_data > 1``. Positional ids keep the metric exact at every scale
  while matching it exactly at ``mult_data = 1`` (where the CSV is already
  target-sorted).

``stripe_partitions`` reproduces the reference's placement (C8, ``:225-226``):
row *i* of the stream goes to partition ``i % P`` — every partition sees a
1/P-thinned copy of the same stream with the same concept boundaries — then
pads each partition to a rectangular ``[P, NB, B]`` microbatch grid with a
validity plane (TPU arrays are rectangular; the reference's last ragged batch
becomes masked padding).
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

from ..engine.loop import Batches, IndexedBatches, PackedIndexedBatches


class StreamData:
    """A prepared drift stream (host-side, numpy).

    When the stream was synthesized by integer duplication of a row table
    (``mult_data >= 1``), only the compressed form is stored:
    ``base_X``/``base_y`` (the table) plus ``src`` (stream position → table
    row), with ``X == base_X[src]``, ``y == base_y[src]``. The striper uses
    it to build :class:`IndexedBatches` so only the table + index planes
    cross the host→device link (see ``engine.loop.IndexedBatches``); the
    dense ``X``/``y`` views materialize **lazily on first access** — the
    compressed execution path never pays the multi-GB expansion the
    reference performs eagerly (``DDM_Process.py:44-49``).
    """

    def __init__(
        self,
        X: np.ndarray | None = None,  # [N, F] f32
        y: np.ndarray | None = None,  # [N] i32, labels re-indexed to 0..C-1
        num_classes: int = 0,
        dist_between_changes: int = 0,  # rows // classes (C2, :55)
        base_X: np.ndarray | None = None,  # [T, F] f32 deduplicated row table
        base_y: np.ndarray | None = None,  # [T] i32
        src: np.ndarray | None = None,  # [N] i32: stream position → table row
        row_ok: np.ndarray | None = None,  # [N] bool; None = every row valid
        base_ok: np.ndarray | None = None,  # [T] bool table-row validity
        quarantine=None,  # io.sanitize.QuarantineReport | None
    ):
        assert (X is not None and y is not None) or src is not None
        self._X = X
        self._y = y
        self.num_classes = num_classes
        self.dist_between_changes = dist_between_changes
        self.base_X = base_X
        self.base_y = base_y
        self.src = src
        # Quarantine mask (io.sanitize): False = the row violated the
        # stream contract and is carried *positionally* — zero content,
        # masked at stripe time so inside jit it is indistinguishable
        # from padding. Compressed streams store the table-row mask and
        # expand lazily, like X/y.
        self._row_ok = row_ok
        self.base_ok = base_ok
        self.quarantine = quarantine

    @property
    def X(self) -> np.ndarray:
        if self._X is None:
            self._X = self.base_X[self.src]
        return self._X

    @property
    def y(self) -> np.ndarray:
        if self._y is None:
            self._y = self.base_y[self.src]
        return self._y

    @property
    def row_ok(self) -> np.ndarray | None:
        if self._row_ok is None and self.base_ok is not None:
            self._row_ok = self.base_ok[self.src]
        return self._row_ok

    @property
    def has_masked_rows(self) -> bool:
        """True when any row is quarantine-masked (checked without
        materializing the per-position mask of a compressed stream)."""
        return self._row_ok is not None or self.base_ok is not None

    @property
    def num_rows(self) -> int:
        return len(self.src) if self.src is not None else len(self._y)

    @property
    def num_features(self) -> int:
        return (self.base_X if self.base_X is not None else self._X).shape[1]


def load_csv(path: str, target_column: str = "target") -> tuple[np.ndarray, np.ndarray]:
    """Load a numeric CSV with a named target column.

    Uses the native multithreaded C++ parser (``io.native``) when available
    — parsing-bound ingest at memory speed — with a NumPy fallback. A
    native-vs-header column-count disagreement is *traced* (a warning
    naming the path and both counts) before the NumPy re-parse, and if the
    NumPy parse disagrees with the header too the load fails loudly with
    both counts — never a silent shape mismatch flowing downstream. For
    the policy-aware loader (quarantine/repair of dirty rows) see
    ``io.sanitize.load_csv_sane``.
    """
    with open(path) as fh:
        header = fh.readline().strip().split(",")
    if target_column not in header:
        raise ValueError(
            f"{path}: target column {target_column!r} not in header; "
            f"columns found: {header}"
        )
    tcol = header.index(target_column)

    from .native import load_csv_native

    raw = load_csv_native(path)
    if raw is not None and raw.shape[1] != len(header):
        import warnings

        warnings.warn(
            f"{path}: native parser returned {raw.shape[1]} column(s) but "
            f"the header names {len(header)}; re-parsing with NumPy",
            stacklevel=2,
        )
        raw = None
    if raw is None:
        raw = np.loadtxt(
            path, delimiter=",", skiprows=1, dtype=np.float32, ndmin=2
        )
        if raw.shape[1] != len(header):
            raise ValueError(
                f"{path}: data rows have {raw.shape[1]} column(s) but the "
                f"header names {len(header)} ({header}); both parsers "
                "disagree with the header — fix the file or the header"
            )
    mask = np.ones(len(header), bool)
    mask[tcol] = False
    return raw[:, mask], raw[:, tcol].astype(np.int64)


def synthesize_stream(
    X: np.ndarray,
    y: np.ndarray,
    mult_data: float = 1.0,
    seed: int = 0,
    standardize: bool = True,
    row_ok: np.ndarray | None = None,
) -> StreamData:
    """Volume-scale, shuffle, sort-by-target — the C2 semantics, seeded.

    ``mult_data >= 1`` composes the duplicate/shuffle/sort as **index
    operations** over the untouched row table: the stream is
    ``base_X[src]`` for a [N] index vector ``src``, and both forms are
    returned (compressed striping path). Standardization statistics are
    computed on the table — the duplicated stream is ``reps`` exact copies
    of it, so the moments are identical. ``mult_data < 1`` subsamples rows
    (and possibly classes), so it materializes directly.

    ``row_ok`` (the quarantine mask from ``io.sanitize`` — or any
    caller-built mask) marks rows excluded from the stream's *statistics*
    but carried positionally: masked rows are canonicalized first
    (``sanitize.mask_rows`` — zero features, smallest-valid-label fill,
    so a dirty quarantined stream and a clean stream with the same rows
    masked become byte-identical inputs here), excluded from the
    standardization moments and the class set, and flow through the
    duplicate/shuffle/sort like every other row — the stripers fold the
    mask into the ``[P, NB, B]`` validity plane so inside jit they read
    as padding. ``dist_between_changes`` keeps counting positions
    (masked included): concept boundaries are positional facts of the
    sorted stream, exactly as the reference's ``rows // classes``.
    """
    rng = np.random.default_rng(seed)
    n = len(y)

    if row_ok is not None:
        row_ok = np.asarray(row_ok, bool)
        if row_ok.shape != (n,):
            raise ValueError(
                f"row_ok shape {row_ok.shape} does not match {n} stream rows"
            )
        if row_ok.all():
            row_ok = None
        else:
            from .sanitize import mask_rows

            X, y = mask_rows(X, y, row_ok)

    def _standardize(A, ok=None):
        A = np.ascontiguousarray(A, np.float32)
        if not standardize:
            return A
        sel = A if ok is None else A[ok]
        mu = sel.mean(axis=0)
        sd = sel.std(axis=0)
        # Zero-variance (or non-finite — a fully masked pathological
        # column) moments must not NaN the whole stream: constant
        # columns standardize to 0, not 0/0.
        sd = np.where((sd > 0) & np.isfinite(sd), sd, np.float32(1.0))
        mu = np.where(np.isfinite(mu), mu, np.float32(0.0))
        out = (A - mu) / sd
        if ok is not None:
            out[~ok] = 0.0  # masked rows keep the canonical zero fill
        return out

    if mult_data < 1.0:
        take = rng.permutation(n)[: max(1, int(round(n * mult_data)))]
        X, y = X[take], y[take]
        ok = row_ok[take] if row_ok is not None else None
        order = np.argsort(y, kind="stable")  # :51, stable like pandas
        X, y = X[order], y[order]
        if ok is not None:
            ok = ok[order]
            if ok.all():
                ok = None
            elif not ok.any():
                raise ValueError(
                    "subsampling left no valid (unmasked) rows in the stream"
                )
        classes, y_idx = np.unique(y, return_inverse=True)
        return StreamData(
            X=_standardize(X, ok),
            y=y_idx.astype(np.int32),
            num_classes=len(classes),
            dist_between_changes=len(y) // len(classes),
            row_ok=ok,
        )

    reps = int(mult_data)
    sel = rng.permutation(n * reps) % n  # each table row exactly `reps` times
    order = np.argsort(y[sel], kind="stable")  # :51
    src = sel[order].astype(np.int32)
    classes, y_base = np.unique(y, return_inverse=True)
    return StreamData(
        num_classes=len(classes),
        dist_between_changes=len(src) // len(classes),
        base_X=_standardize(X, row_ok),
        base_y=y_base.astype(np.int32),
        src=src,
        base_ok=row_ok,
    )


def load_stream(
    path: str,
    mult_data: float = 1.0,
    seed: int = 0,
    standardize: bool = True,
    data_policy: str | None = None,
    quarantine_path: str | None = None,
) -> StreamData:
    """Dataset → prepared stream. ``path`` is a CSV file, or a ``synth:``
    spec (e.g. ``synth:rialto,seed=1`` — see ``io.synth.parse_synth``) for
    the generators standing in for the reference's missing large blobs
    (SURVEY.md C16: ``rialto.csv``).

    ``data_policy`` (None = legacy trusting load) routes CSV ingest
    through the sanitizing loader (``io.sanitize.load_csv_sane``):
    ``'strict'`` raises a structured ``StreamContractError`` on any
    contract violation, ``'quarantine'`` drops violating rows into the
    ``quarantine_path`` sidecar and masks them positionally,
    ``'repair'`` imputes what it can and quarantines the rest. Synthetic
    specs generate by construction and skip validation."""
    row_ok = None
    report = None
    if path.startswith("synth:"):
        from .synth import parse_synth

        X, y = parse_synth(path[len("synth:") :])
    elif data_policy is not None:
        from .sanitize import load_csv_sane

        X, y, row_ok, report = load_csv_sane(
            path, policy=data_policy, quarantine_path=quarantine_path
        )
    else:
        X, y = load_csv(path)
    stream = synthesize_stream(
        X, y, mult_data, seed, standardize, row_ok=row_ok
    )
    stream.quarantine = report
    return stream


def stripe_chunk(
    X: np.ndarray,
    y: np.ndarray,
    start_row: int,
    partitions: int,
    per_batch: int,
    nb: int,
    shuffle_seed: int | None = None,
    feature_dtype=np.float32,
    row_valid: np.ndarray | None = None,
) -> Batches:
    """Pad + row-stripe one contiguous span of the stream into ``[P, NB, B]``.

    Row ``start_row + i`` goes to partition ``(start_row + i) % P`` at the
    next slot (C8 ``:225`` placement); ``start_row`` must be a multiple of
    P·B so striping is chunking-invariant. The single implementation shared
    by the one-shot path (:func:`stripe_partitions`) and the chunk feeder
    (``io.feeder``) — their bit-exact agreement is a correctness contract
    (see ``tests/test_chunked.py``).

    ``shuffle_seed`` applies the reference's per-microbatch shuffle
    (``batch.sample(frac=1)``, ``DDM_Process.py:187,190``) **on the host at
    stripe time** instead of inside the compiled loop: each batch is visited
    exactly once, so a pre-shuffle is semantically identical to the engine's
    in-jit shuffle while costing zero device time. Chunking-invariant
    (counter-based PRNG keyed on the absolute batch slot).

    ``feature_dtype`` is the *transport* dtype of the feature plane
    (default f32 — bit-exact). ``ml_dtypes.bfloat16`` halves the
    host→device bytes of every chunk — the lever for transport-bound
    feeds (the r05 chunked benchmark measured the shared remote-TPU
    tunnel, not the parser, as that path's bottleneck); the engines
    compute in f32 either way (``engine/loop`` and ``engine/window`` cast
    the plane back on device, so every driver — chunked, one-shot, mesh —
    gets f32 compute), and only the feature rounding to bf16 differs.
    Labels, rows and masks are integral and stay exact.

    ``row_valid`` ([n] bool; the quarantine mask of this span,
    ``io.sanitize``) folds into the validity plane — the engine-level
    guard plane of the dirty-stream subsystem: a quarantined row keeps
    its stream position but its grid slot carries the padding fill
    (features 0.0, label 0) and ``valid == False``, so inside jit it is
    indistinguishable from padding — static shapes, no recompiles, and
    the detector's statistics are exactly the clean stream's with those
    rows masked. The content re-fill here is also the numerical guard:
    no NaN/Inf from a dirty row can cross the host→device link even if
    a caller skipped canonicalization.
    """
    n = len(y)
    p, b = partitions, per_batch
    if row_valid is not None:
        row_valid = np.asarray(row_valid, bool)
        if row_valid.shape != (n,):
            raise ValueError(
                f"row_valid shape {row_valid.shape} != span rows ({n},)"
            )
        X = np.where(row_valid[:, None], X, np.asarray(X).dtype.type(0))
        y = np.where(row_valid, y, 0)
    gmap, rows, valid = _stripe_maps(n, start_row, p, b, nb, shuffle_seed)
    if row_valid is not None:
        valid = valid & _pad(row_valid, p * nb * b, False)[gmap]
    return Batches(
        X=_pad(np.asarray(X, feature_dtype), p * nb * b, 0.0)[gmap],
        y=_pad(np.asarray(y, np.int32), p * nb * b, 0)[gmap],
        rows=rows,
        valid=valid,
    )


def _pad(arr: np.ndarray, padded: int, fill) -> np.ndarray:
    out = np.full((padded, *arr.shape[1:]), fill, arr.dtype)
    out[: len(arr)] = arr
    return out


class ChunkStriper:
    """Allocation-pooled twin of :func:`stripe_chunk` for chunk-feed hot
    loops (``io.feeder.csv_chunks``): same placement, same shuffle, same
    validity folding — bit-identical output, pinned by test — but the pad
    staging buffers are **reused across chunks** and the gather map is
    cached when the stream is unshuffled (it is start-invariant then), so
    a steady-state feed stripes with one gather and zero per-chunk staging
    allocation instead of re-building concat + pad + map every time.

    Not thread-safe by design: one striper belongs to one pipeline stage
    (the feeder's sequential assembly loop). The *returned* ``Batches``
    leaves are fresh gather outputs — handing them downstream while the
    striper reuses its staging is safe.
    """

    def __init__(
        self,
        partitions: int,
        per_batch: int,
        chunk_batches: int,
        shuffle_seed: int | None = None,
        feature_dtype=np.float32,
    ):
        self.p, self.b, self.nb = partitions, per_batch, chunk_batches
        self.shuffle_seed = shuffle_seed
        self.feature_dtype = np.dtype(feature_dtype)
        self.span = partitions * per_batch * chunk_batches
        self._gmap: np.ndarray | None = None  # unshuffled: start-invariant
        self._padX: np.ndarray | None = None  # [span, F] staging, pooled
        self._pady = np.zeros(self.span, np.int32)

    def _maps(self, n: int, start_row: int):
        """(gmap, rows, valid) — exactly :func:`_stripe_maps`, with the
        unshuffled gather map computed once and reused."""
        assert self.shuffle_seed is None or start_row % (self.p * self.b) == 0, (
            "stripe-time shuffle needs start_row aligned to "
            "partitions*per_batch (all regular chunk boundaries are)"
        )
        if self.shuffle_seed is None:
            if self._gmap is None:
                self._gmap = _stripe_gmap(
                    _stripe_perms(self.p, self.b, self.nb, None)
                )
            gmap = self._gmap
        else:
            gmap = _stripe_gmap(
                _stripe_perms(
                    self.p, self.b, self.nb, self.shuffle_seed,
                    start_row // (self.p * self.b),
                )
            )
        rows = (start_row + gmap).astype(np.int32)
        return gmap, rows, gmap < n

    def stripe(
        self,
        X: np.ndarray,
        y: np.ndarray,
        start_row: int,
        row_valid: np.ndarray | None = None,
    ) -> Batches:
        """One span → ``[P, NB, B]`` chunk; :func:`stripe_chunk` semantics."""
        n = len(y)
        if n > self.span:
            raise ValueError(f"span of {n} rows exceeds chunk grid {self.span}")
        if row_valid is None and n == self.span:
            # Full clean span (the steady-state shape of a saturated v2
            # serve ingress): padding is vacuous, so gather straight from
            # the caller's arrays and skip the staging copy entirely.
            # Bit-identical by construction — same gather map, and the
            # staging path only differs on pad slots, of which there are
            # none. Dtype mismatches fall through to the staging path
            # (whose assignment performs the transport cast).
            Xa, ya = np.asarray(X), np.asarray(y)
            if (
                Xa.dtype == self.feature_dtype
                and ya.dtype == np.int32
                and Xa.ndim == 2
            ):
                gmap, rows, valid = self._maps(n, start_row)
                return Batches(X=Xa[gmap], y=ya[gmap], rows=rows, valid=valid)
        if row_valid is not None:
            row_valid = np.asarray(row_valid, bool)
            if row_valid.shape != (n,):
                raise ValueError(
                    f"row_valid shape {row_valid.shape} != span rows ({n},)"
                )
            X = np.where(row_valid[:, None], X, np.asarray(X).dtype.type(0))
            y = np.where(row_valid, y, 0)
        gmap, rows, valid = self._maps(n, start_row)
        if row_valid is not None:
            valid = valid & _pad(row_valid, self.span, False)[gmap]
        X = np.asarray(X)
        if self._padX is None or self._padX.shape[1] != X.shape[1]:
            self._padX = np.zeros((self.span, X.shape[1]), self.feature_dtype)
        padX, pady = self._padX, self._pady
        padX[:n] = X  # casts to the transport dtype, like _pad(asarray(X))
        padX[n:] = 0
        pady[:n] = np.asarray(y, np.int32)
        pady[n:] = 0
        return Batches(X=padX[gmap], y=pady[gmap], rows=rows, valid=valid)


def _stripe_maps(
    n: int, start_row: int, p: int, b: int, nb: int, shuffle_seed: int | None
):
    """The stripe as a gather: ``striped[p, s, j] = padded[gmap[p, s, j]]``.

    Padded position ``i`` → partition ``i % P``, slot ``i // P`` (C8 ``:225``),
    so ``gmap[p, s, j] = (s·B + j)·P + p`` — with ``j`` optionally sent
    through the per-batch shuffle permutation (``DDM_Process.py:187,190``,
    seeded; keyed on the absolute batch slot so chunking is invariant).
    Returns ``(gmap, rows, valid)``, each ``[P, NB, B]``; ``rows`` are global
    stream positions, ``valid`` masks padding.
    """
    assert shuffle_seed is None or start_row % (p * b) == 0, (
        "stripe-time shuffle needs start_row aligned to partitions*per_batch "
        "(all regular chunk boundaries are); pass shuffle_seed=None otherwise"
    )
    perms = _stripe_perms(p, b, nb, shuffle_seed, start_row // (p * b))
    gmap = _stripe_gmap(perms)
    rows = (start_row + gmap).astype(np.int32)
    valid = gmap < n
    return gmap, rows, valid


def _stripe_perms(
    p: int, b: int, nb: int, shuffle_seed: int | None, start_slot: int = 0
) -> np.ndarray:
    """Within-batch shuffle permutations ``[P, NB, B]`` (identity when
    unshuffled); counter-based on the absolute batch slot so chunking is
    invariant (``DDM_Process.py:187,190`` semantics, seeded)."""
    if shuffle_seed is None:
        j = np.arange(b, dtype=np.int64)
        return np.broadcast_to(j, (p, nb, b))
    from ..utils.prng import row_uniforms

    u = row_uniforms(shuffle_seed, start_slot * p, nb * p, b, stream_id=3)
    return np.argsort(u.reshape(nb, p, b), axis=-1).swapaxes(0, 1)


def _stripe_gmap(perms: np.ndarray) -> np.ndarray:
    """``gmap[p, s, j] = (s·B + perm[p, s, j])·P + p`` — the stripe gather
    (C8 ``:225`` placement composed with the per-batch shuffle). The same
    formula is replayed on device by ``engine.loop.expand_packed``."""
    p, nb, b = perms.shape
    slot = np.arange(nb, dtype=np.int64)[None, :, None]
    part = np.arange(p, dtype=np.int64)[:, None, None]
    return (slot * b + perms) * p + part


def stripe_geometry(
    num_rows: int, partitions: int, per_batch: int
) -> tuple[int, int]:
    """``(rows per partition, microbatches per partition)`` of the stripe —
    ceil at both levels (partition sizes differ by ≤ 1, C8 ``:225``; the
    last batch is padded + masked). The single source for stripers and for
    audits that need the expected grid independent of any built table."""
    per_part = -(-num_rows // partitions)
    return per_part, -(-per_part // per_batch)


def stripe_partitions(
    stream: StreamData,
    partitions: int,
    per_batch: int,
    shuffle_seed: int | None = None,
) -> Batches:
    """Row-stripe the whole stream over P partitions (one-shot path).

    Returns :class:`Batches` with leading partition axis: ``X [P, NB, B, F]``,
    ``y/rows/valid [P, NB, B]``. ``rows`` holds global stream positions so the
    delay metric (global position % concept length) works per the reference's
    intent. ``shuffle_seed``: see :func:`stripe_chunk`. Quarantined rows
    (``stream.row_ok``) fold into the validity plane (:func:`stripe_chunk`'s
    ``row_valid``).
    """
    _, nb = stripe_geometry(stream.num_rows, partitions, per_batch)
    return stripe_chunk(
        stream.X, stream.y, 0, partitions, per_batch, nb, shuffle_seed,
        row_valid=stream.row_ok,
    )


def stripe_partitions_indexed(
    stream: StreamData,
    partitions: int,
    per_batch: int,
    shuffle_seed: int | None = None,
) -> IndexedBatches:
    """Compressed variant of :func:`stripe_partitions`.

    Same placement, same shuffle, same ``rows``/``valid`` planes — but the
    data plane is ``idx`` (stream's ``src`` map composed with the stripe
    gather) over the deduplicated row table, int16 when the table allows it.
    ``engine.window`` gathers ``X``/``y`` on device;
    ``materialize_batches`` reproduces the exact :class:`Batches` for parity
    checks. Requires a stream synthesized with ``mult_data >= 1``.
    """
    # One construction for both compressed forms: build packed, expand the
    # geometry planes host-side (the exact formula expand_packed replays on
    # device), so the two stripers cannot drift apart.
    packed = stripe_partitions_packed(
        stream, partitions, per_batch, shuffle_seed=shuffle_seed
    )
    gmap = _stripe_gmap(np.asarray(packed.perm, dtype=np.int64))
    return IndexedBatches(
        base_X=packed.base_X,
        base_y=packed.base_y,
        idx=packed.idx,
        rows=gmap.astype(np.int32),
        valid=gmap < int(packed.n_rows),
    )


def stripe_partitions_packed(
    stream: StreamData,
    partitions: int,
    per_batch: int,
    shuffle_seed: int | None = None,
) -> PackedIndexedBatches:
    """Transport-optimal variant of :func:`stripe_partitions_indexed`.

    Same placement, same shuffle, same downstream flags — but the
    geometry-derived ``rows``/``valid`` planes are *not built or shipped*:
    only the row-table gather indices and the one-byte-per-element shuffle
    permutation cross the host→device link, and the planes are synthesized
    in-jit by ``engine.loop.expand_packed`` (~2.3× less transfer than the
    indexed form at the mult=512 headline shape). One-shot path only
    (``start_row = 0``).
    """
    if stream.src is None:
        raise ValueError(
            "stream has no compressed form (subsampled or hand-built); "
            "use stripe_partitions"
        )
    if stream.has_masked_rows:
        # The packed form synthesizes `valid` in-jit from pure geometry
        # (expand_packed: gmap < n) — a quarantine mask is data, not
        # geometry, so masked streams ride the dense striper where the
        # mask folds into the host-built validity plane (api.prepare
        # routes them there; flags are bit-identical across stripers).
        raise ValueError(
            "stream has quarantine-masked rows; the packed striper cannot "
            "carry a row mask — use stripe_partitions"
        )
    n = stream.num_rows
    p, b = partitions, per_batch
    _, nb = stripe_geometry(n, p, b)
    if p * nb * b > 2**31 - 1:
        raise ValueError(
            f"padded stripe grid of {p * nb * b:,} positions exceeds int32 "
            "(expand_packed synthesizes positions as int32)"
        )
    perms = _stripe_perms(p, b, nb, shuffle_seed)
    idx = _pad(stream.src.astype(np.int64), p * nb * b, 0)[_stripe_gmap(perms)]
    dt = np.int16 if len(stream.base_y) <= np.iinfo(np.int16).max else np.int32
    # Smallest lossless dtype for the in-batch permutation (values < b).
    if b <= 256:
        pdt = np.uint8
    elif b <= np.iinfo(np.int16).max + 1:
        pdt = np.int16
    else:
        pdt = np.int32
    return PackedIndexedBatches(
        base_X=stream.base_X,
        base_y=stream.base_y,
        idx=idx.astype(dt),
        perm=np.ascontiguousarray(perms.astype(pdt)),
        n_rows=np.int32(n),
    )


def materialize_batches(batches: IndexedBatches) -> Batches:
    """Expand a compressed grid to the equivalent :class:`Batches` (host).

    Padding slots (``valid == False``) carry ``idx = 0``; mask them back to
    the dense striper's fill values (0.0 / 0) so the result is bit-identical
    to :func:`stripe_partitions` even on ragged grids.
    """
    idx = np.asarray(batches.idx).astype(np.int64)
    valid = np.asarray(batches.valid)
    return Batches(
        X=np.where(valid[..., None], np.asarray(batches.base_X)[idx], np.float32(0)),
        y=np.where(valid, np.asarray(batches.base_y)[idx], 0),
        rows=batches.rows,
        valid=batches.valid,
    )
