"""Merged run timelines as Chrome-trace/Perfetto artifacts.

    python -m distributed_drift_detection_tpu timeline <dir | logs...> \\
        -o run.trace.json

Takes one or many schema-v1 run logs — a single batch run, a serving
daemon plus its load generator, or a multi-host fleet's per-process
logs — and merges them into ONE ``.trace.json`` in the Chrome trace
event format, loadable in Perfetto (https://ui.perfetto.dev) or
``chrome://tracing``. ``span`` events render as duration slices (the
causal per-row serving chains from ``telemetry.tracing``, grouped per
trace); ``phase_completed`` renders as phase slices; progress events
(``chunk_completed``, ``heartbeat``, ``leg_completed``) and findings
(``drift_detected``, ``retrain``, ``alert``, ``rows_quarantined``,
``drift_forensics``) render as instants, so the whole run reads on one
scrollable timeline.

Clock alignment reuses ``correlate``'s rule: logs that belong to ONE
multi-process run (same config digest) are each rebased to their own
``run_started`` timestamp — host wall-clocks on a pod differ by
arbitrary offsets, and ``run_started`` is the one boundary every
process crosses at the same program point, so a constant per-host skew
cancels exactly. Logs from *different* programs on one machine (a
daemon and its loadgen have different configs) are placed on the shared
wall clock instead — their relative offset is the signal, not skew.
Each log becomes one Chrome-trace ``pid`` (named after its run id /
process index); within a log, spans are laid out on per-trace ``tid``
rows and non-span events on a dedicated events row.

Pure stdlib + the schema/correlate modules; no jax — runs wherever the
artifacts land.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .correlate import load_logs
from .registry import INDEX_NAME, SIDECAR_SUFFIXES

TRACE_SUFFIX = ".trace.json"

# tid layout inside one pid: 0 = phases, 1 = instants, 2+ = one row per
# span trace (assigned in first-seen order).
_TID_PHASES = 0
_TID_EVENTS = 1
_TID_TRACES = 2

# Non-span event types rendered as instants, with a short detail lambda.
_INSTANT_DETAIL = {
    "chunk_completed": lambda e: {
        "chunk": e["chunk"],
        "batches_done": e["batches_done"],
        "detections": e["detections"],
    },
    "leg_completed": lambda e: {
        "leg": e["leg"], "rows": e["rows"], "detections": e["detections"]
    },
    "heartbeat": lambda e: {
        "rows_done": e["rows_done"], "elapsed_s": e["elapsed_s"]
    },
    "drift_detected": lambda e: {
        "partition": e["partition"], "global_pos": e["global_pos"]
    },
    "retrain": lambda e: {
        "partition": e["partition"], "batch": e["batch"],
        "forced": e["forced"],
    },
    "alert": lambda e: {
        "rule": e["rule"], "state": e["state"], "value": e["value"],
        "threshold": e["threshold"],
    },
    "rows_quarantined": lambda e: {"rows": e["rows"], "policy": e["policy"]},
    "drift_forensics": lambda e: {
        "partition": e["partition"], "global_pos": e["global_pos"],
        "bundle": e["bundle"],
    },
    "run_retried": lambda e: {
        "attempt": e["attempt"], "reason": e["reason"]
    },
    "compile_completed": lambda e: {
        "cached": e["cached"], "seconds": e["seconds"]
    },
}


class TimelineError(ValueError):
    """The given logs cannot be merged into one timeline."""


def _log_offsets(logs) -> "dict[str, float]":
    """Per-log rebase offset: ``timeline_seconds = ts - offset(log)``.

    The skew rebase applies ONLY to a genuine multi-process run:
    logs sharing ``(config digest, process_count)`` with a declared
    ``process_count > 1`` and pairwise-distinct process indices — one
    process per host, correlate's grouping rule. Those members each
    rebase to their own ``t0`` (host wall-clocks on a pod differ by
    arbitrary offsets; ``run_started`` is the shared program point, so
    constant per-host skew cancels) and the group sits at its earliest
    ``t0`` on the global clock. Everything else — distinct programs
    (daemon vs loadgen), and *repeated runs of one config* (two
    identical replays share a digest but are NOT one run; overlaying
    them at a common origin would fake simultaneity) — sits directly on
    the shared wall clock, preserving real relative placement. Keys are
    log paths.
    """
    if not logs:
        raise TimelineError("no logs to merge")
    base = min(ident["t0"] for ident, _ in logs)
    groups: dict[tuple, list] = {}
    for ident, _ in logs:
        groups.setdefault(
            (ident["digest"], ident["process_count"]), []
        ).append(ident)
    offsets: dict[str, float] = {}
    for (_, process_count), members in groups.items():
        procs = [m["process_index"] for m in members]
        fleet = (
            len(members) > 1
            and (process_count or 0) > 1
            and len(set(procs)) == len(procs)
        )
        if not fleet:
            for m in members:
                offsets[m["path"]] = base
            continue
        group_t0 = min(m["t0"] for m in members)
        for m in members:
            # rebase to the member's own t0 (skew cancels), then shift
            # the whole group to where it started on the global clock
            offsets[m["path"]] = m["t0"] - (group_t0 - base)
    return offsets


def build_timeline(paths: "list[str]") -> dict:
    """Merge run logs into one Chrome-trace JSON object (the data model
    behind the CLI; reusable programmatically)."""
    logs = load_logs(paths)
    offsets = _log_offsets(logs)
    events: list[dict] = []
    for pid, (ident, log_events) in enumerate(logs):
        off = offsets[ident["path"]]
        label = f"proc{ident['process_index']} {ident['run_id']}"
        events.append(
            {
                "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
                "args": {"name": label},
            }
        )
        for tid, tname in ((_TID_PHASES, "phases"), (_TID_EVENTS, "events")):
            events.append(
                {
                    "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
                    "args": {"name": tname},
                }
            )
        trace_tids: dict[str, int] = {}
        for e in log_events:
            t_us = (float(e["ts"]) - off) * 1e6
            etype = e["type"]
            if etype == "span":
                tid = trace_tids.get(e["trace_id"])
                if tid is None:
                    tid = _TID_TRACES + len(trace_tids)
                    trace_tids[e["trace_id"]] = tid
                    events.append(
                        {
                            "ph": "M", "name": "thread_name",
                            "pid": pid, "tid": tid,
                            "args": {"name": f"trace {e['trace_id'][:8]}"},
                        }
                    )
                args = {
                    k: v
                    for k, v in e.items()
                    if k not in ("v", "type", "ts", "seq", "name", "start_ts",
                                 "dur_s")
                }
                events.append(
                    {
                        "name": e["name"],
                        "ph": "X",
                        "ts": (float(e["start_ts"]) - off) * 1e6,
                        "dur": max(float(e["dur_s"]), 0.0) * 1e6,
                        "pid": pid,
                        "tid": tid,
                        "args": args,
                    }
                )
            elif etype == "phase_completed":
                # emitted at phase END; the slice starts dur earlier
                dur = max(float(e["seconds"]), 0.0)
                events.append(
                    {
                        "name": e["phase"],
                        "ph": "X",
                        "ts": t_us - dur * 1e6,
                        "dur": dur * 1e6,
                        "pid": pid,
                        "tid": _TID_PHASES,
                        "args": {},
                    }
                )
            elif etype in ("run_started", "run_completed"):
                events.append(
                    {
                        "name": etype, "ph": "i", "ts": t_us, "pid": pid,
                        "tid": _TID_EVENTS, "s": "p",
                        "args": (
                            {"rows": e["rows"], "seconds": e["seconds"]}
                            if etype == "run_completed"
                            else {"run_id": e["run_id"]}
                        ),
                    }
                )
            else:
                detail = _INSTANT_DETAIL.get(etype)
                if detail is None:
                    continue  # cost/memory snapshots etc: not timeline-shaped
                events.append(
                    {
                        "name": etype, "ph": "i", "ts": t_us, "pid": pid,
                        "tid": _TID_EVENTS, "s": "t", "args": detail(e),
                    }
                )
    events.sort(key=lambda ev: (ev.get("ts", -1), ev["pid"], ev["tid"]))
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "producer": "distributed_drift_detection_tpu timeline",
            "logs": [ident["path"] for ident, _ in logs],
        },
    }


def validate_chrome_trace(obj: dict) -> int:
    """Structural check of a Chrome-trace JSON object (the CI smoke
    gate's contract — a trace that validates is a trace Perfetto/
    ``chrome://tracing`` loads). Returns the renderable event count;
    raises :class:`TimelineError` on any violation."""
    if not isinstance(obj, dict) or not isinstance(
        obj.get("traceEvents"), list
    ):
        raise TimelineError("not a Chrome-trace object (no traceEvents list)")
    n = 0
    for i, ev in enumerate(obj["traceEvents"]):
        if not isinstance(ev, dict):
            raise TimelineError(f"traceEvents[{i}] is not an object")
        ph = ev.get("ph")
        if not ev.get("name") or ph not in ("X", "B", "E", "i", "I", "M", "C"):
            raise TimelineError(
                f"traceEvents[{i}]: bad name/ph {ev.get('name')!r}/{ph!r}"
            )
        if not isinstance(ev.get("pid"), int) or not isinstance(
            ev.get("tid"), int
        ):
            raise TimelineError(f"traceEvents[{i}]: pid/tid must be ints")
        if ph == "M":
            continue
        if not isinstance(ev.get("ts"), (int, float)):
            raise TimelineError(f"traceEvents[{i}]: missing numeric ts")
        if ph == "X" and (
            not isinstance(ev.get("dur"), (int, float)) or ev["dur"] < 0
        ):
            raise TimelineError(f"traceEvents[{i}]: X event needs dur >= 0")
        n += 1
    return n


def _resolve_paths(paths: "list[str]") -> "list[str]":
    """A directory resolves to EVERY run log in it (the timeline merges
    heterogeneous logs — daemon + loadgen — unlike correlate's one-run
    grouping); explicit files pass through."""
    if len(paths) == 1 and os.path.isdir(paths[0]):
        import glob

        found = sorted(
            p
            for p in glob.glob(os.path.join(paths[0], "*.jsonl"))
            if os.path.basename(p) != INDEX_NAME
            # the registry's one sidecar-suffix list: a new sidecar type
            # added there is excluded here automatically
            and not os.path.basename(p).endswith(SIDECAR_SUFFIXES)
        )
        if not found:
            raise TimelineError(f"no run logs in {paths[0]}")
        return found
    return paths


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(
        prog="python -m distributed_drift_detection_tpu timeline",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument(
        "paths",
        nargs="+",
        help="one telemetry directory (every run log in it merges) or "
        "run-log *.jsonl files",
    )
    ap.add_argument(
        "-o",
        "--out",
        default=None,
        help=f"output path (default: <first log stem>{TRACE_SUFFIX}; "
        "'-' writes to stdout)",
    )
    args = ap.parse_args(argv)
    try:
        paths = _resolve_paths(args.paths)
        trace = build_timeline(paths)
        n = validate_chrome_trace(trace)
    except (TimelineError, OSError) as e:
        raise SystemExit(f"timeline: {e}") from None
    out = args.out
    if out == "-":
        json.dump(trace, sys.stdout)
        sys.stdout.write("\n")
        return
    if out is None:
        out = os.path.splitext(paths[0])[0] + TRACE_SUFFIX
    with open(out, "w") as fh:
        json.dump(trace, fh)
        fh.write("\n")
    spans = sum(
        1 for ev in trace["traceEvents"] if ev["ph"] == "X"
    )
    print(
        f"timeline: {len(trace['otherData']['logs'])} log(s) -> {out} "
        f"({n} events, {spans} slices)"
    )


if __name__ == "__main__":
    main(sys.argv[1:])
