"""Drift forensics: evidence bundles for every drift verdict.

    python -m distributed_drift_detection_tpu explain <dir | run.jsonl | bundle>

A drift flag as published today is a *position*: partition p, stream row
r. It carries no evidence of what the detector saw when it fired — the
error-rate level, how close the warn/drift thresholds were, what the
rows around the firing point looked like. This module extracts that
evidence **host-side**, at verdict-publication time, from material the
serving loop already holds: the collected flag table, the sealed chunk's
host copy (features/labels/positions/validity), and a cheap per-chunk
snapshot of the detector carry taken as each chunk enters the kernel.
Nothing is added to jitted code and nothing extra crosses the
device→host link beyond a few scalars per partition per chunk.

One bundle = one JSON file under ``<run-log stem>.forensics/``:

* the firing point (chunk / batch column / partition / tenant / global
  stream position) and the same batch's first warning, if any;
* the detector's configured thresholds AND the *effective* warn/drift
  bars at the firing window (``p_min + level·s_band``, DDM semantics);
* the detector state entering the firing chunk (count, running error
  rate, ``ps_min/p_min/s_min``) — the window stats the threshold
  comparison ran against, matching the sequential oracle's internals
  exactly (pinned by test);
* the running error-rate trajectory over the last N chunk boundaries
  approaching the firing point;
* pre/post context rows around the firing position, from the chunk's
  host copy (feature vector, label, validity — quarantined/padded rows
  visible as invalid);
* the trace ids of any sampled rows in the chunk (telemetry.tracing),
  so a bundle joins back to its causal traces.

Every bundle is announced by a schema-v1 ``drift_forensics`` event and
counted in ``forensics_bundles_total`` (surfaced in ``/statusz``). The
``explain`` CLI renders bundles human-readably. No jax imports — the
snapshot capture is handed in as host arrays by the serve loop; the
CLI runs wherever the artifacts land.
"""

from __future__ import annotations

import argparse
import collections
import glob
import json
import math
import os
import sys
import time

import numpy as np

FORENSICS_SUFFIX = ".forensics"
BUNDLE_VERSION = 1

#: context rows captured on each side of the firing position
DEFAULT_CONTEXT_ROWS = 8
#: chunk-boundary snapshots retained per partition for the trajectory
DEFAULT_TRAJECTORY = 16

FORENSICS_METRIC = "forensics_bundles_total"
FORENSICS_HELP = "Drift evidence bundles written by telemetry.forensics"


def _finite(v) -> "float | None":
    """JSON-safe float: non-finite (inf minima of a fresh detector)
    serialize as None, never as bare ``Infinity``."""
    f = float(v)
    return f if math.isfinite(f) else None


def state_fields(state, partition: int) -> dict:
    """One partition's detector-state scalars as a JSON-safe dict.

    Generic over detector kernels: a NamedTuple state (DDM's
    ``count/err_sum/ps_min/p_min/s_min``, or any other kernel's) maps
    field name → value at ``partition``; unknown structures fall back to
    positional ``leaf<i>`` names. A derived ``error_rate`` is added when
    ``count``/``err_sum`` exist (DDM's running p) — the quantity the
    trajectory plots."""
    if state is None:
        return {}
    if hasattr(state, "_asdict"):
        items = list(state._asdict().items())
    else:
        items = [(f"leaf{i}", leaf) for i, leaf in enumerate(state)]
    out = {}
    for name, leaf in items:
        arr = np.asarray(leaf)
        if arr.ndim >= 1 and partition < arr.shape[0]:
            out[name] = _finite(arr[partition])
        elif arr.ndim == 0:
            out[name] = _finite(arr)
    cnt = out.get("count")
    if cnt and out.get("err_sum") is not None:
        # f32 division, matching the kernel's p = err_sum / count
        out["error_rate"] = _finite(
            np.float32(out["err_sum"]) / np.float32(cnt)
        )
    elif "count" in out:
        out["error_rate"] = None
    return out


def effective_thresholds(window: dict, params: dict) -> dict:
    """The warn/drift bars the DDM comparison used at this window:
    ``p_min + level · s_band`` with the noise-floor band
    (``ops.ddm._band_s`` semantics, recomputed host-side in f32). Empty
    when the state carries no DDM-shaped minima (other kernels)."""
    p_min, s_min = window.get("p_min"), window.get("s_min")
    out_level = params.get("out_control_level")
    if p_min is None or s_min is None or not out_level:
        return {}
    s_band = np.float32(s_min)
    floor = params.get("noise_floor") or 0.0
    if floor:
        s_band = max(s_band, np.float32(floor) / np.float32(out_level))
    return {
        "warn": _finite(
            np.float32(p_min)
            + np.float32(params.get("warning_level", 0.0)) * s_band
        ),
        "drift": _finite(
            np.float32(p_min) + np.float32(out_level) * s_band
        ),
    }


def _context_rows(chunk, partition: int, pos: int, k: int) -> dict:
    """Pre/post context rows around stream position ``pos`` from one
    partition's plane of the chunk's host copy, in stream order."""
    rows = np.asarray(chunk.rows[partition]).ravel()
    X = np.asarray(chunk.X[partition]).reshape(rows.size, -1)
    y = np.asarray(chunk.y[partition]).ravel()
    valid = np.asarray(chunk.valid[partition]).ravel()
    real = rows >= 0  # padding rows carry -1 positions
    order = np.argsort(rows[real], kind="stable")
    r, x, lab, ok = (
        rows[real][order], X[real][order], y[real][order], valid[real][order]
    )

    def pack(idx):
        return [
            {
                "pos": int(r[i]),
                "x": [float(v) for v in x[i]],
                "y": int(lab[i]),
                "valid": bool(ok[i]),
            }
            for i in idx
        ]

    before = np.nonzero(r < pos)[0]
    after = np.nonzero(r >= pos)[0]
    return {"pre": pack(before[-k:]), "post": pack(after[:k])}


class ForensicsExtractor:
    """Per-daemon forensics state: snapshot ring + bundle writer.

    The serve loop calls :meth:`on_publish` once per published chunk
    with the chunk's *entry* detector state (captured before the chunk
    was fed — a few host scalars per partition), the collected host
    flag table, and the chunk's host copy. Drift-free chunks only
    advance the trajectory ring; a chunk with detections writes one
    bundle per firing flag.
    """

    def __init__(
        self,
        out_dir: str,
        *,
        run_id: "str | None" = None,
        detector_params: "dict | None" = None,
        tenants: int = 1,
        context_rows: int = DEFAULT_CONTEXT_ROWS,
        trajectory: int = DEFAULT_TRAJECTORY,
        metrics=None,
    ):
        self.out_dir = out_dir
        self.run_id = run_id
        self.detector_params = dict(detector_params or {})
        self.tenants = max(int(tenants), 1)
        self.context_rows = int(context_rows)
        self.bundles_written = 0
        self._traj: dict[int, collections.deque] = {}
        self._traj_cap = max(int(trajectory), 1)
        self._counter = (
            metrics.counter(FORENSICS_METRIC, help=FORENSICS_HELP)
            if metrics is not None
            else None
        )

    def _record_trajectory(self, meta: dict, entry_state) -> None:
        if entry_state is None:
            return
        # one ring per partition, fed from the [P]-shaped state arrays
        arrs = (
            entry_state._asdict()
            if hasattr(entry_state, "_asdict")
            else {}
        )
        cnt = arrs.get("count")
        esum = arrs.get("err_sum")
        if cnt is None:
            return
        cnt = np.asarray(cnt)
        esum = None if esum is None else np.asarray(esum)
        for p in range(cnt.shape[0] if cnt.ndim else 1):
            ring = self._traj.setdefault(
                p, collections.deque(maxlen=self._traj_cap)
            )
            c = int(cnt[p] if cnt.ndim else cnt)
            e = (
                None
                if esum is None
                else float(esum[p] if esum.ndim else esum)
            )
            ring.append(
                {
                    "chunk": int(meta["chunk"]),
                    "rows_through": int(meta.get("rows_through", 0)),
                    "count": c,
                    "error_rate": (
                        _finite(np.float32(e) / np.float32(c))
                        if e is not None and c > 0
                        else None
                    ),
                }
            )

    def on_publish(
        self,
        meta: dict,
        flags,
        chunk,
        entry_state,
        *,
        log=None,
        trace_ids=(),
    ) -> "list[str]":
        """Process one published chunk; returns the bundle paths written
        (empty for drift-free chunks). ``entry_state`` is the detector
        state entering this chunk as HOST arrays (or None when capture
        is off/unavailable); ``flags`` the collected host flag table;
        ``chunk`` the sealed chunk's host copy."""
        self._record_trajectory(meta, entry_state)
        cg = np.asarray(flags.change_global)
        changed = cg >= 0
        if not changed.any():
            return []
        os.makedirs(self.out_dir, exist_ok=True)
        wl = np.asarray(flags.warning_local)
        wg = np.asarray(flags.warning_global)
        p_per = cg.shape[0] // self.tenants
        written = []
        for b, p in zip(*np.nonzero(changed.T)):
            p, b = int(p), int(b)
            pos = int(cg[p, b])
            window = state_fields(entry_state, p)
            bundle = {
                "v": BUNDLE_VERSION,
                "kind": "drift_forensics",
                "run_id": self.run_id,
                "ts": time.time(),
                "chunk": int(meta["chunk"]),
                "batch": b,
                "partition": p,
                "tenant": p // p_per if self.tenants > 1 else None,
                "tenant_partition": p % p_per if self.tenants > 1 else None,
                "global_pos": pos,
                "warning": (
                    {"local": int(wl[p, b]), "global_pos": int(wg[p, b])}
                    if int(wl[p, b]) >= 0
                    else None
                ),
                "detector": self.detector_params,
                "window": window,
                "thresholds": effective_thresholds(
                    window, self.detector_params
                ),
                "trajectory": list(self._traj.get(p, ())),
                "context": _context_rows(
                    chunk, p, pos, self.context_rows
                ),
                "trace_ids": list(trace_ids),
                "rows_through": int(meta.get("rows_through", 0)),
            }
            path = os.path.join(
                self.out_dir, f"drift-c{meta['chunk']}-p{p}-r{pos}.json"
            )
            with open(path, "w") as fh:
                json.dump(bundle, fh, indent=1)
                fh.write("\n")
            written.append(path)
            self.bundles_written += 1
            if self._counter is not None:
                self._counter.inc()
            if log is not None:
                log.emit(
                    "drift_forensics",
                    chunk=int(meta["chunk"]),
                    partition=p,
                    global_pos=pos,
                    bundle=os.path.relpath(
                        path, os.path.dirname(self.out_dir) or "."
                    ),
                )
        return written


# -- reading + rendering (the `explain` CLI) --------------------------------


def find_bundles(path: str) -> "list[str]":
    """Resolve bundles from a path: a bundle file, a ``.forensics``
    directory, a run log (its sibling ``.forensics`` dir), or a
    telemetry directory (every ``*.forensics/`` under it)."""
    if os.path.isfile(path) and path.endswith(".json"):
        return [path]
    if os.path.isdir(path) and path.endswith(FORENSICS_SUFFIX):
        return sorted(glob.glob(os.path.join(path, "drift-*.json")))
    if os.path.isfile(path):  # a run log: its own forensics dir
        d = os.path.splitext(path)[0] + FORENSICS_SUFFIX
        return sorted(glob.glob(os.path.join(d, "drift-*.json")))
    if os.path.isdir(path):  # a telemetry dir: every run's bundles
        return sorted(
            glob.glob(
                os.path.join(path, "*" + FORENSICS_SUFFIX, "drift-*.json")
            )
        )
    return []


def adaptation_index(bundle_path: str) -> "dict[tuple, list[dict]]":
    """The ``adaptation`` events matching a bundle's run, indexed by
    ``(tenant, trigger_chunk)`` — the join key between a drift's
    *cause* (the forensics bundle) and its *reaction* (the adapt
    subsystem's event). The run log is the bundle directory's sibling
    (``X.forensics/`` ↔ ``X.jsonl``); a missing or partial log (a live
    daemon) yields what is readable, never an error — explain must
    render wherever the artifacts land."""
    d = os.path.dirname(os.path.abspath(bundle_path))
    if not d.endswith(FORENSICS_SUFFIX):
        return {}
    log = d[: -len(FORENSICS_SUFFIX)] + ".jsonl"
    if not os.path.isfile(log):
        return {}
    from .events import SchemaError, read_events

    try:
        events = read_events(log, allow_partial_tail=True)
    except SchemaError:
        return {}
    out: dict = {}
    for e in events:
        if e.get("type") != "adaptation":
            continue
        key = (int(e.get("tenant", 0)), int(e["trigger_chunk"]))
        out.setdefault(key, []).append(e)
    return out


def render_adaptation(events: "list[dict] | None") -> "list[str]":
    """The reaction lines rendered under a bundle: one per matching
    ``adaptation`` event, or the explicit "no reaction" line — one
    command shows cause AND reaction."""
    if not events:
        return ["  reaction       none recorded (on_drift=alert_only?)"]
    out = []
    for e in events:
        verdict = (
            "demoted"
            if e.get("demoted")
            else ("promoted" if e.get("promoted") else "held (champion kept)")
        )
        errs = (
            f"err {_fmt(e.get('err_before'), 3)} -> "
            f"{_fmt(e.get('err_after'), 3)}"
        )
        out.append(
            f"  reaction       policy={e['policy']}  {verdict}  {errs}  "
            f"refit on {e['rows_refit']} row(s)"
            + (
                f"  applied +{e['rows_to_apply']} rows "
                f"(chunk {e.get('applied_chunk')})"
                if e.get("rows_to_apply") is not None
                else ""
            )
        )
    return out


def read_bundle(path: str) -> dict:
    with open(path) as fh:
        bundle = json.load(fh)
    if not isinstance(bundle, dict) or bundle.get("kind") != "drift_forensics":
        raise ValueError(f"{path}: not a drift_forensics bundle")
    return bundle


def _fmt(v, nd=6) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{nd}g}"
    return str(v)


def render_bundle(bundle: dict, adaptation: "list[dict] | None" = None) -> str:
    """Human-readable rendering of one evidence bundle; ``adaptation``
    (the matching ``adaptation`` events, see :func:`adaptation_index`)
    appends the reaction lines so cause and reaction read together."""
    out = []
    tenant = (
        f" tenant {bundle['tenant']} (local p{bundle['tenant_partition']})"
        if bundle.get("tenant") is not None
        else ""
    )
    out.append(
        f"drift @ row {bundle['global_pos']}  — chunk {bundle['chunk']} "
        f"batch {bundle['batch']} partition {bundle['partition']}{tenant}"
    )
    if bundle.get("warning"):
        out.append(
            f"  first warning  row {bundle['warning']['global_pos']} "
            f"(batch-local {bundle['warning']['local']})"
        )
    det = bundle.get("detector") or {}
    if det:
        out.append(
            "  detector       "
            + "  ".join(f"{k}={_fmt(v)}" for k, v in sorted(det.items()))
        )
    w = bundle.get("window") or {}
    if w:
        out.append(
            "  window stats   "
            + "  ".join(f"{k}={_fmt(v)}" for k, v in sorted(w.items()))
        )
    th = bundle.get("thresholds") or {}
    if th:
        out.append(
            f"  thresholds     warn>{_fmt(th.get('warn'))}  "
            f"drift>{_fmt(th.get('drift'))}  (p+s vs p_min+level·s_band)"
        )
    traj = bundle.get("trajectory") or []
    if traj:
        rates = [
            _fmt(t.get("error_rate"), 3) for t in traj
        ]
        out.append(
            f"  error rate     {' -> '.join(rates)}   "
            f"(last {len(traj)} chunk boundaries)"
        )
    ctx = bundle.get("context") or {}
    pre, post = ctx.get("pre") or [], ctx.get("post") or []
    if pre or post:
        out.append(
            f"  context        {len(pre)} row(s) before, "
            f"{len(post)} after the firing point:"
        )
        for r in pre + post:
            marker = ">>" if r["pos"] == bundle["global_pos"] else "  "
            flag = "" if r["valid"] else "  [masked]"
            xs = " ".join(f"{v:.3g}" for v in r["x"][:6])
            more = " ..." if len(r["x"]) > 6 else ""
            out.append(
                f"   {marker} row {r['pos']:>9}  y={r['y']}  "
                f"x=[{xs}{more}]{flag}"
            )
    if bundle.get("trace_ids"):
        out.append(
            "  traces         " + " ".join(bundle["trace_ids"][:4])
            + (" ..." if len(bundle["trace_ids"]) > 4 else "")
        )
    if adaptation is not None:
        out.extend(render_adaptation(adaptation))
    return "\n".join(out)


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(
        prog="python -m distributed_drift_detection_tpu explain",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument(
        "path",
        help="a bundle .json, a <run>.forensics/ directory, a run log, or "
        "a telemetry directory",
    )
    ap.add_argument(
        "--limit", type=int, default=20,
        help="max bundles rendered (default 20; newest-position last)",
    )
    args = ap.parse_args(argv)
    bundles = find_bundles(args.path)
    if not bundles:
        raise SystemExit(f"explain: no forensics bundles under {args.path}")
    shown = bundles[: args.limit]
    adapt_cache: dict = {}  # bundle dir -> adaptation index (one log read)
    for i, p in enumerate(shown):
        if i:
            print()
        bundle = read_bundle(p)
        d = os.path.dirname(os.path.abspath(p))
        if d not in adapt_cache:
            adapt_cache[d] = adaptation_index(p)
        key = (int(bundle.get("tenant") or 0), int(bundle["chunk"]))
        print(render_bundle(bundle, adaptation=adapt_cache[d].get(key, [])))
    hidden = len(bundles) - len(shown)
    print(
        f"\n{len(bundles)} bundle(s)"
        + (f" ({hidden} not shown; --limit)" if hidden > 0 else "")
    )


if __name__ == "__main__":
    main(sys.argv[1:])
