"""Run-log report: render a persisted JSONL event log as a summary.

    python -m distributed_drift_detection_tpu report <run.jsonl>
    python -m distributed_drift_detection_tpu report --dir <telemetry-dir>

Answers the post-hoc questions the reference needs a re-run for: where the
time went (phase breakdown), how fast it ran (throughput), what the
compiler said the detect program costs and how close the run came to it
(cost/memory section: flops, bytes, peak temp allocation, achieved
GFLOP/s — from the ``cost_analysis``/``memory_snapshot`` events), when and
where drift fired (ascii timeline over the stream + per-partition counts),
and — for streaming/soak logs — per-chunk/per-leg progress. ``--dir``
renders a telemetry directory's newest run (the registry-first resolution
shared with the ``watch`` CLI — ``telemetry.registry.newest_run_log``),
so "how did the latest run do" needs no filename archaeology. Pure
stdlib + the schema module; no jax, so it runs anywhere the artifact
lands.
"""

from __future__ import annotations

import argparse
import os
import sys

from .events import read_events
from .registry import newest_run_log

_TIMELINE_BINS = 50
_TIMELINE_GLYPHS = " .:-=+*#%@"


def summarize(events: list[dict]) -> dict:
    """Fold a validated event list into one summary dict (the report's data
    model; rendered by :func:`render_report`, reusable programmatically)."""
    s: dict = {
        "run_id": None,
        "config": {},
        "phases": {},
        "compile": None,
        "drifts": [],
        "retrains": 0,
        "forced_retrains": 0,
        "chunks": [],
        "legs": [],
        "retried": [],
        "alerts": [],
        "spans": 0,
        "trace_ids": set(),
        "forensics": [],
        "quarantine": None,
        "heartbeat": None,
        "completed": None,
        "cost": None,
        "mem_analysis": None,
        "device_mem": {},
    }
    for e in events:
        t = e["type"]
        if t == "run_started":
            s["run_id"] = e["run_id"]
            s["config"] = e.get("config") or {}
        elif t == "phase_completed":
            s["phases"][e["phase"]] = (
                s["phases"].get(e["phase"], 0.0) + e["seconds"]
            )
        elif t == "compile_completed":
            s["compile"] = e
        elif t == "drift_detected":
            s["drifts"].append(e)
        elif t == "retrain":
            s["retrains"] += 1
            s["forced_retrains"] += bool(e["forced"])
        elif t == "chunk_completed":
            s["chunks"].append(e)
        elif t == "leg_completed":
            s["legs"].append(e)
        elif t == "run_retried":
            s["retried"].append(e)
        elif t == "alert":
            s["alerts"].append(e)
        elif t == "span":
            s["spans"] += 1
            s["trace_ids"].add(e["trace_id"])
        elif t == "drift_forensics":
            s["forensics"].append(e)
        elif t == "rows_quarantined":
            s["quarantine"] = e
        elif t == "heartbeat":
            s["heartbeat"] = e  # newest wins: the run's latest known pulse
        elif t == "cost_analysis":
            s["cost"] = e
        elif t == "memory_snapshot":
            if e["source"] == "memory_analysis":
                s["mem_analysis"] = e["stats"]
            else:  # device snapshots, keyed by their `when` label
                s["device_mem"][e.get("when") or f"snap{len(s['device_mem'])}"] = (
                    e["stats"]
                )
        elif t == "run_completed":
            s["completed"] = e
    return s


def _fmt_bytes(n: float) -> str:
    """Human bytes with binary units (exact ints below 1 KiB)."""
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.0f} {unit}" if unit == "B" else f"{n:.1f} {unit}"
        n /= 1024
    return f"{n:.1f} TiB"  # unreachable; keeps type-checkers calm


def _timeline(positions: list[int], rows: int, bins: int = _TIMELINE_BINS) -> str:
    """Ascii density sparkline of drift positions over the stream."""
    counts = [0] * bins
    span = max(rows, max(positions) + 1)
    for pos in positions:
        counts[min(pos * bins // span, bins - 1)] += 1
    peak = max(counts)
    if peak == 0:
        return "|" + " " * bins + "|"
    levels = len(_TIMELINE_GLYPHS) - 1
    body = "".join(
        _TIMELINE_GLYPHS[(c * levels + peak - 1) // peak] if c else " "
        for c in counts
    )
    return f"|{body}|  (peak {peak}/bin)"


def render_report(events: list[dict]) -> str:
    s = summarize(events)
    cfg = s["config"]
    out = []
    out.append(f"run        {s['run_id'] or '<no run_started event>'}")
    if cfg:
        out.append(
            f"config     dataset={cfg.get('dataset')}  model={cfg.get('model')}"
            f"  detector={cfg.get('detector')}"
        )
        out.append(
            f"           partitions={cfg.get('partitions')}"
            f"  per_batch={cfg.get('per_batch')}"
            f"  mult_data={cfg.get('mult_data')}  seed={cfg.get('seed')}"
        )

    done = s["completed"]
    rows = int(done["rows"]) if done else 0
    if s["phases"]:
        total = sum(s["phases"].values())
        out.append("phases")
        for name, secs in sorted(
            s["phases"].items(), key=lambda kv: -kv[1]
        ):
            pct = 100.0 * secs / total if total > 0 else 0.0
            out.append(f"  {name:<12} {secs:9.4f} s  {pct:5.1f}%")
    if s["compile"] is not None:
        c = s["compile"]
        out.append(
            f"compile    build {c['seconds']:.4f} s"
            f"  (runner cache {'hit' if c['cached'] else 'miss'})"
        )
    if done:
        rps = done.get("rows_per_sec") or (
            rows / done["seconds"] if done["seconds"] > 0 else float("nan")
        )
        out.append(
            f"throughput {rps:,.0f} rows/s  "
            f"({rows:,} rows / {done['seconds']:.4f} s Final Time)"
        )
    else:
        out.append("throughput <run incomplete: no run_completed event>")
        hb = s["heartbeat"]
        if hb is not None:
            # An incomplete log with heartbeats: say how far it got (the
            # live view is `watch`; this is the post-mortem of the pulse).
            out.append(
                f"progress   {int(hb['rows_done']):,} rows in "
                f"{hb['elapsed_s']:.1f} s at last heartbeat"
            )

    # Achieved vs available (telemetry.profile): what the compiler's cost
    # model says one runner execution is worth, against the detect phase's
    # wall-clock — over-firing kernels (flops per row jumps) and host-bound
    # runs (tiny achieved GFLOP/s with a healthy detect share) read
    # differently here, offline.
    cost = s["cost"] or {}
    flops = cost.get("flops")
    if s["cost"] is not None:
        where = cost.get("where") or "runner"
        parts = []
        if flops is not None:
            parts.append(f"flops {flops:.4g}")
        if cost.get("bytes_accessed") is not None:
            parts.append(f"bytes accessed {_fmt_bytes(cost['bytes_accessed'])}")
        out.append(
            "cost model "
            + ("  ".join(parts) if parts else "<backend reported none>")
            + f"  ({where}, per execution)"
        )
    if s["mem_analysis"]:
        ma = s["mem_analysis"]
        segs = [
            f"{label} {_fmt_bytes(ma[k])}"
            for k, label in (
                ("argument_bytes", "args"),
                ("output_bytes", "out"),
                ("temp_bytes", "peak temp"),
                ("generated_code_bytes", "code"),
            )
            if ma.get(k) is not None
        ]
        if segs:
            out.append("xla memory " + "  ".join(segs))
    if s["device_mem"]:
        segs = []
        # emit order, not alphabetical: before_detect must read before
        # after_detect or the across-the-span delta reads backwards
        for when, st in s["device_mem"].items():
            if st.get("bytes_in_use") is not None:
                segs.append(f"{when} {_fmt_bytes(st['bytes_in_use'])}")
        peak = max(
            (
                st.get("peak_bytes_in_use", 0) or 0
                for st in s["device_mem"].values()
            ),
            default=0,
        )
        if peak:
            segs.append(f"peak {_fmt_bytes(peak)}")
        if segs:
            out.append("device mem in use: " + "  ".join(segs))
    detect_s = s["phases"].get("detect")
    if flops and detect_s:
        line = (
            f"achieved   {flops / detect_s / 1e9:.3f} GFLOP/s over detect "
            f"{detect_s:.4f} s  (cost-model flops / detect wall-clock)"
        )
        if rows:
            line += f"  ·  {flops / rows:.1f} flops/row"
        out.append(line)

    drifts = s["drifts"]
    # Incomplete-log fallback: streaming engines report detections via
    # their chunk/leg progress events, not per-drift events — sum whatever
    # the log carries (a log has one producer, so these never overlap).
    n_det = (
        done["detections"]
        if done
        else len(drifts)
        + sum(int(c["detections"] or 0) for c in s["chunks"])
        + sum(int(leg["detections"]) for leg in s["legs"])
    )
    out.append(f"detections {n_det}")
    if drifts:
        positions = [int(d["global_pos"]) for d in drifts]
        out.append("drift timeline (stream position, left→right)")
        out.append("  " + _timeline(positions, rows))
        delays = [
            d["delay_rows"] for d in drifts if d["delay_rows"] is not None
        ]
        if delays:
            mean = sum(delays) / len(delays)
            out.append(
                f"  delay mean {mean:.1f} rows"
                f"  min {min(delays)}  max {max(delays)}"
            )
        per_part: dict[int, int] = {}
        for d in drifts:
            per_part[int(d["partition"])] = (
                per_part.get(int(d["partition"]), 0) + 1
            )
        out.append("per-partition detections")
        parts = sorted(per_part)
        for i in range(0, len(parts), 8):
            out.append(
                "  "
                + "  ".join(f"p{q}:{per_part[q]}" for q in parts[i : i + 8])
            )
    if s["quarantine"] is not None:
        q = s["quarantine"]
        line = (
            f"quarantine {int(q['rows'])} row(s) masked out "
            f"(data_policy={q['policy']})"
        )
        if q.get("repaired"):
            line += f", {int(q['repaired'])} cell-repaired row(s)"
        if q.get("sidecar"):
            line += f"  sidecar {q['sidecar']}"
        out.append(line)
    if s["retrains"]:
        out.append(
            f"retrains   {s['retrains']}  ({s['forced_retrains']} forced "
            "by the saturation guard)"
        )
    if s["alerts"]:
        # SLO alert trail (telemetry.slo, serving runs): every crossing,
        # in order, plus whatever is still firing at the log's end.
        firing: dict[str, dict] = {}
        for a in s["alerts"]:
            if a["state"] == "firing":
                firing[a["rule"]] = a
            else:
                firing.pop(a["rule"], None)
        trail = ", ".join(
            f"{a['rule']} {a['state']} at {a['value']:.4g} (>{a['threshold']:g})"
            for a in s["alerts"]
        )
        out.append(f"alerts     {len(s['alerts'])} transition(s): {trail}")
        if firing:
            out.append(
                "           STILL FIRING: " + ", ".join(sorted(firing))
            )
    if s["retried"]:
        # Supervisor retry trail (resilience.supervisor): how many
        # attempts were re-run and why the last one failed — the healed
        # run's registry records carry the matching `attempt` fields.
        last = s["retried"][-1]
        out.append(
            f"retries    {len(s['retried'])} attempt(s) re-run "
            f"(last: attempt {last['attempt']}/{last['max_attempts']} — "
            f"{last['reason']}; backoff {last['backoff_s']:.2f} s)"
        )
    if s["chunks"]:
        last = s["chunks"][-1]
        det = sum(int(c["detections"] or 0) for c in s["chunks"])
        out.append(
            f"chunks     {len(s['chunks'])} processed, "
            f"{last['batches_done']} batches, {det} detections"
        )
    if s["legs"]:
        leg_rows = sum(int(leg["rows"]) for leg in s["legs"])
        det = sum(int(leg["detections"]) for leg in s["legs"])
        out.append(
            f"legs       {len(s['legs'])} completed, {leg_rows:,} rows, "
            f"{det} detections"
        )
    if s["spans"]:
        out.append(
            f"tracing    {s['spans']} span(s) over "
            f"{len(s['trace_ids'])} trace(s)  "
            "(render: the `timeline` CLI)"
        )
    if s["forensics"]:
        newest = s["forensics"][-1]
        out.append(
            f"forensics  {len(s['forensics'])} drift evidence bundle(s)  "
            f"(newest: {newest['bundle']}; render: the `explain` CLI)"
        )
    return "\n".join(out)


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(
        prog="python -m distributed_drift_detection_tpu report",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument(
        "run_log",
        nargs="*",
        help="run-log *.jsonl path(s); a directory renders its newest run",
    )
    ap.add_argument(
        "--dir",
        default=None,
        metavar="DIR",
        help="render a telemetry directory's newest run log (registry-"
        "first resolution; falls back to newest *.jsonl by mtime)",
    )
    args = ap.parse_args(argv)

    def resolve(p: str) -> str:
        if not os.path.isdir(p):
            return p
        newest = newest_run_log(p)
        if newest is None:
            raise SystemExit(f"report: no run logs in {p}")
        return newest

    paths = [resolve(p) for p in args.run_log]
    if args.dir is not None:
        paths.append(resolve(args.dir))
    if not paths:
        ap.error("give run-log path(s) or --dir")
    for i, path in enumerate(paths):
        if i:
            print()
        # Torn-tail tolerant: a crashed or still-writing run is exactly
        # what this post-mortem must render (strict validation is the CI
        # smoke gate's separate read_events call, not this CLI).
        print(render_report(read_events(path, allow_partial_tail=True)))


if __name__ == "__main__":
    main(sys.argv[1:])
