"""Run registry: an append-only ``index.jsonl`` per telemetry directory.

The event logs answer "what did run X do"; the registry answers the fleet
question that comes first — **which runs exist here, what configuration
was each, and did it finish**. One telemetry directory (a grid sweep, a
pod launch, a soak farm) accumulates one ``index.jsonl``: every record is
a status transition ``{ts, run_id, status, ...}`` appended by the
producer (``api.run`` around each telemetered run; ``harness.grid``
around a sweep), so the index is a timeline of the directory's activity
and the *latest* record per ``run_id`` is its current state:

* ``running`` — carries the run's ``config_digest`` (stable SHA-256 of
  the canonical config JSON: two runs with the same digest are the same
  cell, the grid-comparison key), the log's filename, and any host
  identity extras the producer adds.
* ``completed`` / ``failed`` — terminal; ``failed`` is written by
  ``api.run``'s exception path, so a crashed run is *recorded* as
  crashed, not just absent (its partial event log is the evidence; the
  registry is the pointer to it).

Append-only JSONL, flushed per record, same crash posture as the event
sink — and the same torn-tail tolerance on read (a record lost mid-write
costs one status transition, never the index). Pure stdlib, no jax: the
``watch``/``report``/``correlate`` CLIs read it wherever the artifacts
land.
"""

from __future__ import annotations

import contextlib
import contextvars
import glob
import hashlib
import json
import os
import time

INDEX_NAME = "index.jsonl"

# Row-record sidecar suffixes that live next to a run log but are not run
# logs: quarantine sidecars (io.sanitize), the serving daemon's verdict /
# heartbeat sidecars (serve.runner), and placement journals (serve.router's
# ``router.journal.jsonl``, the scheduler's ``sched.journal.jsonl``).
# ``newest_run_log`` must never resolve one — on a *live* serving directory
# the verdict sidecar is usually the most recently appended ``*.jsonl``, and
# resolving it would hand ``report --dir`` / ``watch <dir>`` a file that
# fails event-schema validation.
SIDECAR_SUFFIXES = (
    "quarantine.jsonl",
    "verdicts.jsonl",
    "heartbeat.jsonl",
    "flightrec.jsonl",
    "journal.jsonl",
)

# The only statuses the fold recognizes; producers writing anything else
# fail loudly at append time, not at read time on another machine.
STATUSES = ("running", "completed", "failed")


def config_digest(config: dict) -> str:
    """Stable short digest of a run configuration: canonical (sorted-key)
    JSON, SHA-256, first 12 hex chars. Same config → same digest across
    processes and sessions, so a multi-host run's N per-process records
    (and a sweep's repeated trials of one cell) correlate by digest."""
    canon = json.dumps(config, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(canon.encode()).hexdigest()[:12]


def index_path(telemetry_dir: str) -> str:
    return os.path.join(telemetry_dir, INDEX_NAME)


# Supervision context (resilience.supervisor): while an attempt scope is
# active, every record the producer writes carries an ``attempt`` field —
# so a supervised run's index timeline reads failed(attempt=1) →
# completed(attempt=2), not as two unexplained runs. A contextvar, not a
# parameter, because the producer (api.run) is policy-agnostic: it must
# not need to know whether something above it is retrying.
_ATTEMPT: contextvars.ContextVar["int | None"] = contextvars.ContextVar(
    "registry_attempt", default=None
)


@contextlib.contextmanager
def attempt_scope(attempt: "int | None"):
    """Bracket one supervised attempt: records written inside carry
    ``attempt`` (1-based) unless they set their own."""
    token = _ATTEMPT.set(None if attempt is None else int(attempt))
    try:
        yield
    finally:
        _ATTEMPT.reset(token)


def current_attempt() -> "int | None":
    return _ATTEMPT.get()


def _open_locked_append(path: str):
    """Open ``path`` for append with an exclusive ``flock``, re-opening
    if a compaction replaced the inode between open and lock (the
    standard flock-with-rename dance: without the re-stat, a writer that
    opened the pre-compaction file would append its record to an
    unlinked inode and silently lose it). Non-POSIX / no-flock
    filesystems degrade to the plain append the registry always did."""
    while True:
        fh = open(path, "a")
        try:
            import fcntl

            fcntl.flock(fh, fcntl.LOCK_EX)
        except (ImportError, OSError):
            return fh  # best-effort append (pre-compaction behaviour)
        try:
            if os.fstat(fh.fileno()).st_ino == os.stat(path).st_ino:
                return fh
        except OSError:
            pass  # replaced and momentarily absent: reopen
        fh.close()


def record(telemetry_dir: str, run_id: str, status: str, **extras) -> dict:
    """Append one status record; returns it. Creates the directory and
    index on first use. ``extras`` ride along verbatim (``config_digest``,
    ``log``, host identity, sweep totals, ...). Inside an
    :func:`attempt_scope` the record additionally carries ``attempt``."""
    if status not in STATUSES:
        raise ValueError(
            f"unknown registry status {status!r}; expected one of {STATUSES}"
        )
    attempt = _ATTEMPT.get()
    if attempt is not None:
        extras.setdefault("attempt", attempt)
    rec = {"ts": time.time(), "run_id": str(run_id), "status": status, **extras}
    os.makedirs(telemetry_dir, exist_ok=True)
    fh = _open_locked_append(index_path(telemetry_dir))
    with fh:
        fh.write(json.dumps(rec) + "\n")
        fh.flush()
        # fsync like the results CSV: the registry is what `heal` diffs a
        # sweep spec against, so a `completed` that evaporates in a power
        # loss would make heal re-run (duplicate) a recorded trial.
        os.fsync(fh.fileno())
    return rec


def read_index(telemetry_dir: str) -> list[dict]:
    """All records in append order; ``[]`` when no index exists yet.
    Torn-tail tolerant (a writer may be mid-append right now); an interior
    malformed line is corruption and raises."""
    path = index_path(telemetry_dir)
    if not os.path.exists(path):
        return []
    records = []
    with open(path) as fh:
        lines = fh.readlines()
    for lineno, line in enumerate(lines, 1):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            if lineno == len(lines):
                break  # torn trailing record: one transition lost, not the index
            raise ValueError(f"{path}:{lineno}: corrupt registry record")
        if isinstance(rec, dict) and rec.get("run_id"):
            records.append(rec)
    return records


def runs(telemetry_dir: str) -> dict[str, dict]:
    """Fold the index into current state: ``run_id`` → latest record, with
    ``started_ts`` preserved from the run's first record (the fold's one
    derived field — 'newest run' means newest *start*, not newest status
    flip: a week-old run failing now must not outrank today's)."""
    out: dict[str, dict] = {}
    for rec in read_index(telemetry_dir):
        prev = out.get(rec["run_id"])
        folded = dict(rec)
        folded["started_ts"] = (
            prev["started_ts"] if prev is not None else rec["ts"]
        )
        if prev is not None:  # status records may omit the start's extras
            folded = {**prev, **folded}
        out[rec["run_id"]] = folded
    return out


def newest_run_log(telemetry_dir: str) -> str | None:
    """Resolve the directory's newest run log — the shared resolution
    behind ``report --dir`` and ``watch <dir>``.

    Registered runs are ranked by *start* time (the registry knows start
    order exactly; a status flip on an old run must not outrank a newer
    start). Logs the registry never heard of — producers driving
    ``EventLog.open_run`` directly (streaming examples, the multihost
    worker), or pre-registry artifacts — compete by mtime: a directory
    mixing both must resolve to whichever run is actually newest, not to
    whatever happens to be indexed. The index itself is never a
    candidate."""
    registered: set[str] = set()
    best_reg: "tuple[float, str] | None" = None  # (recency, path)
    for rec in runs(telemetry_dir).values():
        log = rec.get("log")
        if not log:
            continue
        registered.add(log)
        path = os.path.join(telemetry_dir, log)
        if not os.path.exists(path):
            continue
        # Recency = the later of start and last write: a long-lived run
        # still appending must not lose to anything that merely happened
        # after it *started*.
        recency = max(rec["started_ts"], os.path.getmtime(path))
        if best_reg is None or recency > best_reg[0]:
            best_reg = (recency, path)
    unregistered = [
        p
        for p in glob.glob(os.path.join(telemetry_dir, "*.jsonl"))
        if os.path.basename(p) != INDEX_NAME
        and os.path.basename(p) not in registered
        # sidecars (quarantine rows, serve verdicts/heartbeats) live next
        # to their run log but are row records, not event logs — never
        # "the newest run", even while being actively appended to
        and not os.path.basename(p).endswith(SIDECAR_SUFFIXES)
    ]
    best_unreg: "tuple[float, str] | None" = None
    if unregistered:
        path = max(unregistered, key=os.path.getmtime)
        best_unreg = (os.path.getmtime(path), path)
    if best_reg is not None and best_unreg is not None:
        # Both recencies are wall-clock stamps from the same host — the
        # more recently alive run wins, registered or not.
        return max(best_reg, best_unreg)[1]
    for best in (best_reg, best_unreg):
        if best is not None:
            return best[1]
    return None


# --- compaction --------------------------------------------------------------
#
# A long-lived producer (the sched/ scheduler appends a record per lease
# attempt; a serving farm appends per run) grows index.jsonl without bound,
# and every fold (`runs()`, heal's digest diff, `newest_run_log`) re-reads
# the whole timeline. Compaction rewrites the index as ONE record per
# run_id — its current folded state, stamped with its *start* time — which
# preserves every semantic the consumers rely on:
#
# * `runs()` folds the compacted index to the same current-state map
#   (extras were already merged by the fold that produced the snapshot);
# * `newest_run_log` ranks registered runs by start (ts == started_ts);
# * heal / sched audit digest-matching sees the same `completed` multiset.
#
# What it deliberately drops is the *history* (failed→completed attempt
# timelines collapse to the final state, with the latest record's fields);
# the per-run event logs remain the evidence trail.


def compact_index(telemetry_dir: str) -> "dict | None":
    """Atomically compact ``index.jsonl`` to one record per run; returns
    ``{records_before, records_after}`` (``None`` when there is nothing
    to compact).

    Crash-safe by construction: the snapshot is written to a temp file,
    fsynced, and ``os.replace``d over the index — a compaction torn at
    any point leaves either the intact old index (+ a stray ``*.tmp``
    the next compaction overwrites) or the complete new one, never a
    half-written index. Concurrent appenders are excluded by the same
    ``flock`` :func:`record` takes (and re-check the inode after locking,
    so no record can land on the unlinked pre-compaction file)."""
    path = index_path(telemetry_dir)
    if not os.path.exists(path):
        return None
    with open(path, "a") as lock_fh:
        try:
            import fcntl

            fcntl.flock(lock_fh, fcntl.LOCK_EX)
        except (ImportError, OSError):
            pass  # best-effort exclusion (same posture as record())
        before = read_index(telemetry_dir)
        if not before:
            return None
        folded = sorted(
            runs(telemetry_dir).values(), key=lambda r: r["started_ts"]
        )
        tmp = f"{path}.compact-{os.getpid()}.tmp"
        with open(tmp, "w") as fh:
            for rec in folded:
                rec = dict(rec)
                # ts = start: the fold re-derives started_ts from the
                # first (now only) record, keeping newest_run_log's
                # newest-*start* ranking exact across compaction.
                rec["ts"] = rec.pop("started_ts")
                fh.write(json.dumps(rec) + "\n")
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
    return {"records_before": len(before), "records_after": len(folded)}


# Amortization state for maybe_compact: index path → record count right
# after its last compaction in this process (the floor the index cannot
# shrink below — one folded record per run). Without it, a directory
# whose *distinct-run* count exceeds the threshold would trigger a full
# O(n) rewrite on every subsequent append, quadratic in sweep size.
_COMPACT_FLOOR: "dict[str, int]" = {}


def maybe_compact(telemetry_dir: str, *, max_records: int) -> "dict | None":
    """Compact when the index holds more than ``max_records`` records —
    the auto-compaction hook a long-lived scheduler calls as completions
    land. Cheap when under threshold (one line count, no JSON parse),
    and amortized O(1) per append past it: once a compaction has run,
    the next one waits until the index doubles past that compaction's
    floor (compaction cannot shrink below one record per run, so
    re-compacting sooner would be a full rewrite for nothing)."""
    if max_records <= 0:
        return None
    path = index_path(telemetry_dir)
    try:
        with open(path, "rb") as fh:
            lines = sum(1 for _ in fh)
    except OSError:
        return None
    key = os.path.realpath(path)
    if lines <= max(max_records, 2 * _COMPACT_FLOOR.get(key, 0)):
        return None
    out = compact_index(telemetry_dir)
    if out is not None:
        _COMPACT_FLOOR[key] = out["records_after"]
    return out


def main(argv=None) -> None:
    """``registry`` subcommand: jax-free index maintenance.

        python -m distributed_drift_detection_tpu registry compact DIR \\
            [--min-records N]

    ``compact`` rewrites DIR's ``index.jsonl`` as one record per run
    (see :func:`compact_index`); with ``--min-records`` it is a no-op
    below the threshold (the cron-safe form). Exit 0 either way; the
    summary goes to stdout."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m distributed_drift_detection_tpu registry",
        description=main.__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("action", choices=["compact"])
    ap.add_argument("dir", help="telemetry directory (holds index.jsonl)")
    ap.add_argument(
        "--min-records", type=int, default=0, metavar="N",
        help="only compact past N records (default: always)",
    )
    args = ap.parse_args(argv)
    if args.min_records > 0:
        out = maybe_compact(args.dir, max_records=args.min_records)
    else:
        out = compact_index(args.dir)
    if out is None:
        print("registry: nothing to compact")
    else:
        print(
            f"registry: compacted {out['records_before']} → "
            f"{out['records_after']} records"
        )


if __name__ == "__main__":
    import sys

    main(sys.argv[1:])
