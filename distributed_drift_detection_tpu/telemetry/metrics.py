"""Metrics registry: counters, gauges, histograms with two exporters.

A deliberately small, dependency-free subset of the Prometheus client
model — enough to persist "how much work did this process do" next to the
event log (:mod:`.events` answers "what happened when"):

* ``counter`` — monotone totals (``detections_total{partition="3"}``,
  ``rows_processed_total``); negative increments are rejected.
* ``gauge`` — last-written value (``compile_seconds``).
* ``histogram`` — cumulative-bucket distributions (``phase_seconds``),
  Prometheus semantics: ``_bucket{le=...}`` counts are cumulative,
  ``+Inf`` equals ``_count``, plus ``_sum``.

Exporters: :meth:`MetricsRegistry.to_json` (one dict, stable ordering) and
:meth:`MetricsRegistry.to_prometheus_text` (the text exposition format,
deterministic — sorted names, sorted label sets, ``le`` rendered last —
so golden tests can pin it byte-for-byte). :func:`parse_prometheus_text`
closes the round trip for tests and ad-hoc scraping.

No jax imports; safe anywhere, including the feeder's producer thread
(each sample is one dict write — the GIL makes that atomic enough for the
single-producer use here).
"""

from __future__ import annotations

import json
import re

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# Wall-clock-seconds buckets: sub-ms dispatch latencies up to multi-minute
# soak legs.
DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 300.0,
)


def _label_key(labels: dict) -> tuple:
    for name in labels:
        if not _LABEL_RE.match(name):
            raise ValueError(f"invalid label name {name!r}")
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def _fmt(v: float) -> str:
    """Deterministic number rendering: integral values print as integers
    (Prometheus counters are conventionally integer-looking), the rest via
    repr (shortest round-trippable float)."""
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


class Counter:
    kind = "counter"

    def __init__(self, name: str, help: str):
        self.name, self.help = name, help
        self.values: dict[tuple, float] = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise ValueError(
                f"counter {self.name} increment must be >= 0, got {amount}"
            )
        k = _label_key(labels)
        self.values[k] = self.values.get(k, 0.0) + amount


class Gauge:
    kind = "gauge"

    def __init__(self, name: str, help: str):
        self.name, self.help = name, help
        self.values: dict[tuple, float] = {}

    def set(self, value: float, **labels) -> None:
        self.values[_label_key(labels)] = float(value)


class Histogram:
    kind = "histogram"

    def __init__(self, name: str, help: str, buckets=DEFAULT_BUCKETS):
        if list(buckets) != sorted(set(buckets)):
            raise ValueError(f"histogram buckets must be sorted/unique: {buckets}")
        self.name, self.help = name, help
        self.buckets = tuple(float(b) for b in buckets)
        # label key -> [per-bucket counts (+1 overflow slot), sum, count]
        self.values: dict[tuple, list] = {}

    def observe(self, value: float, **labels) -> None:
        k = _label_key(labels)
        slot = self.values.get(k)
        if slot is None:
            slot = self.values[k] = [[0] * (len(self.buckets) + 1), 0.0, 0]
        counts, _, _ = slot
        for i, b in enumerate(self.buckets):
            if value <= b:
                counts[i] += 1
                break
        else:
            counts[-1] += 1
        slot[1] += float(value)
        slot[2] += 1

    def cumulative(self, key: tuple) -> list[tuple[str, int]]:
        """``(le, cumulative count)`` pairs ending with ``+Inf``."""
        counts, _, total = self.values[key]
        out, acc = [], 0
        for b, c in zip(self.buckets, counts):
            acc += c
            out.append((_fmt(b), acc))
        out.append(("+Inf", total))
        return out


class MetricsRegistry:
    """Named metrics, created on first use, re-fetched idempotently (a
    kind/bucket mismatch on re-registration fails loudly)."""

    def __init__(self):
        self._metrics: dict[str, object] = {}

    def _get(self, cls, name: str, help: str, **kw):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        m = self._metrics.get(name)
        if m is None:
            m = self._metrics[name] = cls(name, help, **kw)
        elif not isinstance(m, cls):
            raise ValueError(
                f"metric {name!r} already registered as {m.kind}"
            )
        elif kw.get("buckets") and tuple(kw["buckets"]) != m.buckets:
            raise ValueError(f"metric {name!r} re-registered with new buckets")
        return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)

    def histogram(
        self, name: str, help: str = "", buckets=DEFAULT_BUCKETS
    ) -> Histogram:
        return self._get(Histogram, name, help, buckets=buckets)

    # -- exporters -----------------------------------------------------------

    def to_json(self) -> dict:
        """Stable dict form: metric name -> kind/help/samples."""
        out = {}
        # .copy(): exporters may run on a scraping thread (the serving
        # daemon's ops plane) while producers insert first-use metrics —
        # a dict snapshot keeps iteration safe; per-sample reads are
        # GIL-atomic enough for a monitoring scrape.
        for name in sorted(self._metrics.copy()):
            m = self._metrics[name]
            samples = []
            for key in sorted(m.values.copy()):
                labels = dict(key)
                if m.kind == "histogram":
                    _, total_sum, count = m.values[key]
                    samples.append(
                        {
                            "labels": labels,
                            "count": count,
                            "sum": total_sum,
                            "buckets": {
                                le: c for le, c in m.cumulative(key)
                            },
                        }
                    )
                else:
                    samples.append(
                        {"labels": labels, "value": m.values[key]}
                    )
            out[name] = {"kind": m.kind, "help": m.help, "samples": samples}
        return out

    def to_prometheus_text(self) -> str:
        """Prometheus text exposition format, deterministically ordered.
        Safe to call from a scraping thread (see :meth:`to_json`)."""
        lines = []
        for name in sorted(self._metrics.copy()):
            m = self._metrics[name]
            # HELP and TYPE for EVERY series (exposition-format
            # conformance: scrapers key docs off HELP presence); an empty
            # help renders as a bare `# HELP name` line, never skipped.
            lines.append(f"# HELP {name} {_escape(m.help)}".rstrip())
            lines.append(f"# TYPE {name} {m.kind}")
            for key in sorted(m.values.copy()):
                if m.kind == "histogram":
                    _, total_sum, count = m.values[key]
                    for le, c in m.cumulative(key):
                        lines.append(
                            f"{name}_bucket{_render(key, le=le)} {c}"
                        )
                    lines.append(f"{name}_sum{_render(key)} {_fmt(total_sum)}")
                    lines.append(f"{name}_count{_render(key)} {count}")
                else:
                    lines.append(f"{name}{_render(key)} {_fmt(m.values[key])}")
        return "\n".join(lines) + "\n"


def _render(key: tuple, le: str | None = None) -> str:
    pairs = [f'{k}="{_escape(v)}"' for k, v in key]
    if le is not None:
        pairs.append(f'le="{le}"')  # convention: le last
    return "{" + ",".join(pairs) + "}" if pairs else ""


_ESCAPES = {"\\": "\\", '"': '"', "n": "\n"}


def _unescape(v: str) -> str:
    # Single left-to-right pass (inverse of _escape): sequential str.replace
    # would re-scan the output of earlier replacements and corrupt values
    # like 'C:\new' (escaped 'C:\\new', where the literal backslash's escape
    # must not pair with the following 'n').
    return re.sub(
        r"\\(.)", lambda m: _ESCAPES.get(m.group(1), m.group(0)), v
    )


def parse_prometheus_text(text: str) -> dict[tuple, float]:
    """Inverse of :meth:`MetricsRegistry.to_prometheus_text` for tests and
    ad-hoc scraping: ``{(sample name, ((label, value), ...)): value}``."""
    out: dict[tuple, float] = {}
    sample_re = re.compile(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{(.*)\})?\s+(\S+)$")
    label_re = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = sample_re.match(line)
        if not m:
            raise ValueError(f"unparseable sample line: {line!r}")
        name, _, labelstr, value = m.groups()
        labels = tuple(
            (k, _unescape(v)) for k, v in label_re.findall(labelstr or "")
        )
        out[(name, labels)] = float(value)
    return out


def write_exports(registry: MetricsRegistry, base_path: str) -> tuple[str, str]:
    """Write both exporter outputs next to a run log: ``<base>.metrics.json``
    and ``<base>.prom``; returns the two paths."""
    json_path = base_path + ".metrics.json"
    prom_path = base_path + ".prom"
    with open(json_path, "w") as fh:
        json.dump(registry.to_json(), fh, indent=1)
        fh.write("\n")
    with open(prom_path, "w") as fh:
        fh.write(registry.to_prometheus_text())
    return json_path, prom_path
