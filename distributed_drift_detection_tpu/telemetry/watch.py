"""Live run watcher: tail a run log (or a telemetry directory's newest
run), render progress/ETA, and fail loudly on a stall.

    python -m distributed_drift_detection_tpu watch <run.jsonl | dir> \\
        [--stall-after S] [--interval S] [--once]

The run log is flushed per event precisely so a long chunked/soak run is
observable *while running*; this is the consumer. It tails the file
incrementally (re-reading only new bytes, tolerant of a torn final line —
the writer may be mid-append), folds progress events (``heartbeat`` rows
done + monotonic elapsed, ``chunk_completed``/``leg_completed``) into a
status line with throughput and — when ``run_started.config`` carries
``total_rows`` — an ETA, and exits by a **scriptable health contract**:

* ``0`` — healthy: the run completed (``run_completed`` seen), or, with
  ``--once``, is making progress within ``--stall-after``.
* ``3`` — stalled: no new event for more than ``--stall-after`` seconds
  and no ``run_completed``. CI gates and pod launchers branch on this.
* ``4`` — nothing to watch: no run log at/under the given path.
* ``2`` — usage errors (argparse).

Staleness compares the log's own event timestamps against this process's
clock, so run the watcher on the writing host or an NTP-synced peer; an
empty-so-far log falls back to its file mtime. Without ``--stall-after``
the watcher never exits nonzero on silence — it just keeps reporting.

Pure stdlib + the schema module; no jax — runs on the pod host, in CI,
or anywhere the artifact is mirrored.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from .events import SchemaError, validate_event
from .registry import newest_run_log

EXIT_OK = 0
EXIT_STALLED = 3
EXIT_NO_LOG = 4


class LogTail:
    """Incremental JSONL reader: each :meth:`poll` yields the complete,
    schema-valid events appended since the last poll.

    The offset only ever advances past the final newline consumed, so a
    torn trailing line (writer mid-append, crash mid-write) is simply not
    consumed yet — it is re-read on the next poll once its newline lands.
    A *complete* malformed line is a producer bug and raises
    :class:`SchemaError` (the emit path validates, so this never happens
    to a log this package wrote).
    """

    def __init__(self, path: str):
        self.path = path
        self._offset = 0

    def poll(self) -> list[dict]:
        with open(self.path, "rb") as fh:
            fh.seek(self._offset)
            blob = fh.read()
        end = blob.rfind(b"\n")
        if end < 0:
            return []
        chunk, self._offset = blob[: end + 1], self._offset + end + 1
        events = []
        for line in chunk.decode("utf-8", errors="replace").splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                events.append(validate_event(json.loads(line)))
            except json.JSONDecodeError as e:
                raise SchemaError(
                    f"{self.path}: complete line is not JSON ({e})"
                ) from None
        return events


class WatchState:
    """Folded view of the events seen so far (the watcher's data model)."""

    def __init__(self) -> None:
        self.run_id: str | None = None
        self.config: dict = {}
        self.total_rows: int | None = None
        self.rows_done: int | None = None
        self.elapsed_s: float | None = None
        # First heartbeat seen: rates come from heartbeat DELTAS, so a
        # checkpoint-resumed soak (stream-absolute rows_done, this-process
        # elapsed) cannot inflate the reported throughput.
        self._first_hb: tuple[int, float] | None = None
        self.detections = 0
        self.chunks = 0
        self.legs = 0
        # Currently firing SLO alerts (serving daemons emit `alert`
        # transitions; firing adds, resolved removes) — rendered in the
        # status line so a watched daemon's degradation is visible
        # without scraping /healthz.
        self.alerts: dict[str, dict] = {}
        self.n_events = 0
        self.last_ts: float | None = None
        self.last_type: str | None = None
        self.completed: dict | None = None

    def fold(self, events: list[dict]) -> None:
        for e in events:
            self.n_events += 1
            self.last_ts, self.last_type = float(e["ts"]), e["type"]
            t = e["type"]
            if t == "run_started":
                self.run_id = e["run_id"]
                self.config = e.get("config") or {}
                total = self.config.get("total_rows")
                if isinstance(total, (int, float)) and total > 0:
                    self.total_rows = int(total)
            elif t == "heartbeat":
                self.rows_done = int(e["rows_done"])
                self.elapsed_s = float(e["elapsed_s"])
                if self._first_hb is None:
                    self._first_hb = (self.rows_done, self.elapsed_s)
            elif t == "drift_detected":
                self.detections += 1
            elif t == "chunk_completed":
                self.chunks += 1
                self.detections += int(e["detections"] or 0)
            elif t == "leg_completed":
                self.legs += 1
                self.detections += int(e["detections"] or 0)
            elif t == "alert":
                if e["state"] == "firing":
                    self.alerts[e["rule"]] = e
                else:
                    self.alerts.pop(e["rule"], None)
            elif t == "run_completed":
                self.completed = e

    def rate(self) -> float | None:
        """Rows/s from heartbeat deltas (single-heartbeat logs fall back
        to that beat's own ratio); ``None`` until a positive rate exists."""
        if self.rows_done is None or not self.elapsed_s:
            return None
        r0, e0 = self._first_hb or (0, 0.0)
        if self.elapsed_s > e0 and self.rows_done > r0:
            return (self.rows_done - r0) / (self.elapsed_s - e0)
        if self.elapsed_s > 0 and self.rows_done > 0:
            return self.rows_done / self.elapsed_s
        return None

    def status_line(self, now: float) -> str:
        bits = [self.run_id or "<no run_started yet>"]
        if self.completed is not None:
            done = self.completed
            rate = done["rows"] / done["seconds"] if done["seconds"] else 0.0
            bits.append(
                f"completed: {done['rows']:,} rows / {done['seconds']:.3f}s "
                f"({rate:,.0f} rows/s), {done['detections']} detections"
            )
            return "  ".join(bits)
        if self.rows_done is not None:
            prog = f"rows {self.rows_done:,}"
            if self.total_rows:
                pct = 100.0 * self.rows_done / self.total_rows
                prog += f"/{self.total_rows:,} ({pct:.1f}%)"
            bits.append(prog)
            rate = self.rate()
            if rate:
                bits.append(f"{rate:,.0f} rows/s")
                if self.total_rows:
                    remaining = max(self.total_rows - self.rows_done, 0)
                    bits.append(f"eta {remaining / rate:,.0f}s")
        if self.chunks:
            bits.append(f"{self.chunks} chunks")
        if self.legs:
            bits.append(f"{self.legs} legs")
        if self.detections:
            bits.append(f"{self.detections} detections")
        if self.alerts:
            bits.append("ALERTS " + ",".join(sorted(self.alerts)))
        if self.last_ts is not None:
            bits.append(f"last {self.last_type} {now - self.last_ts:.1f}s ago")
        return "  ".join(bits)


def resolve_log(path: str) -> str | None:
    """A file is itself; a directory resolves to its newest run log (the
    registry-first resolution shared with ``report --dir``)."""
    if os.path.isdir(path):
        return newest_run_log(path)
    return path if os.path.exists(path) else None


def staleness_s(
    last_ts: "float | None", path: "str | None" = None, *, now: float
) -> float:
    """Seconds since the last sign of life — THE stall-contract quantity.

    One copy of the semantics shared by the watch loop (event timestamps,
    falling back to the log file's mtime while the log is still empty)
    and the ``sched/`` scheduler (per-worker heartbeat stamps: a worker
    whose staleness exceeds its lease TTL is dead or wedged either way,
    exactly the ``--stall-after`` contract applied to the control
    plane). Clamped at 0 — a clock skewed slightly ahead must not read
    as negative staleness."""
    if last_ts is not None:
        return max(now - last_ts, 0.0)
    if path is not None:
        try:
            return max(now - os.path.getmtime(path), 0.0)
        except OSError:
            pass
    return 0.0


def _age(state: WatchState, log_path: str, now: float) -> float:
    return staleness_s(state.last_ts, log_path, now=now)


def watch(
    path: str,
    *,
    stall_after: float | None = None,
    interval: float = 2.0,
    once: bool = False,
    clock=time.time,
    sleep=time.sleep,
    out=print,
) -> int:
    """Drive the watch loop; returns the exit code (see module contract).
    ``clock``/``sleep``/``out`` are injectable for tests."""
    log_path = resolve_log(path)
    if log_path is None:
        out(f"watch: no run log at {path}")
        return EXIT_NO_LOG
    tail = LogTail(log_path)
    state = WatchState()
    out(f"watching {log_path}")
    while True:
        events = tail.poll()
        state.fold(events)
        now = clock()
        if events or once:
            out(state.status_line(now))
        if state.completed is not None:
            return EXIT_OK
        stalled = (
            stall_after is not None and _age(state, log_path, now) > stall_after
        )
        if stalled:
            out(
                f"STALLED: no event for {_age(state, log_path, now):.1f}s "
                f"(> --stall-after {stall_after:g}s) and no run_completed"
            )
            return EXIT_STALLED
        if once:
            return EXIT_OK
        sleep(interval)


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(
        prog="python -m distributed_drift_detection_tpu watch",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument(
        "path",
        help="a run-log *.jsonl, or a telemetry directory (newest run)",
    )
    ap.add_argument(
        "--stall-after",
        type=float,
        default=None,
        metavar="S",
        help="exit 3 when no new event lands for S seconds (and the run "
        "has not completed); default: never — report forever",
    )
    ap.add_argument(
        "--interval",
        type=float,
        default=2.0,
        metavar="S",
        help="poll interval in seconds (default 2)",
    )
    ap.add_argument(
        "--once",
        action="store_true",
        help="one health check instead of a loop: read the whole log, "
        "print the status, exit 0 healthy / 3 stalled",
    )
    args = ap.parse_args(argv)
    raise SystemExit(
        watch(
            args.path,
            stall_after=args.stall_after,
            interval=args.interval,
            once=args.once,
        )
    )


if __name__ == "__main__":
    main(sys.argv[1:])
