"""Durable time-series plane: the fleet's memory of its own metrics.

Every observability surface so far is a point-in-time scrape —
``/metrics``, ``/statusz``, ``/fleetz`` and ``top`` show the current
instant, and the SLO engine judged instantaneous threshold crossings.
Drift is a *temporal* signal, and so is fleet health: tenant-hotness
ranking, error-budget burn and rate trends all need history. This module
is that substrate — an append-only, segment-rotated on-disk series store
with the same durability idiom as every other sink in the repo (flushed
appends, atomic segment rotation, torn-tail-tolerant reads), plus the
query primitives the consumers share:

* :class:`HistoryStore` — the single writer: samples append to an active
  ``series-NNNNNNNN.jsonl`` segment (one JSON object per line, flushed
  per batch), rotation finalizes the active segment with an fsync and
  opens the next sequence number (readers only ever see whole segments
  plus at most one torn trailing line), retention drops whole finalized
  segments by age and/or total size — never the active one, never a
  partial segment.
* :func:`read_samples` / :func:`range_query` — raw and step-aligned
  reads. Downsampling is **step-aligned** (buckets are
  ``floor(ts/step)·step``) and conservative: ``agg='sum'`` over the
  buckets of a series sums to exactly the raw samples' sum (the
  property test's conservation invariant).
* :func:`rate` — per-second increase of a counter series over a window,
  counter-reset tolerant (negative deltas contribute 0, the Prometheus
  convention). Within one writer run (same ``boot`` token) elapsed time
  comes from the **monotonic** stamps, so a wall-clock step between two
  samples cannot fake or hide a rate — the correlate/timeline skew-rebase
  convention applied to scrapes.
* :func:`quantile_over_time` / :func:`avg_over_time` — windowed
  aggregates over gauge series (the burn-rate SLO food).
* :func:`top_tenants` — ranks per-tenant labeled series
  (``serve_tenant_rows_total{tenant=...}``, exported by serve daemons
  under ``--tenant-series``) by windowed rate, folding in per-tenant
  adaptation-event rates — the exact activity ranking the tenant
  residency manager (ROADMAP item 2) consumes.
* :func:`main` — the ``history`` CLI: range/rate/quantile/top-tenants
  queries with JSON or ASCII-sparkline output.

Single-writer contract: one process appends to a store directory at a
time (the collector daemon, or ``top --record``); readers are fully
concurrent — they never lock and tolerate the writer mid-append exactly
like every JSONL reader here. No jax, stdlib only.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
import time

SEGMENT_PREFIX = "series-"
SEGMENT_SUFFIX = ".jsonl"

#: Rotation default: segments stay small enough that retention (whole
#: segments only) tracks the requested bounds closely.
DEFAULT_SEGMENT_BYTES = 1 << 20

_SEGMENT_RE = re.compile(
    re.escape(SEGMENT_PREFIX) + r"(\d{8})" + re.escape(SEGMENT_SUFFIX) + "$"
)

AGGS = ("avg", "sum", "min", "max", "last", "count")

#: The per-tenant hotness series a serve daemon exports under
#: ``--tenant-series`` (telemetry/collector scrapes it into the store).
TENANT_ROWS_METRIC = "serve_tenant_rows_total"
TENANT_ROWS_HELP = (
    "Stream rows published per tenant (serve --tenant-series; "
    "cardinality-guarded — refused beyond ServeParams.tenant_series_max "
    "tenants)"
)
#: Per-tenant adaptation events already ride adaptations_total
#: (adapt.refit.ADAPT_METRIC); top_tenants folds their rate in.
TENANT_ADAPT_METRIC = "adaptations_total"


def label_key(labels: "dict | None") -> tuple:
    """Canonical series-identity tuple (sorted ``(name, value)`` pairs,
    values stringified) — the same normalization as the metrics
    registry's label keys."""
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def segment_path(root: str, seq: int) -> str:
    return os.path.join(root, f"{SEGMENT_PREFIX}{seq:08d}{SEGMENT_SUFFIX}")


def list_segments(root: str) -> list[str]:
    """Store segments in sequence order (``[]`` for a fresh/absent dir)."""
    if not os.path.isdir(root):
        return []
    paths = [
        p
        for p in glob.glob(
            os.path.join(root, SEGMENT_PREFIX + "*" + SEGMENT_SUFFIX)
        )
        if _SEGMENT_RE.search(os.path.basename(p))
    ]
    return sorted(paths)


class HistoryStore:
    """The single writer of one store directory.

    ``segment_bytes`` bounds the active segment (rotation is checked
    after each append batch); ``retention_s``/``retention_bytes`` bound
    the whole store by sample age / total size (``None`` = unbounded).
    ``boot`` tokens one writer process run: samples stamped with the
    same boot share a monotonic clock, which :func:`rate` prefers over
    wall time for elapsed-time math.
    """

    def __init__(
        self,
        root: str,
        *,
        segment_bytes: int = DEFAULT_SEGMENT_BYTES,
        retention_s: "float | None" = None,
        retention_bytes: "int | None" = None,
        boot: "str | None" = None,
    ):
        self.root = root
        self.segment_bytes = max(int(segment_bytes), 1)
        self.retention_s = retention_s
        self.retention_bytes = retention_bytes
        # One token per writer process run: pid + monotonic-origin hash.
        self.boot = boot or f"{os.getpid():x}-{int(time.monotonic() * 1e3):x}"
        os.makedirs(root, exist_ok=True)
        segments = list_segments(root)
        if segments:
            self._seq = int(_SEGMENT_RE.search(segments[-1]).group(1))
            # A crash mid-append leaves a torn trailing line in the
            # then-active segment. Readers skip it, but a resumed writer
            # about to APPEND must truncate it first or the next sample
            # would concatenate into a permanently corrupt interior line
            # (the serve verdict sidecar's reconcile idiom).
            self._reconcile_torn_tail(segments[-1])
        else:
            self._seq = 1
        self._fh = open(segment_path(root, self._seq), "a")

    @staticmethod
    def _reconcile_torn_tail(path: str) -> bool:
        with open(path, "rb+") as fh:
            data = fh.read()
            if not data or data.endswith(b"\n"):
                return False
            cut = data.rfind(b"\n")
            fh.truncate(cut + 1)
        return True

    # -- append path ---------------------------------------------------------

    def append(
        self,
        name: str,
        value: float,
        *,
        labels: "dict | None" = None,
        ts: "float | None" = None,
        mono: "float | None" = None,
    ) -> dict:
        """Append one sample; returns the record written."""
        return self.append_samples(
            [(name, labels or {}, value)], ts=ts, mono=mono
        )[0]

    def append_samples(
        self,
        samples,
        *,
        ts: "float | None" = None,
        mono: "float | None" = None,
    ) -> list[dict]:
        """Append a batch of ``(name, labels, value)`` samples sharing one
        timestamp pair (a scrape cycle), flushing once at the end —
        either the whole batch is on disk after the flush or (on a crash
        mid-write) a torn trailing line readers skip."""
        if ts is None:
            ts = time.time()
        if mono is None:
            mono = time.monotonic()
        records = []
        for name, labels, value in samples:
            rec = {
                "ts": round(float(ts), 6),
                "mono": round(float(mono), 6),
                "boot": self.boot,
                "name": str(name),
                "labels": {str(k): str(v) for k, v in (labels or {}).items()},
                "value": float(value),
            }
            self._fh.write(json.dumps(rec) + "\n")
            records.append(rec)
        self._fh.flush()
        if self._fh.tell() >= self.segment_bytes:
            self._rotate()
        return records

    def _rotate(self) -> None:
        """Finalize the active segment (fsync — rotation is the atomic
        durability point) and open the next sequence number."""
        os.fsync(self._fh.fileno())
        self._fh.close()
        self._seq += 1
        self._fh = open(segment_path(self.root, self._seq), "a")

    def enforce_retention(self, *, now: "float | None" = None) -> list[str]:
        """Drop the oldest finalized segments beyond the age/size bounds;
        returns the deleted paths. The active segment always survives,
        so retention can never tear the append path out from under the
        writer."""
        if now is None:
            now = time.time()
        deleted: list[str] = []
        segments = list_segments(self.root)
        active = segment_path(self.root, self._seq)
        finalized = [p for p in segments if p != active]
        if self.retention_s is not None:
            for path in list(finalized):
                bounds = _segment_bounds(path)
                if bounds is None or bounds[1] < now - self.retention_s:
                    os.remove(path)
                    finalized.remove(path)
                    deleted.append(path)
        if self.retention_bytes is not None:
            sizes = {p: os.path.getsize(p) for p in finalized}
            total = sum(sizes.values()) + (
                os.path.getsize(active) if os.path.exists(active) else 0
            )
            for path in list(finalized):  # oldest first
                if total <= self.retention_bytes:
                    break
                total -= sizes[path]
                os.remove(path)
                deleted.append(path)
        return deleted

    def close(self) -> None:
        if not self._fh.closed:
            self._fh.flush()
            os.fsync(self._fh.fileno())
            self._fh.close()

    def __enter__(self) -> "HistoryStore":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _segment_bounds(path: str) -> "tuple[float, float] | None":
    """(first ts, last ts) of a segment's complete records, or ``None``
    for an empty/unreadable one. The tail is read tolerantly — the last
    line may be torn."""
    first = last = None
    try:
        with open(path) as fh:
            lines = fh.readlines()
    except OSError:
        return None
    for i, line in enumerate(lines):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            if i == len(lines) - 1:
                break
            raise ValueError(f"{path}:{i + 1}: corrupt history record")
        ts = float(rec["ts"])
        first = ts if first is None else first
        last = ts
    return None if first is None else (first, last)


# -- read path ---------------------------------------------------------------


def _match(rec: dict, name: "str | None", labels: "dict | None") -> bool:
    if name is not None and rec.get("name") != name:
        return False
    if labels:
        rl = rec.get("labels") or {}
        for k, v in labels.items():
            if rl.get(str(k)) != str(v):
                return False
    return True


def read_samples(
    root: str,
    *,
    name: "str | None" = None,
    labels: "dict | None" = None,
    start: "float | None" = None,
    end: "float | None" = None,
) -> list[dict]:
    """Raw matching samples across all segments, in append order.

    ``labels`` is a **subset** selector: a sample matches when every
    selector pair is present (extra sample labels are fine — selecting
    ``{"tenant": "3"}`` matches any instance). Each segment tolerates
    one torn trailing line (a crash mid-append, or the live writer mid-
    write); a malformed *interior* line is corruption and raises.
    Segments wholly outside ``[start, end]`` are skipped without
    parsing every line (bounds peek)."""
    out: list[dict] = []
    for path in list_segments(root):
        if start is not None or end is not None:
            bounds = _segment_bounds(path)
            if bounds is None:
                continue
            if start is not None and bounds[1] < start:
                continue
            if end is not None and bounds[0] > end:
                continue
        with open(path) as fh:
            lines = fh.readlines()
        for i, line in enumerate(lines):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                if i == len(lines) - 1:
                    break  # torn tail: skipped exactly once per segment
                raise ValueError(f"{path}:{i + 1}: corrupt history record")
            ts = float(rec["ts"])
            if start is not None and ts < start:
                continue
            if end is not None and ts > end:
                continue
            if _match(rec, name, labels):
                out.append(rec)
    return out


def series_keys(records: list[dict]) -> "dict[tuple, list[dict]]":
    """Group records by series identity ``(name, label_key(labels))``."""
    out: dict[tuple, list[dict]] = {}
    for rec in records:
        out.setdefault(
            (rec["name"], label_key(rec.get("labels"))), []
        ).append(rec)
    return out


def list_series(root: str) -> "list[tuple[str, tuple]]":
    """Every distinct series in the store (sorted) — the CLI's
    discovery surface."""
    return sorted(series_keys(read_samples(root)))


def _aggregate(values: list[float], agg: str) -> float:
    if agg == "avg":
        return sum(values) / len(values)
    if agg == "sum":
        return sum(values)
    if agg == "min":
        return min(values)
    if agg == "max":
        return max(values)
    if agg == "last":
        return values[-1]
    if agg == "count":
        return float(len(values))
    raise ValueError(f"unknown agg {agg!r}; expected one of {AGGS}")


def range_query(
    root: str,
    name: str,
    *,
    labels: "dict | None" = None,
    start: "float | None" = None,
    end: "float | None" = None,
    step: "float | None" = None,
    agg: str = "avg",
) -> "dict[tuple, list[tuple[float, float]]]":
    """``(ts, value)`` points per matching series, time-ordered.

    With ``step``, points are downsampled into **step-aligned** buckets
    (bucket timestamp = ``floor(ts/step)·step``) under ``agg``; without,
    raw points. Conservation contract: ``agg='sum'`` buckets of a series
    sum to exactly the raw samples' sum over the same range."""
    if agg not in AGGS:
        raise ValueError(f"unknown agg {agg!r}; expected one of {AGGS}")
    grouped = series_keys(
        read_samples(root, name=name, labels=labels, start=start, end=end)
    )
    out: dict[tuple, list[tuple[float, float]]] = {}
    for key, recs in grouped.items():
        recs.sort(key=lambda r: (float(r["ts"])))
        if step is None or step <= 0:
            out[key[1]] = [(float(r["ts"]), float(r["value"])) for r in recs]
            continue
        buckets: dict[float, list[float]] = {}
        for r in recs:
            b = float(r["ts"]) // step * step
            buckets.setdefault(b, []).append(float(r["value"]))
        out[key[1]] = [
            (b, _aggregate(vs, agg)) for b, vs in sorted(buckets.items())
        ]
    return out


def _elapsed(first: dict, last: dict) -> float:
    """Elapsed seconds between two samples — monotonic when both carry
    stamps from the same writer boot (a wall-clock step between scrapes
    cannot fake or hide time), wall otherwise (different boots share no
    monotonic origin)."""
    if (
        first.get("boot")
        and first.get("boot") == last.get("boot")
        and first.get("mono") is not None
        and last.get("mono") is not None
    ):
        return float(last["mono"]) - float(first["mono"])
    return float(last["ts"]) - float(first["ts"])


def rate(
    root: str,
    name: str,
    *,
    labels: "dict | None" = None,
    window_s: float = 300.0,
    at: "float | None" = None,
) -> "dict[tuple, float | None]":
    """Per-second increase of a counter series over ``[at - window_s,
    at]``, per matching series; ``None`` with fewer than two samples.

    Counter-reset tolerant: only positive deltas count (a restarted
    daemon's counter dropping to 0 contributes nothing, never a negative
    rate). Elapsed time is monotonic within one writer boot
    (:func:`_elapsed`)."""
    if at is None:
        at = time.time()
    grouped = series_keys(
        read_samples(
            root, name=name, labels=labels, start=at - window_s, end=at
        )
    )
    out: dict[tuple, float | None] = {}
    for key, recs in grouped.items():
        recs.sort(key=lambda r: float(r["ts"]))
        if len(recs) < 2:
            out[key[1]] = None
            continue
        increase = 0.0
        for prev, cur in zip(recs, recs[1:]):
            d = float(cur["value"]) - float(prev["value"])
            if d > 0:
                increase += d
        dt = _elapsed(recs[0], recs[-1])
        out[key[1]] = (increase / dt) if dt > 0 else None
    return out


def _window_values(
    root: str,
    name: str,
    labels: "dict | None",
    window_s: float,
    at: "float | None",
) -> "dict[tuple, list[float]]":
    if at is None:
        at = time.time()
    grouped = series_keys(
        read_samples(
            root, name=name, labels=labels, start=at - window_s, end=at
        )
    )
    return {
        key[1]: [
            float(r["value"])
            for r in sorted(recs, key=lambda r: float(r["ts"]))
        ]
        for key, recs in grouped.items()
    }


def quantile_over_time(
    root: str,
    name: str,
    q: float,
    *,
    labels: "dict | None" = None,
    window_s: float = 300.0,
    at: "float | None" = None,
) -> "dict[tuple, float | None]":
    """The ``q``-quantile (0..1, linear interpolation) of each matching
    series' samples over the window; ``None`` for an empty window."""
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"quantile must be in [0, 1], got {q}")
    out: dict[tuple, float | None] = {}
    for key, values in _window_values(root, name, labels, window_s, at).items():
        if not values:
            out[key] = None
            continue
        vs = sorted(values)
        pos = q * (len(vs) - 1)
        lo = int(pos)
        hi = min(lo + 1, len(vs) - 1)
        out[key] = vs[lo] + (vs[hi] - vs[lo]) * (pos - lo)
    return out


def avg_over_time(
    root: str,
    name: str,
    *,
    labels: "dict | None" = None,
    window_s: float = 300.0,
    at: "float | None" = None,
) -> "dict[tuple, float | None]":
    """Windowed mean per matching series (the burn-rate SLO primitive)."""
    return {
        key: (sum(vs) / len(vs) if vs else None)
        for key, vs in _window_values(root, name, labels, window_s, at).items()
    }


def last_over_time(
    root: str,
    name: str,
    *,
    labels: "dict | None" = None,
    window_s: float = 300.0,
    at: "float | None" = None,
) -> "dict[tuple, float | None]":
    """The newest sample value per matching series over the window —
    the fleet-index primitive for monotone per-instance gauges/counters
    (``serve_incidents_total{instance=...}``: the latest scrape IS the
    current count; averaging or summing a cumulative count would lie)."""
    return {
        key: (vs[-1] if vs else None)
        for key, vs in _window_values(root, name, labels, window_s, at).items()
    }


def top_tenants(
    root: str,
    *,
    window_s: float = 300.0,
    at: "float | None" = None,
    metric: str = TENANT_ROWS_METRIC,
    adapt_metric: str = TENANT_ADAPT_METRIC,
    limit: "int | None" = None,
) -> list[dict]:
    """Per-tenant activity ranking over the window: rows/s (the rank
    key, summed across instances — a migrated tenant's rate follows it
    across backends) plus adaptation events/s. The input the tenant
    residency manager (ROADMAP item 2) pages by."""
    rows_rate = rate(root, metric, window_s=window_s, at=at)
    adapt_rate = rate(root, adapt_metric, window_s=window_s, at=at)

    def _fold(rates: "dict[tuple, float | None]") -> dict[str, float]:
        per: dict[str, float] = {}
        for key, r in rates.items():
            if r is None:
                continue
            tenant = dict(key).get("tenant")
            if tenant is not None:
                per[tenant] = per.get(tenant, 0.0) + r
        return per

    rows = _fold(rows_rate)
    adapts = _fold(adapt_rate)
    ranked = [
        {
            "tenant": t,
            "rows_per_sec": round(rows.get(t, 0.0), 3),
            "adaptations_per_sec": round(adapts.get(t, 0.0), 6),
        }
        for t in sorted(
            set(rows) | set(adapts),
            key=lambda t: (-rows.get(t, 0.0), t),
        )
    ]
    return ranked[:limit] if limit else ranked


# -- rendering ---------------------------------------------------------------

_SPARK = "▁▂▃▄▅▆▇█"


def sparkline(values, width: "int | None" = None) -> str:
    """ASCII(-ish) trend glyphs for a value sequence; ``None`` gaps
    render as spaces. With ``width``, the newest ``width`` points."""
    vs = list(values)
    if width is not None and len(vs) > width:
        vs = vs[-width:]
    present = [v for v in vs if v is not None]
    if not present:
        return ""
    lo, hi = min(present), max(present)
    span = hi - lo
    chars = []
    for v in vs:
        if v is None:
            chars.append(" ")
        elif span <= 0:
            chars.append(_SPARK[0])
        else:
            idx = int((v - lo) / span * (len(_SPARK) - 1))
            chars.append(_SPARK[idx])
    return "".join(chars)


def _fmt_key(key: tuple) -> str:
    return (
        "{" + ",".join(f'{k}="{v}"' for k, v in key) + "}" if key else "{}"
    )


# -- CLI ---------------------------------------------------------------------


def _parse_labels(specs) -> dict:
    labels = {}
    for spec in specs or ():
        k, sep, v = spec.partition("=")
        if not sep:
            raise SystemExit(f"history: bad --label {spec!r} (want k=v)")
        labels[k] = v
    return labels


def main(argv=None) -> int:
    """``history``: query a time-series store from the shell."""
    ap = argparse.ArgumentParser(
        prog="python -m distributed_drift_detection_tpu history",
        description=(
            "Query a history store (telemetry.history): range/rate/"
            "quantile over any stored series, per-tenant hotness "
            "ranking, JSON or sparkline output."
        ),
    )
    ap.add_argument(
        "query",
        choices=("range", "rate", "quantile", "top-tenants", "series"),
    )
    ap.add_argument("store", help="history store directory")
    ap.add_argument("name", nargs="?", help="series name (not for top-tenants)")
    ap.add_argument(
        "--label",
        action="append",
        default=[],
        metavar="K=V",
        help="label selector (subset match), repeatable",
    )
    ap.add_argument(
        "--window", type=float, default=300.0, metavar="S",
        help="look-back window in seconds (default 300)",
    )
    ap.add_argument(
        "--at", type=float, default=None, metavar="TS",
        help="window end as unix seconds (default: now)",
    )
    ap.add_argument(
        "--step", type=float, default=None, metavar="S",
        help="range: step-aligned downsampling bucket width",
    )
    ap.add_argument(
        "--agg", choices=AGGS, default="avg",
        help="range downsampling aggregate (default avg)",
    )
    ap.add_argument("--q", type=float, default=0.99, help="quantile (0..1)")
    ap.add_argument("--limit", type=int, default=None, metavar="N")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)
    if not list_segments(args.store):
        print(f"history: no store at {args.store}", file=sys.stderr)
        return 4
    if args.query in ("range", "rate", "quantile") and not args.name:
        ap.error(f"{args.query} needs a series name")
    labels = _parse_labels(args.label)
    at = args.at if args.at is not None else time.time()

    if args.query == "series":
        keys = list_series(args.store)
        if args.json:
            print(json.dumps([[n, list(k)] for n, k in keys], indent=1))
        else:
            for n, k in keys:
                print(f"{n}{_fmt_key(k)}")
        return 0

    if args.query == "top-tenants":
        ranked = top_tenants(
            args.store, window_s=args.window, at=at, limit=args.limit
        )
        if args.json:
            print(json.dumps(ranked, indent=1))
        else:
            print(f"{'TENANT':<8} {'ROWS/S':>12} {'ADAPT/S':>10}")
            for r in ranked:
                print(
                    f"{r['tenant']:<8} {r['rows_per_sec']:>12,.1f} "
                    f"{r['adaptations_per_sec']:>10.4f}"
                )
        if not ranked:
            print("history: no tenant series in window", file=sys.stderr)
            return 3
        return 0

    if args.query == "range":
        series = range_query(
            args.store,
            args.name,
            labels=labels,
            start=at - args.window,
            end=at,
            step=args.step,
            agg=args.agg,
        )
        if args.json:
            print(
                json.dumps(
                    {
                        _fmt_key(k): [[t, v] for t, v in pts]
                        for k, pts in sorted(series.items())
                    },
                    indent=1,
                )
            )
        else:
            for k, pts in sorted(series.items()):
                vals = [v for _, v in pts]
                spark = sparkline(vals, width=60)
                tail = f" last={vals[-1]:g}" if vals else ""
                print(f"{args.name}{_fmt_key(k)} [{spark}]{tail}")
        return 0 if series else 3

    if args.query == "rate":
        rates = rate(
            args.store, args.name, labels=labels, window_s=args.window, at=at
        )
    else:  # quantile
        rates = quantile_over_time(
            args.store,
            args.name,
            args.q,
            labels=labels,
            window_s=args.window,
            at=at,
        )
    if args.json:
        print(
            json.dumps(
                {_fmt_key(k): v for k, v in sorted(rates.items())}, indent=1
            )
        )
    else:
        for k, v in sorted(rates.items()):
            print(
                f"{args.name}{_fmt_key(k)} "
                f"{'-' if v is None else f'{v:,.4f}'}"
            )
    return 0 if any(v is not None for v in rates.values()) else 3


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
