"""The live ops plane: a dependency-free HTTP server + crash flight
recorder for the serving daemon.

Until now the live ``MetricsRegistry`` only ever reached disk at run end
(``metrics.write_exports``) — useless to an operator watching a daemon
*now*. :class:`OpsServer` is a threaded stdlib ``http.server`` exposing
three read-only endpoints (``--ops-port``; loopback by default, like the
ingress):

=============  ==========================================================
``/metrics``   the live registry in Prometheus text exposition format —
               **byte-identical** to what ``write_exports`` would put in
               the ``.prom`` file for the same registry state (both call
               ``MetricsRegistry.to_prometheus_text``; pinned by tests)
``/healthz``   the scriptable liveness contract: HTTP 200 while healthy
               (serving or draining), 503 while any SLO alert is firing
               or the ingress poisoned the batcher; the JSON body names
               the reasons
``/statusz``   one JSON snapshot of the daemon: run id, row/chunk
               accounting, queue depth, AOT/compile-cache state, live
               latency percentiles, last-verdict age, active alerts,
               and the serve-pipeline section (stage busy shares +
               dominant stage)
``/fleetz``    aggregators only (``fleetz_fn``; the tenant router and
               sweep scheduler): the merged fleet view — summed rows/s,
               max per-stage busy share, per-backend bottleneck. A
               plain daemon 404s here.
``/incidentz`` incident-plane daemons only (``incidentz_fn``): bundle
               count, open-alert count, and the latest incident
               manifest. A pre-incident daemon 404s here; the collector
               treats that as "no incident plane", never as down.
=============  ==========================================================

Handlers never *write* daemon state: the server is constructed with
three read-only callables and the GIL makes the scalar reads atomic;
the one mutable structure it renders — the registry — is snapshotted
defensively (a scrape racing a metric insertion retries, never crashes
the daemon or the scrape).

:class:`FlightRecorder` is the crash story: a bounded ring of the most
recent run-log events (installed as the ``EventLog`` tap), dumped to
``<run-log stem>.flightrec.jsonl`` only when the daemon dies — the last
N events an operator needs first, next to the artifact they came from,
without re-reading a multi-GB log. Each dumped line is a verbatim,
already-schema-valid event, so :func:`read_flight_record` is just
``read_events`` with torn-tail tolerance; a clean drain leaves **no**
dump (its absence is the clean-exit signal CI asserts).
:meth:`FlightRecorder.event_age_s` exposes the ring's staleness for
ad-hoc probes (the SLO ``stall_s`` rule itself reads the serve loop's
own liveness stamp, which also works without a run log).

No jax anywhere here; stdlib + the sibling telemetry modules only.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .events import read_events

FLIGHTREC_SUFFIX = ".flightrec.jsonl"

PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class FlightRecorder:
    """Bounded ring buffer of recent events + the last-emit clock."""

    def __init__(self, capacity: int = 256, *, clock=time.monotonic):
        self._buf: collections.deque = collections.deque(
            maxlen=max(int(capacity), 1)
        )
        self._clock = clock
        self._lock = threading.Lock()
        self._last_mono = clock()

    def record(self, event: dict) -> None:
        """The ``EventLog.tap`` hook: remember one emitted event.

        ``alert`` events ride in the ring but do NOT advance the
        staleness clock: the SLO evaluator emits them from its own
        thread, so counting them as liveness would let a stall-shaped
        alert reset the very staleness that fired it (fire → emit →
        "fresh event" → resolve → re-fire, forever)."""
        with self._lock:
            self._buf.append(event)
            if event.get("type") != "alert":
                self._last_mono = self._clock()

    def event_age_s(self) -> float:
        """Monotonic seconds since the last recorded event — the SLO
        ``stall_s`` snapshot value."""
        with self._lock:
            return max(self._clock() - self._last_mono, 0.0)

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)

    def dump(self, path: str) -> "str | None":
        """Write the ring to ``path`` (one event per line, verbatim);
        returns the path actually written, or ``None`` when the ring is
        empty (no file — an empty dump would read as evidence).

        Collision-safe for multi-dump runs: if ``path`` already exists
        (an earlier dump in the same process lifetime — the incident
        plane may dump the ring many times before a crash does), the
        write lands at ``<stem>-2{suffix}``, ``-3``, ... instead of
        overwriting evidence. The compound ``.flightrec.jsonl`` suffix is
        kept intact so the registry's sidecar skip still recognizes the
        renamed dump, and a first dump keeps the bare name — the crash
        path's "absence = clean exit" CI signal is untouched.
        Best-effort by contract: called from crash paths, it must not
        mask the original error."""
        with self._lock:
            events = list(self._buf)
        if not events:
            return None
        if path.endswith(FLIGHTREC_SUFFIX):
            base, ext = path[: -len(FLIGHTREC_SUFFIX)], FLIGHTREC_SUFFIX
        else:
            base, ext = os.path.splitext(path)
        k = 1
        while os.path.exists(path):
            k += 1
            path = f"{base}-{k}{ext}"
        try:
            with open(path, "x") as fh:
                for e in events:
                    fh.write(json.dumps(e) + "\n")
                fh.flush()
                os.fsync(fh.fileno())
        except OSError:
            return None
        return path


def read_flight_record(path: str) -> list[dict]:
    """Parse a flight-recorder dump: schema-valid events, tolerating a
    torn trailing line (the dump may itself have died mid-write)."""
    return read_events(path, allow_partial_tail=True)


class _OpsHandler(BaseHTTPRequestHandler):
    server: "OpsServer"

    def do_GET(self) -> None:  # noqa: N802 (stdlib naming)
        path = self.path.split("?", 1)[0]
        try:
            if path == "/metrics":
                body = self.server.metrics_text().encode()
                code, ctype = 200, PROM_CONTENT_TYPE
            elif path == "/healthz":
                code, payload = self.server.health_fn()
                body = (json.dumps(payload) + "\n").encode()
                ctype = "application/json"
            elif path in ("/statusz", "/"):
                body = (
                    json.dumps(self.server.status_fn(), indent=1) + "\n"
                ).encode()
                code, ctype = 200, "application/json"
            elif path == "/fleetz" and self.server.fleetz_fn is not None:
                # aggregators only (router/scheduler): the merged fleet
                # view; a plain daemon keeps 404-ing here
                body = (
                    json.dumps(self.server.fleetz_fn(), indent=1) + "\n"
                ).encode()
                code, ctype = 200, "application/json"
            elif path == "/incidentz" and self.server.incidentz_fn is not None:
                # incident-plane daemons only: bundle count + latest
                # manifest; a pre-incident daemon keeps 404-ing here (the
                # collector treats that as "no incident plane", not down)
                body = (
                    json.dumps(self.server.incidentz_fn(), indent=1) + "\n"
                ).encode()
                code, ctype = 200, "application/json"
            else:
                body = b'{"error": "not found"}\n'
                code, ctype = 404, "application/json"
        except Exception as e:  # a broken snapshot must not kill the thread
            body = (json.dumps({"error": repr(e)}) + "\n").encode()
            code, ctype = 500, "application/json"
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        try:
            self.wfile.write(body)
        except OSError:
            pass  # scraper already gone

    def log_message(self, *args) -> None:  # quiet: scrapes are not news
        pass


class OpsServer(ThreadingHTTPServer):
    """The ops listener (one daemon accept thread, one per request).

    ``metrics_fn`` → the exposition text (or ``None`` for an empty
    registry); ``health_fn`` → ``(http status, JSON payload)``;
    ``status_fn`` → the ``/statusz`` JSON dict. ``port=0`` requests an
    OS-assigned port (read :attr:`port` after construction).
    """

    daemon_threads = True
    allow_reuse_address = True

    def __init__(
        self,
        host: str,
        port: int,
        *,
        metrics_fn,
        health_fn,
        status_fn,
        fleetz_fn=None,
        incidentz_fn=None,
    ):
        super().__init__((host, port), _OpsHandler)
        self._metrics_fn = metrics_fn
        self.health_fn = health_fn
        self.status_fn = status_fn
        # Optional merged fleet view (``/fleetz``): set by aggregators
        # (the tenant router, the sweep scheduler); None = 404, so a
        # plain daemon's ops surface is unchanged.
        self.fleetz_fn = fleetz_fn
        # Optional incident index (``/incidentz``): set by daemons with
        # an IncidentRecorder; None = 404 (pre-incident daemons).
        self.incidentz_fn = incidentz_fn
        self._thread: "threading.Thread | None" = None

    @property
    def port(self) -> int:
        return self.server_address[1]

    def metrics_text(self) -> str:
        """Render the registry (the exporters snapshot their dicts, so a
        scrape racing a first-use metric insertion is safe; any other
        failure becomes the handler's 500)."""
        text = self._metrics_fn()
        return text if text is not None else "\n"

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self.serve_forever, name="serve-ops", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self.shutdown()
        self.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
