"""Causal end-to-end tracing: trace-context propagation + span events.

The ops plane (telemetry.trace) answers *how slow* in aggregate; this
module answers *why this row*: a head-sampled row entering the serving
ingress carries a **trace context** — ``(trace_id, span_id)`` — through
admission, microbatching, the kernel dispatch and verdict publication,
and every stage attaches a child ``span`` event (schema v1) to the run
log, so a sidecar verdict joins back to its originating ingress packet.
The ``timeline`` CLI (telemetry.timeline) merges one or many run logs'
spans into a single Chrome-trace/Perfetto artifact.

Design rules:

* **Head-based sampling, zero hot-path work at rate 0.** The sampling
  decision is made once, at the head of the pipeline (the load
  generator stamping the wire, or the ingress sampling unstamped rows);
  everything downstream only acts on rows that already carry a context.
  A :class:`HeadSampler` at rate 0 is *falsy*, and every call site
  guards with ``if sampler:`` — the disabled path executes no tracing
  code, allocates nothing, and reads no clock.
* **Wire format.** A ``TRACE <trace_id> <span_id>`` protocol line marks
  the **next** data row on the connection as sampled (see
  ``serve.ingress``); ids are opaque lowercase-hex tokens (W3C
  traceparent widths: 32-hex trace, 16-hex span).
* **Spans are events.** One schema-v1 ``span`` event per span, emitted
  host-side strictly outside jitted code and outside any reference-
  parity Final Time span. Monotonic pipeline stamps are rebased to
  wall-clock at emit (:func:`wall_of`), so cross-process merge uses the
  same clock-skew alignment as ``correlate``.

The serving pipeline's per-row span chain (:func:`emit_row_spans`)::

    ingress (client root, loadgen's log)
     └─ serve (daemon)
         ├─ admission   ingest stamp → microbatch sealed
         ├─ batch       sealed → handed to the device feed
         ├─ kernel      fed → flags collected host-side
         └─ verdict     collected → verdict line flushed

No jax; numpy + stdlib only (safe in ingress handler threads and
jax-free CLIs).
"""

from __future__ import annotations

import random
import threading
import time

# Wire directive marking the NEXT data row on a connection as sampled.
TRACE_DIRECTIVE = "TRACE"

# The per-row serving span chain, pipeline order (docs + tests pin this).
ROW_STAGES = ("admission", "batch", "kernel", "verdict")

_ID_ALPHABET = "0123456789abcdef"
_MAX_ID_LEN = 64  # wire-side sanity bound for untrusted client ids


def _hex_token(rng: "random.Random | None", nhex: int) -> str:
    r = rng if rng is not None else random
    return "".join(r.choice(_ID_ALPHABET) for _ in range(nhex))


def new_trace_id(rng: "random.Random | None" = None) -> str:
    """A fresh 128-bit trace id (32 lowercase hex chars)."""
    return _hex_token(rng, 32)


def new_span_id(rng: "random.Random | None" = None) -> str:
    """A fresh 64-bit span id (16 lowercase hex chars)."""
    return _hex_token(rng, 16)


def check_trace_token(token: str) -> str:
    """Validate an untrusted wire-side id token (lowercase hex, bounded
    length). Raises ``ValueError`` — the ingress turns that into an
    ``ERR`` + connection drop, exactly like a malformed TENANT id."""
    if not token or len(token) > _MAX_ID_LEN:
        raise ValueError(f"trace id token length {len(token)} not in 1..64")
    if any(c not in _ID_ALPHABET for c in token):
        raise ValueError(f"trace id token {token!r:.80} is not lowercase hex")
    return token


def wall_of(mono: float, *, anchor: "tuple[float, float] | None" = None) -> float:
    """Rebase a ``time.monotonic()`` stamp onto the wall clock.

    ``anchor`` is an optional ``(wall_now, mono_now)`` pair so one batch
    of conversions shares a single clock read (sub-ms consistency across
    the spans of one chunk)."""
    if anchor is None:
        anchor = (time.time(), time.monotonic())
    wall_now, mono_now = anchor
    return wall_now - (mono_now - mono)


class HeadSampler:
    """Seeded head-sampling decision maker.

    ``rate`` is clamped to [0, 1]. At rate 0 the instance is **falsy**
    and callers skip all tracing work (``if sampler:``) — the zero-cost
    contract. Thread-safe: ingress handler threads share one instance.
    """

    def __init__(self, rate: float, seed: "int | None" = None):
        self.rate = min(max(float(rate), 0.0), 1.0)
        self._rng = random.Random(seed)
        self._lock = threading.Lock()

    def __bool__(self) -> bool:
        return self.rate > 0.0

    def sample(self) -> bool:
        """One head decision."""
        if self.rate <= 0.0:
            return False
        if self.rate >= 1.0:
            return True
        with self._lock:
            return self._rng.random() < self.rate

    def sample_block(self, n: int) -> "list[int]":
        """Indices of the sampled rows in a block of ``n`` (vector form of
        :meth:`sample` — one lock acquisition per ingress block)."""
        if self.rate <= 0.0 or n <= 0:
            return []
        if self.rate >= 1.0:
            return list(range(n))
        with self._lock:
            rnd = self._rng.random
            return [i for i in range(n) if rnd() < self.rate]

    def new_context(self) -> "tuple[str, str]":
        """A fresh root ``(trace_id, span_id)`` pair (daemon-side sampling
        of unstamped rows)."""
        with self._lock:
            return new_trace_id(self._rng), new_span_id(self._rng)


def emit_span(
    log,
    *,
    name: str,
    trace_id: str,
    span_id: "str | None" = None,
    parent_id: "str | None" = None,
    start_ts: float,
    dur_s: float,
    **extra,
) -> dict:
    """Emit one schema-v1 ``span`` event; returns the record (its
    ``span_id`` is generated when not given)."""
    return log.emit(
        "span",
        name=name,
        trace_id=trace_id,
        span_id=span_id or new_span_id(),
        parent_id=parent_id,
        start_ts=float(start_ts),
        dur_s=max(float(dur_s), 0.0),
        **extra,
    )


def emit_row_spans(
    log,
    meta: dict,
    *,
    collected_mono: float,
    published_mono: float,
) -> "list[str]":
    """Emit the serving span chain for every traced row of one published
    microbatch; returns the trace ids covered (the verdict record's
    ``traces`` field and the /statusz counter both come from this).

    ``meta`` is the sealed chunk's accounting dict: the admission layer
    stamps ``traces`` (``[{"idx", "trace_id", "parent_id", "tenant"?},
    ...]`` — ``idx`` indexes the per-row ``ingest_mono`` array) and
    ``sealed_mono``; the serve loop supplies ``fed_mono`` plus the two
    publication stamps. All stamps are monotonic; one shared anchor
    rebases them to wall-clock.
    """
    traces = meta.get("traces") or ()
    if not traces:
        return []
    anchor = (time.time(), time.monotonic())
    ingest_arr = meta.get("ingest_mono")
    sealed = float(meta.get("sealed_mono", collected_mono))
    fed = float(meta.get("fed_mono", sealed))
    out = []
    for t in traces:
        idx = int(t["idx"])
        ingest = (
            float(ingest_arr[idx])
            if ingest_arr is not None and idx < len(ingest_arr)
            else sealed
        )
        common = {"chunk": meta.get("chunk"), "row": idx}
        if "tenant" in t:
            common["tenant"] = t["tenant"]
        serve_span = emit_span(
            log,
            name="serve",
            trace_id=t["trace_id"],
            parent_id=t.get("parent_id"),
            start_ts=wall_of(ingest, anchor=anchor),
            dur_s=published_mono - ingest,
            **common,
        )
        bounds = {
            "admission": (ingest, sealed),
            "batch": (sealed, fed),
            "kernel": (fed, collected_mono),
            "verdict": (collected_mono, published_mono),
        }
        for stage in ROW_STAGES:
            lo, hi = bounds[stage]
            emit_span(
                log,
                name=stage,
                trace_id=t["trace_id"],
                parent_id=serve_span["span_id"],
                start_ts=wall_of(lo, anchor=anchor),
                dur_s=hi - lo,
                **common,
            )
        out.append(t["trace_id"])
    return out


class ChunkTracer:
    """Head-sampled per-chunk span emitter for the batch/streaming
    pipeline (``io.feeder`` ingest stages + ``engine.chunked`` kernel
    feeds share one instance, so one chunk's spans share one trace).

    Each sampled chunk gets its OWN trace id — one traced unit of work
    per chunk, exactly like the serving side's one-trace-per-row — so
    the ``timeline`` CLI lays chunks out on separate lanes and the
    pipelined overlap (chunk k+1's ingest against chunk k's kernel) is
    visible instead of colliding on one thread row. The sampling
    decision is memoized per chunk index — the ingest span and the
    kernel span of chunk *k* are sampled (or not) together. A ``None``
    log or rate 0 makes the tracer falsy; every call site guards with
    ``if tracer:``.
    """

    def __init__(
        self,
        log,
        rate: float = 1.0,
        seed: "int | None" = None,
    ):
        self.log = log
        self.sampler = HeadSampler(rate, seed)
        self._rng = random.Random(seed) if seed is not None else None
        self._decisions: dict[int, bool] = {}
        self._trace_ids: dict[int, str] = {}
        self._roots: dict[int, str] = {}

    def __bool__(self) -> bool:
        return self.log is not None and bool(self.sampler)

    def sampled(self, chunk: int) -> bool:
        """Stable per-chunk head decision."""
        if not self:
            return False
        got = self._decisions.get(chunk)
        if got is None:
            got = self._decisions[chunk] = self.sampler.sample()
        return got

    def span(
        self,
        name: str,
        chunk: int,
        start_mono: float,
        end_mono: float,
        **extra,
    ) -> "str | None":
        """Emit one per-chunk stage span (sampled chunks only); the first
        span of a chunk becomes the parent of its later stages. Returns
        the emitted span id, or ``None`` when the chunk is unsampled."""
        if not self.sampled(chunk):
            return None
        trace_id = self._trace_ids.get(chunk)
        if trace_id is None:
            trace_id = self._trace_ids[chunk] = new_trace_id(self._rng)
        rec = emit_span(
            self.log,
            name=name,
            trace_id=trace_id,
            parent_id=self._roots.get(chunk),
            start_ts=wall_of(start_mono),
            dur_s=end_mono - start_mono,
            chunk=chunk,
            **extra,
        )
        self._roots.setdefault(chunk, rec["span_id"])
        return rec["span_id"]
