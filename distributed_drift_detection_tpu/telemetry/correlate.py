"""Cross-host trace correlation: merge one multi-host run's N per-process
event logs into a single normalized timeline, and say who straggled.

    python -m distributed_drift_detection_tpu correlate <dir | run logs...>

In a ``jax.distributed`` run every process writes its **own** JSONL log
(``api.run`` opens one per process; the filename carries a ``procN``
segment and ``run_started`` carries the ``hostname`` / ``process_index``
/ ``process_count`` identity extras — ``parallel.multihost.
host_identity``). Each log is a correct single-host view; the fleet
questions — did every host run the same config, which host was slow,
where did the collective wait — need them merged.

Clock skew is absorbed by **alignment, not trust**: host wall-clocks on a
pod differ by arbitrary offsets, so absolute ``ts`` values are never
compared across logs. Every event is rebased to its own host's
``run_started`` timestamp (``t_rel = ts − t0``) — the one boundary every
process crosses at the same program point — and the merged timeline
orders on ``(t_rel, process_index, seq)``, which is deterministic for a
given set of logs regardless of argument order or filesystem iteration.
(Constant per-host offset cancels exactly; residual drift over a run is
bounded by the run's own length, which for the phase-spread diagnostics
below is the signal, not noise.)

Straggler diagnostics: per-host detect-phase spread (the embarrassingly
parallel loop should take the same time everywhere — a slow host here is
a real straggler, since the drift-vote all-reduce makes everyone wait for
it), and per-host throughput skew from the streaming progress events
(``chunk_completed`` / ``leg_completed`` pacing and ``heartbeat``
rows/elapsed). Pure stdlib + the schema module; no jax — runs wherever
the artifacts land.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

from .events import read_events
from .registry import INDEX_NAME, config_digest

_TIMELINE_LIMIT = 40  # rendered merged-timeline rows (full list in the data)


class CorrelationError(ValueError):
    """The given logs cannot be correlated (no run_started, mixed configs
    with no common group, ...)."""


def _identity_of(started: dict, ordinal: int) -> dict:
    """Host identity from a run_started event (extras written by api.run;
    logs from older producers fall back to the load ordinal)."""
    return {
        "run_id": started["run_id"],
        "config": started.get("config") or {},
        "digest": config_digest(started.get("config") or {}),
        "hostname": started.get("hostname") or "?",
        "process_index": int(started.get("process_index", ordinal)),
        "process_count": int(started.get("process_count", 0)) or None,
        "t0": float(started["ts"]),
    }


def _identity(events: list[dict], ordinal: int) -> dict:
    started = next((e for e in events if e["type"] == "run_started"), None)
    if started is None:
        raise CorrelationError(
            "log has no run_started event — cannot align its clock"
        )
    return _identity_of(started, ordinal)


def _first_started(path: str) -> dict | None:
    """The log's run_started event read cheaply — first non-empty line
    only (the schema puts run_started first). ``None`` for empty,
    unparseable, or foreign files: grouping must skim a directory without
    paying a full parse per log (the chosen group is fully read and
    validated by :func:`correlate` afterwards)."""
    try:
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                event = json.loads(line)
                if (
                    isinstance(event, dict)
                    and event.get("type") == "run_started"
                    and event.get("run_id")
                    and "ts" in event
                ):
                    return event
                return None
    except (OSError, json.JSONDecodeError, UnicodeDecodeError):
        return None
    return None


def load_logs(paths: list[str]) -> list[tuple[dict, list[dict]]]:
    """Read + identify each log (torn-tail tolerant: a live or crashed
    sibling is still correlatable); returns ``[(identity, events), ...]``."""
    out = []
    for i, path in enumerate(sorted(paths)):
        events = read_events(path, allow_partial_tail=True)
        ident = _identity(events, ordinal=i)
        ident["path"] = path
        out.append((ident, events))
    return out


def group_run_logs(telemetry_dir: str) -> list[str]:
    """The newest multi-host run group in a telemetry directory: logs
    sharing one ``(config digest, process_count)``, newest group by its
    earliest ``run_started``. Single-process directories resolve to the
    newest single log (correlating one log is a valid degenerate case)."""
    paths = [
        p
        for p in glob.glob(os.path.join(telemetry_dir, "*.jsonl"))
        if os.path.basename(p) != INDEX_NAME
    ]
    if not paths:
        raise CorrelationError(f"no run logs in {telemetry_dir}")
    groups: dict[tuple, list[tuple[dict, str]]] = {}
    for path in paths:
        started = _first_started(path)
        if started is None:
            continue  # unreadable/empty/foreign log: not part of any group
        ident = _identity_of(started, ordinal=0)
        key = (ident["digest"], ident["process_count"])
        groups.setdefault(key, []).append((ident, path))
    if not groups:
        raise CorrelationError(f"no correlatable run logs in {telemetry_dir}")

    def group_recency(members):
        # Newest MEMBER, not earliest: a group accumulates every run of
        # one config, so its earliest t0 is pinned at that config's first
        # run ever — ranking on it would let any fresher config shadow a
        # re-run of an older one.
        return max(ident["t0"] for ident, _ in members)

    # Newest run wins; within the group keep the latest log per process
    # index (repeated runs of one config in one directory supersede).
    members = max(groups.values(), key=group_recency)
    by_proc: dict[int, tuple[dict, str]] = {}
    for ident, path in members:
        prev = by_proc.get(ident["process_index"])
        if prev is None or ident["t0"] > prev[0]["t0"]:
            by_proc[ident["process_index"]] = (ident, path)
    return [path for _, (_, path) in sorted(by_proc.items())]


def correlate(paths: list[str]) -> dict:
    """Merge per-process logs into the normalized fleet view.

    Returns ``{"hosts": [per-host summary ...], "timeline": [merged
    events ...], "stragglers": {...}}`` — the data model behind
    :func:`render_correlation`, reusable programmatically. Host order and
    the timeline are deterministic for a given set of logs (sorted on
    rebased time + process index + per-log sequence, never on load
    order)."""
    logs = load_logs(paths)
    if not logs:
        raise CorrelationError("no logs to correlate")
    digests = {ident["digest"] for ident, _ in logs}
    if len(digests) > 1:
        raise CorrelationError(
            f"logs carry {len(digests)} different config digests "
            f"({sorted(digests)}): not one run — pass one run's logs, or a "
            "directory (the newest coherent group is picked automatically)"
        )
    by_proc: dict[int, list[str]] = {}
    for ident, _ in logs:
        by_proc.setdefault(ident["process_index"], []).append(ident["run_id"])
    dupes = {k: v for k, v in by_proc.items() if len(v) > 1}
    if dupes:
        # Same config digest but a repeated process index = two runs of one
        # configuration, not one fleet — merging them would interleave
        # unrelated timelines and corrupt the straggler stats.
        raise CorrelationError(
            "multiple logs claim the same process index — these are "
            f"separate runs of one config, not one run: {dupes}; pass one "
            "run's logs, or a directory (the newest run is picked "
            "automatically)"
        )

    hosts = []
    timeline = []
    for ident, events in logs:
        h = {
            **{k: ident[k] for k in (
                "run_id", "hostname", "process_index", "process_count",
                "path", "t0",
            )},
            "phases": {},
            "rows": None,
            "seconds": None,
            "detections": 0,
            "last_t": 0.0,
            "last_type": None,
            "progress_rate": None,  # rows/s from the newest heartbeat
            "completed": False,
        }
        leg_rows, leg_t = 0, 0.0  # heartbeat-free fallback (older logs)
        first_hb = None  # (rows_done, elapsed_s): rates come from DELTAS
        for e in events:
            t_rel = float(e["ts"]) - ident["t0"]
            timeline.append(
                {"t": t_rel, "host": ident["process_index"], **e}
            )
            h["last_t"], h["last_type"] = t_rel, e["type"]
            if e["type"] == "phase_completed":
                h["phases"][e["phase"]] = (
                    h["phases"].get(e["phase"], 0.0) + e["seconds"]
                )
            elif e["type"] == "drift_detected":
                h["detections"] += 1
            elif e["type"] == "heartbeat":
                # Delta rate, same rule as watch.WatchState.rate(): a
                # checkpoint-resumed soak's rows_done is stream-absolute
                # while elapsed_s is this-process — the single-beat ratio
                # would overstate a resumed host by its resume offset and
                # invert the straggler diagnosis.
                rows, el = int(e["rows_done"]), float(e["elapsed_s"])
                if first_hb is None:
                    first_hb = (rows, el)
                r0, e0 = first_hb
                if el > e0 and rows > r0:
                    h["progress_rate"] = (rows - r0) / (el - e0)
                elif el > 0 and rows > 0:
                    h["progress_rate"] = rows / el
            elif e["type"] == "leg_completed":
                leg_rows += int(e["rows"])
                leg_t = t_rel
            elif e["type"] == "run_completed":
                h["rows"] = e["rows"]
                h["seconds"] = e["seconds"]
                h["detections"] = e["detections"]
                h["completed"] = True
        if h["rows"] is not None and h["seconds"]:
            h["progress_rate"] = h["rows"] / h["seconds"]
        elif h["progress_rate"] is None and leg_rows and leg_t > 0:
            # pre-heartbeat soak logs: pace the legs by their own rebased
            # completion times (coarser than heartbeats, same skew story)
            h["progress_rate"] = leg_rows / leg_t
        hosts.append(h)
    hosts.sort(key=lambda h: h["process_index"])
    timeline.sort(key=lambda e: (e["t"], e["host"], e["seq"]))

    return {
        "digest": next(iter(digests)),
        "config": logs[0][0]["config"],
        "hosts": hosts,
        "timeline": timeline,
        "stragglers": straggler_stats(hosts),
    }


def straggler_stats(hosts: list[dict]) -> dict:
    """Fleet-health numbers over the per-host summaries.

    ``detect``: per-host detect-phase seconds, spread (max−min) and the
    slowest host — the partitions never talk during the loop, so a wide
    spread is pure straggle the end-of-run all-reduce serializes on.
    ``throughput``: per-host rows/s (run totals, else the newest
    heartbeat) and the max/min skew factor.
    """
    out: dict = {"detect": None, "throughput": None}
    detect = {
        h["process_index"]: h["phases"]["detect"]
        for h in hosts
        if "detect" in h["phases"]
    }
    if len(detect) >= 1:
        slowest = max(detect, key=lambda k: detect[k])
        fastest = min(detect, key=lambda k: detect[k])
        out["detect"] = {
            "per_host": detect,
            "slowest": slowest,
            "fastest": fastest,
            "spread_s": detect[slowest] - detect[fastest],
            "ratio": (
                detect[slowest] / detect[fastest]
                if detect[fastest] > 0
                else None
            ),
        }
    rates = {
        h["process_index"]: h["progress_rate"]
        for h in hosts
        if h["progress_rate"]
    }
    if rates:
        slowest = min(rates, key=lambda k: rates[k])
        out["throughput"] = {
            "per_host": rates,
            "slowest": slowest,
            "skew": (
                max(rates.values()) / rates[slowest]
                if rates[slowest] > 0
                else None
            ),
        }
    return out


def render_correlation(corr: dict, timeline_limit: int = _TIMELINE_LIMIT) -> str:
    hosts = corr["hosts"]
    want = hosts[0]["process_count"]
    out = [
        f"correlated {len(hosts)} process log(s)"
        f"  (config {corr['digest']}"
        + (f", process_count={want}" if want else "")
        + ")"
    ]
    if want and want != len(hosts):
        out.append(
            f"warning    {len(hosts)}/{want} process logs present — "
            "missing hosts never wrote (or their logs were not passed)"
        )
    out.append(
        f"{'host':<24} {'detect_s':>9} {'rows/s':>12} {'detections':>10}"
        f"  last event"
    )
    for h in hosts:
        rate = f"{h['progress_rate']:,.0f}" if h["progress_rate"] else "-"
        det_s = (
            f"{h['phases']['detect']:.4f}" if "detect" in h["phases"] else "-"
        )
        last = (
            f"{h['last_type']} @ +{h['last_t']:.3f}s"
            if h["last_type"]
            else "-"
        )
        if not h["completed"]:
            last += "  (incomplete)"
        out.append(
            f"proc{h['process_index']} {h['hostname']:<18.18} {det_s:>9} "
            f"{rate:>12} {h['detections']:>10}  {last}"
        )
    st = corr["stragglers"]
    if st["detect"] and len(st["detect"]["per_host"]) > 1:
        d = st["detect"]
        pct = f"  ({(d['ratio'] - 1) * 100:+.0f}%)" if d["ratio"] else ""
        out.append(
            f"detect spread  {d['spread_s']:.4f} s — slowest "
            f"proc{d['slowest']}, fastest proc{d['fastest']}{pct}"
        )
    if st["throughput"] and len(st["throughput"]["per_host"]) > 1:
        t = st["throughput"]
        skew = f"{t['skew']:.2f}x" if t["skew"] else "?"
        out.append(
            f"throughput skew {skew} — slowest proc{t['slowest']}"
        )
    out.append(
        "merged timeline (t relative to each host's run_started — clock "
        "skew rebased)"
    )
    shown = corr["timeline"][:timeline_limit]
    for e in shown:
        detail = {
            "phase_completed": lambda e: f"{e['phase']} {e['seconds']:.4f}s",
            "chunk_completed": lambda e: (
                f"chunk {e['chunk']} ({e['batches_done']} batches, "
                f"{e['detections']} det)"
            ),
            "leg_completed": lambda e: (
                f"leg {e['leg']} ({e['rows']:,} rows, {e['detections']} det)"
            ),
            "heartbeat": lambda e: (
                f"{e['rows_done']:,} rows in {e['elapsed_s']:.2f}s"
            ),
            "drift_detected": lambda e: (
                f"partition {e['partition']} @ {e['global_pos']}"
            ),
            "run_completed": lambda e: (
                f"{e['rows']:,} rows / {e['seconds']:.4f}s"
            ),
        }.get(e["type"], lambda e: "")(e)
        out.append(
            f"  +{e['t']:9.4f}s  proc{e['host']}  {e['type']:<16} {detail}"
        )
    hidden = len(corr["timeline"]) - len(shown)
    if hidden > 0:
        out.append(f"  ... {hidden} more events")
    return "\n".join(out)


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(
        prog="python -m distributed_drift_detection_tpu correlate",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument(
        "paths",
        nargs="+",
        help="one telemetry directory (newest coherent multi-host group is "
        "picked) or the run-log *.jsonl files of one run",
    )
    ap.add_argument(
        "--timeline",
        type=int,
        default=_TIMELINE_LIMIT,
        help=f"merged-timeline rows to render (default {_TIMELINE_LIMIT})",
    )
    args = ap.parse_args(argv)
    paths = args.paths
    if len(paths) == 1 and os.path.isdir(paths[0]):
        paths = group_run_logs(paths[0])
    try:
        corr = correlate(paths)
    except CorrelationError as e:
        raise SystemExit(f"correlate: {e}") from None
    print(render_correlation(corr, timeline_limit=args.timeline))


if __name__ == "__main__":
    main(sys.argv[1:])
