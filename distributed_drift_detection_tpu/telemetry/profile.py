"""Compiler/device-level performance introspection.

The event log and metrics registry (PR 1) explain where *host wall-clock*
went at phase granularity; this module captures what the **compiler and
devices** report, so "is the detect phase anywhere near what the compiled
HLO could deliver" and "how much HBM does this (window × rotations ×
partitions) configuration actually need" become offline-answerable too:

* :func:`compiled_stats` — AOT-lower a jitted callable at concrete args and
  read ``Compiled.cost_analysis()`` (flops, bytes accessed) and
  ``Compiled.memory_analysis()`` (argument/output/temp/generated-code
  bytes). Never raises: a backend that doesn't implement an analysis yields
  ``None`` for that half, not a crashed run.
* :func:`device_memory_stats` — ``device.memory_stats()`` filtered to its
  numeric fields (``bytes_in_use``, ``peak_bytes_in_use``, …); ``None``
  where the backend provides nothing (XLA CPU).
* emit/record helpers mapping both onto the schema-v1 event types
  (``cost_analysis``, ``memory_snapshot`` — :mod:`.events`) and the
  registry gauges (``xla_*``, ``device_*`` — :mod:`.metrics`).

Discipline (same as the rest of the telemetry package): everything here is
host-side, runs only when telemetry/profiling is opted into, and is called
strictly **outside** the reference-parity Final Time span — ``api.run``
extracts compiled stats in its post-span ``_finish_telemetry`` and takes
device-memory snapshots before the span opens / after it closes. The one
real cost is :func:`compiled_stats` re-lowering and AOT-compiling the
runner (a host-side re-trace plus roughly one extra XLA compile, unless a
persistent compile cache serves it — bench.py enables one) — the opt-in
observability trade, paid after the span.

Unlike the package's jax-free core, this module *talks to* jax — but only
lazily inside functions, so importing :mod:`telemetry` (the report/perf
CLI path) still never initialises a backend.
"""

from __future__ import annotations

__all__ = [
    "compiled_stats",
    "device_memory_stats",
    "emit_compiled_events",
    "emit_device_memory_event",
    "memory_analysis_dict",
    "normalize_cost_analysis",
    "record_compiled_gauges",
    "record_device_memory_gauges",
]

# CompiledMemoryStats attributes persisted (device-relevant sizes; the
# host_* mirror fields are zero everywhere this framework runs).
_MEMORY_FIELDS = (
    "argument_size_in_bytes",
    "output_size_in_bytes",
    "temp_size_in_bytes",
    "alias_size_in_bytes",
    "generated_code_size_in_bytes",
)


def normalize_cost_analysis(raw) -> dict | None:
    """``Compiled.cost_analysis()`` → one flat ``{metric: float}`` dict.

    Normalises the cross-version/backend shapes: jax ≤ 0.4.x wraps the map
    in a one-element list, keys use spaces (``"bytes accessed"``) — emitted
    keys are underscore-joined (``bytes_accessed``) so they are valid
    metric/JSON identifiers. Non-numeric values are dropped."""
    if isinstance(raw, (list, tuple)):
        raw = raw[0] if raw else None
    if not isinstance(raw, dict):
        return None
    out = {}
    for k, v in raw.items():
        try:
            out[str(k).replace(" ", "_")] = float(v)
        except (TypeError, ValueError):
            continue
    return out or None


def memory_analysis_dict(ma) -> dict | None:
    """``Compiled.memory_analysis()`` → ``{argument_bytes, output_bytes,
    temp_bytes, alias_bytes, generated_code_bytes}`` (ints), or ``None``
    when the backend returns nothing."""
    if ma is None:
        return None
    out = {}
    for field in _MEMORY_FIELDS:
        v = getattr(ma, field, None)
        if v is not None:
            out[field.replace("_size_in_bytes", "_bytes")] = int(v)
    return out or None


def compiled_stats(jitted, *args, **kwargs) -> dict:
    """AOT-lower ``jitted`` at ``args`` → ``{"cost": ..., "memory": ...}``.

    Both halves are ``None`` when unavailable (backend without the
    analysis, or a callable that refuses to lower) — introspection must
    never take down the run it describes. Prefer calling with the SAME
    (committed, sharded) arguments the runner executed with, so the
    analyzed program is the executed one; host arrays with matching avals
    lower a default-placement twin instead. ``.compile()`` costs roughly
    one extra XLA compile unless a persistent compile cache serves it.
    """
    try:
        compiled = jitted.lower(*args, **kwargs).compile()
    except Exception:
        return {"cost": None, "memory": None}
    cost = memory = None
    try:
        cost = normalize_cost_analysis(compiled.cost_analysis())
    except Exception:
        pass
    try:
        memory = memory_analysis_dict(compiled.memory_analysis())
    except Exception:
        pass
    return {"cost": cost, "memory": memory}


def device_memory_stats(device=None) -> dict | None:
    """Numeric fields of ``device.memory_stats()``; ``None`` where the
    backend provides none (XLA CPU) or the call fails."""
    if device is None:
        import jax

        device = jax.devices()[0]
    try:
        stats = device.memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    out = {
        k: v
        for k, v in stats.items()
        if isinstance(v, (int, float)) and not isinstance(v, bool)
    }
    return out or None


# -- event emission ---------------------------------------------------------


def emit_compiled_events(log, stats: dict, where: str = "detect_runner") -> None:
    """Emit one ``cost_analysis`` (+ one ``memory_snapshot`` when the
    compiler reported memory sizes) from a :func:`compiled_stats` result.
    No-op when both halves are ``None``."""
    cost, memory = stats.get("cost"), stats.get("memory")
    if cost is None and memory is None:
        return
    cost = cost or {}
    log.emit(
        "cost_analysis",
        where=where,
        flops=cost.get("flops"),
        bytes_accessed=cost.get("bytes_accessed"),
        analysis=cost or None,
    )
    if memory:
        log.emit(
            "memory_snapshot", source="memory_analysis", stats=memory,
            where=where,
        )


def emit_device_memory_event(log, stats: dict | None, when: str) -> None:
    """Emit one device ``memory_snapshot`` (no-op when the backend gave
    nothing — absence of a snapshot means "backend doesn't report", never
    a fabricated zero)."""
    if stats:
        log.emit("memory_snapshot", source="device", stats=stats, when=when)


# -- registry gauges --------------------------------------------------------


def record_compiled_gauges(registry, stats: dict) -> None:
    """Record a :func:`compiled_stats` result as ``xla_*`` gauges."""
    cost = stats.get("cost") or {}
    for key, name in (
        ("flops", "xla_flops"),
        ("bytes_accessed", "xla_bytes_accessed"),
    ):
        if cost.get(key) is not None:
            registry.gauge(
                name, help=f"XLA cost analysis: {key} per runner execution"
            ).set(cost[key])
    for key, value in (stats.get("memory") or {}).items():
        registry.gauge(
            f"xla_{key}", help=f"XLA memory analysis: {key}"
        ).set(value)


def record_device_memory_gauges(
    registry, stats: dict | None, when: str = ""
) -> None:
    """Record a device-memory snapshot as gauges (no-op on ``None``).

    ``device_bytes_in_use{when=...}`` is last-write-wins per label (the
    engines call this per chunk/leg — the gauge tracks the latest point);
    ``device_peak_bytes_in_use`` keeps the max across every call, so a
    transient allocation spike between snapshots the backend itself peaked
    on is not lost to the last-write semantics."""
    if not stats:
        return
    in_use = stats.get("bytes_in_use")
    if in_use is not None:
        g = registry.gauge(
            "device_bytes_in_use", help="Device memory in use at snapshot"
        )
        g.set(in_use, **({"when": when} if when else {}))
    peak = stats.get("peak_bytes_in_use", in_use)
    if peak is not None:
        g = registry.gauge(
            "device_peak_bytes_in_use",
            help="Max device bytes in use across snapshots",
        )
        prior = g.values.get((), float("-inf"))
        g.set(max(float(peak), prior))
