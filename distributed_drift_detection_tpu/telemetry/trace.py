"""End-to-end row tracing for the serving path.

The batch pipeline measures itself with phase spans; a serving daemon
needs *per-row latency attribution* — where did the time between a row
arriving at the ingress and its verdict landing actually go? This module
is the shared vocabulary for that: one live histogram,

    ``serve_row_latency_seconds{stage=...}``

fed by the serve loop as each microbatch publishes, with the pipeline
stages as labels:

* ``admission`` — per **row**: monotonic ingest stamp (taken when the
  admission layer pushed the row into the :class:`~..serve.admission.
  MicroBatcher`) → the microbatch sealing. How long rows waited for the
  grid to fill (bounded by the linger deadline).
* ``queue`` — per chunk: sealed → handed to the device feed (queue wait
  behind the double-buffered pipeline + host→device placement dispatch).
* ``device`` — per chunk: fed → flags collected host-side (device
  compute + the d2h sync).
* ``collect`` — per chunk: collected → verdict line flushed to the
  sidecar (host flag scan + the publication write).
* ``total`` — per **row**: ingest → verdict flushed. The end-to-end
  row→verdict latency; its live p50/p99 must agree with what ``loadgen``
  derives post-hoc from the verdict sidecar (pinned by tests within
  histogram-bucket tolerance).

Per-row stages are observed **vectorized** (:func:`observe_array` — one
``searchsorted`` + ``bincount`` per microbatch, identical semantics to N
``Histogram.observe`` calls), so tracing costs O(buckets) per chunk, not
O(rows) Python work. Quantiles come back out of the cumulative buckets
via :func:`hist_quantile` (live registry object) or
:func:`prom_histogram_quantile` (a parsed ``/metrics`` scrape) — linear
interpolation inside the bucket, Prometheus ``histogram_quantile``
semantics.

No jax; numpy only (safe in the ops/evaluator threads and jax-free CLIs).
"""

from __future__ import annotations

import numpy as np

from .metrics import DEFAULT_BUCKETS, Histogram, MetricsRegistry, _label_key

LATENCY_METRIC = "serve_row_latency_seconds"
LATENCY_HELP = (
    "Row-to-verdict latency of the serving pipeline by stage "
    "(admission/queue/device/collect per-chunk or per-row; total = "
    "ingest to published verdict per row)"
)

STAGES = ("admission", "queue", "device", "collect", "total")


def latency_histogram(registry: MetricsRegistry) -> Histogram:
    """The one serving-latency histogram (idempotent fetch)."""
    return registry.histogram(
        LATENCY_METRIC, help=LATENCY_HELP, buckets=DEFAULT_BUCKETS
    )


def observe_array(hist: Histogram, values, **labels) -> None:
    """Observe a whole array into one histogram label set, bit-identical
    to calling :meth:`~.metrics.Histogram.observe` per element (``value
    <= bucket`` boundary semantics) but O(buckets) Python work."""
    values = np.asarray(values, np.float64).ravel()
    if values.size == 0:
        return
    k = _label_key(labels)
    slot = hist.values.get(k)
    if slot is None:
        slot = hist.values[k] = [[0] * (len(hist.buckets) + 1), 0.0, 0]
    # side='left': first bucket b with value <= b — the observe() rule.
    idx = np.searchsorted(np.asarray(hist.buckets), values, side="left")
    counts = np.bincount(idx, minlength=len(hist.buckets) + 1)
    for i, c in enumerate(counts):
        if c:
            slot[0][i] += int(c)
    slot[1] += float(values.sum())
    slot[2] += int(values.size)


def observe_chunk_stages(
    hist: Histogram,
    meta: dict,
    *,
    fed_mono: float,
    collected_mono: float,
    published_mono: float,
) -> None:
    """Attribute one published microbatch across the pipeline stages.

    ``meta`` is the sealed chunk's accounting dict; the admission layer
    stamps ``ingest_mono`` (per-admitted-row monotonic array) and
    ``sealed_mono`` into it, the serve loop supplies the rest. Negative
    deltas (sub-poll clock granularity) clamp to zero."""
    sealed = float(meta.get("sealed_mono", fed_mono))
    ingest = meta.get("ingest_mono")
    if ingest is not None and len(ingest):
        ingest = np.asarray(ingest, np.float64)
        observe_array(hist, np.maximum(sealed - ingest, 0.0), stage="admission")
        observe_array(
            hist, np.maximum(published_mono - ingest, 0.0), stage="total"
        )
    hist.observe(max(fed_mono - sealed, 0.0), stage="queue")
    hist.observe(max(collected_mono - fed_mono, 0.0), stage="device")
    hist.observe(max(published_mono - collected_mono, 0.0), stage="collect")


def _quantile_from_cumulative(
    pairs: list[tuple[float, float]], q: float
) -> "float | None":
    """Prometheus ``histogram_quantile`` over ``(upper_bound, cumulative
    count)`` pairs (``+Inf`` as ``math.inf``), linear interpolation inside
    the bucket; the overflow bucket reports its lower bound (nothing
    finite to interpolate toward)."""
    if not pairs:
        return None
    pairs = sorted(pairs)
    total = pairs[-1][1]
    if total <= 0:
        return None
    target = q * total
    prev_bound, prev_cum = 0.0, 0.0
    for bound, cum in pairs:
        if cum >= target:
            if bound == float("inf"):
                return prev_bound
            width = bound - prev_bound
            in_bucket = cum - prev_cum
            if in_bucket <= 0 or width <= 0:
                return bound
            return prev_bound + width * (target - prev_cum) / in_bucket
        prev_bound, prev_cum = bound, cum
    return prev_bound


def hist_quantile(hist: Histogram, q: float, **labels) -> "float | None":
    """Quantile ``q`` (0..1) of one label set of a live histogram;
    ``None`` while it has no samples."""
    k = _label_key(labels)
    if k not in hist.values:
        return None
    pairs = [
        (float("inf") if le == "+Inf" else float(le), float(c))
        for le, c in hist.cumulative(k)
    ]
    return _quantile_from_cumulative(pairs, q)


def prom_histogram_quantile(
    samples: dict, name: str, q: float, **labels
) -> "float | None":
    """Quantile ``q`` from a :func:`~.metrics.parse_prometheus_text`
    sample map — the scrape-side counterpart of :func:`hist_quantile`
    (tests pin the two against each other)."""
    want = {(k, str(v)) for k, v in labels.items()}
    pairs = []
    for (sname, slabels), value in samples.items():
        if sname != name + "_bucket":
            continue
        lmap = dict(slabels)
        le = lmap.pop("le", None)
        if le is None or set(lmap.items()) != want:
            continue
        pairs.append(
            (float("inf") if le == "+Inf" else float(le), float(value))
        )
    return _quantile_from_cumulative(pairs, q)
