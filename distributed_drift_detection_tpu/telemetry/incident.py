"""Incident autopsy plane: alert-triggered cross-plane evidence capture,
a deterministic diagnosis engine, and the fleet incident index.

The repo grew four separate evidence planes — the flight-recorder ring
(dumped only on crash), trace/forensics bundles, pipeline stage
attribution, and the durable metrics history — but when an SLO alert
fired on a live daemon nobody snapshotted any of them: the operator (or
the ROADMAP item-3 autoscaler) was left joining five CLIs by hand after
the window of evidence had rotated away. This module closes that gap:

* :class:`IncidentRecorder` — subscribed to the
  :class:`~.slo.SloEngine`'s fire/resolve transitions (the engine's
  ``observer`` hook, invoked on the SLO evaluator thread — never the
  serve loop) and to the crash path. Every ``firing`` transition
  captures a numbered, self-contained evidence bundle under
  ``<run-log stem>.incidents/incident-NNNN/``:

  =======================  ==============================================
  ``flightrec.jsonl``      the flight ring at firing time (the crash-only
                           dump, generalized)
  ``pipeline.json``        live stage attribution: busy shares, dominant
                           stage, the wedged-stage breadcrumb
  ``statusz.json``         the full ``/statusz`` snapshot
  ``history.jsonl``        a window extract from the history store around
                           the firing timestamp (when a store is
                           configured)
  ``top_tenants.json``     the per-tenant hotness ranking over the window
  ``verdicts_tail.jsonl``  the newest verdict sidecar lines
  ``quarantine_tail.jsonl`` the newest quarantine sidecar lines
  ``manifest.json``        firing rule + value + threshold + file list —
                           written LAST, atomically: its presence is the
                           bundle-complete marker
  ``resolved.json``        the resolve transition, appended when the
                           alert clears (open incidents lack it)
  =======================  ==============================================

  A daemon killed mid-capture leaves a directory without a manifest;
  :func:`read_bundle` surfaces that as a loud ``partial: true``, never a
  crash or a silently-complete-looking report. Verdict sidecars are
  bit-identical with incidents on or off (pinned by tests): capture runs
  entirely off the serve hot loop and only *reads* runner state.

* :func:`diagnose` — a deterministic, jax-free rule engine ranking
  probable causes from the bundle alone, each verdict citing the exact
  numbers it used: ``<stage>-bound`` (wedged-stage breadcrumb under a
  ``stall_s`` firing, or dominant pipeline share), ``under-driven``
  (seal_wait dominant), ``hot-tenant-skew`` (top tenant vs. fleet
  median), ``quarantine-spike``, ``adaptation-storm`` (flight-ring
  adaptation events), ``backend-down`` (``up == 0`` in the history
  extract). The autoscaler reads a diagnosis, not a bare alert bit.

* :func:`main` — the ``incident`` CLI (``list`` / ``show`` /
  ``diagnose``), JSON or a rendered report with history sparklines, plus
  a ``--store`` fleet incident index (the collector scrapes every
  daemon's ``/incidentz`` into ``serve_incidents_total{instance=...}``).

Exit codes follow the ``watch``/``history`` convention: 0 ok, 3 empty,
4 nothing resolvable. No jax anywhere; stdlib + sibling telemetry
modules only — importable by every jax-free CLI.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
import threading
import time

INCIDENTS_SUFFIX = ".incidents"
BUNDLE_PREFIX = "incident-"

MANIFEST_NAME = "manifest.json"
RESOLVED_NAME = "resolved.json"
FLIGHT_NAME = "flightrec.jsonl"
PIPELINE_NAME = "pipeline.json"
STATUSZ_NAME = "statusz.json"
HISTORY_NAME = "history.jsonl"
TENANTS_NAME = "top_tenants.json"
VERDICTS_TAIL_NAME = "verdicts_tail.jsonl"
QUARANTINE_TAIL_NAME = "quarantine_tail.jsonl"

INCIDENT_CAPTURES_METRIC = "incident_captures_total"
INCIDENT_CAPTURES_HELP = (
    "Incident bundles captured, labeled by the firing rule (or 'crash')"
)
INCIDENT_OPEN_METRIC = "incident_open"
INCIDENT_OPEN_HELP = (
    "Captured incidents whose firing alert has not resolved yet"
)

#: The fleet-index series the collector lifts from each daemon's
#: ``/incidentz`` into the history store (``instance`` labeled).
INCIDENTS_TOTAL_SERIES = "serve_incidents_total"
INCIDENT_OPEN_SERIES = "serve_incident_open"

_BUNDLE_RE = re.compile(re.escape(BUNDLE_PREFIX) + r"\d{4,}$")


# -- small tolerant IO helpers ------------------------------------------------


def _write_json(path: str, obj) -> bool:
    """Atomic best-effort JSON write (tmp + rename); False on failure."""
    tmp = path + ".tmp"
    try:
        with open(tmp, "w") as fh:
            json.dump(obj, fh, indent=1)
            fh.write("\n")
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
    except (OSError, TypeError, ValueError):
        try:
            os.remove(tmp)
        except OSError:
            pass
        return False
    return True


def _write_lines(path: str, lines) -> bool:
    lines = list(lines)
    if not lines:
        return False
    try:
        with open(path, "w") as fh:
            for line in lines:
                fh.write(line.rstrip("\n") + "\n")
            fh.flush()
    except OSError:
        return False
    return True


def _load_json(path: str):
    """One JSON document, or ``None`` (absent/torn — evidence reading
    never raises)."""
    try:
        with open(path) as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


def _load_jsonl(path: str) -> list[dict]:
    """Tolerant JSONL read: unparseable lines (a torn tail from a killed
    writer) are skipped, never raised — a partial bundle must still read."""
    out: list[dict] = []
    try:
        with open(path) as fh:
            lines = fh.readlines()
    except OSError:
        return out
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict):
            out.append(rec)
    return out


def _tail_lines(path: str, n: int, max_bytes: int = 1 << 20) -> list[str]:
    """Last ``n`` complete lines of a (possibly huge) sidecar, reading at
    most ``max_bytes`` from the end — capture must stay cheap no matter
    how large the sidecar has grown."""
    try:
        with open(path, "rb") as fh:
            fh.seek(0, os.SEEK_END)
            size = fh.tell()
            fh.seek(max(0, size - max_bytes))
            data = fh.read()
    except OSError:
        return []
    raw = data.split(b"\n")
    if size > max_bytes and raw:
        raw = raw[1:]  # the seek likely landed mid-line: drop the torn head
    lines = [ln.decode("utf-8", "replace") for ln in raw if ln.strip()]
    return lines[-max(int(n), 0):]


# -- capture ------------------------------------------------------------------


class IncidentRecorder:
    """Alert/crash-triggered evidence capture for one serving daemon.

    All capture callables only *read* runner state (the same contract as
    the ops handlers); bundles are written on the calling thread — the
    SLO evaluator for alerts, the dying loop thread for crashes — never
    the serve hot loop. :meth:`on_transition` is wired as
    ``SloEngine.observer``.
    """

    def __init__(
        self,
        stem: str,
        *,
        flight=None,
        statusz_fn=None,
        pipeline_fn=None,
        verdicts_path: "str | None" = None,
        store: "str | None" = None,
        window_s: float = 120.0,
        metrics=None,
        max_bundles: int = 32,
        tail_rows: int = 64,
    ):
        """``flight`` is the daemon's :class:`~.ops.FlightRecorder` (or
        ``None``); ``store`` a history-store directory for the window
        extract; ``max_bundles`` bounds captures per process lifetime
        (an alert-storm must not fill the disk — skips are counted)."""
        self.stem = stem
        self.root = stem + INCIDENTS_SUFFIX
        self._flight = flight
        self._statusz_fn = statusz_fn
        self._pipeline_fn = pipeline_fn
        self._verdicts_path = verdicts_path
        self._store = store or None
        self._window_s = float(window_s)
        self._max = max(int(max_bundles), 1)
        self._tail_rows = int(tail_rows)
        self._lock = threading.Lock()
        self._seq = 0
        self._captured = 0
        self._skipped = 0
        self._open: dict[str, str] = {}  # firing rule -> bundle name
        self._latest: "dict | None" = None
        self.last_capture_ms: "float | None" = None
        self._counter = self._gauge = None
        if metrics is not None:
            self._counter = metrics.counter(
                INCIDENT_CAPTURES_METRIC, help=INCIDENT_CAPTURES_HELP
            )
            self._gauge = metrics.gauge(
                INCIDENT_OPEN_METRIC, help=INCIDENT_OPEN_HELP
            )
            self._gauge.set(0.0)

    # - the SloEngine.observer hook (evaluator thread) -

    def on_transition(self, t: dict) -> None:
        """One successfully-emitted alert transition: ``firing`` captures
        a bundle and opens the incident, ``resolved`` closes it (writing
        the resolve transition into the bundle as ``resolved.json``)."""
        rule = str(t.get("rule") or "")
        if t.get("state") == "firing":
            name = self.capture(t)
            if name is not None:
                with self._lock:
                    self._open[rule] = name
        else:
            with self._lock:
                name = self._open.pop(rule, None)
            if name is not None:
                _write_json(os.path.join(self.root, name, RESOLVED_NAME), t)
        self._sync_gauge()

    def capture(self, reason: dict, kind: str = "alert") -> "str | None":
        """Write one evidence bundle; returns its directory name, or
        ``None`` (bundle cap reached, or the manifest could not land —
        the latter leaves a partial bundle readers flag loudly). Every
        artifact is individually best-effort: a broken snapshot source
        costs that file, never the bundle."""
        t0 = time.monotonic()
        with self._lock:
            if self._seq >= self._max:
                self._skipped += 1
                return None
            self._seq += 1
            seq = self._seq
        name = f"{BUNDLE_PREFIX}{seq:04d}"
        path = os.path.join(self.root, name)
        try:
            os.makedirs(path, exist_ok=True)
        except OSError:
            return None
        files: list[str] = []
        if self._flight is not None:
            try:
                if self._flight.dump(os.path.join(path, FLIGHT_NAME)):
                    files.append(FLIGHT_NAME)
            except Exception:
                pass
        for fname, fn in (
            (PIPELINE_NAME, self._pipeline_fn),
            (STATUSZ_NAME, self._statusz_fn),
        ):
            if fn is None:
                continue
            try:
                obj = fn()
            except Exception:
                obj = None
            if obj is not None and _write_json(
                os.path.join(path, fname), obj
            ):
                files.append(fname)
        if self._verdicts_path and _write_lines(
            os.path.join(path, VERDICTS_TAIL_NAME),
            _tail_lines(self._verdicts_path, self._tail_rows),
        ):
            files.append(VERDICTS_TAIL_NAME)
        qlines: list[str] = []
        for qpath in sorted(
            glob.glob(glob.escape(self.stem) + "*quarantine.jsonl")
        ):
            qlines.extend(_tail_lines(qpath, self._tail_rows))
        if qlines and _write_lines(
            os.path.join(path, QUARANTINE_TAIL_NAME),
            qlines[-self._tail_rows:],
        ):
            files.append(QUARANTINE_TAIL_NAME)
        if self._store:
            try:
                from .history import list_segments, read_samples, top_tenants

                if list_segments(self._store):
                    now = time.time()
                    recs = read_samples(
                        self._store,
                        start=now - self._window_s,
                        end=now + 1.0,
                    )
                    if recs and _write_lines(
                        os.path.join(path, HISTORY_NAME),
                        [json.dumps(r) for r in recs],
                    ):
                        files.append(HISTORY_NAME)
                    ranked = top_tenants(
                        self._store, window_s=self._window_s, at=now
                    )
                    if ranked and _write_json(
                        os.path.join(path, TENANTS_NAME), ranked
                    ):
                        files.append(TENANTS_NAME)
            except Exception:
                pass
        capture_ms = round((time.monotonic() - t0) * 1e3, 3)
        manifest = {
            "v": 1,
            "id": name,
            "seq": seq,
            "kind": kind,
            "ts": round(time.time(), 6),
            "mono": round(time.monotonic(), 6),
            "rule": reason.get("rule"),
            "state": reason.get("state", "firing"),
            "value": reason.get("value"),
            "threshold": reason.get("threshold"),
            **(
                {"alert_mono": reason["mono"]} if "mono" in reason else {}
            ),
            **({"error": reason["error"]} if "error" in reason else {}),
            "stem": os.path.basename(self.stem),
            "files": files,
            "capture_ms": capture_ms,
        }
        # The manifest lands LAST, atomically: its presence IS the
        # bundle-complete marker. A daemon killed before this point
        # leaves a manifest-less dir that reads as partial.
        if not _write_json(os.path.join(path, MANIFEST_NAME), manifest):
            return None
        self.last_capture_ms = capture_ms
        with self._lock:
            self._captured += 1
            self._latest = manifest
        if self._counter is not None:
            self._counter.inc(1.0, rule=str(reason.get("rule") or kind))
        return name

    def capture_crash(self, error: str) -> "str | None":
        """The crash-path generalization of the flight-recorder dump:
        a failing daemon leaves a full bundle too, rule ``crash``."""
        return self.capture(
            {"rule": "crash", "state": "firing", "error": str(error)},
            kind="crash",
        )

    def _sync_gauge(self) -> None:
        if self._gauge is not None:
            with self._lock:
                n = len(self._open)
            self._gauge.set(float(n))

    # - surfaces -

    def count(self) -> int:
        with self._lock:
            return self._captured

    def statusz_section(self) -> dict:
        """The ``/statusz`` ``incidents`` section (``backend_snapshot``
        lifts ``count`` into the fleet view)."""
        with self._lock:
            return {
                "count": self._captured,
                "open": len(self._open),
                "skipped": self._skipped,
                "dir": self.root,
            }

    def incidentz(self) -> dict:
        """The ``/incidentz`` payload: counts + the latest manifest."""
        with self._lock:
            return {
                "count": self._captured,
                "open": len(self._open),
                "skipped": self._skipped,
                "dir": self.root,
                "last_capture_ms": self.last_capture_ms,
                "latest": dict(self._latest) if self._latest else None,
            }


# -- reading ------------------------------------------------------------------


def list_bundles(root: str) -> list[str]:
    """Bundle directories under one ``.incidents`` root, capture order."""
    if not os.path.isdir(root):
        return []
    return sorted(
        p
        for p in glob.glob(os.path.join(root, BUNDLE_PREFIX + "*"))
        if os.path.isdir(p) and _BUNDLE_RE.search(os.path.basename(p))
    )


def resolve_incidents_dir(source: str) -> "str | None":
    """Map any supported ``source`` to an ``.incidents`` root: the root
    itself, a run log (its stem's sibling), or a telemetry dir (the
    newest ``*.incidents`` inside). ``None`` when nothing resolves."""
    if source.endswith(".jsonl"):
        root = os.path.splitext(source)[0] + INCIDENTS_SUFFIX
        return root if os.path.isdir(root) else None
    if not os.path.isdir(source):
        return None
    base = os.path.basename(os.path.normpath(source))
    if base.endswith(INCIDENTS_SUFFIX) or list_bundles(source):
        return source
    roots = [
        p
        for p in glob.glob(os.path.join(source, "*" + INCIDENTS_SUFFIX))
        if os.path.isdir(p)
    ]
    if not roots:
        return None
    return max(roots, key=os.path.getmtime)


def read_bundle(path: str) -> dict:
    """One bundle directory → the in-memory evidence dict
    :func:`diagnose` consumes. Never raises on torn evidence: a missing
    or unparseable manifest marks the bundle ``partial: true`` (the
    daemon died mid-capture), and every artifact reads tolerantly."""
    manifest = _load_json(os.path.join(path, MANIFEST_NAME))
    return {
        "path": path,
        "id": os.path.basename(os.path.normpath(path)),
        "partial": not isinstance(manifest, dict),
        "manifest": manifest if isinstance(manifest, dict) else None,
        "resolved": _load_json(os.path.join(path, RESOLVED_NAME)),
        "pipeline": _load_json(os.path.join(path, PIPELINE_NAME)),
        "statusz": _load_json(os.path.join(path, STATUSZ_NAME)),
        "top_tenants": _load_json(os.path.join(path, TENANTS_NAME)),
        "flightrec": _load_jsonl(os.path.join(path, FLIGHT_NAME)),
        "history": _load_jsonl(os.path.join(path, HISTORY_NAME)),
        "verdicts_tail": _load_jsonl(
            os.path.join(path, VERDICTS_TAIL_NAME)
        ),
        "quarantine_tail": _load_jsonl(
            os.path.join(path, QUARANTINE_TAIL_NAME)
        ),
    }


# -- diagnosis ----------------------------------------------------------------


def _fmt(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:g}"
    return str(v)


def diagnose(bundle: dict) -> list[dict]:
    """Rank probable causes from one bundle — deterministic, jax-free,
    bundle-only (runs identically on the daemon host or a laptop).
    Returns ``[{"cause", "score", "evidence"}, ...]`` sorted by score
    descending; every verdict cites the exact numbers it used."""
    manifest = bundle.get("manifest") or {}
    pipe = bundle.get("pipeline") or {}
    statusz = bundle.get("statusz") or {}
    shares = pipe.get("shares") or {}
    busy = pipe.get("busy_s") or {}
    wall = pipe.get("wall_s")
    rule = str(manifest.get("rule") or "")
    value = manifest.get("value")
    threshold = manifest.get("threshold")
    causes: dict[str, dict] = {}

    def add(cause: str, score: float, evidence: str) -> None:
        score = round(float(score), 4)
        cur = causes.get(cause)
        if cur is None or score > cur["score"]:
            causes[cause] = {
                "cause": cause,
                "score": score,
                "evidence": evidence,
            }

    # 1. Wedged loop: a stall_s firing plus the loop's wedged-stage
    # breadcrumb names the stage the loop is stuck INSIDE right now —
    # mid-stall, the stage's busy counter hasn't been credited yet, so
    # shares alone would misattribute.
    cur = pipe.get("current_stage") or {}
    if rule == "stall_s" and cur.get("stage") and cur["stage"] != "seal_wait":
        add(
            f"{cur['stage']}-bound",
            0.95,
            f"serve loop wedged inside stage '{cur['stage']}' for "
            f"{_fmt(cur.get('for_s'))}s at capture "
            f"(stall_s {_fmt(value)} > threshold {_fmt(threshold)})",
        )

    # 2. Stage-bound: the dominant pipeline stage holds the busy share.
    dom = pipe.get("dominant_stage")
    if dom and dom != "seal_wait":
        share = float(shares.get(dom) or 0.0)
        if share >= 0.4:
            add(
                f"{dom}-bound",
                min(share, 0.94),
                f"stage '{dom}' holds {_fmt(busy.get(dom))}s busy = "
                f"{share * 100:.1f}% of measured busy time "
                f"over {_fmt(wall)}s loop wall",
            )

    # 3. Under-driven: the loop mostly waits for input.
    seal = float(shares.get("seal_wait") or 0.0)
    if seal >= 0.5:
        add(
            "under-driven",
            min(seal * 0.9, 0.9),
            f"seal_wait holds {_fmt(busy.get('seal_wait'))}s = "
            f"{seal * 100:.1f}% of measured busy time — the loop is "
            "waiting for input, not working",
        )

    # 4. Hot-tenant skew: top tenant vs. the median of the rest.
    tenants = bundle.get("top_tenants") or []
    if len(tenants) >= 2:
        top = tenants[0]
        top_rate = float(top.get("rows_per_sec") or 0.0)
        rest = sorted(
            float(t.get("rows_per_sec") or 0.0) for t in tenants[1:]
        )
        median = rest[len(rest) // 2]
        if top_rate > 0 and top_rate >= 4.0 * max(median, 1e-9):
            ratio = top_rate / max(median, 1e-9)
            add(
                "hot-tenant-skew",
                min(0.85, ratio / (ratio + 4.0)),
                f"tenant {top.get('tenant')} at {top_rate:g} rows/s vs "
                f"fleet median {median:g} rows/s "
                f"({min(ratio, 9999.0):.1f}x) over the capture window",
            )

    # 5. Quarantine spike: dirty-traffic share at admission.
    rows = statusz.get("rows") or {}
    seen = rows.get("ingress_seen")
    quar = rows.get("quarantined")
    if seen and quar is not None:
        pct = 100.0 * float(quar) / float(seen)
        if rule == "quarantine_pct" or pct > 5.0:
            add(
                "quarantine-spike",
                0.9 if rule == "quarantine_pct" else min(0.8, 0.3 + pct / 100.0),
                f"{int(quar)} of {int(seen)} ingress rows quarantined "
                f"({pct:.2f}%)"
                + (
                    f"; quarantine_pct {_fmt(value)} > "
                    f"threshold {_fmt(threshold)}"
                    if rule == "quarantine_pct"
                    else ""
                ),
            )

    # 6. Adaptation storm: the flight ring is full of refit events.
    ring = bundle.get("flightrec") or []
    n_adapt = sum(1 for e in ring if e.get("type") == "adaptation")
    if n_adapt >= 3:
        add(
            "adaptation-storm",
            min(0.75, 0.25 + 0.05 * n_adapt),
            f"{n_adapt} adaptation events among the {len(ring)} newest "
            "flight-ring events",
        )

    # 7. Backend down: the history extract saw up==0, or the aggregator's
    # own statusz names dead backends.
    down = sorted(
        {
            (r.get("labels") or {}).get("instance", "?")
            for r in bundle.get("history") or []
            if r.get("name") == "up" and float(r.get("value") or 0.0) == 0.0
        }
    )
    dead_rules = [
        str(a.get("rule"))
        for a in statusz.get("alerts") or []
        if str(a.get("rule") or "").startswith("backend_dead")
    ]
    if down or dead_rules:
        who = down or [r.partition(":")[2] or r for r in dead_rules]
        add(
            "backend-down",
            0.9,
            f"up=0 scraped for instance(s) {', '.join(who)} in the "
            "capture window"
            if down
            else f"aggregator alert(s) {', '.join(dead_rules)} firing",
        )

    out = sorted(
        causes.values(), key=lambda c: (-c["score"], c["cause"])
    )
    if not out:
        out = [
            {
                "cause": rule or "unknown",
                "score": 0.1,
                "evidence": (
                    f"alert {rule} fired (value {_fmt(value)} > "
                    f"threshold {_fmt(threshold)}) but no corroborating "
                    "evidence was captured"
                    if rule
                    else "no manifest and no corroborating evidence "
                    "(partial bundle)"
                ),
            }
        ]
    return out


# -- rendering ----------------------------------------------------------------


def _history_sparklines(bundle: dict, limit: int = 6) -> list[str]:
    """Sparkline rows for the bundle's history extract (one per series,
    newest-biased, at most ``limit``)."""
    from .history import sparkline

    series: dict[str, list[float]] = {}
    for rec in bundle.get("history") or []:
        labels = rec.get("labels") or {}
        inst = labels.get("instance")
        key = str(rec.get("name", "?")) + (
            f"{{instance={inst}}}" if inst else ""
        )
        try:
            series.setdefault(key, []).append(float(rec.get("value")))
        except (TypeError, ValueError):
            continue
    rows = []
    for key in sorted(series):
        vals = series[key]
        if len(vals) < 2:
            continue
        rows.append(
            f"  {key:<44} [{sparkline(vals, width=40)}] last={vals[-1]:g}"
        )
    return rows[:limit]


def render_bundle(bundle: dict) -> str:
    """The human ``incident show`` report."""
    lines = []
    man = bundle.get("manifest") or {}
    head = f"incident {bundle['id']}"
    if man:
        head += (
            f" — rule {man.get('rule')} {man.get('state', 'firing')}, "
            f"value {_fmt(man.get('value'))} > "
            f"threshold {_fmt(man.get('threshold'))}"
        )
    lines.append(head)
    if bundle.get("partial"):
        lines.append(
            "  PARTIAL: true — no manifest; the daemon died mid-capture, "
            "evidence below may be incomplete"
        )
    if man:
        lines.append(
            f"  captured ts={_fmt(man.get('ts'))} "
            f"capture_ms={_fmt(man.get('capture_ms'))} "
            f"kind={man.get('kind', 'alert')}"
        )
        if man.get("error"):
            lines.append(f"  error: {man['error']}")
        lines.append(f"  files: {' '.join(man.get('files') or ()) or '-'}")
    res = bundle.get("resolved")
    lines.append(
        f"  resolved: value {_fmt(res.get('value'))} at "
        f"mono {_fmt(res.get('mono'))}"
        if res
        else "  resolved: no (incident still open at last write)"
    )
    pipe = bundle.get("pipeline") or {}
    if pipe:
        dom = pipe.get("dominant_stage")
        share = (pipe.get("shares") or {}).get(dom)
        cur = pipe.get("current_stage") or {}
        extra = (
            f", loop inside '{cur.get('stage')}' for "
            f"{_fmt(cur.get('for_s'))}s"
            if cur.get("stage")
            else ""
        )
        lines.append(
            f"  pipeline: dominant {dom} "
            f"(share {share * 100:.1f}%)" + extra
            if dom and share is not None
            else f"  pipeline: (no busy time){extra}"
        )
    tenants = bundle.get("top_tenants") or []
    if tenants:
        tops = ", ".join(
            f"{t.get('tenant')}@{float(t.get('rows_per_sec') or 0):g}r/s"
            for t in tenants[:4]
        )
        lines.append(f"  top tenants: {tops}")
    sparks = _history_sparklines(bundle)
    if sparks:
        lines.append("  history window:")
        lines.extend(sparks)
    tails = [
        (name, len(bundle.get(key) or []))
        for name, key in (
            ("flightrec", "flightrec"),
            ("verdicts", "verdicts_tail"),
            ("quarantine", "quarantine_tail"),
        )
    ]
    lines.append(
        "  tails: " + " ".join(f"{n}={c}" for n, c in tails)
    )
    return "\n".join(lines)


def render_diagnosis(bundle: dict, verdicts: list[dict]) -> str:
    man = bundle.get("manifest") or {}
    lines = [
        f"diagnosis — {bundle['id']}"
        + (
            f" (rule {man.get('rule')}, value {_fmt(man.get('value'))} > "
            f"{_fmt(man.get('threshold'))})"
            if man
            else ""
        )
    ]
    if bundle.get("partial"):
        lines.append(
            "  PARTIAL: true — no manifest (daemon died mid-capture); "
            "diagnosis runs on whatever evidence landed"
        )
    for i, v in enumerate(verdicts, 1):
        lines.append(
            f"  {i}. {v['cause']:<18} score {v['score']:.2f}  {v['evidence']}"
        )
    return "\n".join(lines)


# -- CLI ----------------------------------------------------------------------


def _pick_bundle(source: str) -> "tuple[str | None, list[str]]":
    """(bundle path or None, all bundles of the resolved root)."""
    if os.path.isdir(source) and _BUNDLE_RE.search(
        os.path.basename(os.path.normpath(source))
    ):
        return source, [source]
    root = resolve_incidents_dir(source)
    if root is None:
        return None, []
    bundles = list_bundles(root)
    return (bundles[-1] if bundles else None), bundles


def main(argv=None) -> int:
    """``incident``: list/show/diagnose captured incident bundles."""
    ap = argparse.ArgumentParser(
        prog="python -m distributed_drift_detection_tpu incident",
        description=(
            "Incident autopsy (telemetry.incident): list captured "
            "bundles, render one, or rank probable causes from its "
            "evidence — all offline, from the bundle alone."
        ),
    )
    ap.add_argument("cmd", choices=("list", "show", "diagnose"))
    ap.add_argument(
        "source",
        help="an incident-NNNN bundle, a <stem>.incidents dir, a run "
        "log, or a telemetry dir (newest .incidents inside)",
    )
    ap.add_argument("--json", action="store_true")
    ap.add_argument(
        "--store",
        default=None,
        metavar="DIR",
        help="history store: `list` adds the fleet incident index "
        "(latest serve_incidents_total per instance)",
    )
    ap.add_argument(
        "--window", type=float, default=600.0, metavar="S",
        help="fleet-index look-back window for --store (default 600)",
    )
    args = ap.parse_args(argv)

    bundle_path, bundles = _pick_bundle(args.source)
    if not bundles and bundle_path is None:
        if resolve_incidents_dir(args.source) is None:
            print(
                f"incident: no incidents at {args.source}", file=sys.stderr
            )
            return 4

    if args.cmd == "list":
        rows = [read_bundle(p) for p in bundles]
        fleet = None
        if args.store:
            from .history import last_over_time, list_segments

            if list_segments(args.store):
                fleet = {
                    dict(k).get("instance", "?"): v
                    for k, v in last_over_time(
                        args.store,
                        INCIDENTS_TOTAL_SERIES,
                        window_s=args.window,
                    ).items()
                    if v is not None
                }
        if args.json:
            print(
                json.dumps(
                    {
                        "bundles": [
                            {
                                "id": b["id"],
                                "partial": b["partial"],
                                "manifest": b["manifest"],
                                "resolved": b["resolved"] is not None,
                            }
                            for b in rows
                        ],
                        **(
                            {"fleet_incidents": fleet}
                            if fleet is not None
                            else {}
                        ),
                    },
                    indent=1,
                )
            )
        else:
            print(
                f"{'INCIDENT':<16} {'RULE':<22} {'STATE':<9} "
                f"{'VALUE':>10} {'THRESH':>8} FILES"
            )
            for b in rows:
                man = b["manifest"] or {}
                state = (
                    "PARTIAL"
                    if b["partial"]
                    else ("resolved" if b["resolved"] else "open")
                )
                print(
                    f"{b['id']:<16} {str(man.get('rule', '-')):<22} "
                    f"{state:<9} {_fmt(man.get('value')):>10} "
                    f"{_fmt(man.get('threshold')):>8} "
                    f"{len(man.get('files') or ())}"
                )
            if fleet is not None:
                print("fleet incidents (latest per instance):")
                for inst in sorted(fleet):
                    print(f"  {inst:<24} {int(fleet[inst])}")
        return 0 if rows else 3

    if bundle_path is None:
        print(f"incident: no bundles under {args.source}", file=sys.stderr)
        return 3
    bundle = read_bundle(bundle_path)
    if args.cmd == "show":
        if args.json:
            print(json.dumps(bundle, indent=1))
        else:
            print(render_bundle(bundle))
        return 0
    verdicts = diagnose(bundle)
    if args.json:
        print(
            json.dumps(
                {
                    "id": bundle["id"],
                    "partial": bundle["partial"],
                    "causes": verdicts,
                },
                indent=1,
            )
        )
    else:
        print(render_diagnosis(bundle, verdicts))
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
