"""Live terminal dashboard: ``top`` for drift-serving runs.

    python -m distributed_drift_detection_tpu top <run.jsonl | dir>... \\
        [--statusz URL]... [--interval S] [--once]

``watch`` renders one run as a status line; ``top`` renders a fleet as a
refreshing table — throughput, latency percentiles, drift rate,
quarantine rate, and active alerts for one or many runs at once. Two
data sources, freely mixed:

* **run logs / telemetry dirs** (positional args — a directory resolves
  to its newest run log): tailed incrementally with the same
  :class:`~.watch.LogTail` the watch CLI uses, folded through
  :class:`~.watch.WatchState` plus the ops-plane extras (``alert``
  transitions, quarantine counts riding on ``run_completed``);
* **``--statusz`` URLs** (a serving daemon's ``--ops-port``): the JSON
  snapshot carries what a log cannot — live latency percentiles,
  queue depth, quarantine share — fetched fresh every frame with a
  short timeout (an unreachable daemon renders as ``down``, never
  crashes the dashboard).

Rates are deltas between frames (cumulative ÷ uptime on the first
frame / ``--once``). Pure stdlib, no jax — runs wherever the artifacts
or endpoints are reachable, same contract as ``watch``/``report``.

Three history-plane hooks (:mod:`.history`):

* ``--store DIR`` — point the dashboard at a collector's (or recorded)
  history store: every row gains a TREND sparkline of its recent rows/s
  (``top_rows_per_sec`` falling back to the collector's
  ``serve_rows_per_sec``, keyed by ``instance``);
* ``--record DIR`` — write every rendered frame's samples into a store
  in the history format (one ``append_samples`` batch per frame, one
  shared timestamp), turning any live incident into a durable artifact;
* ``--replay DIR`` — play a recorded session back frame by frame, no
  daemons required: the post-incident review runs on the artifact.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.error
import urllib.request

from .watch import LogTail, WatchState, resolve_log

_CLEAR = "\x1b[2J\x1b[H"


def _frame_rate(prev, now_mono, rows, fallback):
    """Rows/s from the delta against the previous frame; returns
    ``(rate, new prev)``. A computed delta of 0 means 0 — a stalled run
    must never fall back to a healthy-looking cumulative average —
    ``fallback()`` serves only the first frame / ``--once``."""
    if rows is None:
        return None, prev
    if prev is not None:
        dt = now_mono - prev[0]
        rate = (rows - prev[1]) / dt if dt > 0 and rows >= prev[1] else None
    else:
        rate = fallback()
    return rate, (now_mono, rows)


class LogSource:
    """One tailed run log folded into dashboard columns."""

    def __init__(self, path: str):
        self.path = path
        self.tail = LogTail(path)
        self.state = WatchState()  # folds alerts too (watch.py)
        self.quarantined = 0
        self._prev: "tuple[float, int] | None" = None  # (poll mono, rows)

    def poll(self, now_mono: float) -> dict:
        events = self.tail.poll()
        self.state.fold(events)
        for e in events:
            if e["type"] == "rows_quarantined":
                self.quarantined += int(e["rows"])
            elif e["type"] == "run_completed":
                self.quarantined = int(
                    e.get("rows_quarantined") or self.quarantined
                )
        s = self.state
        rows = s.rows_done
        if rows is None and s.completed is not None:
            rows = int(s.completed["rows"])
        rate, self._prev = _frame_rate(self._prev, now_mono, rows, s.rate)
        age = None if s.last_ts is None else max(time.time() - s.last_ts, 0.0)
        return {
            "run": s.run_id or os.path.basename(self.path),
            "status": "done" if s.completed is not None else "live",
            "rows": rows,
            "rows_per_sec": rate,
            "p50_ms": None,
            "p99_ms": None,
            "detections": s.detections,
            "quarantined": self.quarantined,
            "wire": None,  # per-protocol counters live on /statusz only
            "busy": None,  # pipeline shares live on /statusz only
            "alerts": sorted(s.alerts),
            "age_s": age,
        }


class StatuszSource:
    """One serving daemon's ``/statusz`` endpoint → dashboard columns."""

    def __init__(self, url: str, *, timeout: float = 2.0):
        self.url = url if "://" in url else "http://" + url
        if not self.url.rstrip("/").endswith("/statusz"):
            self.url = self.url.rstrip("/") + "/statusz"
        self.timeout = timeout
        self._prev: "tuple[float, int] | None" = None

    def poll(self, now_mono: float) -> dict:
        try:
            with urllib.request.urlopen(self.url, timeout=self.timeout) as r:
                s = json.load(r)
        except (urllib.error.URLError, OSError, ValueError) as e:
            return {
                "run": self.url,
                "status": "down",
                "rows": None,
                "rows_per_sec": None,
                "p50_ms": None,
                "p99_ms": None,
                "detections": None,
                "quarantined": None,
                "wire": None,
                "busy": None,
                "alerts": [f"unreachable: {getattr(e, 'reason', e)}"],
                "age_s": None,
            }
        if s.get("sched"):
            return self._sched_row(s, now_mono)
        rows = (s.get("rows") or {}).get("published")
        rate, self._prev = _frame_rate(
            self._prev,
            now_mono,
            rows,
            lambda: rows / s["uptime_s"] if rows and s.get("uptime_s") else None,
        )
        lat = s.get("latency_ms") or {}
        # Per-protocol ingress mix ("v1:12 v2:340[ err:2]") from the
        # /statusz ingress section (serve ingress counters); socketless
        # embeddings report None there and the column stays "-".
        ingress = s.get("ingress") or None
        wire = None
        if ingress is not None:
            wire = f"v1:{ingress.get('frames_v1', 0)} v2:{ingress.get('frames_v2', 0)}"
            if ingress.get("decode_errors"):
                wire += f" err:{ingress['decode_errors']}"
        status = "draining" if s.get("draining") else "live"
        # BUSY: the serve-pipeline observatory's dominant stage + its
        # busy share ("device:62%") from the /statusz pipeline section;
        # absent ("-") under --no-pipeline-metrics or on old daemons.
        busy = _busy_cell(s.get("pipeline") or {})
        fleet_rows: list = []
        if s.get("router"):
            # A tenant router's /statusz (serve.router): the row reads
            # like a daemon serving the whole fleet, with the fleet
            # health riding the WIRE column — backends alive, graceful
            # migrations, failovers, rows lost past replay buffers.
            status = "router" if not s.get("draining") else "draining"
            backs = s.get("backends") or []
            alive = sum(1 for b in backs if b.get("alive"))
            fleet = (
                f"be:{alive}/{len(backs)} mig:{s.get('migrations', 0)} "
                f"fo:{s.get('failovers', 0)}"
            )
            if s.get("rows_lost"):
                fleet += f" lost:{s['rows_lost']}"
            wire = f"{wire} {fleet}" if wire else fleet
            # the merged fleet view: one indented row per backend with
            # its own BUSY cell, then one fleet-aggregate row
            fleet_rows = self._fleet_rows()
        row = {
            "run": s.get("run_id") or self.url,
            "status": status,
            "rows": rows,
            "rows_per_sec": rate,
            "p50_ms": lat.get("p50"),
            "p99_ms": lat.get("p99"),
            "detections": s.get("detections"),
            "quarantined": (s.get("rows") or {}).get("quarantined"),
            # incident autopsy bundles captured this run (the /statusz
            # incidents section; "-" on pre-incident daemons)
            "incidents": (s.get("incidents") or {}).get("count"),
            "wire": wire,
            "busy": busy,
            "alerts": sorted(a["rule"] for a in s.get("alerts") or []),
            "age_s": s.get("last_verdict_age_s"),
        }
        return [row, *fleet_rows] if fleet_rows else row

    def _fleet_rows(self) -> list[dict]:
        """Per-backend + fleet-aggregate dashboard rows from an
        aggregator's ``/fleetz`` (missing endpoint = no extra rows —
        a pre-observatory router renders exactly as before)."""
        url = self.url[: -len("/statusz")] + "/fleetz"
        try:
            with urllib.request.urlopen(url, timeout=self.timeout) as r:
                fz = json.load(r)
        except (urllib.error.URLError, OSError, ValueError):
            return []
        rows = []
        for b in fz.get("backends") or []:
            share = b.get("busy_share") or {}
            dom = b.get("bottleneck")
            rows.append(
                {
                    "run": "  " + (b.get("name") or "?"),
                    "status": "live" if b.get("alive") else "down",
                    "rows": b.get("rows"),
                    "rows_per_sec": b.get("rows_per_sec"),
                    "incidents": b.get("incidents"),
                    "busy": (
                        _share_cell(dom, share.get(dom)) if dom else None
                    ),
                    "alerts": (
                        [f"{int(b['alerts'])} firing"]
                        if b.get("alerts")
                        else []
                    ),
                }
            )
        fleet = fz.get("fleet") or {}
        shares = fleet.get("stage_busy_share_max") or {}
        busy = None
        if shares:
            stage = max(sorted(shares), key=lambda k: shares[k]["share"])
            busy = _share_cell(stage, shares[stage]["share"])
        # fleet-wide live alert count (summed per-backend SLO engines,
        # pipeline.aggregate_fleet): the fleet row says "N firing"
        n_alerts = int(fleet.get("alerts") or 0)
        rows.append(
            {
                "run": (
                    f"  fleet ({fleet.get('alive', 0)}/"
                    f"{fleet.get('backends', 0)})"
                ),
                "status": "fleet",
                "rows": fleet.get("rows"),
                "rows_per_sec": fleet.get("rows_per_sec"),
                "incidents": fleet.get("incidents"),
                "busy": busy,
                "alerts": [f"{n_alerts} firing"] if n_alerts else [],
            }
        )
        return rows

    def _sched_row(self, s: dict, now_mono: float) -> dict:
        """A sweep scheduler's ``/statusz`` (sched/scheduler.py): the row
        reads like a daemon whose "rows" are the fleet's cumulative cell
        rows, with the queue/lease/worker health riding the WIRE column —
        the PR-14 router-row pattern for the control plane."""
        cells = s.get("cells") or {}
        workers = s.get("workers") or []
        alive = sum(1 for w in workers if w.get("alive"))
        rows = sum(int(w.get("rows_done") or 0) for w in workers) or None
        rate, self._prev = _frame_rate(
            self._prev,
            now_mono,
            rows,
            lambda: rows / s["uptime_s"] if rows and s.get("uptime_s") else None,
        )
        fleet = (
            f"q:{cells.get('queued', 0)} l:{cells.get('leased', 0)} "
            f"c:{cells.get('completed', 0)} f:{cells.get('failed', 0)} "
            f"wk:{alive}/{len(workers)}"
        )
        if s.get("evictions"):
            fleet += f" ev:{s['evictions']}"
        alerts = []
        if cells.get("failed"):
            alerts.append("cells_failed")
        ages = [
            w.get("age_s") for w in workers
            if w.get("alive") and w.get("age_s") is not None
        ]
        return {
            "run": s.get("run_id") or self.url,
            "status": "done" if s.get("whole") else "sched",
            "rows": rows,
            "rows_per_sec": rate,
            "p50_ms": None,
            "p99_ms": None,
            "detections": None,
            "quarantined": None,
            "wire": fleet,
            "alerts": alerts,
            "age_s": min(ages) if ages else None,
        }


def _share_cell(stage: str, share) -> str:
    """"device:62%" — a stage plus its busy share, the BUSY cell."""
    if share is None:
        return stage
    return f"{stage}:{share * 100:.0f}%"


def _busy_cell(pipe: dict) -> "str | None":
    dom = pipe.get("dominant_stage")
    if not dom:
        return None
    return _share_cell(dom, (pipe.get("shares") or {}).get(dom))


_COLUMNS = (
    ("RUN", "run", 38),
    ("ST", "status", 8),
    ("ROWS", "rows", 12),
    ("ROWS/S", "rows_per_sec", 10),
    ("P50ms", "p50_ms", 10),
    ("P99ms", "p99_ms", 10),
    ("DET", "detections", 7),
    ("QUAR", "quarantined", 7),
    ("INC", "incidents", 5),
    ("WIRE", "wire", 16),
    ("BUSY", "busy", 14),
    ("TREND", "trend", 14),
    ("AGE", "age_s", 7),
    ("ALERTS", "alerts", 0),
)

#: Numeric row columns a ``--record`` store captures (as ``top_<col>``
#: series keyed by ``instance``) and ``--replay`` restores.
_RECORD_COLS = (
    "rows",
    "rows_per_sec",
    "p50_ms",
    "p99_ms",
    "detections",
    "quarantined",
    "incidents",
    "age_s",
)

#: The trend sparkline's preferred series, most-specific first: a
#: ``--record``ed store carries ``top_rows_per_sec``; a collector-built
#: store carries the scraped ``serve_rows_per_sec``.
_TREND_SERIES = ("top_rows_per_sec", "serve_rows_per_sec")


def record_frame(store, rows: list[dict], *, ts=None) -> int:
    """Append one rendered frame's samples to a history store (one
    batch, one shared timestamp — replay regroups frames by it);
    returns the sample count. Statuses ride as a label on ``top_up``
    (history values are floats), alert *counts* on
    ``top_alerts_active`` — the replayable skeleton of the frame."""
    samples: list = []
    for r in rows:
        inst = str(r.get("run") or "?").strip()
        samples.append(
            (
                "top_up",
                {"instance": inst, "status": str(r.get("status") or "?")},
                0.0 if r.get("status") == "down" else 1.0,
            )
        )
        samples.append(
            (
                "top_alerts_active",
                {"instance": inst},
                float(len(r.get("alerts") or [])),
            )
        )
        for key in _RECORD_COLS:
            v = r.get(key)
            if isinstance(v, (int, float)):
                samples.append((f"top_{key}", {"instance": inst}, float(v)))
    store.append_samples(samples, ts=ts)
    return len(samples)


def replay_frames(store_dir: str) -> "list[tuple[float, list[dict]]]":
    """Reconstruct recorded frames from a ``--record`` store: samples
    sharing one timestamp are one frame, one row per instance (insertion
    order preserved — the order the dashboard rendered them in)."""
    from .history import read_samples

    frames: dict[float, dict[str, dict]] = {}
    for rec in read_samples(store_dir):
        name = rec["name"]
        if not name.startswith("top_"):
            continue
        labels = rec.get("labels") or {}
        inst = labels.get("instance", "?")
        by_inst = frames.setdefault(float(rec["ts"]), {})
        row = by_inst.setdefault(inst, {"run": inst, "alerts": []})
        if name == "top_up":
            row["status"] = labels.get("status", "?")
        elif name == "top_alerts_active":
            n = int(rec["value"])
            row["alerts"] = [f"{n} firing"] if n else []
        elif name[len("top_"):] in _RECORD_COLS:
            key = name[len("top_"):]
            v = rec["value"]
            row[key] = int(v) if key in ("rows", "detections",
                                         "quarantined", "incidents") else v
    return [
        (ts, list(by_inst.values())) for ts, by_inst in sorted(frames.items())
    ]


class TrendSource:
    """Per-instance rows/s sparklines from a history store (``--store``):
    the dashboard's memory. Reads are torn-tail tolerant and fully
    concurrent with a live collector writing the same store."""

    def __init__(self, store_dir: str, *, window_s: float = 600.0,
                 width: int = 12):
        self.store_dir = store_dir
        self.window_s = window_s
        self.width = width

    def cell(self, run: str, now: "float | None" = None) -> "str | None":
        from .history import range_query, sparkline

        if now is None:
            now = time.time()
        inst = str(run).strip()
        for name in _TREND_SERIES:
            series = range_query(
                self.store_dir,
                name,
                labels={"instance": inst},
                start=now - self.window_s,
                end=now,
            )
            for pts in series.values():
                if pts:
                    return sparkline(
                        [v for _, v in pts], width=self.width
                    ) or None
        return None


def _cell(value) -> str:
    if value is None:
        return "-"
    if isinstance(value, list):
        return ",".join(str(v) for v in value) or "-"
    if isinstance(value, float):
        return f"{value:,.1f}"
    return f"{value:,}" if isinstance(value, int) else str(value)


def render(rows: list[dict], now: float) -> str:
    """One dashboard frame (pure function of the polled rows — tests pin
    it without a terminal)."""
    header = "".join(
        (f"{h:<{w}}" if w else h) for h, _, w in _COLUMNS
    ).rstrip()
    lines = [
        time.strftime("top  %Y-%m-%d %H:%M:%S", time.localtime(now))
        + f"  ({len(rows)} run{'s' if len(rows) != 1 else ''})",
        header,
    ]
    for r in rows:
        cells = []
        for _, key, w in _COLUMNS:
            text = _cell(r.get(key))
            cells.append(f"{text:<{w}}" if w else text)
        lines.append("".join(cells).rstrip())
    firing = sum(
        1 for r in rows if r.get("alerts") and r.get("status") != "down"
    )
    if firing:
        lines.append(f"!! {firing} run(s) with active alerts")
    return "\n".join(lines)


def top(
    targets: list[str],
    statusz: list[str],
    *,
    interval: float = 2.0,
    once: bool = False,
    out=print,
    sleep=time.sleep,
    frames: "int | None" = None,
    store: "str | None" = None,
    record: "str | None" = None,
) -> int:
    """Drive the dashboard; returns an exit code (0 ok, 4 = nothing to
    show — no resolvable log and no endpoint, the watch convention).
    ``store`` adds the TREND sparkline column from a history store;
    ``record`` appends every frame's samples to one."""
    sources: list = []
    for t in targets:
        path = resolve_log(t)
        if path is not None:
            sources.append(LogSource(path))
        else:
            out(f"top: no run log at {t}")
    sources.extend(StatuszSource(u) for u in statusz)
    if not sources:
        return 4
    trend = TrendSource(store) if store else None
    recorder = None
    if record:
        from .history import HistoryStore

        recorder = HistoryStore(record)
    try:
        n = 0
        while True:
            now_mono = time.monotonic()
            now = time.time()
            rows = []
            for src in sources:
                polled = src.poll(now_mono)
                rows.extend(polled if isinstance(polled, list) else [polled])
            if trend is not None:
                for r in rows:
                    r["trend"] = trend.cell(r.get("run") or "?", now=now)
            if recorder is not None:
                record_frame(recorder, rows, ts=now)
            frame = render(rows, now)
            out(frame if once else _CLEAR + frame)
            n += 1
            if once or (frames is not None and n >= frames):
                return 0
            sleep(interval)
    finally:
        if recorder is not None:
            recorder.close()


def replay(
    store_dir: str,
    *,
    interval: float = 0.0,
    out=print,
    sleep=time.sleep,
    clear: bool = False,
) -> int:
    """Play a ``--record``ed session back frame by frame (exit 4 when
    the store holds no frames — the nothing-to-show convention)."""
    recorded = replay_frames(store_dir)
    if not recorded:
        return 4
    for i, (ts, rows) in enumerate(recorded):
        frame = render(rows, ts)
        out(_CLEAR + frame if clear else frame)
        if interval > 0 and i < len(recorded) - 1:
            sleep(interval)
    return 0


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(
        prog="python -m distributed_drift_detection_tpu top",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument(
        "targets",
        nargs="*",
        help="run-log *.jsonl files or telemetry directories (newest run)",
    )
    ap.add_argument(
        "--statusz",
        action="append",
        default=[],
        metavar="URL",
        help="a serving daemon's ops endpoint (host:port or full URL), "
        "repeatable — adds live latency/queue columns",
    )
    ap.add_argument("--interval", type=float, default=2.0, metavar="S")
    ap.add_argument(
        "--once", action="store_true", help="print one frame and exit"
    )
    ap.add_argument(
        "--store", default=None, metavar="DIR",
        help="history store (telemetry.history): adds the TREND "
        "rows/s sparkline column per row",
    )
    ap.add_argument(
        "--record", default=None, metavar="DIR",
        help="append every rendered frame's samples to a history store "
        "— the incident becomes a replayable artifact",
    )
    ap.add_argument(
        "--replay", default=None, metavar="DIR",
        help="play a --record'ed session back frame by frame and exit "
        "(no daemons; ignores targets/--statusz)",
    )
    args = ap.parse_args(argv)
    if args.replay:
        if args.record:
            ap.error("--replay plays an existing store; drop --record")
        raise SystemExit(
            replay(args.replay, interval=args.interval if not args.once
                   else 0.0, clear=not args.once)
        )
    if not args.targets and not args.statusz:
        ap.error("nothing to watch: give a run log/dir or --statusz URL")
    raise SystemExit(
        top(
            args.targets,
            args.statusz,
            interval=args.interval,
            once=args.once,
            store=args.store,
            record=args.record,
        )
    )


if __name__ == "__main__":
    main(sys.argv[1:])
