"""Declarative SLO rules + the serving daemon's alerting engine.

A run log records what happened; nothing so far ever *judged* it. This
module closes that gap for the online path: a small set of declarative
rules is evaluated on a cadence against a live snapshot of the daemon,
and every threshold crossing emits a schema-v1 ``alert`` event
(``state="firing"`` / ``"resolved"``) into the run log — so alerts are
ordinary, durable, torn-tail-tolerant telemetry that ``report``/``top``
render and the ops plane's ``/healthz`` surfaces as a status code.

Rule kinds (one rule per kind; the snapshot supplies the value under the
same key, ``None`` = not currently measurable, rule skipped):

=====================  ====================================================
``p99_ms``             live p99 of ``serve_row_latency_seconds{stage=
                       "total"}`` (``telemetry.trace.hist_quantile``), ms
``verdict_age_s``      seconds since the last verdict was published —
                       staleness of the daemon's *output*
``quarantine_pct``     100 · quarantined / ingress rows seen — dirty-
                       traffic share at admission
``stall_s``            seconds since the serve loop last completed an
                       iteration (its in-process liveness stamp — works
                       with or without a run log; heartbeat events are
                       the durable trace of the same signal) — the
                       in-process twin of ``watch --stall-after``: a
                       firing means the loop itself is wedged
=====================  ====================================================

The evaluator runs on its own daemon thread (:func:`start_evaluator`):
the serve loop's blocking points (device sync, an injected
``serve.flush`` stall) are exactly what ``stall_s`` must detect, so the
judge cannot live on the thread being judged. ``EventLog.emit`` is
thread-safe (internal lock), and the evaluator only ever *reads* runner
state — it owns no locks of its own. Alerts are emitted strictly outside
any ``api.run`` Final Time span (the evaluator exists only in the serve
daemon; the purity tests are untouched by construction).

No jax, stdlib only — importable by the jax-free CLIs.
"""

from __future__ import annotations

import threading
from typing import Callable, NamedTuple

RULE_KINDS = ("p99_ms", "verdict_age_s", "quarantine_pct", "stall_s")


class SloRule(NamedTuple):
    """One declarative rule: fire while ``value > threshold``."""

    kind: str
    threshold: float


def parse_rules(specs) -> tuple[SloRule, ...]:
    """Parse ``kind=threshold`` strings (the ``--slo`` CLI grammar) into
    rules; unknown kinds and unparseable thresholds fail loudly. The
    single spec ``none`` (or ``off``) disables alerting entirely."""
    rules: list[SloRule] = []
    specs = list(specs)
    if [s.strip().lower() for s in specs] in (["none"], ["off"]):
        return ()
    for spec in specs:
        kind, sep, value = spec.partition("=")
        kind = kind.strip()
        if not sep or kind not in RULE_KINDS:
            raise ValueError(
                f"bad SLO rule {spec!r}; expected kind=threshold with kind "
                f"one of {RULE_KINDS} (or the single spec 'none')"
            )
        try:
            threshold = float(value)
        except ValueError:
            raise ValueError(
                f"bad SLO threshold in {spec!r}: {value!r} is not a number"
            ) from None
        if any(r.kind == kind for r in rules):
            # One rule per kind is the engine's state-machine contract:
            # two thresholds on one kind would fire/resolve against each
            # other every evaluator tick, flooding the log with alerts.
            raise ValueError(f"duplicate SLO rule kind {kind!r}")
        rules.append(SloRule(kind, threshold))
    return tuple(rules)


class SloEngine:
    """Threshold-crossing state machine over the rule set.

    :meth:`evaluate` is called with a snapshot dict (rule kind → current
    value or ``None``); each crossing INTO violation emits one
    ``firing`` transition, each crossing back OUT one ``resolved`` —
    never a re-fire per cadence tick. :meth:`active` lists the currently
    firing alerts (the ``/healthz`` and ``/statusz`` surface).
    """

    def __init__(self, rules: "tuple[SloRule, ...]"):
        self.rules = tuple(rules)
        self._firing: dict[str, dict] = {}
        self._lock = threading.Lock()

    def evaluate(self, snapshot: dict, emit=None) -> list[dict]:
        """One cadence tick; returns the transitions (also handed, one by
        one, to ``emit(etype, **fields)`` — an ``EventLog.emit``-shaped
        callable — when given)."""
        transitions: list[dict] = []
        with self._lock:
            for rule in self.rules:
                value = snapshot.get(rule.kind)
                if value is None:
                    continue
                value = float(value)
                firing = value > rule.threshold
                was = rule.kind in self._firing
                if firing and not was:
                    rec = {
                        "rule": rule.kind,
                        "state": "firing",
                        "value": value,
                        "threshold": rule.threshold,
                    }
                    self._firing[rule.kind] = rec
                    transitions.append(rec)
                elif firing and was:
                    # keep the surfaced value current for /statusz
                    self._firing[rule.kind]["value"] = value
                elif not firing and was:
                    del self._firing[rule.kind]
                    transitions.append(
                        {
                            "rule": rule.kind,
                            "state": "resolved",
                            "value": value,
                            "threshold": rule.threshold,
                        }
                    )
        if emit is not None:
            for i, t in enumerate(transitions):
                try:
                    emit("alert", **t)
                except Exception:
                    # The log refused the event (full disk, closed file):
                    # roll back this AND every not-yet-emitted transition
                    # of the tick, so surfaced state never diverges from
                    # the log and the next tick re-attempts the same
                    # crossings instead of losing them.
                    with self._lock:
                        for u in transitions[i:]:
                            if u["state"] == "firing":
                                self._firing.pop(u["rule"], None)
                            else:
                                self._firing[u["rule"]] = {
                                    **u, "state": "firing"
                                }
                    return transitions[:i]
        return transitions

    def active(self) -> list[dict]:
        """Currently firing alerts (copies, newest values)."""
        with self._lock:
            return [dict(v) for v in self._firing.values()]


def start_evaluator(
    engine: SloEngine,
    snapshot_fn: Callable[[], dict],
    emit,
    interval_s: float,
) -> "tuple[threading.Thread, threading.Event]":
    """Run the engine on a daemon thread every ``interval_s`` seconds
    until the returned stop event is set. A snapshot failure skips the
    tick and retries next cadence (emit failures are already rolled
    back inside :meth:`SloEngine.evaluate`): a transient error must not
    permanently kill the judge — a dead evaluator would freeze
    ``/healthz`` at whatever state it last surfaced."""
    stop = threading.Event()

    def loop() -> None:
        while not stop.is_set():
            try:
                engine.evaluate(snapshot_fn(), emit)
            except Exception:
                pass  # transient; the wait below bounds the retry rate
            stop.wait(max(interval_s, 0.01))

    thread = threading.Thread(target=loop, name="serve-slo", daemon=True)
    thread.start()
    return thread, stop
