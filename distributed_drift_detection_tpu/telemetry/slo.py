"""Declarative SLO rules + the serving daemon's alerting engine.

A run log records what happened; nothing so far ever *judged* it. This
module closes that gap for the online path: a small set of declarative
rules is evaluated on a cadence against a live snapshot of the daemon,
and every threshold crossing emits a schema-v1 ``alert`` event
(``state="firing"`` / ``"resolved"``) into the run log — so alerts are
ordinary, durable, torn-tail-tolerant telemetry that ``report``/``top``
render and the ops plane's ``/healthz`` surfaces as a status code.

Rule kinds (one rule per kind; the snapshot supplies the value under the
same key, ``None`` = not currently measurable, rule skipped):

=====================  ====================================================
``p99_ms``             live p99 of ``serve_row_latency_seconds{stage=
                       "total"}`` (``telemetry.trace.hist_quantile``), ms
``verdict_age_s``      seconds since the last verdict was published —
                       staleness of the daemon's *output*
``quarantine_pct``     100 · quarantined / ingress rows seen — dirty-
                       traffic share at admission
``stall_s``            seconds since the serve loop last completed an
                       iteration (its in-process liveness stamp — works
                       with or without a run log; heartbeat events are
                       the durable trace of the same signal) — the
                       in-process twin of ``watch --stall-after``: a
                       firing means the loop itself is wedged
=====================  ====================================================

Beyond single-sample thresholds, ``kind=burn_rate`` rules judge a series
*over time* — the SRE multi-window error-budget pattern. The grammar is
``burn_rate=SERIES:OBJECTIVE:FAST/SLOW:FACTOR`` (e.g.
``burn_rate=p99_ms:250:30/300:1.0``): the rule computes the windowed
average of SERIES over a FAST and a SLOW window (seconds), divides each
by OBJECTIVE to get a burn rate, and fires only while **both** exceed
FACTOR — the fast window gives detection latency, the slow window
vetoes one-sample blips, so a transient spike never pages but a
sustained burn does. Series values come either from the snapshot dict
(in-process serve mode: the engine keeps its own ring of recent samples,
one per evaluator tick) or from a ``window_avg_fn`` the caller injects
(collector mode: ``history.avg_over_time`` over the fleet store). Burn
alerts are named ``burn_rate:SERIES`` and ride the same schema-v1
``alert`` events, so ``report``/``top``/``/healthz`` need no new
plumbing; multiple burn rules may coexist as long as their series
differ.

When constructed with a :class:`~.metrics.MetricsRegistry`, the engine
also exports live alert state as ``slo_alert_active{rule}`` gauges —
1 while firing, 0 otherwise, pre-registered at 0 for every rule so the
series (and its HELP line) exists on ``/metrics`` before anything ever
fires. Gauges are re-synced from the firing set *after* emit-failure
rollback, so the scraped state never diverges from the log.

The evaluator runs on its own daemon thread (:func:`start_evaluator`):
the serve loop's blocking points (device sync, an injected
``serve.flush`` stall) are exactly what ``stall_s`` must detect, so the
judge cannot live on the thread being judged. ``EventLog.emit`` is
thread-safe (internal lock), and the evaluator only ever *reads* runner
state — it owns no locks of its own. Alerts are emitted strictly outside
any ``api.run`` Final Time span (the evaluator exists only in the serve
daemon; the purity tests are untouched by construction).

No jax, stdlib only — importable by the jax-free CLIs.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, NamedTuple

RULE_KINDS = ("p99_ms", "verdict_age_s", "quarantine_pct", "stall_s")

#: The multi-window burn-rate kind (its value part has its own grammar,
#: see the module docstring; not a snapshot key itself — ``series`` is).
BURN_KIND = "burn_rate"

#: Live alert state on /metrics: 1 while the labeled rule fires.
ALERT_ACTIVE_METRIC = "slo_alert_active"
ALERT_ACTIVE_HELP = (
    "1 while the SLO rule named by the 'rule' label is firing, 0 "
    "otherwise (pre-registered at 0 for every configured rule)"
)


class SloRule(NamedTuple):
    """One declarative rule: fire while ``value > threshold``. For
    ``kind=burn_rate`` the threshold is the burn FACTOR and the extra
    fields describe the series and window pair (zero/empty otherwise)."""

    kind: str
    threshold: float
    series: str = ""
    objective: float = 0.0
    fast_s: float = 0.0
    slow_s: float = 0.0


def rule_name(rule: SloRule) -> str:
    """The alert/gauge identity of a rule: the kind for threshold rules,
    ``burn_rate:SERIES`` for burn rules (several may coexist)."""
    return f"{rule.kind}:{rule.series}" if rule.kind == BURN_KIND else rule.kind


def _parse_burn(spec: str, value: str) -> SloRule:
    """``SERIES:OBJECTIVE:FAST/SLOW:FACTOR`` → a burn-rate rule."""
    parts = value.split(":")
    bad = ValueError(
        f"bad burn_rate rule {spec!r}; expected "
        "burn_rate=SERIES:OBJECTIVE:FAST/SLOW:FACTOR "
        "(e.g. burn_rate=p99_ms:250:30/300:1.0)"
    )
    if len(parts) != 4 or not parts[0].strip():
        raise bad
    series = parts[0].strip()
    fast_str, sep, slow_str = parts[2].partition("/")
    if not sep:
        raise bad
    try:
        objective = float(parts[1])
        fast_s = float(fast_str)
        slow_s = float(slow_str)
        factor = float(parts[3])
    except ValueError:
        raise bad from None
    if objective <= 0 or factor <= 0:
        raise ValueError(
            f"burn_rate rule {spec!r}: objective and factor must be > 0"
        )
    if not 0 < fast_s < slow_s:
        raise ValueError(
            f"burn_rate rule {spec!r}: need 0 < FAST < SLOW "
            f"(got {fast_s:g}/{slow_s:g}) — the slow window is the veto"
        )
    return SloRule(BURN_KIND, factor, series, objective, fast_s, slow_s)


def parse_rules(specs) -> tuple[SloRule, ...]:
    """Parse ``kind=threshold`` strings (the ``--slo`` CLI grammar) into
    rules; unknown kinds and unparseable thresholds fail loudly. The
    single spec ``none`` (or ``off``) disables alerting entirely."""
    rules: list[SloRule] = []
    specs = list(specs)
    if [s.strip().lower() for s in specs] in (["none"], ["off"]):
        return ()
    for spec in specs:
        kind, sep, value = spec.partition("=")
        kind = kind.strip()
        if sep and kind == BURN_KIND:
            rule = _parse_burn(spec, value)
            if any(rule_name(r) == rule_name(rule) for r in rules):
                raise ValueError(
                    f"duplicate burn_rate rule for series {rule.series!r}"
                )
            rules.append(rule)
            continue
        if not sep or kind not in RULE_KINDS:
            raise ValueError(
                f"bad SLO rule {spec!r}; expected kind=threshold with kind "
                f"one of {RULE_KINDS + (BURN_KIND,)} (or the single spec "
                "'none')"
            )
        try:
            threshold = float(value)
        except ValueError:
            raise ValueError(
                f"bad SLO threshold in {spec!r}: {value!r} is not a number"
            ) from None
        if any(r.kind == kind for r in rules):
            # One rule per kind is the engine's state-machine contract:
            # two thresholds on one kind would fire/resolve against each
            # other every evaluator tick, flooding the log with alerts.
            raise ValueError(f"duplicate SLO rule kind {kind!r}")
        rules.append(SloRule(kind, threshold))
    return tuple(rules)


class SloEngine:
    """Threshold-crossing state machine over the rule set.

    :meth:`evaluate` is called with a snapshot dict (rule kind → current
    value or ``None``); each crossing INTO violation emits one
    ``firing`` transition, each crossing back OUT one ``resolved`` —
    never a re-fire per cadence tick. :meth:`active` lists the currently
    firing alerts (the ``/healthz`` and ``/statusz`` surface).
    """

    def __init__(
        self,
        rules: "tuple[SloRule, ...]",
        *,
        window_avg_fn=None,
        metrics=None,
        now_fn: Callable[[], float] = time.monotonic,
    ):
        """``window_avg_fn(series, window_s) -> float | None`` supplies
        windowed averages for burn rules from an external store (the
        collector injects ``history.avg_over_time`` over the fleet
        store); without it, the engine rings up its own samples from the
        snapshot, one per tick. ``metrics`` (a MetricsRegistry) enables
        the ``slo_alert_active{rule}`` gauges."""
        self.rules = tuple(rules)
        self._firing: dict[str, dict] = {}
        self._lock = threading.Lock()
        self._window_avg_fn = window_avg_fn
        self._now_fn = now_fn
        #: Optional transition observer, called once per *successfully
        #: emitted* transition dict, on the evaluator thread — the
        #: incident recorder's capture hook. Rolled-back transitions are
        #: never observed (they re-fire next tick), so an observer sees
        #: exactly the transitions the log recorded. Failures are
        #: swallowed: evidence capture must never kill the judge.
        self.observer = None
        # rule name -> ring of (mono_ts, value) bounded by the slow window
        self._history: dict[str, deque] = {}
        self._gauge = None
        if metrics is not None:
            self._gauge = metrics.gauge(ALERT_ACTIVE_METRIC, ALERT_ACTIVE_HELP)
            for rule in self.rules:
                self._gauge.set(0.0, rule=rule_name(rule))

    def _burn_value(self, rule: SloRule, snapshot: dict) -> "float | None":
        """Current burn of a burn-rate rule: the *limiting* (smaller) of
        the fast/slow window burns — above the factor iff BOTH windows
        burn, which folds the multi-window AND into one scalar the
        generic threshold state machine can judge. ``None`` while either
        window is empty."""
        if self._window_avg_fn is not None:
            fast = self._window_avg_fn(rule.series, rule.fast_s)
            slow = self._window_avg_fn(rule.series, rule.slow_s)
        else:
            v = snapshot.get(rule.series)
            ring = self._history.setdefault(rule_name(rule), deque())
            now = self._now_fn()
            if v is not None:
                ring.append((now, float(v)))
            while ring and ring[0][0] < now - rule.slow_s:
                ring.popleft()
            fast_vals = [x for t, x in ring if t >= now - rule.fast_s]
            slow_vals = [x for _, x in ring]
            fast = sum(fast_vals) / len(fast_vals) if fast_vals else None
            slow = sum(slow_vals) / len(slow_vals) if slow_vals else None
        if fast is None or slow is None:
            return None
        # min(): the rule fires iff BOTH burns exceed the factor, i.e.
        # iff the smaller one does — so the generic `value > threshold`
        # state machine below needs no special casing.
        return min(fast / rule.objective, slow / rule.objective)

    def evaluate(self, snapshot: dict, emit=None) -> list[dict]:
        """One cadence tick; returns the transitions (also handed, one by
        one, to ``emit(etype, **fields)`` — an ``EventLog.emit``-shaped
        callable — when given)."""
        transitions: list[dict] = []
        with self._lock:
            for rule in self.rules:
                if rule.kind == BURN_KIND:
                    value = self._burn_value(rule, snapshot)
                else:
                    value = snapshot.get(rule.kind)
                if value is None:
                    continue
                value = float(value)
                name = rule_name(rule)
                firing = value > rule.threshold
                was = name in self._firing
                if firing and not was:
                    rec = {
                        "rule": name,
                        "state": "firing",
                        "value": value,
                        "threshold": rule.threshold,
                        # monotonic stamp as a schema-legal extra, so
                        # incident/history timelines rebase alert
                        # transitions across restarts exactly like
                        # heartbeats (events.py carries extras verbatim)
                        "mono": self._now_fn(),
                    }
                    self._firing[name] = rec
                    transitions.append(rec)
                elif firing and was:
                    # keep the surfaced value current for /statusz
                    self._firing[name]["value"] = value
                elif not firing and was:
                    del self._firing[name]
                    transitions.append(
                        {
                            "rule": name,
                            "state": "resolved",
                            "value": value,
                            "threshold": rule.threshold,
                            "mono": self._now_fn(),
                        }
                    )
        emitted = transitions
        if emit is not None:
            for i, t in enumerate(transitions):
                try:
                    emit("alert", **t)
                except Exception:
                    # The log refused the event (full disk, closed file):
                    # roll back this AND every not-yet-emitted transition
                    # of the tick, so surfaced state never diverges from
                    # the log and the next tick re-attempts the same
                    # crossings instead of losing them.
                    with self._lock:
                        for u in transitions[i:]:
                            if u["state"] == "firing":
                                self._firing.pop(u["rule"], None)
                            else:
                                self._firing[u["rule"]] = {
                                    **u, "state": "firing"
                                }
                    emitted = transitions[:i]
                    break
        self._sync_gauges()
        if self.observer is not None:
            for t in emitted:
                try:
                    self.observer(dict(t))
                except Exception:
                    pass  # capture failure must never kill the evaluator
        return emitted

    def _sync_gauges(self) -> None:
        """Re-derive every ``slo_alert_active`` gauge from the firing set
        — called after emit handling so rollback is reflected too."""
        if self._gauge is None:
            return
        with self._lock:
            firing = set(self._firing)
        for rule in self.rules:
            name = rule_name(rule)
            self._gauge.set(1.0 if name in firing else 0.0, rule=name)

    def active(self) -> list[dict]:
        """Currently firing alerts (copies, newest values)."""
        with self._lock:
            return [dict(v) for v in self._firing.values()]


def start_evaluator(
    engine: SloEngine,
    snapshot_fn: Callable[[], dict],
    emit,
    interval_s: float,
) -> "tuple[threading.Thread, threading.Event]":
    """Run the engine on a daemon thread every ``interval_s`` seconds
    until the returned stop event is set. A snapshot failure skips the
    tick and retries next cadence (emit failures are already rolled
    back inside :meth:`SloEngine.evaluate`): a transient error must not
    permanently kill the judge — a dead evaluator would freeze
    ``/healthz`` at whatever state it last surfaced."""
    stop = threading.Event()

    def loop() -> None:
        while not stop.is_set():
            try:
                engine.evaluate(snapshot_fn(), emit)
            except Exception:
                pass  # transient; the wait below bounds the retry rate
            stop.wait(max(interval_s, 0.01))

    thread = threading.Thread(target=loop, name="serve-slo", daemon=True)
    thread.start()
    return thread, stop
