"""Nested wall-clock spans: the successor of ``utils.timing.PhaseTimer``.

``PhaseTimer`` recorded six flat cumulative phase buckets. A span tracker
keeps that contract (:meth:`SpanTracker.as_dict` is the same ``{name:
total seconds}`` dict) and adds what the flat buckets could not express:

* **nesting** — ``span("detect")`` inside ``span("leg")`` records under
  the path ``"leg/detect"``; sibling re-entry accumulates.
* **call counts** — every path carries how many times it ran.
* **first-call split** — per path, the first call's duration is kept
  separate from the steady-state remainder: for jitted work the first call
  absorbs trace + XLA compile, so ``first_s`` vs ``rest of the calls`` is
  the compile-vs-kernel split (bench.py's ``compile_s`` block is exactly
  this, measured over its warm-up/repetition structure).

``utils.timing.PhaseTimer`` is now a thin compatibility shim over this
class. No jax imports.
"""

from __future__ import annotations

import contextlib
import time


class SpanTracker:
    SEP = "/"

    def __init__(self):
        # path -> [count, total_s, first_s, min_s, max_s]
        self._stats: dict[str, list] = {}
        self._stack: list[str] = []

    @contextlib.contextmanager
    def span(self, name: str):
        """Time a (possibly nested) span; exceptions still record."""
        path = self.SEP.join(self._stack + [name])
        self._stack.append(name)
        t0 = time.perf_counter()
        try:
            yield self
        finally:
            dt = time.perf_counter() - t0
            self._stack.pop()
            s = self._stats.get(path)
            if s is None:
                self._stats[path] = [1, dt, dt, dt, dt]
            else:
                s[0] += 1
                s[1] += dt
                s[3] = min(s[3], dt)
                s[4] = max(s[4], dt)

    def as_dict(self) -> dict[str, float]:
        """Flat ``{path: total seconds}`` — the PhaseTimer contract."""
        return {path: s[1] for path, s in self._stats.items()}

    def stats(self) -> dict[str, dict]:
        """Full per-path record, including the first-call split."""
        out = {}
        for path, (count, total, first, mn, mx) in self._stats.items():
            out[path] = {
                "count": count,
                "total_s": total,
                "first_s": first,
                "min_s": mn,
                "max_s": mx,
                # Steady state = everything after the first call (compile
                # and one-time setup live in the first call of jitted work).
                "steady_total_s": total - first,
                "steady_mean_s": (
                    (total - first) / (count - 1) if count > 1 else None
                ),
            }
        return out

    def compile_split(self, path: str) -> dict | None:
        """The first-call-vs-steady-state view of one span path, or ``None``
        if the path never ran."""
        full = self.stats().get(path)
        if full is None:
            return None
        return {
            "first_call_s": full["first_s"],
            "steady_mean_s": full["steady_mean_s"],
            "calls": full["count"],
        }
