"""Serve-pipeline observatory: stage busy accounting + bottleneck report.

The serving daemon publishes verdicts through a fixed sequence of host
stages — seal-wait, feed/h2d, device compute, collect, sidecar publish,
forensics, adapt — and until now nothing measured where the wall-clock
went between an admitted row and its published verdict. This module is
the jax-free measurement vocabulary and the report that reads it:

* :class:`ServeStageClock` — the serve twin of ``io.feeder.StageClock``
  (PR 10's ingest pattern): per-stage busy seconds accumulated locally
  and mirrored into ``serve_stage_busy_seconds_total{stage=...}``. The
  serve loop is single-threaded, so unlike the ingest clock the stage
  busy sum can never exceed serve-loop wall-clock — the conservation
  property tests pin.
* :func:`attribute` — the one attribution computation every renderer
  shares (``/statusz`` pipeline section, the ``pipeline`` CLI, bench's
  ``serve_pipeline_s`` rider, the router's fleet plane): per-stage busy
  share, utilization against wall, implied per-stage rows/s ceiling,
  and the named dominant stage.
* :func:`main` — the ``pipeline`` CLI: reads a ``.prom`` / run-log
  sibling / live ``/statusz`` URL and renders the bottleneck report
  ROADMAP item 1's perf work is judged against. ``--window S`` points
  it at a history store (:mod:`.history`) instead and attributes the
  busy-counter *deltas* over the last S seconds
  (:func:`load_window_report`) — where the recent wall went, not
  cumulative-since-boot.
* :func:`aggregate_fleet` — folds per-backend snapshots into the
  ``/fleetz`` envelope the router and scheduler publish (summed rows/s,
  max per-stage busy share, per-backend bottleneck).

No jax anywhere here; stdlib + the sibling telemetry modules only.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import urllib.parse
import urllib.request

#: Serve-loop stages in publish order. ``seal_wait`` is the loop blocking
#: in ``batcher.get`` (idle-for-input, accounted so utilization is
#: honest); ``feed`` is place()+feed() dispatch (h2d + enqueue, NOT the
#: device wait); ``device`` is the blocking host sync pulling flags back;
#: ``collect`` is host flag scan + verdict-record assembly; ``publish``
#: is the sidecar write+flush; ``forensics``/``adapt`` are the post-
#: publish hooks.
SERVE_STAGES = (
    "seal_wait",
    "feed",
    "device",
    "collect",
    "publish",
    "forensics",
    "adapt",
)

SERVE_STAGE_BUSY_METRIC = "serve_stage_busy_seconds_total"
SERVE_STAGE_BUSY_HELP = (
    "Cumulative busy seconds per serve-loop stage (single-threaded loop: "
    "the sum over stages never exceeds serve-loop wall-clock)"
)
#: Serve-loop wall-clock gauge: seconds since the loop's first iteration,
#: refreshed on every publish — what makes a scraped ``.prom`` file
#: self-sufficient for utilization (busy/wall) without the daemon.
SERVE_WALL_METRIC = "serve_loop_wall_seconds"
SERVE_WALL_HELP = "Serve-loop wall-clock seconds since the first iteration"
SERVE_ROWS_METRIC = "serve_rows_published"
SERVE_ROWS_HELP = "Stream rows published to the verdict sidecar"


class ServeStageClock:
    """Per-stage busy-seconds accounting for the serve publish path.

    Accumulates locally (``.busy`` — ``/statusz`` and bench read it
    directly) and, when a metrics registry is given, mirrors into the
    ``serve_stage_busy_seconds_total{stage=...}`` counter. Single-writer
    by construction: only the serve loop thread calls :meth:`add`.
    """

    def __init__(self, metrics=None):
        self.busy: dict[str, float] = {}
        self._c = (
            metrics.counter(SERVE_STAGE_BUSY_METRIC, help=SERVE_STAGE_BUSY_HELP)
            if metrics is not None
            else None
        )

    def add(self, stage: str, seconds: float) -> None:
        if seconds < 0:  # clock skew paranoia; counters reject negatives
            return
        self.busy[stage] = self.busy.get(stage, 0.0) + seconds
        if self._c is not None:
            self._c.inc(seconds, stage=stage)


def serve_stage_breakdown(metrics, ndigits: int = 4) -> dict[str, float]:
    """The per-stage busy-seconds map a registry accumulated
    (``SERVE_STAGE_BUSY_METRIC`` samples → ``{stage: seconds}``) — the
    ONE extraction bench.py's serve rider and the ``pipeline`` CLI
    share, mirroring ``io.feeder.stage_breakdown``."""
    c = metrics.counter(SERVE_STAGE_BUSY_METRIC)
    return {
        dict(key)["stage"]: round(v, ndigits)
        for key, v in sorted(c.values.items())
    }


def dominant_stage(busy: dict) -> "str | None":
    """The stage holding the most busy time, ``seal_wait`` excluded —
    seal-wait is waiting *for input*, so it names an under-driven loop,
    not a pipeline bottleneck. Only when nothing else measured any time
    at all does seal_wait get named (an idle loop's honest answer)."""
    work = {s: t for s, t in busy.items() if s != "seal_wait" and t > 0}
    if work:
        return max(sorted(work), key=lambda s: work[s])
    if busy.get("seal_wait", 0.0) > 0:
        return "seal_wait"
    return None


def attribute(
    busy: dict,
    wall_s: "float | None" = None,
    rows: "float | None" = None,
    ndigits: int = 4,
) -> dict:
    """Fold a ``{stage: busy seconds}`` map into the attribution record
    every renderer shares.

    ``share`` is each stage's fraction of total measured busy time;
    ``utilization`` is busy/wall (needs ``wall_s``); ``ceiling_rows_per_sec``
    is rows/busy — the throughput the pipeline would reach if that stage
    were the only cost (needs ``rows``). ``coverage`` (busy sum / wall)
    is the instrumentation-honesty ratio the acceptance bar pins near 1.
    """
    busy = {s: float(t) for s, t in busy.items() if float(t) >= 0}
    total = sum(busy.values())
    stages = {}
    for stage in sorted(busy, key=lambda s: (-busy[s], s)):
        t = busy[stage]
        cell = {"busy_s": round(t, ndigits)}
        cell["share"] = round(t / total, ndigits) if total > 0 else 0.0
        if wall_s and wall_s > 0:
            cell["utilization"] = round(t / wall_s, ndigits)
        if rows and t > 0:
            cell["ceiling_rows_per_sec"] = round(rows / t, 1)
        stages[stage] = cell
    out = {
        "stages": stages,
        "busy_total_s": round(total, ndigits),
        "dominant_stage": dominant_stage(busy),
    }
    if wall_s is not None:
        out["wall_s"] = round(float(wall_s), ndigits)
        if wall_s > 0:
            out["coverage"] = round(total / wall_s, ndigits)
    if rows is not None:
        out["rows"] = int(rows)
    return out


# -- report sources ----------------------------------------------------------


def _samples_from_prom(text: str) -> "tuple[dict, float | None, float | None]":
    """Extract (busy map, wall, rows) from Prometheus exposition text."""
    from .metrics import parse_prometheus_text

    samples = parse_prometheus_text(text)
    busy: dict[str, float] = {}
    wall = rows = None
    for (name, labels), value in samples.items():
        if name == SERVE_STAGE_BUSY_METRIC:
            busy[dict(labels).get("stage", "")] = value
        elif name == SERVE_WALL_METRIC:
            wall = value
        elif name == SERVE_ROWS_METRIC:
            rows = value
    busy.pop("", None)
    return busy, wall, rows


def _load_statusz(obj: dict) -> dict:
    """Attribution from a ``/statusz`` snapshot's ``pipeline`` section."""
    pipe = obj.get("pipeline") or {}
    busy = pipe.get("busy_s") or {}
    if not busy:
        raise ValueError(
            "statusz has no pipeline section (daemon started with "
            "--no-pipeline-metrics, or predates the observatory)"
        )
    rows = (obj.get("rows") or {}).get("published")
    return attribute(busy, pipe.get("wall_s"), rows)


def load_report(source: str, timeout: float = 5.0) -> dict:
    """Build the attribution record from any supported source:

    * ``http(s)://…`` — a live daemon; ``/statusz`` is fetched (the path
      is appended unless the URL already names one).
    * ``*.prom`` — a scraped/exported exposition file.
    * ``*.metrics.json`` — the JSON exporter twin.
    * a run log (``*.jsonl``) — its ``<stem>.prom`` export sibling.
    """
    if source.startswith(("http://", "https://")):
        url = source
        if not urllib.parse.urlparse(url).path.strip("/"):
            url = url.rstrip("/") + "/statusz"
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            obj = json.loads(resp.read().decode())
        report = _load_statusz(obj)
        report["source"] = url
        return report
    path = source
    if path.endswith(".jsonl"):
        sibling = os.path.splitext(path)[0] + ".prom"
        if not os.path.exists(sibling):
            raise FileNotFoundError(
                f"run log has no metrics export sibling: {sibling}"
            )
        path = sibling
    if path.endswith(".metrics.json"):
        with open(path) as fh:
            exported = json.load(fh)

        def _val(name):
            m = exported.get(name) or {}
            return {
                tuple(sorted((s.get("labels") or {}).items())): s["value"]
                for s in m.get("samples", ())
            }

        busy = {
            dict(k).get("stage", ""): v
            for k, v in _val(SERVE_STAGE_BUSY_METRIC).items()
        }
        busy.pop("", None)
        wall = next(iter(_val(SERVE_WALL_METRIC).values()), None)
        rows = next(iter(_val(SERVE_ROWS_METRIC).values()), None)
    else:
        with open(path) as fh:
            busy, wall, rows = _samples_from_prom(fh.read())
    if not busy:
        raise ValueError(
            f"{path}: no {SERVE_STAGE_BUSY_METRIC} samples — not a serve "
            "export, or the daemon ran with --no-pipeline-metrics"
        )
    report = attribute(busy, wall, rows)
    report["source"] = source
    return report


def load_window_report(
    store_dir: str,
    window_s: float,
    *,
    instance: "str | None" = None,
    at: "float | None" = None,
) -> dict:
    """Attribution over a TIME RANGE from a history store
    (``pipeline --window``): per-stage busy deltas between the window's
    edge samples of ``serve_stage_busy_seconds_total``, wall from the
    daemon's own ``serve_loop_wall_seconds`` delta (falling back to the
    scrape timestamps), rows from the ``serve_rows_published`` delta —
    "where did the last N minutes go", not cumulative-since-boot. The
    same :func:`attribute` fold as every other renderer, so live and
    windowed reports are directly comparable."""
    import time as _time

    from .history import read_samples

    if at is None:
        at = _time.time()
    labels = {"instance": instance} if instance else None
    recs = read_samples(
        store_dir,
        name=SERVE_STAGE_BUSY_METRIC,
        labels=labels,
        start=at - window_s,
        end=at,
    )
    if not recs:
        raise ValueError(
            f"{store_dir}: no {SERVE_STAGE_BUSY_METRIC} samples in the "
            f"last {window_s:g}s"
            + (f" for instance {instance!r}" if instance else "")
            + " (collector not scraping, or daemon ran with "
            "--no-pipeline-metrics)"
        )
    instances = sorted(
        {(r.get("labels") or {}).get("instance", "") for r in recs}
    )
    if instance is None and len(instances) > 1:
        raise ValueError(
            f"store holds {len(instances)} instances "
            f"({', '.join(instances)}); pick one with --instance"
        )
    # per-stage counter delta between the window's edge samples
    by_stage: dict[str, list] = {}
    for r in recs:
        stage = (r.get("labels") or {}).get("stage")
        if stage:
            by_stage.setdefault(stage, []).append(r)
    busy: dict[str, float] = {}
    edges: "tuple | None" = None
    for stage, srecs in by_stage.items():
        srecs.sort(key=lambda r: float(r["ts"]))
        first, last = srecs[0], srecs[-1]
        # counter semantics: a restarted daemon resets to 0 — a negative
        # delta means the window spans the restart; count from zero then
        d = float(last["value"]) - float(first["value"])
        busy[stage] = d if d >= 0 else float(last["value"])
        if edges is None or float(last["ts"]) - float(first["ts"]) > (
            float(edges[1]["ts"]) - float(edges[0]["ts"])
        ):
            edges = (first, last)

    def _series_delta(name: str) -> "float | None":
        srecs = read_samples(
            store_dir, name=name, labels=labels, start=at - window_s, end=at
        )
        if len(srecs) < 2:
            return None
        srecs.sort(key=lambda r: float(r["ts"]))
        d = float(srecs[-1]["value"]) - float(srecs[0]["value"])
        return d if d >= 0 else float(srecs[-1]["value"])

    wall = _series_delta(SERVE_WALL_METRIC)
    if wall is None and edges is not None:
        wall = float(edges[1]["ts"]) - float(edges[0]["ts"])
    rows = _series_delta(SERVE_ROWS_METRIC)
    report = attribute(busy, wall, rows)
    report["window_s"] = float(window_s)
    if instance:
        report["instance"] = instance
    return report


def render_report(report: dict) -> str:
    """The human table: one row per stage, busy-ordered, dominant first."""
    lines = []
    src = report.get("source", "")
    lines.append(f"serve pipeline — {src}" if src else "serve pipeline")
    wall = report.get("wall_s")
    head = f"  busy total {report.get('busy_total_s', 0.0):.3f}s"
    if wall is not None:
        head += f" / wall {wall:.3f}s"
        cov = report.get("coverage")
        if cov is not None:
            head += f" (coverage {cov * 100:.1f}%)"
    lines.append(head)
    rows = report.get("rows")
    if rows is not None:
        lines.append(f"  rows published {rows}")
    lines.append("")
    lines.append(
        f"  {'STAGE':<10} {'BUSY_S':>10} {'SHARE':>7} {'UTIL':>7} "
        f"{'CEIL_ROWS/S':>12}"
    )
    for stage, cell in report.get("stages", {}).items():
        util = cell.get("utilization")
        ceil = cell.get("ceiling_rows_per_sec")
        lines.append(
            f"  {stage:<10} {cell['busy_s']:>10.4f} "
            f"{cell['share'] * 100:>6.1f}% "
            f"{(f'{util * 100:.1f}%' if util is not None else '-'):>7} "
            f"{(f'{ceil:,.0f}' if ceil is not None else '-'):>12}"
        )
    lines.append("")
    dom = report.get("dominant_stage")
    lines.append(
        f"  dominant stage: {dom}" if dom else "  dominant stage: (no busy time)"
    )
    return "\n".join(lines)


# -- fleet aggregation -------------------------------------------------------


def backend_snapshot(
    name: str,
    statusz: "dict | None",
    metrics_text: "str | None" = None,
    ops: "str | None" = None,
) -> dict:
    """One backend's row in the ``/fleetz`` envelope, from its scraped
    ``/statusz`` (``None`` statusz = unreachable backend). When the
    statusz carries no ``pipeline`` section but a ``/metrics`` scrape is
    given, the busy map is recovered from the exposition text instead.
    ``ops`` (the backend's ``host:ops_port``) rides along verbatim — the
    history collector's ``--fleetz`` discovery resolves scrape targets
    from it."""
    if not statusz:
        return {
            "name": name,
            "alive": False,
            **({"ops": ops} if ops else {}),
        }
    pipe = statusz.get("pipeline") or {}
    busy = pipe.get("busy_s") or {}
    wall = pipe.get("wall_s")
    if not busy and metrics_text:
        busy, wall, _ = _samples_from_prom(metrics_text)
    attr = attribute(busy, wall) if busy else {}
    rows = (statusz.get("rows") or {}).get("published", 0)
    out = {
        "name": name,
        "alive": True,
        "rows": rows,
        "rows_per_sec": statusz.get("rows_per_sec", 0.0),
        # live SLO alert count from the backend's own engine — summed
        # into the fleet row so `top` can show fleet-wide alert state
        "alerts": len(statusz.get("alerts") or []),
        # captured incident bundles (statusz `incidents` section; 0 for
        # pre-incident daemons) — the fleet incident index's per-backend cell
        "incidents": int((statusz.get("incidents") or {}).get("count") or 0),
        "bottleneck": attr.get("dominant_stage"),
        "busy_share": {
            s: c["share"] for s, c in attr.get("stages", {}).items()
        },
    }
    if ops:
        out["ops"] = ops
    return out


def aggregate_fleet(backends: list[dict]) -> dict:
    """Fold per-backend snapshots (:func:`backend_snapshot` rows) into
    the merged fleet view: summed rows/s, max per-stage busy share with
    the backend holding it, per-backend bottleneck stages."""
    alive = [b for b in backends if b.get("alive")]
    share_max: dict[str, dict] = {}
    for b in alive:
        for stage, share in (b.get("busy_share") or {}).items():
            cur = share_max.get(stage)
            if cur is None or share > cur["share"]:
                share_max[stage] = {"share": share, "backend": b["name"]}
    return {
        "fleet": {
            "backends": len(backends),
            "alive": len(alive),
            "rows": sum(int(b.get("rows") or 0) for b in alive),
            "rows_per_sec": round(
                sum(float(b.get("rows_per_sec") or 0.0) for b in alive), 3
            ),
            "alerts": sum(int(b.get("alerts") or 0) for b in alive),
            "incidents": sum(int(b.get("incidents") or 0) for b in alive),
            "stage_busy_share_max": {
                s: share_max[s] for s in sorted(share_max)
            },
            "bottlenecks": {
                b["name"]: b.get("bottleneck")
                for b in alive
                if b.get("bottleneck")
            },
        },
        "backends": backends,
    }


def fleet_metrics_lines(fleetz: dict) -> list[str]:
    """Render the ``fleet_*`` Prometheus series for an aggregator's
    ``/metrics`` endpoint (router and scheduler share this; hand-rolled
    exposition lines, matching the router's ``router_*`` idiom)."""
    fleet = fleetz.get("fleet", {})
    lines = [
        "# HELP fleet_rows_per_sec Summed published rows/s across alive backends",
        "# TYPE fleet_rows_per_sec gauge",
        f"fleet_rows_per_sec {fleet.get('rows_per_sec', 0.0)}",
        "# HELP fleet_backends_alive Alive backends in the scraped fleet",
        "# TYPE fleet_backends_alive gauge",
        f"fleet_backends_alive {fleet.get('alive', 0)}",
        "# HELP fleet_incidents Captured incident bundles summed across "
        "alive backends",
        "# TYPE fleet_incidents gauge",
        f"fleet_incidents {fleet.get('incidents', 0)}",
    ]
    shares = fleet.get("stage_busy_share_max") or {}
    if shares:
        lines.append(
            "# HELP fleet_stage_busy_share_max Max per-backend busy share "
            "per serve stage"
        )
        lines.append("# TYPE fleet_stage_busy_share_max gauge")
        for stage in sorted(shares):
            lines.append(
                f'fleet_stage_busy_share_max{{stage="{stage}"}} '
                f"{shares[stage]['share']}"
            )
    bottlenecks = fleet.get("bottlenecks") or {}
    if bottlenecks:
        lines.append(
            "# HELP fleet_backend_bottleneck Dominant serve stage per "
            "backend (value is always 1)"
        )
        lines.append("# TYPE fleet_backend_bottleneck gauge")
        for name in sorted(bottlenecks):
            lines.append(
                f'fleet_backend_bottleneck{{backend="{name}",'
                f'stage="{bottlenecks[name]}"}} 1'
            )
    return lines


# -- CLI ---------------------------------------------------------------------


def main(argv=None) -> int:
    """``pipeline``: render the serve bottleneck-attribution report."""
    ap = argparse.ArgumentParser(
        prog="python -m distributed_drift_detection_tpu pipeline",
        description=(
            "Serve-pipeline bottleneck report: per-stage busy share, "
            "utilization, implied rows/s ceiling, dominant stage. Reads "
            "a .prom/.metrics.json export, a run log's export sibling, "
            "or a live daemon's /statusz URL."
        ),
    )
    ap.add_argument(
        "source",
        help="metrics export (.prom/.metrics.json), run log (.jsonl), "
        "http://host:ops_port of a live daemon, or — with --window — a "
        "history store directory",
    )
    ap.add_argument(
        "--json", action="store_true", help="emit the attribution record as JSON"
    )
    ap.add_argument("--timeout", type=float, default=5.0)
    ap.add_argument(
        "--window", type=float, default=None, metavar="S",
        help="windowed mode: source is a telemetry.history store; "
        "attribute stage busy-counter DELTAS over the last S seconds "
        "(where the recent wall-clock went, not since boot)",
    )
    ap.add_argument(
        "--instance", default=None, metavar="NAME",
        help="with --window: the store instance label to attribute "
        "(required when the store holds several)",
    )
    ap.add_argument(
        "--at", type=float, default=None, metavar="TS",
        help="with --window: window end as unix seconds (default: now)",
    )
    args = ap.parse_args(argv)
    if (args.instance or args.at is not None) and args.window is None:
        ap.error("--instance/--at only apply to --window mode")
    try:
        if args.window is not None:
            report = load_window_report(
                args.source, args.window, instance=args.instance, at=args.at
            )
            report["source"] = args.source
        else:
            report = load_report(args.source, timeout=args.timeout)
    except (OSError, ValueError) as e:
        print(f"pipeline: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(report, indent=1))
    else:
        print(render_report(report))
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
