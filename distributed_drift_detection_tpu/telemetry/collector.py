"""Fleet metrics collector: the scraper that feeds the history plane.

A serve daemon's ``/metrics`` is a point-in-time exposition; the history
store (:mod:`.history`) is durable time. This module is the pump between
them — a small scraper daemon that discovers ops endpoints, polls
``/metrics`` + ``/statusz`` on an interval, and appends every sample to
a :class:`~.history.HistoryStore`, from which ``history``/``top``/
``pipeline --window`` answer questions about the past and burn-rate SLO
rules judge sustained behaviour.

Discovery (any mix; targets are deduped by resolved ops address):

==================  ========================================================
``--statusz URL``   explicit ops base (``http://host:port`` or a full
                    ``/statusz`` URL), repeatable — zero-infrastructure
                    loopback use
``--fleetz URL``    a router/scheduler aggregator: its ``/fleetz`` rows now
                    carry each backend's ``ops`` address — scrape the whole
                    fleet by asking the one process that already knows it
``--registry DIR``  the telemetry run registry: every ``kind="serve"`` run
                    still ``running`` whose record carries an ``ops``
                    address (the daemon appends a second "running" record
                    with the bound port once its ops server is up)
==================  ========================================================

Each scrape cycle stamps ONE ``(wall, monotonic)`` pair shared by every
sample it lands (the correlate/timeline skew-rebase convention: the
monotonic stamp is the truth for elapsed time within one collector run,
wall time is the cross-run join key). Per-target failures mark the
target down (``up{instance=...} = 0``) and move on — a dead daemon is a
*data point*, never a collector crash. The collector meters itself
(``collector_scrape_seconds``, ``collector_targets_up``,
``collector_samples_total``, ``collector_errors_total``) into the same
store, and can evaluate ``burn_rate`` SLO rules (:mod:`.slo`) against
the store it builds, emitting ordinary schema-v1 ``alert`` events into
its own run log — fleet-level alerting without touching a daemon.

Non-perturbing by construction: the collector only ever issues GETs
against ops endpoints; the serving data path never sees it (the history
smoke proves verdict sidecars bit-identical with and without one
attached). No jax, stdlib only.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.error
import urllib.request

from .history import DEFAULT_SEGMENT_BYTES, HistoryStore, avg_over_time
from .incident import INCIDENT_OPEN_SERIES, INCIDENTS_TOTAL_SERIES
from .metrics import MetricsRegistry, parse_prometheus_text

#: Self-metering series (stored with instance="collector").
SCRAPE_SECONDS_METRIC = "collector_scrape_seconds"
SCRAPE_SECONDS_HELP = "Wall seconds spent per full scrape cycle"
TARGETS_UP_METRIC = "collector_targets_up"
TARGETS_UP_HELP = "Targets answering their ops endpoints this cycle"
SAMPLES_METRIC = "collector_samples_total"
SAMPLES_HELP = "Samples appended to the history store"
ERRORS_METRIC = "collector_errors_total"
ERRORS_HELP = "Scrape failures, labeled by instance"

#: Synthetic per-target liveness series in the store.
UP_METRIC = "up"
#: /statusz fields lifted into store series (gauge semantics).
STATUSZ_SERIES = (
    ("serve_rows_per_sec", ("rows_per_sec",)),
    ("serve_last_verdict_age_s", ("last_verdict_age_s",)),
    ("serve_p99_ms", ("latency_ms", "p99")),
)


class Target:
    """One scrape target: a resolved ops base URL plus an instance name
    (the label every stored sample carries)."""

    def __init__(self, name: str, base_url: str):
        self.name = name
        self.base_url = base_url.rstrip("/")
        self.up = False

    def __repr__(self):
        return f"Target({self.name!r}, {self.base_url!r})"


def _normalize_base(url: str) -> str:
    """Accept ``host:port``, ``http://host:port`` or any full ops-path
    URL; return the bare ``http://host:port`` base."""
    if "://" not in url:
        url = "http://" + url
    for suffix in ("/statusz", "/metrics", "/healthz", "/fleetz"):
        if url.rstrip("/").endswith(suffix):
            url = url.rstrip("/")[: -len(suffix)]
            break
    return url.rstrip("/")


def _get_json(url: str, timeout: float) -> dict:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.load(r)


def _get_text(url: str, timeout: float) -> str:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read().decode()


def discover(
    statusz_urls=(),
    fleetz_url: "str | None" = None,
    registry_dir: "str | None" = None,
    timeout: float = 5.0,
) -> list[Target]:
    """Resolve the target set from the three discovery sources; targets
    are deduped by base URL (first name wins). Discovery failures of the
    *aggregator/registry* raise — a collector pointed at a dead router
    should say so loudly at startup; per-target scrape failures later
    are down-markings, not errors."""
    targets: list[Target] = []
    seen: set[str] = set()

    def _add(name: str, base: str) -> None:
        base = _normalize_base(base)
        if base not in seen:
            seen.add(base)
            targets.append(Target(name, base))

    for url in statusz_urls or ():
        base = _normalize_base(url)
        _add(base.split("://", 1)[-1], base)
    if fleetz_url:
        base = _normalize_base(fleetz_url)
        fleetz = _get_json(base + "/fleetz", timeout)
        for b in fleetz.get("backends") or []:
            ops = b.get("ops")
            if ops:
                _add(str(b.get("name") or ops), ops)
    if registry_dir:
        from . import registry as run_registry

        for run_id, rec in sorted(run_registry.runs(registry_dir).items()):
            if (
                rec.get("kind") == "serve"
                and rec.get("status") == "running"
                and rec.get("ops")
            ):
                _add(str(rec.get("name") or run_id), rec["ops"])
    return targets


def scrape_once(
    store: HistoryStore,
    targets: list[Target],
    *,
    metrics: "MetricsRegistry | None" = None,
    timeout: float = 5.0,
) -> dict:
    """One scrape cycle: every target's ``/metrics`` + ``/statusz`` into
    the store under ONE shared ``(wall, mono)`` stamp pair; returns the
    cycle summary. A failing target is down-marked (``up{instance}=0``)
    and the cycle continues — the collector never dies of a dead
    daemon."""
    t0 = time.monotonic()
    ts, mono = time.time(), time.monotonic()
    samples: list = []
    errors = 0
    for target in targets:
        try:
            prom = parse_prometheus_text(
                _get_text(target.base_url + "/metrics", timeout)
            )
            statusz = _get_json(target.base_url + "/statusz", timeout)
        except (urllib.error.URLError, OSError, ValueError) as e:
            target.up = False
            errors += 1
            samples.append((UP_METRIC, {"instance": target.name}, 0.0))
            if metrics is not None:
                metrics.counter(ERRORS_METRIC, ERRORS_HELP).inc(
                    1.0, instance=target.name
                )
            print(
                f"collector: {target.name} down: {e}",
                file=sys.stderr,
                flush=True,
            )
            continue
        target.up = True
        samples.append((UP_METRIC, {"instance": target.name}, 1.0))
        for (name, labels), value in sorted(prom.items()):
            # histogram buckets are a cardinality explosion the store
            # gains nothing from (quantile_over_time works on the raw
            # gauge series); _sum/_count still land, so rates survive
            if name.endswith("_bucket"):
                continue
            samples.append(
                (name, {**dict(labels), "instance": target.name}, value)
            )
        for name, path in STATUSZ_SERIES:
            value = statusz
            for part in path:
                value = (value or {}).get(part) if isinstance(
                    value, dict
                ) else None
            if value is not None:
                samples.append(
                    (name, {"instance": target.name}, float(value))
                )
        alerts = statusz.get("alerts")
        if alerts is not None:
            samples.append(
                (
                    "serve_alerts_active",
                    {"instance": target.name},
                    float(len(alerts)),
                )
            )
        # Incident plane (telemetry.incident): lift /incidentz into the
        # fleet index series. Its OWN try block — a pre-incident daemon
        # 404s here (HTTPError ⊂ URLError) and must NOT be down-marked;
        # the /metrics+/statusz scrape above already proved it alive.
        try:
            inc = _get_json(target.base_url + "/incidentz", timeout)
        except (urllib.error.URLError, OSError, ValueError):
            inc = None
        if isinstance(inc, dict):
            samples.append(
                (
                    INCIDENTS_TOTAL_SERIES,
                    {"instance": target.name},
                    float(inc.get("count") or 0),
                )
            )
            samples.append(
                (
                    INCIDENT_OPEN_SERIES,
                    {"instance": target.name},
                    float(inc.get("open") or 0),
                )
            )
    up_count = sum(1 for t in targets if t.up)
    scrape_s = time.monotonic() - t0
    # self-metering rides the same store (and registry, when given)
    samples.append((SCRAPE_SECONDS_METRIC, {"instance": "collector"}, scrape_s))
    samples.append((TARGETS_UP_METRIC, {"instance": "collector"}, up_count))
    if metrics is not None:
        metrics.histogram(SCRAPE_SECONDS_METRIC, SCRAPE_SECONDS_HELP).observe(
            scrape_s
        )
        metrics.gauge(TARGETS_UP_METRIC, TARGETS_UP_HELP).set(float(up_count))
        metrics.counter(SAMPLES_METRIC, SAMPLES_HELP).inc(float(len(samples)))
    store.append_samples(samples, ts=ts, mono=mono)
    store.enforce_retention(now=ts)
    return {
        "targets": len(targets),
        "up": up_count,
        "errors": errors,
        "samples": len(samples),
        "scrape_s": round(scrape_s, 4),
    }


def run_collector(
    store_dir: str,
    *,
    statusz_urls=(),
    fleetz_url: "str | None" = None,
    registry_dir: "str | None" = None,
    interval_s: float = 5.0,
    count: "int | None" = None,
    timeout: float = 5.0,
    segment_bytes: int = DEFAULT_SEGMENT_BYTES,
    retention_s: "float | None" = None,
    retention_bytes: "int | None" = None,
    telemetry_dir: "str | None" = None,
    slo_specs=(),
    rediscover_every: int = 12,
    stop_check=None,
) -> int:
    """The collector loop: discover, scrape every ``interval_s`` into
    the store (``count`` bounds the cycles — CI mode; ``None`` = until
    killed), re-resolving discovery every ``rediscover_every`` cycles so
    restarted daemons re-appear. With ``--telemetry-dir``, the collector
    keeps its own run log + registry record and evaluates any
    ``burn_rate`` SLO rules against the store it builds."""
    from .slo import BURN_KIND, SloEngine, parse_rules

    rules = parse_rules(slo_specs)
    bad = [r for r in rules if r.kind != BURN_KIND]
    if bad:
        raise ValueError(
            "collector --slo accepts only burn_rate rules (threshold "
            "kinds judge in-process daemon state the collector does not "
            f"have); got {[r.kind for r in bad]}"
        )
    metrics = MetricsRegistry()
    log = None
    engine = None
    if telemetry_dir:
        from .events import EventLog
        from . import registry as run_registry

        log = EventLog.open_run(telemetry_dir, name="collector")
        log.emit(
            "run_started",
            run_id=log.run_id,
            config={"store": store_dir, "interval_s": interval_s},
        )
        run_registry.record(
            telemetry_dir,
            log.run_id,
            "running",
            kind="collector",
            store=store_dir,
        )
    if rules:

        def _window_avg(series: str, window_s: float) -> "float | None":
            vals = [
                v
                for v in avg_over_time(
                    store_dir, series, window_s=window_s
                ).values()
                if v is not None
            ]
            # fleet semantics: the rule judges the worst instance — one
            # burning backend must page even if the fleet mean is fine
            return max(vals) if vals else None

        engine = SloEngine(rules, window_avg_fn=_window_avg, metrics=metrics)

    targets = discover(statusz_urls, fleetz_url, registry_dir, timeout)
    print(
        json.dumps(
            {
                "collector": True,
                "store": store_dir,
                "targets": [
                    {"name": t.name, "ops": t.base_url} for t in targets
                ],
                "interval_s": interval_s,
                "slo_rules": len(rules),
            }
        ),
        flush=True,
    )
    cycles = 0
    rc = 0
    t_start = time.monotonic()
    try:
        with HistoryStore(
            store_dir,
            segment_bytes=segment_bytes,
            retention_s=retention_s,
            retention_bytes=retention_bytes,
        ) as store:
            while count is None or cycles < count:
                if stop_check is not None and stop_check():
                    break
                cycle_start = time.monotonic()
                if cycles and rediscover_every and (
                    cycles % rediscover_every == 0
                ):
                    try:
                        targets = discover(
                            statusz_urls, fleetz_url, registry_dir, timeout
                        )
                    except (urllib.error.URLError, OSError, ValueError):
                        pass  # keep the last known set; retry next round
                summary = scrape_once(
                    store, targets, metrics=metrics, timeout=timeout
                )
                if engine is not None:
                    engine.evaluate(
                        {}, log.emit if log is not None else None
                    )
                cycles += 1
                if count is not None:
                    print(json.dumps(summary), flush=True)
                if count is None or cycles < count:
                    elapsed = time.monotonic() - cycle_start
                    time.sleep(max(interval_s - elapsed, 0.0))
    except KeyboardInterrupt:
        pass
    except Exception:
        if log is not None:
            from . import registry as run_registry

            run_registry.record(telemetry_dir, log.run_id, "failed")
            log.close()
        raise
    if log is not None:
        from . import registry as run_registry

        # rows/detections are a *stream* run's totals; a collector run
        # has neither — zeros keep the schema, `cycles` rides as extra
        log.emit(
            "run_completed",
            rows=0,
            seconds=round(time.monotonic() - t_start, 3),
            detections=0,
            cycles=cycles,
        )
        run_registry.record(telemetry_dir, log.run_id, "completed")
        log.close()
    return rc


def main(argv=None) -> int:
    """``collector``: scrape a fleet's ops planes into a history store."""
    ap = argparse.ArgumentParser(
        prog="python -m distributed_drift_detection_tpu collector",
        description=(
            "Scraper daemon feeding the history plane: discovers ops "
            "endpoints (explicit --statusz, a router's /fleetz, or the "
            "run registry), polls /metrics + /statusz into a "
            "telemetry.history store, optionally judging burn_rate SLO "
            "rules against it. GET-only: provably non-perturbing."
        ),
    )
    ap.add_argument("--store", required=True, help="history store directory")
    ap.add_argument(
        "--statusz",
        action="append",
        default=[],
        metavar="URL",
        help="explicit ops base (host:port or URL), repeatable",
    )
    ap.add_argument(
        "--fleetz", default=None, metavar="URL",
        help="router/scheduler ops base: scrape every backend its "
        "/fleetz lists (rows carry each backend's ops address)",
    )
    ap.add_argument(
        "--registry", default=None, metavar="DIR",
        help="telemetry dir: scrape every running kind=serve run whose "
        "registry record carries an ops address",
    )
    ap.add_argument("--interval", type=float, default=5.0, metavar="S")
    ap.add_argument(
        "--count", type=int, default=None, metavar="N",
        help="stop after N cycles (CI mode; default: run until killed)",
    )
    ap.add_argument("--timeout", type=float, default=5.0, metavar="S")
    ap.add_argument(
        "--segment-bytes", type=int, default=DEFAULT_SEGMENT_BYTES,
        help="store segment rotation size",
    )
    ap.add_argument(
        "--retention-s", type=float, default=None,
        help="drop finalized segments older than this",
    )
    ap.add_argument(
        "--retention-bytes", type=int, default=None,
        help="cap total store size (oldest finalized segments drop first)",
    )
    ap.add_argument(
        "--telemetry-dir", default=None,
        help="collector's own run log + registry (required for --slo "
        "alert events)",
    )
    ap.add_argument(
        "--slo", action="append", default=[],
        metavar="burn_rate=SERIES:OBJ:FAST/SLOW:FACTOR",
        help="burn_rate rule judged against the store each cycle "
        "(worst instance across the fleet), repeatable",
    )
    args = ap.parse_args(argv)
    if not (args.statusz or args.fleetz or args.registry):
        ap.error("no targets: give --statusz, --fleetz, and/or --registry")
    if args.slo and not args.telemetry_dir:
        ap.error("--slo needs --telemetry-dir (alerts are run-log events)")
    try:
        return run_collector(
            args.store,
            statusz_urls=args.statusz,
            fleetz_url=args.fleetz,
            registry_dir=args.registry,
            interval_s=args.interval,
            count=args.count,
            timeout=args.timeout,
            segment_bytes=args.segment_bytes,
            retention_s=args.retention_s,
            retention_bytes=args.retention_bytes,
            telemetry_dir=args.telemetry_dir,
            slo_specs=args.slo,
        )
    except ValueError as e:
        print(f"collector: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
