"""Structured run events: typed records over an append-only JSONL sink.

One run = one ``*.jsonl`` file; one line = one event. Every event carries
the envelope ``{"v": SCHEMA_VERSION, "type": ..., "ts": <unix seconds>,
"seq": <per-log counter>}`` plus its type's required payload fields
(:data:`EVENT_SCHEMA`). Unknown types and missing required fields are
rejected at **both** ends — :meth:`EventLog.emit` refuses to write them and
:func:`read_events` refuses to parse them — so a run log that loads is a
run log the ``report`` CLI can render. Extra payload fields are allowed
(forward compatibility); required ones may be ``None`` only where the
schema note says so.

Engines never emit from inside jitted code: drift/retrain events are
extracted host-side from the already-collected flag tables
(:func:`emit_flag_events`), after the timed span closes.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time

import numpy as np

from ..resilience import faults

SCHEMA_VERSION = 1

# type -> required payload fields (beyond the v/type/ts/seq envelope).
# Nullable-by-contract: drift_detected.delay_rows is None for streams
# without planted-boundary geometry (no ground truth to measure against).
EVENT_SCHEMA: dict[str, tuple[str, ...]] = {
    # one per run log, first event: identity + the requested configuration
    "run_started": ("run_id", "config"),
    # runner construction: ``cached`` = served from the in-process runner
    # cache; ``seconds`` = closure/jit build time. The XLA compile itself is
    # lazy and lands in the first ``detect`` phase_completed of a fresh
    # config (bench.py's compile_s split measures it explicitly).
    "compile_completed": ("cached", "seconds"),
    # one per PhaseTimer/SpanTracker phase (prepare/upload/detect/collect)
    "phase_completed": ("phase", "seconds"),
    # one per detector change flag: where drift fired
    "drift_detected": ("partition", "global_pos", "delay_rows"),
    # one per model rotate/refit; ``forced`` = saturation-guard fallback
    # (RunConfig.retrain_error_threshold), not a detector change
    "retrain": ("partition", "batch", "forced"),
    # streaming progress: one per ChunkedDetector chunk
    "chunk_completed": ("chunk", "batches_done", "detections"),
    # soak progress: one per chained-soak leg (engine.soak.run_soak_chained)
    "leg_completed": ("leg", "rows", "detections"),
    # liveness beacon for long streaming/soak runs (the `watch` CLI's food):
    # ``rows_done`` = cumulative rows consumed so far, ``elapsed_s`` =
    # monotonic seconds since the engine started feeding — monotonic, not
    # wall-clock, so a host clock step mid-run cannot fake progress or a
    # stall. Emitted host-side between device programs (per chunk / per
    # leg), never from jitted code and never inside api.run's
    # reference-parity Final Time span (api.run emits none: a one-shot run
    # has no mid-flight to report).
    "heartbeat": ("rows_done", "elapsed_s"),
    # XLA cost analysis of a compiled runner (telemetry.profile), extracted
    # host-side after the timed span. ``where`` names the program (e.g.
    # "detect_runner"); flops/bytes_accessed are None where the backend's
    # cost model reports nothing — the full normalized map rides as the
    # ``analysis`` extra.
    "cost_analysis": ("where", "flops", "bytes_accessed"),
    # A memory measurement: ``source`` = "memory_analysis" (compiler-
    # reported argument/output/temp/generated-code bytes of a compiled
    # runner) or "device" (``device.memory_stats()``, taken before/after
    # the detect phase); ``stats`` is the non-empty numeric dict. Absence
    # of a device snapshot means the backend reports none (XLA CPU) —
    # never a fabricated zero.
    "memory_snapshot": ("source", "stats"),
    # ingest quarantine (io.sanitize via api.run): ``rows`` stream rows
    # violated the ingest contract and were masked out under ``policy``
    # ('quarantine'/'repair'); the per-row evidence lives in the
    # quarantine.jsonl sidecar (its path rides as the ``sidecar`` extra,
    # repaired-cell count as ``repaired``). Emitted between prepare and
    # the Final Time span — outside the timed region — and only when the
    # count is nonzero: clean streams leave no trace.
    "rows_quarantined": ("rows", "policy"),
    # supervised retry (resilience.supervisor): attempt ``attempt`` of
    # ``max_attempts`` failed with ``reason`` (the classified exception,
    # as "Type: message") and will be re-run after ``backoff_s`` seconds.
    # Emitted by the supervisor between attempts — strictly outside any
    # run's Final Time span — into its own per-supervision log; the
    # failed attempt's own run log + registry record carry the evidence.
    "run_retried": ("attempt", "max_attempts", "reason", "backoff_s"),
    # SLO alert transition (telemetry.slo, serving daemon): ``rule`` (one
    # of slo.RULE_KINDS) crossed into ("firing") or out of ("resolved")
    # violation; ``value`` is the measured quantity at the transition,
    # ``threshold`` the rule's bar. Emitted by the daemon's evaluator
    # thread — the serve path only, strictly outside any api.run Final
    # Time span (purity holds by construction).
    "alert": ("rule", "state", "value", "threshold"),
    # One causal trace span (telemetry.tracing): ``trace_id`` groups every
    # span of one traced unit of work (a sampled ingress row, a batch
    # chunk), ``span_id`` names this span, ``parent_id`` its parent (None
    # for a root span). ``start_ts`` is the span's wall-clock start in
    # unix seconds (monotonic stamps are rebased host-side before emit,
    # telemetry.tracing.wall_of), ``dur_s`` its duration. Head-sampled:
    # at sample rate 0 nothing on the hot path even looks at a clock.
    # The ``timeline`` CLI merges spans (with correlate's clock
    # alignment) into a Chrome-trace/Perfetto artifact.
    "span": ("name", "trace_id", "span_id", "parent_id", "start_ts", "dur_s"),
    # A drift evidence bundle landed (telemetry.forensics, serving
    # daemon): partition/global_pos locate the firing flag exactly like
    # ``drift_detected``; ``bundle`` is the bundle file's path relative
    # to the run log's directory (under ``<run>.forensics/``). Extracted
    # host-side from the already-collected flag tables + the chunk's
    # host copy — never from jitted code.
    "drift_forensics": ("chunk", "partition", "global_pos", "bundle"),
    # A drift adaptation decision landed (adapt subsystem): tenant
    # ``tenant``'s ``policy`` (retrain|shadow) consumed the drift verdict
    # of ``trigger_chunk``, refitted on ``rows_refit`` post-drift window
    # rows, and measured champion-vs-challenger error on that window
    # (``err_before``/``err_after`` — None when the window held no valid
    # rows). ``promoted`` = the challenger now serves (always True for
    # retrain; gated on measured error for shadow; False with the
    # ``demoted`` extra when a probation window reverted a promotion).
    # Extras: ``applied_chunk``, ``rows_to_apply`` (rows from verdict to
    # application), ``pre_drift_err``, ``window_rows``. Emitted host-side
    # at verdict publication — never from jitted code, serve/chunked
    # paths only (api.run's Final Time purity holds by construction).
    "adaptation": (
        "tenant", "trigger_chunk", "policy", "rows_refit",
        "err_before", "err_after", "promoted",
    ),
    # one per run log, last event: totals over the reference's Final Time
    "run_completed": ("rows", "seconds", "detections"),
}


class SchemaError(ValueError):
    """An event violates the run-log schema (unknown type, missing field,
    wrong envelope version, or a line that is not a JSON object)."""


# The only required fields allowed to be null (see the schema notes above).
_NULLABLE = frozenset(
    {
        ("drift_detected", "delay_rows"),
        ("cost_analysis", "flops"),
        ("cost_analysis", "bytes_accessed"),
        ("span", "parent_id"),  # root spans have no parent
        # an empty/fully-masked refit window has no error to measure
        ("adaptation", "err_before"),
        ("adaptation", "err_after"),
    }
)


def validate_event(event: object) -> dict:
    """Validate one event dict against :data:`EVENT_SCHEMA`; returns it."""
    if not isinstance(event, dict):
        raise SchemaError(f"event is not a JSON object: {event!r:.80}")
    etype = event.get("type")
    if etype not in EVENT_SCHEMA:
        raise SchemaError(
            f"unknown event type {etype!r}; expected one of "
            f"{sorted(EVENT_SCHEMA)}"
        )
    if event.get("v") != SCHEMA_VERSION:
        raise SchemaError(
            f"schema version {event.get('v')!r} != {SCHEMA_VERSION} "
            f"(event {etype!r})"
        )
    for field in ("ts", "seq"):
        if field not in event:
            raise SchemaError(f"event {etype!r} missing envelope {field!r}")
    missing = [f for f in EVENT_SCHEMA[etype] if f not in event]
    if missing:
        raise SchemaError(f"event {etype!r} missing required {missing}")
    # Presence is not enough: a null where the report does arithmetic
    # (int(done["rows"]), timeline positions) would turn "a log that loads
    # is a log the report can render" into a downstream TypeError.
    null = [
        f
        for f in EVENT_SCHEMA[etype]
        if event[f] is None and (etype, f) not in _NULLABLE
    ]
    if null:
        raise SchemaError(f"event {etype!r} has null required {null}")
    return event


_RUN_COUNTER = 0
_SAFE_NAME = re.compile(r"[^A-Za-z0-9._-]+")


class EventLog:
    """Append-only JSONL event sink for one run.

    Lines are flushed as written (the log survives a crash mid-run — that
    is half its point), and every emitted event is schema-validated first,
    so a malformed emit fails the *producer* loudly instead of poisoning
    the artifact.
    """

    def __init__(self, path: str, *, clock=time.time):
        self.path = path
        self.run_id = os.path.splitext(os.path.basename(path))[0]
        self._clock = clock
        self._seq = 0
        self._fh = open(path, "a")
        # Emission is serialized: the serving daemon's SLO evaluator
        # thread emits alerts into the same log as the serve loop, and an
        # interleaved seq/write would corrupt the artifact.
        self._lock = threading.Lock()
        # Optional per-event observer (e.g. the ops plane's
        # FlightRecorder): called with each validated record after it is
        # flushed, under the same lock (ring order == log order).
        self.tap = None

    @classmethod
    def open_run(
        cls,
        telemetry_dir: str,
        name: str = "",
        process_index: "int | None" = None,
    ) -> "EventLog":
        """Create the directory and a fresh per-run log file inside it.

        ``name`` (e.g. the resolved app name — the grid harness's per-cell
        config key) is sanitized into the filename; a timestamp + pid +
        process-local counter suffix keeps concurrent and repeated runs
        from colliding. ``process_index`` (a ``jax.distributed`` process id,
        see ``parallel.multihost.host_identity``) adds a ``procN`` segment:
        in a multi-host run every process writes its own log into a shared
        directory, and without the segment the N sibling logs of one run
        are indistinguishable on disk (``telemetry.correlate`` groups them
        by the ``run_started`` identity extras; the filename is for humans
        and shell globs).
        """
        global _RUN_COUNTER
        os.makedirs(telemetry_dir, exist_ok=True)
        stem = _SAFE_NAME.sub("_", name).strip("_") or "run"
        proc = "" if process_index is None else f"-proc{int(process_index)}"
        _RUN_COUNTER += 1
        fname = (
            f"{stem}-{time.strftime('%Y%m%d-%H%M%S')}{proc}"
            f"-{os.getpid()}-{_RUN_COUNTER}.jsonl"
        )
        return cls(os.path.join(telemetry_dir, fname))

    def emit(self, etype: str, **fields) -> dict:
        """Validate and append one event; returns the full record."""
        with self._lock:
            event = {
                "v": SCHEMA_VERSION,
                "type": etype,
                "ts": self._clock(),
                "seq": self._seq,
                **fields,
            }
            validate_event(event)
            payload = json.dumps(event)
            # Fault-injection site (resilience.faults, no-op unless armed):
            # kind='torn_write' appends a partial prefix of this payload with
            # no newline and raises — the exact torn-tail artifact the
            # allow_partial_tail read path and crash tests exercise.
            faults.fire(
                "telemetry.emit", fh=self._fh, payload=payload, seq=self._seq
            )
            self._fh.write(payload + "\n")
            self._fh.flush()
            self._seq += 1
            if self.tap is not None:
                self.tap(event)
        return event

    def close(self) -> None:
        if not self._fh.closed:
            self._fh.close()

    def __enter__(self) -> "EventLog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def read_events(path: str, *, allow_partial_tail: bool = False) -> list[dict]:
    """Parse and schema-validate a run log; raises :class:`SchemaError` on
    any malformed line (the CI smoke gate's contract: a log that loads is a
    log the report can render).

    ``allow_partial_tail=True`` tolerates exactly one **torn trailing
    line** — the crash/live-tail read path. The sink appends
    ``json.dumps(event) + "\\n"`` per emit, so a reader racing the writer
    (or a log cut off by a crash/full volume mid-write) can see one final
    line that is an incomplete JSON prefix; that line is skipped, never a
    line before it (a malformed *interior* line is corruption either way),
    and never a line that parses as JSON but violates the schema (a
    complete-but-invalid event is a producer bug a tear cannot produce —
    no strict prefix of the serialized object form is itself valid JSON).
    The strict default is the CI smoke gate's contract.
    """
    events = []
    with open(path) as fh:
        lines = fh.readlines()
    for lineno, line in enumerate(lines, 1):
        stripped = line.strip()
        if not stripped:
            continue
        try:
            event = json.loads(stripped)
        except json.JSONDecodeError as e:
            if allow_partial_tail and lineno == len(lines):
                break  # the one torn trailing line; everything before stands
            raise SchemaError(f"{path}:{lineno}: not JSON ({e})") from None
        try:
            validate_event(event)
        except SchemaError as e:
            raise SchemaError(f"{path}:{lineno}: {e}") from None
        events.append(event)
    return events


def emit_flag_events(
    log: EventLog,
    change_global: np.ndarray,
    forced_retrain: np.ndarray,
    dist_between_changes: int = 0,
) -> int:
    """Emit drift/retrain events from a collected ``[P, NB-1]`` flag table.

    Called host-side on the already-transferred numpy flags, after the
    timed span — never from jitted code. Every detector change becomes a
    ``drift_detected`` (with its delay when the stream has planted-boundary
    geometry, else ``delay_rows=None``); every model rotation — change- or
    saturation-guard-triggered — becomes a ``retrain`` (``batch`` is the
    1-based processed-batch index, matching the flag table's column + 1).
    Returns the number of drift events emitted.
    """
    cg = np.asarray(change_global)
    fr = np.asarray(forced_retrain)
    dist = int(dist_between_changes)
    changed = cg >= 0
    # Column-major (batch-then-partition) order: the log reads as a timeline.
    for b, p in zip(*np.nonzero(changed.T)):
        pos = int(cg[p, b])
        log.emit(
            "drift_detected",
            partition=int(p),
            global_pos=pos,
            delay_rows=(pos % dist) if dist > 0 else None,
            batch=int(b) + 1,
        )
    for b, p in zip(*np.nonzero((changed | fr).T)):
        log.emit(
            "retrain",
            partition=int(p),
            batch=int(b) + 1,
            forced=bool(fr[p, b]),
        )
    return int(changed.sum())
