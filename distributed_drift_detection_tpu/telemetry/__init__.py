"""Run telemetry (aux subsystem: observability, SURVEY.md §5).

The reference's only instrumentation is one wall-clock span
(``DDM_Process.py:224,260``); answering "what did this run do, where did
the time go, and when/where did drift fire" requires re-running it. This
subsystem persists that answer as artifacts instead:

* :mod:`.events` — typed, timestamped records (``run_started``,
  ``phase_completed``, ``drift_detected``, …) appended to a JSONL run log
  with a versioned schema (``docs/OBSERVABILITY.md``).
* :mod:`.metrics` — a counters/gauges/histograms registry with JSON and
  Prometheus-text exporters.
* :mod:`.spans` — nested wall-clock spans with call counts and a
  first-call-vs-steady-state split; ``utils.timing.PhaseTimer`` is now a
  thin compatibility shim over it.
* :mod:`.report` — ``python -m distributed_drift_detection_tpu report
  <run.jsonl>``: phase breakdown, throughput, cost/memory section, drift
  timeline, per-partition detection counts from a persisted run log.
* :mod:`.profile` — compiler/device introspection (XLA
  ``cost_analysis``/``memory_analysis``, ``device.memory_stats()``)
  mapped onto the event schema and registry gauges.
* :mod:`.perf` — ``python -m distributed_drift_detection_tpu perf
  BENCH_r*.json``: per-cell diff of bench artifacts across rounds,
  nonzero exit on gated regressions beyond a tolerance.
* :mod:`.registry` — append-only ``index.jsonl`` per telemetry dir:
  run_id → config digest, status running/completed/failed, artifact
  paths (written by ``api.run`` and the grid harness); the fleet's
  "which runs exist here and did they finish".
* :mod:`.correlate` — ``python -m distributed_drift_detection_tpu
  correlate <dir|logs>``: merge one multi-host run's N per-process logs
  into a single clock-skew-rebased timeline with straggler diagnostics
  (per-host detect spread, throughput skew).
* :mod:`.watch` — ``python -m distributed_drift_detection_tpu watch
  <run.jsonl|dir>``: live-tail a run log (torn-tail tolerant), render
  progress/ETA from ``heartbeat`` events, exit 3 when stalled past
  ``--stall-after`` — the scriptable health check for CI and pod
  launchers.
* :mod:`.ops` — the serving daemon's live HTTP ops plane
  (``--ops-port``): ``/metrics`` (the registry, byte-identical to the
  ``.prom`` exporter), ``/healthz`` (200/503 by SLO/poison state),
  ``/statusz`` (JSON snapshot) + the crash flight recorder
  (``<run>.flightrec.jsonl``).
* :mod:`.trace` — end-to-end row tracing: the
  ``serve_row_latency_seconds{stage=…}`` live histograms (vectorized
  per-row observe) and histogram-quantile helpers for both the live
  registry and parsed scrapes.
* :mod:`.tracing` — the causal trace plane: trace-context propagation
  (``TRACE`` wire lines, head-sampled — zero hot-path work at rate 0)
  and schema-v1 ``span`` events for the serving chain
  (ingress→admission→batch→kernel→verdict) and the batch pipeline
  (``ChunkTracer``: ingest/kernel per chunk).
* :mod:`.timeline` — ``python -m distributed_drift_detection_tpu
  timeline <dir|logs>``: merge any set of run logs (daemon + loadgen,
  multi-host fleets — clock-skew aligned per correlate's rule) into one
  Chrome-trace/Perfetto ``.trace.json``.
* :mod:`.forensics` — drift evidence bundles: on a drift verdict the
  serving daemon extracts error-rate trajectory, warn/drift thresholds,
  detector window stats, context rows and sampled trace ids into
  ``<run>.forensics/`` (announced by ``drift_forensics`` events,
  counted in ``/statusz``); ``python -m distributed_drift_detection_tpu
  explain`` renders bundles.
* :mod:`.slo` — declarative SLO rules (p99 latency, verdict staleness,
  quarantine rate, event stall) evaluated on a cadence; threshold
  crossings emit schema-v1 ``alert`` events and drive ``/healthz``.
* :mod:`.top` — ``python -m distributed_drift_detection_tpu top``: one
  refreshing terminal dashboard over many runs, from tailed logs and/or
  ``/statusz`` endpoints; ``--store`` adds per-row TREND sparklines from
  a history store, ``--record``/``--replay`` persist and play back
  dashboard frames.
* :mod:`.history` — the durable time-series plane: an append-only,
  segment-rotated on-disk store for scraped samples, with retention by
  age/size, step-aligned downsampling and PromQL-ish query primitives
  (``range``/``rate``/``quantile_over_time``/``top-tenants``) behind the
  ``history`` CLI.
* :mod:`.collector` — the fleet scraper daemon: discovers serve targets
  from ``--statusz`` URLs, a router's ``/fleetz`` or the telemetry
  registry, polls ``/metrics`` + ``/statusz`` on an interval into a
  history store (wall + monotonic stamps, per-target ``up`` marking,
  self-metering), and can evaluate multi-window burn-rate SLO rules
  against the store.
* :mod:`.pipeline` — serve-pipeline bottleneck attribution from stage
  busy counters; ``--window`` replays the same attribution from a
  history store over a trailing window.

Telemetry is **off by default** (``RunConfig.telemetry_dir=None``): every
hook is an ``if log is not None`` guard outside the timed span, so the
disabled path executes no telemetry code at all. The package core never
imports jax — the report and perf CLIs and the exporters work anywhere;
:mod:`.profile` is the one module that talks to jax, and only lazily
inside its functions.
"""

from .events import (
    EVENT_SCHEMA,
    SCHEMA_VERSION,
    EventLog,
    SchemaError,
    emit_flag_events,
    read_events,
    validate_event,
)
from .metrics import (
    MetricsRegistry,
    parse_prometheus_text,
    write_exports,
)
from .spans import SpanTracker

__all__ = [
    "EVENT_SCHEMA",
    "SCHEMA_VERSION",
    "EventLog",
    "SchemaError",
    "emit_flag_events",
    "read_events",
    "validate_event",
    "MetricsRegistry",
    "parse_prometheus_text",
    "write_exports",
    "SpanTracker",
]
